"""Profiling hooks: jax.profiler traces + wall-clock step timing.

The reference has no profiling at all (SURVEY §5: "Tracing/profiling:
ABSENT" — only tqdm bars).  TPU-first observability:

* ``profile_trace(dir)`` captures an XLA/TPU trace viewable in TensorBoard /
  Perfetto (device timelines, HLO ops, ICI collectives);
* ``StepTimer`` measures steady-state step time with an explicit
  ``block_until_ready`` fence — the JAX analogue of the reference's
  ``cuda.synchronize`` timing hygiene (utils/train_eval_utils.py:55-57);
* ``device_watchdog`` / ``await_devices`` fail fast when backend
  acquisition hangs (a dead accelerator tunnel blocks ``jax.devices()``
  forever — round-4 incident).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time
from typing import Optional

import jax


def device_watchdog(seconds: float = 300.0, on_timeout=None):
    """Fail FAST if JAX backend/device acquisition hangs.

    A dead accelerator tunnel makes ``jax.devices()`` block forever with
    no output — a silently hung benchmark/driver process.  Arm this
    BEFORE the first backend touch and ``.set()`` the returned event
    right after ``jax.devices()`` returns; if it isn't set within
    ``seconds`` the process prints one clear stderr line and exits 3.
    Generous default: a cold tunnel handshake is legitimately slow.

    ``on_timeout``: optional callback run before the exit — benchmark
    entry points use it to emit a machine-readable null result so the
    driver's artifact records WHY there is no number (r5; the bare rc=3
    of r4 took a human to interpret).  Exceptions in it are swallowed:
    the exit must happen regardless.
    """
    armed = threading.Event()

    def boom():
        if not armed.wait(seconds):
            # Re-check after the wait: jax.devices() may have returned
            # just before the deadline with armed.set() not yet executed
            # — killing a healthy process with a false "unreachable"
            # artifact (code-review r5).  One grace second closes the
            # set-vs-timeout race; a genuinely hung backend cannot set
            # the event at all.
            if armed.wait(1.0):
                return
            import sys

            if on_timeout is not None:
                try:
                    on_timeout()
                # can-tpu-lint: disable=SWALLOW(process is about to _exit(3); the fatal print below is the record)
                except Exception:
                    pass
            print(f"[watchdog] FATAL: no JAX device within {seconds:.0f}s "
                  f"— accelerator backend unreachable", file=sys.stderr,
                  flush=True)
            os._exit(3)

    threading.Thread(target=boom, daemon=True).start()
    return armed


def emit_null_result(metric: str, **extra):
    """on_timeout callback factory for benchmark entry points: print one
    machine-readable null-result line before the watchdog exit, so the
    recorded artifact says WHY there is no number instead of a bare
    rc=3 (r5).  Usage: ``await_devices(on_timeout=emit_null_result(...))``."""

    def emit():
        import json

        print(json.dumps(dict(
            {"metric": metric, "value": None,
             "error": "accelerator backend unreachable (watchdog timeout)"},
            **extra)), flush=True)

    return emit


def await_devices(seconds: float = 300.0, on_timeout=None):
    """Arm the watchdog, force backend init, disarm; returns devices.
    One call at the top of every benchmark entry point.  Disarms in
    ``finally``: a backend that RAISES (refused connection) instead of
    hanging must not leave the timer to kill the caller's fallback path
    minutes later."""
    armed = device_watchdog(seconds, on_timeout=on_timeout)
    try:
        return jax.devices()
    finally:
        armed.set()


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """Capture a jax.profiler trace into ``log_dir`` (no-op if None)."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


class StepTimer:
    """Step wall-time accounting: rolling mean, bounded sample reservoir
    (p50/p95/max), and optional per-shape-bucket breakdown.

    The first ``skip_first`` steps are excluded from every statistic (they
    carry compile time; ``mean`` is NaN until a post-skip step lands).
    The reservoir keeps the most recent ``reservoir`` samples (deque, not
    true reservoir sampling: for telemetry the RECENT distribution is the
    one that predicts the next hour).  ``stop(shape=...)`` tags the sample
    with its batch bucket so a bimodal p95 can be attributed to the bucket
    causing it instead of read as noise."""

    def __init__(self, skip_first: int = 2, reservoir: int = 4096):
        import collections

        self.skip_first = skip_first
        self._count = 0
        self._total = 0.0
        self._last: Optional[float] = None
        self._samples = collections.deque(maxlen=max(int(reservoir), 1))
        self._window: list = []  # samples since the last drain_window()
        self._shapes: dict = {}  # shape -> [count, total_s]

    def start(self) -> None:
        self._last = time.perf_counter()

    def stop(self, result=None, *, shape=None, record: bool = True) -> float:
        """Fence on ``result`` (if given) and record the elapsed time.

        In an async-dispatch loop, call WITHOUT ``result``: the sample is
        then the host-side step interval (the window-flush step absorbs
        the device sync), whose sum over a window is honest wall time.
        ``record=False`` measures but records nothing — for steps whose
        time is accounted elsewhere (a first-call compile, attributed by
        its own ``compile`` event; folding it in here would let one 10 s
        compile masquerade as the steady-state p95/max)."""
        if self._last is None:
            raise RuntimeError("StepTimer.stop() before start()")
        if result is not None:
            jax.block_until_ready(result)
        dt = time.perf_counter() - self._last
        self._last = None
        if not record:
            return dt
        return self.record(dt, shape=shape)

    def record(self, dt: float, *, shape=None) -> float:
        """Record an externally measured sample — for durations that don't
        fit the sequential start/stop pattern (e.g. serve request
        latencies, measured per request across threads).  Same reservoir,
        window, skip_first, and per-shape accounting as ``stop``."""
        self._count += 1
        if self._count > self.skip_first:
            self._total += dt
            self._samples.append(dt)
            self._window.append(dt)
            if shape is not None:
                rec = self._shapes.setdefault(shape, [0, 0.0])
                rec[0] += 1
                rec[1] += dt
        return dt

    @property
    def mean(self) -> float:
        n = self._count - self.skip_first
        return self._total / n if n > 0 else float("nan")

    def percentiles(self) -> dict:
        """``{n, p50_s, p95_s, max_s}`` over the reservoir (post-skip
        samples); Nones when nothing has been recorded yet."""
        if not self._samples:
            return {"n": 0, "p50_s": None, "p95_s": None, "max_s": None}
        import numpy as np

        arr = np.asarray(self._samples, np.float64)
        return {"n": int(arr.size),
                "p50_s": float(np.percentile(arr, 50)),
                "p95_s": float(np.percentile(arr, 95)),
                "max_s": float(arr.max())}

    def percentile(self, q: float) -> Optional[float]:
        """One percentile over the reservoir (None when empty) — the
        autoscaler reads p99 here; ``percentiles()`` stays the fixed
        p50/p95/max report shape."""
        if not self._samples:
            return None
        import numpy as np

        return float(np.percentile(
            np.asarray(self._samples, np.float64), q))

    def shape_totals(self) -> dict:
        """Raw per-shape accounting, ``{shape: (n, total_s)}`` — the
        lossless feed the ProgramCostLedger joins against compiled-program
        flops (``shape_summary`` stringifies keys and rounds, which is
        right for the JSONL payload and wrong for arithmetic)."""
        return {shape: (n, total) for shape, (n, total)
                in self._shapes.items()}

    def shape_summary(self) -> dict:
        """Per-bucket breakdown: ``{shape_str: {n, total_s, mean_s}}``."""
        return {str(shape): {"n": n, "total_s": round(total, 4),
                             "mean_s": round(total / n, 6)}
                for shape, (n, total) in sorted(self._shapes.items(),
                                                key=lambda kv: str(kv[0]))}

    def drain_window(self) -> list:
        """Return (and reset) the samples recorded since the last drain —
        the per-window payload for ``step_window`` telemetry events."""
        window, self._window = self._window, []
        return window
