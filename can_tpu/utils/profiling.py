"""Profiling hooks: jax.profiler traces + wall-clock step timing.

The reference has no profiling at all (SURVEY §5: "Tracing/profiling:
ABSENT" — only tqdm bars).  TPU-first observability:

* ``profile_trace(dir)`` captures an XLA/TPU trace viewable in TensorBoard /
  Perfetto (device timelines, HLO ops, ICI collectives);
* ``StepTimer`` measures steady-state step time with an explicit
  ``block_until_ready`` fence — the JAX analogue of the reference's
  ``cuda.synchronize`` timing hygiene (utils/train_eval_utils.py:55-57).
"""

from __future__ import annotations

import contextlib
import time
from typing import Optional

import jax


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]):
    """Capture a jax.profiler trace into ``log_dir`` (no-op if None)."""
    if not log_dir:
        yield
        return
    with jax.profiler.trace(log_dir):
        yield


class StepTimer:
    """Rolling mean of step wall-times, excluding the first (compile) steps."""

    def __init__(self, skip_first: int = 2):
        self.skip_first = skip_first
        self._count = 0
        self._total = 0.0
        self._last: Optional[float] = None

    def start(self) -> None:
        self._last = time.perf_counter()

    def stop(self, result=None) -> float:
        """Fence on ``result`` (if given) and record the elapsed time."""
        if result is not None:
            jax.block_until_ready(result)
        dt = time.perf_counter() - self._last
        self._count += 1
        if self._count > self.skip_first:
            self._total += dt
        return dt

    @property
    def mean(self) -> float:
        n = self._count - self.skip_first
        return self._total / n if n > 0 else float("nan")
