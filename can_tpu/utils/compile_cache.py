"""Persistent XLA compilation cache (on by default in the CLIs/benches).

The bucketed variable-resolution configs compile one program per bucket
shape — a 180-200 s bill the eager reference never pays, and without a
persistent cache it is repaid on EVERY fresh process (resume, eval, every
restart).  JAX's on-disk compilation cache amortises it to once per
(machine, jaxlib, topology): warm starts deserialise the executable in
~100 ms instead of recompiling.

Default location: ``~/.cache/can_tpu/xla`` (override with the
``CAN_TPU_COMPILE_CACHE`` env var or the CLIs' ``--compile-cache`` flag;
``off`` disables).  Must be called before the first compilation.
"""

from __future__ import annotations

import os
from typing import Optional

_OFF_VALUES = ("off", "none", "0", "disabled")


def default_cache_dir() -> str:
    return os.environ.get(
        "CAN_TPU_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "can_tpu", "xla"))


def enable_compilation_cache(cache_dir: Optional[str] = None) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir``.

    cache_dir: directory path; None -> :func:`default_cache_dir`, but only
    on accelerator backends — XLA:CPU's AOT deserialisation logs a spurious
    machine-feature-mismatch error per cache hit (and CPU compiles are not
    the 180 s bill this cache exists to kill), so auto mode skips the CPU
    backend; pass an explicit directory to force it there.  Any of
    "off"/"none"/"0" -> disabled (returns None).  Returns the directory in
    effect, or None when disabled.

    Thresholds are zeroed so every program is cached — the workload's many
    per-bucket-shape programs each take seconds to compile but can fall
    under JAX's default minimum-compile-time gate on fast hosts.
    """
    import jax

    if cache_dir is None:
        if jax.default_backend() == "cpu":
            return None
        cache_dir = default_cache_dir()
    if str(cache_dir).strip().lower() in _OFF_VALUES:
        return None

    cache_dir = os.path.abspath(os.path.expanduser(str(cache_dir)))
    os.makedirs(cache_dir, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", cache_dir)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    return cache_dir
