"""can_tpu — a TPU-native (JAX/XLA/Pallas) crowd-counting training framework.

A ground-up re-design of the capabilities of the reference repo
``zgzhengSEU/CAN-distributed-pytorch`` (CANNet multi-GPU DDP training,
see /root/reference) for TPU hardware:

* NHWC layouts, static shapes, bf16-capable compute (MXU-friendly).
* Adaptive pooling / align-corners bilinear resize expressed as small
  matmuls instead of gathers (reference: model/CANNet.py:42-81).
* Data parallelism via ``jax.sharding`` + ``jit`` with XLA collectives
  over ICI instead of NCCL DDP (reference: train.py:121-122,
  utils/distributed_utils.py:23-27).
* Spatial (context) parallelism for very-high-resolution images via
  ``shard_map`` + halo exchange with ``lax.ppermute`` — the CNN analogue
  of ring attention (the reference handles high-res only via batch=1).
* Bucketed, masked batching for variable-resolution images
  (reference: batch_size=1 + fully dynamic shapes, train.py:177).
"""

__version__ = "0.1.0"
