"""Prepared-dataset store + decoded-item cache (the host data-pipeline L2).

The round-5 verdict's top finding: the host input pipeline is the one
measured axis slower than the accelerator it feeds — every epoch re-loads
the full-resolution float32 density ``.npy`` (~1.7 MB/item at 576x768) and
cv2-resizes it to 1/8 (~27 KB) inside ``CrowdDataset.__getitem__``, while
the chip consumes 94.5 img/s and the host delivers 88.5.  The density map
is a pure function of the GT file (the CAN training recipe never augments
it beyond the horizontal flip), so that work belongs offline.  Two pieces
live here:

**Prepared store** (``write_store`` / ``PreparedStore``): the snapped
1/8-resolution density maps baked to disk ONCE — the exact
``cv2.resize(dmap, (W//8, H//8)) * ds * ds`` the loader would compute,
f32, so the online fast path is a 27 KB ``np.load`` instead of a 1.7 MB
load + resize.  Both flip orientations are baked (``<base>.npy`` and
``<base>.flip.npy``): the legacy path flips the FULL-res map before the
resize, and flip does not commute with cv2's bilinear resample bit-for-bit
(~4e-6 relative, measured at every tested size) — flipping the small map
online would silently break the f32 path's bit-exact reference parity for
augmented items.  A ``manifest.json`` (version, gt_downsample, per-item
snapped shapes, prepared-file sizes, source ``.npy`` size+mtime, CRCs)
makes a stale or mismatched store DETECTABLE: ``CrowdDataset`` falls back
to the legacy decode path (with a ``data.prepared`` telemetry note) when
auto-probing, and an explicitly requested store that fails validation
raises instead of silently degrading.

**Decoded-item cache** (``ItemCache``): a bounded-bytes, thread-safe LRU
over fully-decoded ``(image, dmap)`` items, keyed on the full decode
config ``(img_root, gt_root, gt_downsample, u8_output, index, flip)`` —
the flip is in the key precisely so a hit returns bit-identical output to
a fresh decode (caching the unflipped item and flipping on hit would hit
the same non-commutation as above, this time on the image resize path),
and the config is in the key so datasets with different decode modes can
share one cache without serving each other's items.  For datasets that fit in host RAM
(ShanghaiTech A test split: ~0.5 GB decoded) the steady-state epoch does
zero decode work.  Hit/miss/bytes counters are emitted as ``data.cache``
telemetry events by the CLIs and summarized by
``tools/telemetry_report.py``.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import zlib
from typing import Dict, Iterable, Optional, Sequence, Tuple

import numpy as np

MANIFEST_NAME = "manifest.json"
STORE_DIRNAME = "prepared"  # conventional location: <gt_dmap_root>/prepared
DMAPS_DIRNAME = "dmaps"
STORE_VERSION = 1


class StaleStoreError(RuntimeError):
    """The prepared store is absent, unreadable, or out of date.

    ``CrowdDataset`` catches this on the auto-probe path (legacy fallback
    + telemetry note); an explicitly requested store propagates it —
    silently handing a user the slow path they opted out of would hide
    exactly the staleness the manifest exists to catch."""


def _crc32_file(path: str) -> int:
    crc = 0
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            crc = zlib.crc32(chunk, crc)
    return crc


def _image_files(img_root: str) -> list:
    return sorted(f for f in os.listdir(img_root)
                  if os.path.isfile(os.path.join(img_root, f)))


def _image_size(path: str) -> Tuple[int, int]:
    """(H, W) from the header only — no pixel decode."""
    from PIL import Image

    with Image.open(path) as im:
        w, h = im.size
    return h, w


def prepared_paths(root: str, img_name: str) -> Tuple[str, str]:
    """(unflipped, flipped) prepared-map paths for one image name."""
    base, _ = os.path.splitext(img_name)
    d = os.path.join(root, DMAPS_DIRNAME)
    return (os.path.join(d, base + ".npy"),
            os.path.join(d, base + ".flip.npy"))


def write_store(img_root: str, gt_dmap_root: str, out_root: Optional[str] = None,
                *, gt_downsample: int = 8, verbose: bool = False) -> str:
    """Bake the prepared store for one (images, ground_truth) pair.

    For every image: load the full-res density ``.npy``, apply EXACTLY the
    loader's math (f32 cast, cv2 bilinear resize to the snapped 1/8 grid,
    ``* ds * ds`` count conservation — two sequential multiplies, matching
    ``dataset.py`` operation for operation) in both flip orientations, and
    save the two small f32 maps.  The manifest is written LAST (atomic
    rename), so an interrupted bake leaves no manifest and the loader
    falls back rather than reading a half-written store.

    Returns the store root (default ``<gt_dmap_root>/prepared``).
    """
    import cv2

    ds = int(gt_downsample)
    if ds <= 1:
        raise ValueError("prepared store requires gt_downsample > 1 "
                         "(there is no offline resize to reuse otherwise)")
    root = out_root or os.path.join(gt_dmap_root, STORE_DIRNAME)
    os.makedirs(os.path.join(root, DMAPS_DIRNAME), exist_ok=True)
    items: Dict[str, dict] = {}
    for name in _image_files(img_root):
        h, w = _image_size(os.path.join(img_root, name))
        rows, cols = h // ds, w // ds
        if rows == 0 or cols == 0:
            raise ValueError(
                f"image {os.path.join(img_root, name)} is smaller than one "
                f"{ds}px density cell; remove or upscale it")
        base, _ = os.path.splitext(name)
        src = os.path.join(gt_dmap_root, base + ".npy")
        full = np.asarray(np.load(src), dtype=np.float32)
        plain_path, flip_path = prepared_paths(root, name)
        entry = {"hw": [rows * ds, cols * ds],
                 "src_bytes": os.stat(src).st_size,
                 "src_mtime_ns": os.stat(src).st_mtime_ns}
        for arr, path, bkey, ckey in (
                (full, plain_path, "bytes", "crc32"),
                (full[:, ::-1], flip_path, "bytes_flip", "crc32_flip")):
            small = cv2.resize(np.ascontiguousarray(arr), (cols, rows))
            small = small * ds * ds  # two multiplies, as the loader does
            np.save(path, small.astype(np.float32))
            entry[bkey] = os.stat(path).st_size
            entry[ckey] = _crc32_file(path)
        items[name] = entry
        if verbose:
            print(f"[prepare] {name}: {h}x{w} -> {rows}x{cols} x2")
    manifest = {"version": STORE_VERSION, "gt_downsample": ds,
                "created_ts": time.time(),
                "semantics": "cv2 bilinear half-pixel; flip baked offline "
                             "(flip-then-resize != resize-then-flip in f32)",
                "items": items}
    tmp = os.path.join(root, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
    os.replace(tmp, os.path.join(root, MANIFEST_NAME))
    return root


class PreparedStore:
    """An opened, validated prepared store.

    ``open()`` is the only constructor that should be used: it performs
    the full staleness protocol (manifest presence/version/gt_downsample,
    item coverage, snapped-shape cross-check against the live dataset,
    prepared-file existence+size, source ``.npy`` size+mtime) and raises
    :class:`StaleStoreError` with a specific reason — a mismatched store
    is never silently used.  ``verify()`` additionally re-reads every
    prepared file and checks its CRC (the bake records one per file);
    that is the tool's ``--verify-store`` path, not the hot path.
    """

    def __init__(self, root: str, manifest: dict):
        self.root = root
        self.manifest = manifest
        self.gt_downsample = int(manifest["gt_downsample"])

    @staticmethod
    def default_root(gt_dmap_root: str) -> str:
        return os.path.join(gt_dmap_root, STORE_DIRNAME)

    @classmethod
    def open(cls, root: str, *, gt_dmap_root: Optional[str] = None,
             gt_downsample: Optional[int] = None,
             img_names: Optional[Sequence[str]] = None,
             expected_hw: Optional[Dict[str, Tuple[int, int]]] = None,
             check_sources: bool = True) -> "PreparedStore":
        mpath = os.path.join(root, MANIFEST_NAME)
        if not os.path.isfile(mpath):
            raise StaleStoreError(f"no prepared store (missing {mpath}); "
                                  "run tools/prepare_data.py --prepared")
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            raise StaleStoreError(f"unreadable manifest {mpath}: {e}") from e
        if manifest.get("version") != STORE_VERSION:
            raise StaleStoreError(
                f"store version {manifest.get('version')!r} != "
                f"{STORE_VERSION} (re-bake with tools/prepare_data.py)")
        if (gt_downsample is not None
                and int(manifest.get("gt_downsample", -1)) != int(gt_downsample)):
            raise StaleStoreError(
                f"store baked at gt_downsample="
                f"{manifest.get('gt_downsample')}, loader wants "
                f"{gt_downsample}")
        items = manifest.get("items", {})
        for name in (img_names or ()):
            entry = items.get(name)
            if entry is None:
                raise StaleStoreError(
                    f"dataset item {name} not in store manifest "
                    "(images added since the bake?)")
            if expected_hw is not None and name in expected_hw:
                if tuple(entry["hw"]) != tuple(expected_hw[name]):
                    raise StaleStoreError(
                        f"{name}: snapped shape changed "
                        f"({tuple(entry['hw'])} baked vs "
                        f"{tuple(expected_hw[name])} now)")
            plain_path, flip_path = prepared_paths(root, name)
            for path, bkey in ((plain_path, "bytes"),
                               (flip_path, "bytes_flip")):
                try:
                    st = os.stat(path)
                except OSError:
                    raise StaleStoreError(f"prepared map missing: {path}")
                if st.st_size != entry[bkey]:
                    raise StaleStoreError(
                        f"prepared map truncated/rewritten: {path}")
            if check_sources and gt_dmap_root is not None:
                base, _ = os.path.splitext(name)
                src = os.path.join(gt_dmap_root, base + ".npy")
                try:
                    st = os.stat(src)
                except OSError:
                    raise StaleStoreError(
                        f"source density map gone: {src}")
                if (st.st_size != entry["src_bytes"]
                        or st.st_mtime_ns != entry["src_mtime_ns"]):
                    raise StaleStoreError(
                        f"source {src} changed since the bake; re-run "
                        "tools/prepare_data.py --prepared")
        return cls(root, manifest)

    def load(self, img_name: str, *, flip: bool = False) -> np.ndarray:
        """The prepared 1/8 density map, (h, w) float32 — already snapped
        and count-scaled; the loader only appends the channel axis."""
        plain_path, flip_path = prepared_paths(self.root, img_name)
        arr = np.load(flip_path if flip else plain_path)
        if arr.dtype != np.float32 or arr.ndim != 2:
            raise StaleStoreError(
                f"prepared map {img_name} has dtype {arr.dtype} / "
                f"ndim {arr.ndim}; expected 2-D float32")
        return arr

    def verify(self, img_names: Optional[Iterable[str]] = None) -> int:
        """Re-read prepared files and check CRCs; returns files checked."""
        names = list(img_names) if img_names is not None \
            else sorted(self.manifest.get("items", ()))
        checked = 0
        for name in names:
            entry = self.manifest["items"].get(name)
            if entry is None:
                raise StaleStoreError(f"{name} not in manifest")
            plain_path, flip_path = prepared_paths(self.root, name)
            for path, ckey in ((plain_path, "crc32"),
                               (flip_path, "crc32_flip")):
                if _crc32_file(path) != entry[ckey]:
                    raise StaleStoreError(f"checksum mismatch: {path}")
                checked += 1
        return checked


class ItemCache:
    """Bounded-bytes, thread-safe LRU over decoded ``(image, dmap)`` items.

    Keys carry the full decode config plus ``(index, flip)`` — the caller
    decides the flip BEFORE consulting the cache, so a hit is
    bit-identical to a fresh decode (see module docstring).  Values are cached exactly as returned
    (the dataset marks the arrays read-only: consumers only read, and a
    silent in-place edit would poison every later epoch's view).  An item
    larger than the whole budget is skipped, not thrashed through.

    Counters (hits/misses/inserts/evictions/bytes) are cumulative and
    cheap; the CLIs snapshot them per epoch as ``data.cache`` telemetry.
    """

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._entries: "collections.OrderedDict" = collections.OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.evictions = 0
        self.oversize_skips = 0

    def get(self, key):
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return entry[0]

    def put(self, key, value) -> bool:
        nbytes = sum(int(a.nbytes) for a in value)
        with self._lock:
            if key in self._entries:
                return False
            if nbytes > self.max_bytes:
                self.oversize_skips += 1
                return False
            while self._bytes + nbytes > self.max_bytes and self._entries:
                _, (_, old_bytes) = self._entries.popitem(last=False)
                self._bytes -= old_bytes
                self.evictions += 1
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            self.inserts += 1
            return True

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def stats(self) -> dict:
        with self._lock:
            total = self.hits + self.misses
            return {"hits": self.hits, "misses": self.misses,
                    "hit_rate": round(self.hits / total, 4) if total else None,
                    "inserts": self.inserts, "evictions": self.evictions,
                    "oversize_skips": self.oversize_skips,
                    "items": len(self._entries), "bytes": self._bytes,
                    "capacity_bytes": self.max_bytes}
