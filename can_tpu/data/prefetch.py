"""Async host-side prefetch: overlap data loading/transfer with device work.

The reference gets this from torch DataLoader worker processes
(reference: train.py:87-91, num_workers); here a single background thread
runs the (numpy) batch materialisation + host->device transfer while the
device crunches the previous step — with JAX's async dispatch that is enough
to hide the input pipeline entirely.

Observability: pass ``stall=obs.StallClock()`` to account the seconds the
CONSUMER spends blocked waiting for a batch that isn't ready — genuine
input-pipeline starvation, the thing that silently caps throughput when the
host can't keep up with the chip.  Time is added only when the popped
future wasn't already done, so an overlapped (hidden) load costs zero.
"""

from __future__ import annotations

import collections
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator, Optional


class PrefetchPutError(RuntimeError):
    """``put_fn`` failed inside the prefetch worker thread.

    The worker's exception only surfaces when its future is popped — up to
    ``depth`` batches after the failing one, by which point "which batch?"
    is gone from the traceback (the generator frame swallowed it).  This
    wrapper pins the failing batch index; the original exception rides
    along as ``__cause__`` with its full worker-thread traceback."""

    def __init__(self, batch_index: int):
        super().__init__(f"put_fn failed on batch {batch_index} "
                         f"(prefetched in a worker thread; see the chained "
                         f"cause for the original traceback)")
        self.batch_index = batch_index


def prefetch_to_device(batches: Iterable, put_fn: Callable, *,
                       depth: int = 2, stall=None) -> Iterator:
    """Yield ``put_fn(batch)`` for each batch, computed ``depth`` ahead in a
    background thread.  depth<=0 disables prefetching (synchronous path:
    exceptions propagate untouched, and ``stall`` accounts the full load
    time — nothing overlaps it)."""
    if depth <= 0:
        for b in batches:
            if stall is not None:
                t0 = time.perf_counter()
                out = put_fn(b)
                stall.add(time.perf_counter() - t0)
                yield out
            else:
                yield put_fn(b)
        return

    it = iter(batches)
    _done = object()
    n_submitted = 0

    def load_next(index: int):
        try:
            batch = next(it)
        except StopIteration:
            return _done
        try:
            return put_fn(batch)
        except Exception as e:
            raise PrefetchPutError(index) from e

    def submit():
        nonlocal n_submitted
        fut = ex.submit(load_next, n_submitted)
        n_submitted += 1
        return fut

    ex = ThreadPoolExecutor(max_workers=1)
    try:
        queue = collections.deque(submit() for _ in range(depth))
        while queue:
            fut = queue.popleft()
            if stall is not None and not fut.done():
                t0 = time.perf_counter()
                result = fut.result()
                stall.add(time.perf_counter() - t0)
            else:
                result = fut.result()
            if result is _done:
                break
            queue.append(submit())
            yield result
    finally:
        # On consumer abandonment (GeneratorExit: a raised
        # NonFiniteLossError, Ctrl-C, an early break) the queued
        # load_next futures must be CANCELLED, not awaited — each runs a
        # host->device transfer, and `with ThreadPoolExecutor` would
        # block generator close behind up to ``depth`` full loads (or
        # forever on a wedged accelerator tunnel, the round-4 incident
        # class; code-review r5).  The one in-flight call still finishes
        # (a worker thread can't be interrupted), but nothing new starts.
        ex.shutdown(wait=False, cancel_futures=True)
