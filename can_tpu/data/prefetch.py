"""Async host-side prefetch: overlap data loading/transfer with device work.

The reference gets this from torch DataLoader worker processes
(reference: train.py:87-91, num_workers); here a single background thread
runs the (numpy) batch materialisation + host->device transfer while the
device crunches the previous step — with JAX's async dispatch that is enough
to hide the input pipeline entirely.
"""

from __future__ import annotations

import collections
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Iterable, Iterator


def prefetch_to_device(batches: Iterable, put_fn: Callable, *,
                       depth: int = 2) -> Iterator:
    """Yield ``put_fn(batch)`` for each batch, computed ``depth`` ahead in a
    background thread.  depth<=0 disables prefetching."""
    if depth <= 0:
        for b in batches:
            yield put_fn(b)
        return

    it = iter(batches)
    _done = object()

    def load_next():
        try:
            return put_fn(next(it))
        except StopIteration:
            return _done

    ex = ThreadPoolExecutor(max_workers=1)
    try:
        queue = collections.deque(ex.submit(load_next) for _ in range(depth))
        while queue:
            result = queue.popleft().result()
            if result is _done:
                break
            queue.append(ex.submit(load_next))
            yield result
    finally:
        # On consumer abandonment (GeneratorExit: a raised
        # NonFiniteLossError, Ctrl-C, an early break) the queued
        # load_next futures must be CANCELLED, not awaited — each runs a
        # host->device transfer, and `with ThreadPoolExecutor` would
        # block generator close behind up to ``depth`` full loads (or
        # forever on a wedged accelerator tunnel, the round-4 incident
        # class; code-review r5).  The one in-flight call still finishes
        # (a worker thread can't be interrupted), but nothing new starts.
        ex.shutdown(wait=False, cancel_futures=True)
