"""Variable-resolution crowd dataset pipeline (host-side, numpy).

Re-implements the reference loader semantics
(reference: model/CrowdDataset.py:16-70) with TPU-first output:

* image read as RGB float in [0,1]; grayscale expanded to 3 channels
  (CrowdDataset.py:38-43);
* paired ``.npy`` density map (CrowdDataset.py:45-46);
* 50% horizontal flip of both in the train phase (CrowdDataset.py:48-50) —
  but driven by an explicit seeded ``numpy.random.Generator`` instead of the
  reference's unseeded global ``random`` (train.py:66 seeds only CUDA);
* H, W snapped *down* to multiples of ``gt_downsample`` (=8) via cv2 bilinear
  resize; density map resized straight to (H/8, W/8) and rescaled by 8*8 to
  conserve the head count (CrowdDataset.py:53-62);
* ImageNet mean/std normalisation (CrowdDataset.py:64-66).

Differences by design:

* output is **NHWC float32** (TPU lane layout), not CHW torch tensors;
* the ``gt_downsample <= 1`` path — a latent NameError in the reference
  (CrowdDataset.py:53-69) — is implemented rather than crashing;
* deterministic: item transforms take the RNG as an argument, so a given
  (seed, epoch, index) always yields the same sample on every host.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import cv2
import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], dtype=np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], dtype=np.float32)


def _read_image_raw(path: str) -> np.ndarray:
    """Decoded pixels as stored (u8 for JPEG/8-bit PNG), RGB (H, W, 3)."""
    from PIL import Image

    with Image.open(path) as im:
        if im.mode not in ("RGB", "RGBA", "L"):
            # ALLOWLIST, not a blocklist of known-bad modes: palette ('P')
            # decodes to colormap indices, 'LA' to 2-channel arrays that
            # dodge both branches below, 'I' to int32 that mis-normalises
            # — every non-RGB/L mode needs a real conversion
            # (code-review r5)
            im = im.convert("RGB")
        arr = np.asarray(im)
    if arr.ndim == 2:  # grayscale -> 3 channels (reference :41-43)
        arr = np.stack([arr] * 3, axis=-1)
    if arr.shape[-1] == 4:  # drop alpha
        arr = arr[..., :3]
    return arr


def _read_image(path: str) -> np.ndarray:
    """RGB float32 in [0,1], (H, W, 3)."""
    arr = _read_image_raw(path)
    if np.issubdtype(arr.dtype, np.integer):
        # scale by the dtype's full range (uint8 -> /255, 16-bit PNG -> /65535)
        return arr.astype(np.float32) / float(np.iinfo(arr.dtype).max)
    return arr.astype(np.float32)


def _read_image_u8(path: str) -> np.ndarray:
    """RGB uint8 (H, W, 3) — the zero-float-math decode for u8 mode."""
    arr = _read_image_raw(path)
    if arr.dtype == np.uint8:
        return arr
    if np.issubdtype(arr.dtype, np.integer):  # e.g. 16-bit PNG
        # match the f32 path's full-range convention (/iinfo.max): shift
        # so the dtype's max lands on 255 — signed types have one fewer
        # value bit, so the shift comes from log2(max+1), not itemsize
        shift = max(0, int(np.iinfo(arr.dtype).max + 1).bit_length() - 1 - 8)
        # clip negatives BEFORE the u8 cast: a signed source (e.g. int32 -1)
        # would otherwise wrap to a bright value, unlike the f32 path whose
        # /max keeps the sign (advisor r3)
        return np.clip(arr >> shift, 0, 255).astype(np.uint8)
    return np.clip(np.round(arr * 255.0), 0, 255).astype(np.uint8)


def normalize_host(img: np.ndarray) -> np.ndarray:
    """u8 (H, W, 3) -> ImageNet-normalised float32 (the host-side twin of
    train.steps.normalize_on_device, for viz/inference helpers).  Float
    input (already normalised) passes through unchanged."""
    if img.dtype != np.uint8:
        return img
    return ((img.astype(np.float32) / 255.0 - IMAGENET_MEAN)
            / IMAGENET_STD).astype(np.float32)


class CrowdDataset:
    """Indexable dataset of (image NHWC, density map (h, w, 1)) numpy pairs.

    u8_output=True is the TPU-first transfer mode: images stay uint8 pixels
    on the host end to end (u8 decode, u8 flip, cv2 fixed-point u8 resize,
    NO normalisation) and the compiled step normalises on device
    (train/steps.py::normalize_on_device) — 4x fewer host->device bytes,
    XLA fuses the normalise into the first conv, and the host does about
    half the per-item work (no float conversion/normalise).  The reference
    ships normalised f32 tensors through its DataLoader
    (CrowdDataset.py:64-66).  Pixel values differ from the f32 path only by
    u8 rounding in the resize (<~1/255 per pixel); the default stays f32
    for bit-exact reference parity.

    prepared: "auto" (default) probes ``<gt_dmap_root>/prepared`` for a
    baked 1/8-density store (tools/prepare_data.py --prepared) and uses it
    when the manifest validates — the density ``.npy`` load+resize drops
    from ~1.7 MB/item to a 27 KB load, numerics bit-identical (both flip
    orientations are baked offline; see data/prepared.py).  A stale or
    mismatched store falls back to the legacy path, reason recorded in
    ``prepared_note``.  "off" disables; an explicit path is REQUIRED to
    validate (StaleStoreError propagates).

    item_cache: optional :class:`~can_tpu.data.prepared.ItemCache` shared
    across datasets — fully-decoded items keyed on (img_root, index,
    flip); a hit skips decode entirely and is bit-identical by
    construction.
    """

    def __init__(self, img_root: str, gt_dmap_root: str, *,
                 gt_downsample: int = 8, phase: str = "train",
                 u8_output: bool = False, prepared: Optional[str] = "auto",
                 item_cache=None):
        self.img_root = img_root
        self.gt_dmap_root = gt_dmap_root
        self.gt_downsample = int(gt_downsample)
        self.phase = phase
        self.u8_output = bool(u8_output)
        self.item_cache = item_cache
        # sorted (the reference uses os.listdir order, which is fs-dependent;
        # sorting makes sharding identical across hosts)
        self.img_names = sorted(
            f for f in os.listdir(img_root)
            if os.path.isfile(os.path.join(img_root, f))
        )
        # Reject sub-gt_downsample images at LISTING time: an image
        # shorter/narrower than one density cell snaps to a 0 extent,
        # which the batcher would bucket and cv2.resize would then crash
        # on mid-epoch deep in a loader thread (code-review r5).  The
        # header reads are cached — the bucketing batcher asks for every
        # snapped shape anyway, so this costs one pass, not two.
        self._snapped_cache: Optional[list] = None
        if self.gt_downsample > 1:
            shapes = [self._snapped_shape_uncached(i)
                      for i in range(len(self.img_names))]
            for f, (h, w) in zip(self.img_names, shapes):
                if h == 0 or w == 0:
                    raise ValueError(
                        f"image {os.path.join(img_root, f)} is smaller than "
                        f"one {self.gt_downsample}px density cell "
                        f"(snapped shape {h}x{w}); remove or upscale it")
            self._snapped_cache = shapes
        self.prepared = None
        self._resolve_prepared(prepared)

    def _resolve_prepared(self, spec) -> None:
        """Open the prepared 1/8-density store per ``spec`` ("auto"/"off"/
        path).  Auto-probe failures degrade to the legacy path with the
        reason recorded in ``prepared_note`` (the CLIs surface it as a
        ``data.prepared`` telemetry event); an EXPLICIT path that fails
        validation raises — never silently hand back the slow path the
        caller opted out of."""
        from can_tpu.data.prepared import PreparedStore, StaleStoreError

        spec = "off" if spec is None else spec
        self.prepared_note = {"mode": str(spec), "active": False,
                              "root": None, "reason": None}
        if spec == "off":
            self.prepared_note["reason"] = "disabled"
            return
        if self.gt_downsample <= 1:
            self.prepared_note["reason"] = \
                "gt_downsample <= 1 (no offline resize to reuse)"
            return
        root = (PreparedStore.default_root(self.gt_dmap_root)
                if spec == "auto" else spec)
        self.prepared_note["root"] = root
        expected = dict(zip(self.img_names, self._snapped_cache or ()))
        try:
            self.prepared = PreparedStore.open(
                root, gt_dmap_root=self.gt_dmap_root,
                gt_downsample=self.gt_downsample,
                img_names=self.img_names, expected_hw=expected)
            self.prepared_note["active"] = True
        except StaleStoreError as e:
            if spec != "auto":
                raise
            self.prepared_note["reason"] = str(e)

    def __len__(self) -> int:
        return len(self.img_names)

    def snapped_shape(self, index: int) -> Tuple[int, int]:
        """(H, W) the item will have after /8 snapping — header-only read,
        cached at listing time; used by the bucketing batcher to group
        shapes without decoding full images."""
        if self._snapped_cache is not None:
            return self._snapped_cache[index]
        return self._snapped_shape_uncached(index)

    def _snapped_shape_uncached(self, index: int) -> Tuple[int, int]:
        from PIL import Image

        with Image.open(os.path.join(self.img_root, self.img_names[index])) as im:
            w, h = im.size
        ds = self.gt_downsample
        if ds > 1:
            return (h // ds) * ds, (w // ds) * ds
        return h, w

    def __getitem__(self, index: int,
                    rng: Optional[np.random.Generator] = None):
        name = self.img_names[index]
        path = os.path.join(self.img_root, name)
        # the flip decision comes FIRST (one rng draw, same consumption as
        # before): both the item cache and the prepared store key on it —
        # a cached or baked item must be bit-identical to a fresh decode,
        # and flip does not commute with the resize (data/prepared.py)
        flip = bool(self.phase == "train" and rng is not None
                    and rng.integers(0, 2) == 1)
        if self.item_cache is not None:
            # the FULL decode config rides in the key: a shared cache must
            # never serve an f32 item to a u8 dataset (or across ds/gt
            # roots) as a "hit" — that would be silent numeric corruption,
            # not an error
            cache_key = (self.img_root, self.gt_dmap_root,
                         self.gt_downsample, self.u8_output, index, flip)
            hit = self.item_cache.get(cache_key)
            if hit is not None:
                return hit
        # u8 mode keeps pixels as bytes END TO END on the host: u8 decode,
        # u8 flip, cv2's fixed-point u8 bilinear resize, no normalise —
        # about half the host work per item of the f32 path (the normalise
        # runs inside the compiled step instead).  Pixels differ from the
        # f32 path only by the resize's u8 rounding (<~1/255 per pixel).
        img = _read_image_u8(path) if self.u8_output else _read_image(path)
        if flip:
            img = img[:, ::-1]
        ds = self.gt_downsample
        if self.prepared is not None:
            # fast path: the snapped, count-scaled 1/8 map (in the right
            # flip orientation) was baked offline — a 27 KB load replaces
            # the ~1.7 MB full-res load + resize.  Image math unchanged.
            rows, cols = img.shape[0] // ds, img.shape[1] // ds
            img = cv2.resize(np.ascontiguousarray(img), (cols * ds, rows * ds))
            dmap = self.prepared.load(name, flip=flip)
            if dmap.shape != (rows, cols):
                from can_tpu.data.prepared import StaleStoreError

                raise StaleStoreError(
                    f"prepared map {name} is {dmap.shape}, expected "
                    f"{(rows, cols)} — store out of date")
        else:
            base, _ = os.path.splitext(name)
            dmap = np.load(os.path.join(self.gt_dmap_root, base + ".npy"))
            dmap = np.asarray(dmap, dtype=np.float32)
            if flip:
                dmap = dmap[:, ::-1]
            if ds > 1:
                rows, cols = img.shape[0] // ds, img.shape[1] // ds
                # cv2 bilinear, half-pixel centers — bit-exact with the
                # reference (CrowdDataset.py:56-60) on the f32 path.
                img = cv2.resize(np.ascontiguousarray(img),
                                 (cols * ds, rows * ds))
                dmap = cv2.resize(np.ascontiguousarray(dmap), (cols, rows))
                dmap = dmap * ds * ds  # conserve count (reference :61-62)

        dmap = dmap[..., np.newaxis].astype(np.float32)
        if not self.u8_output:
            img = ((img - IMAGENET_MEAN) / IMAGENET_STD).astype(np.float32)
        if self.item_cache is not None:
            # read-only before sharing: every later epoch returns these
            # same buffers, so a consumer's in-place edit would silently
            # poison them (pad_batch and the step factories only read)
            img.setflags(write=False)
            dmap.setflags(write=False)
            self.item_cache.put(cache_key, (img, dmap))
        return img, dmap
