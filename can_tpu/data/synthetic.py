"""Synthetic crowd data for tests and benchmarks (no dataset download).

Writes ``images/*.jpg`` + ``ground_truth/*.npy`` pairs in the exact on-disk
layout the reference trains from (reference: train.py:49-57 — paired image /
density-map roots), with density maps produced by the same geometry-adaptive
Gaussian generator used for real annotations (data/density.py).
"""

from __future__ import annotations

import os
from typing import Sequence, Tuple

import numpy as np

from can_tpu.data.density import gaussian_density_map


def make_synthetic_dataset(root: str, n: int, *,
                           sizes: Sequence[Tuple[int, int]] = ((256, 320), (320, 256), (384, 512)),
                           max_people: int = 40, seed: int = 0,
                           ) -> Tuple[str, str]:
    """Create n synthetic (image, density-map) pairs under ``root``.

    Returns (img_root, gt_dmap_root).
    """
    from PIL import Image

    img_root = os.path.join(root, "images")
    gt_root = os.path.join(root, "ground_truth")
    os.makedirs(img_root, exist_ok=True)
    os.makedirs(gt_root, exist_ok=True)
    rng = np.random.default_rng(seed)
    for i in range(n):
        h, w = sizes[int(rng.integers(len(sizes)))]
        npeople = int(rng.integers(1, max_people + 1))
        # heads as (col, row) — the ShanghaiTech .mat convention.
        points = np.stack([rng.uniform(0, w, npeople),
                           rng.uniform(0, h, npeople)], axis=1)
        img = rng.uniform(0.0, 1.0, (h, w, 3)).astype(np.float32)
        # draw bright blobs at head positions so the image correlates with
        # the density target (lets smoke-training actually reduce loss).
        for c, r in points.astype(int):
            r0, r1 = max(0, r - 3), min(h, r + 4)
            c0, c1 = max(0, c - 3), min(w, c + 4)
            img[r0:r1, c0:c1] = 1.0
        dmap = gaussian_density_map(points, (h, w))
        Image.fromarray((img * 255).astype(np.uint8)).save(
            os.path.join(img_root, f"IMG_{i:04d}.jpg"), quality=95)
        np.save(os.path.join(gt_root, f"IMG_{i:04d}.npy"), dmap)
    return img_root, gt_root
