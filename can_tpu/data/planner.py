"""Cost-model-driven batch planning for the varres bucket schedule.

Until round 7 the batch planner was three ad-hoc heuristics scattered
through ``batching.py``: ``_menu_for`` capped a too-big cell to the largest
remnant-menu size that fit HBM, ``_partial_plan`` greedily merged straggler
groups pairwise and dropped the smallest menu size when over the compile
budget, and ``_decompose`` ran a per-cell DP — each locally sensible, none
sharing an objective, and the measured result was a 30.7% schedule
overhead for b16 varres vs 21.7% at b8 (BENCH_SUITE_r05, VERDICT r5
item 7).  This module replaces them with ONE explicit objective,

    plan_cost = area * padded_slots + launch_cost_px * n_launches

(the unit is pixels; ``launch_cost_px`` converts a step launch's fixed
dispatch/device overhead into pixel-equivalents, calibrated by
``cli/common.py::measure_launch_cost_mpx`` — probe-vs-step ratio 1.15 on
chip, r5) and a deterministic search over the joint plan space:

* **per-cell batch size** — a cell whose full global batch exceeds the
  ``max_launch_px`` HBM cap prices EVERY fitting launch size (full-cell
  lowered runs vs cap-to-menu decompositions) and runs the cheapest;
* **remnant menu composition** — cost mode plans over every multiple of
  the batch quantum (dp-divisibility is the only hard divisibility
  constraint; the old power-of-two menu was a compile-count convenience),
  letting straggler groups launch at their EXACT size instead of padding
  up to the next power of two; the budget loop drops sizes when the
  program count would exceed ``max_buckets``;
* **group packing** — greedy pairwise merging is kept but extended with
  steepest-descent local search (move one source cell between groups,
  extract one back out), so a bad early join can be undone;
* **bucket-boundary placement** — ``ShardedBatcher._resolve_auto_buckets``
  scores every (kh, kw) ladder grid with kh*kw <= max_buckets by the FULL
  plan cost of the schedule it induces (not by padded area alone, which is
  blind to dead slots and launch counts), via ``GlobalPlanner.plan``.

Everything is a pure function of the shape histogram and the planner
config, so every host computes bit-identical plans (the lockstep-schedule
contract) and the plan is identical across epochs (the shuffle only
permutes which items fill the slots).

``mode="legacy"`` preserves the round-5 behaviour exactly (max-fitting
full size, power-of-two menu, pairwise merge + drop-smallest) — it is the
baseline arm of ``tools/plan_ablation.py`` and the escape hatch if a
regression ever points here.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, FrozenSet, List, NamedTuple, Optional, Tuple

Key = Tuple[int, int]


def decompose(n: int, menu: Tuple[int, ...], area: float = 1.0,
              launch_cost: float = 0.0) -> Tuple[int, ...]:
    """Cover ``n`` items with menu-size parts minimising
    ``area * total_slots + launch_cost * n_parts`` — exact bottom-up DP
    (n is at most a few global batches; recursion would blow the stack at
    batch_quantum=1, ADVICE r4).

    Ties on cost prefer fewer launches, then the lexicographically
    smallest part tuple — the determinism the multi-host byte-identical
    plan contract rests on.  Parts return descending, so any fill slots
    land in the final (smallest) part."""
    base = (0.0, 0, ())
    best = [base] * (n + 1 if n > 0 else 1)
    for r in range(1, n + 1):
        best[r] = min(
            (area * s + launch_cost + sub[0], 1 + sub[1], (s,) + sub[2])
            for s in menu
            for sub in (best[r - s] if r > s else base,))
    return tuple(sorted(best[n if n > 0 else 0][2], reverse=True))


@dataclasses.dataclass(frozen=True)
class PlanCostModel:
    """The planner's single pricing function.

    menu: legal launch sizes (global units), descending; every size is a
      multiple of the batch quantum so any launch splits evenly across
      hosts and the mesh dp axis.
    launch_cost_px: fixed cost of one step launch, in pixel-equivalents.
    max_launch_px: HBM ceiling per launch (batch * H * W), or None.
    """

    menu: Tuple[int, ...]
    launch_cost_px: float = 0.0
    max_launch_px: Optional[float] = None

    @staticmethod
    def area(key: Key) -> int:
        return key[0] * key[1]

    def fits(self, key: Key, size: int) -> bool:
        return (self.max_launch_px is None
                or size * self.area(key) <= self.max_launch_px)

    def fits_any(self, key: Key, menu: Optional[Tuple[int, ...]] = None) -> bool:
        return any(self.fits(key, s) for s in (menu or self.menu))

    def fitting(self, key: Key,
                menu: Optional[Tuple[int, ...]] = None) -> Tuple[int, ...]:
        """Menu filtered by the per-launch pixel cap; the smallest size
        always survives (the quantum floor — refusing the cell would drop
        data, so an over-cap floor launch is the documented degradation,
        warned by the caller)."""
        menu = menu or self.menu
        kept = tuple(s for s in menu if self.fits(key, s))
        return kept or (min(menu),)

    def parts(self, key: Key, count: int,
              menu: Optional[Tuple[int, ...]] = None) -> Tuple[int, ...]:
        """Cheapest launch-size cover of ``count`` items in this cell."""
        return decompose(count, self.fitting(key, menu), float(self.area(key)),
                         self.launch_cost_px)

    def parts_cost(self, key: Key, parts: Tuple[int, ...]) -> float:
        return self.area(key) * sum(parts) + self.launch_cost_px * len(parts)

    def cell_cost(self, key: Key, count: int,
                  menu: Optional[Tuple[int, ...]] = None) -> float:
        return self.parts_cost(key, self.parts(key, count, menu))

    def full_size(self, key: Key, count: int) -> int:
        """Launch size for this cell's full (exactly-filled) runs: every
        fitting size is priced over the WHOLE cell (full chunks at that
        size + the cheapest decomposition of the remainder) and the
        cheapest wins — 'run the whole cell at a lower batch' is a
        first-class candidate, not a cap fallback.  Ties prefer the
        larger size (fewer, fuller launches)."""
        fit = self.fitting(key)
        if count <= 0 or len(fit) == 1:
            return max(fit)

        def whole_cell_cost(s: int) -> float:
            n_full = count // s
            rem = count - n_full * s
            cost = n_full * (self.area(key) * s + self.launch_cost_px)
            if rem:
                cost += self.cell_cost(key, rem)
            return cost

        return max(fit, key=lambda s: (-whole_cell_cost(s), s))


class PlannedGroup(NamedTuple):
    """One remnant launch group: stragglers from ``sources`` cells run at
    the elementwise-max ``key`` in launches of sizes ``parts``."""

    key: Key
    sources: Tuple[Key, ...]
    count: int
    parts: Tuple[int, ...]


class Plan(NamedTuple):
    """A complete epoch-invariant launch plan for one shape histogram."""

    full_parts: Dict[Key, Tuple[int, ...]]  # exactly-filled launches/cell
    groups: Tuple[PlannedGroup, ...]        # remnant groups (may have fill)
    menu: Tuple[int, ...]                   # after any budget drops
    programs: FrozenSet[Tuple[Key, int]]    # distinct (shape, size) pairs
    cost: float                             # model cost of the whole plan
    scheduled_px: float                     # area * slots over all launches
    launches: int
    legacy_fallback: bool = False           # pad-to-gbs path proved cheaper

    @property
    def lowered_cells(self) -> int:
        """Cells whose full runs launch below the top menu size (the
        HBM-cap batch-lowering the r5 verdict asked to price, item 7)."""
        if not self.full_parts:
            return 0
        top = max(self.menu)
        return sum(1 for parts in self.full_parts.values()
                   if parts and parts[0] < top)

    @property
    def lowered_launches(self) -> int:
        if not self.full_parts:
            return 0
        top = max(self.menu)
        return sum(sum(1 for p in parts if p < top)
                   for parts in self.full_parts.values())


class GlobalPlanner:
    """Search the joint plan space for one shape-count histogram.

    mode="cost" (default): full-cell size pricing, exact-size menus,
    merge + move/extract local search, drop-any-size budget lever.
    mode="legacy": the pre-r8 heuristics, bit-compatible — the ablation
    baseline.
    """

    def __init__(self, model: PlanCostModel, *, max_buckets: int,
                 mode: str = "cost",
                 warn: Optional[Callable[[str], None]] = None):
        if mode not in ("cost", "legacy"):
            raise ValueError(f"unknown planner mode {mode!r}")
        self.model = model
        self.max_buckets = int(max_buckets)
        self.mode = mode
        self.warn = warn or (lambda msg: None)
        self._parts_cache: Dict[Tuple, Tuple[int, ...]] = {}
        self._floor_warned: set = set()

    # -- cached pricing ---------------------------------------------------
    def _parts(self, key: Key, count: int,
               menu: Tuple[int, ...]) -> Tuple[int, ...]:
        ck = (key, count, menu)
        got = self._parts_cache.get(ck)
        if got is None:
            got = self._parts_cache[ck] = self.model.parts(key, count, menu)
        return got

    def _cost(self, key: Key, count: int, menu: Tuple[int, ...]) -> float:
        return self.model.parts_cost(key, self._parts(key, count, menu))

    # -- the search -------------------------------------------------------
    def plan(self, counts: Dict[Key, int]) -> Plan:
        model = self.model
        menu = tuple(sorted(model.menu, reverse=True))

        full_parts: Dict[Key, Tuple[int, ...]] = {}
        pool: List[Tuple[Key, int]] = []  # (cell key, remnant count)
        for k, c in sorted(counts.items()):
            if self.mode == "cost":
                cf = model.full_size(k, c)
            else:
                cf = max(model.fitting(k))
            if not model.fits(k, min(menu)) and k not in self._floor_warned:
                self._floor_warned.add(k)
                self.warn(
                    f"bucket {k[0]}x{k[1]} exceeds the per-launch pixel cap "
                    f"even at the minimum batch {min(menu)} "
                    f"({min(menu) * model.area(k) / 1e6:.1f} Mpx > "
                    f"{(model.max_launch_px or 0) / 1e6:.1f} Mpx) — "
                    f"launching anyway; expect HBM pressure (shrink "
                    f"batch_quantum or image sizes)")
            if c >= cf:
                full_parts[k] = (cf,) * (c // cf)
            if c % cf:
                pool.append((k, c % cf))

        groups: List[FrozenSet[int]] = [frozenset({i})
                                        for i in range(len(pool))]

        def join_of(srcs: FrozenSet[int]) -> Key:
            return (max(pool[i][0][0] for i in srcs),
                    max(pool[i][0][1] for i in srcs))

        def count_of(srcs: FrozenSet[int]) -> int:
            return sum(pool[i][1] for i in srcs)

        def gcost(srcs: FrozenSet[int], m: Tuple[int, ...]) -> float:
            if not srcs:
                return 0.0
            return self._cost(join_of(srcs), count_of(srcs), m)

        def gfits(srcs: FrozenSet[int], m: Tuple[int, ...]) -> bool:
            # the no-OOM promise outranks the compile budget: never create
            # a join cell with NO cap-fitting launch size — the floor
            # fallback would launch it above the cap (code-review r5)
            return model.fits_any(join_of(srcs), m)

        def programs_of(m: Tuple[int, ...]) -> FrozenSet[Tuple[Key, int]]:
            ps = {(k, s) for k, parts in full_parts.items() for s in parts}
            for g in groups:
                j = join_of(g)
                ps.update((j, s) for s in self._parts(j, count_of(g), m))
            return frozenset(ps)

        def resort():
            # keep the candidate enumeration order (hence tie-breaking)
            # independent of lever history: the pre-r8 planner re-sorted
            # its (key, count, sources) triples after every merge, and the
            # byte-identical multi-host plan contract rides on it
            groups.sort(key=lambda g: (join_of(g), count_of(g),
                                       tuple(sorted(pool[i][0]
                                                    for i in g))))

        # Two phases, each provably terminating (interleaving improvement
        # moves with forced budget merges could cycle: an extract can
        # undo the merge the budget just forced):
        #
        # Phase A (cost mode only) — steepest-descent improvement: MERGE
        # two groups at their elementwise-max join cell, MOVE one source
        # cell between groups, or EXTRACT one back out, cheapest
        # (most negative cost delta) first; strictly decreasing cost over
        # a finite state space, so it terminates.
        if self.mode == "cost":
            while True:
                best = None
                for i in range(len(groups)):
                    for j in range(i + 1, len(groups)):
                        u = groups[i] | groups[j]
                        if not gfits(u, menu):
                            continue
                        d = (gcost(u, menu) - gcost(groups[i], menu)
                             - gcost(groups[j], menu))
                        if d < -1e-9 and (best is None or d < best[0]):
                            best = (d, "merge", (i, j))
                    if len(groups[i]) <= 1:
                        continue
                    for s in sorted(groups[i]):
                        rest = groups[i] - {s}
                        base_d = gcost(rest, menu) - gcost(groups[i], menu)
                        for j in range(len(groups)):
                            if j == i:
                                continue
                            u = groups[j] | {s}
                            if not gfits(u, menu):
                                continue
                            d = (base_d + gcost(u, menu)
                                 - gcost(groups[j], menu))
                            if d < -1e-9 and (best is None or d < best[0]):
                                best = (d, "move", (i, j, s))
                        d = base_d + gcost(frozenset({s}), menu)
                        if d < -1e-9 and (best is None or d < best[0]):
                            best = (d, "extract", (i, s))
                if best is None:
                    break
                _, lever, payload = best
                if lever == "merge":
                    i, j = payload
                    groups[i] = groups[i] | groups[j]
                    groups.pop(j)
                elif lever == "move":
                    i, j, s = payload
                    groups[j] = groups[j] | {s}
                    groups[i] = groups[i] - {s}
                    groups = [g for g in groups if g]
                else:
                    i, s = payload
                    groups[i] = groups[i] - {s}
                    groups.append(frozenset({s}))
                    groups = [g for g in groups if g]
                resort()

        # Phase B — the budget loop (both modes; ≡ the pre-r8 loop when
        # no moves preceded it): improvement MERGES always apply, forced
        # merges and menu DROPS only while the program count exceeds
        # ``max_buckets``.  Merges shrink the group list and drops shrink
        # the menu, so this terminates too.
        while True:
            over = len(programs_of(menu)) > self.max_buckets
            best = None  # (delta, lever, payload)
            for i in range(len(groups)):
                for j in range(i + 1, len(groups)):
                    u = groups[i] | groups[j]
                    if not gfits(u, menu):
                        continue
                    d = (gcost(u, menu)
                         - gcost(groups[i], menu) - gcost(groups[j], menu))
                    if (d < -1e-9 or over) and (best is None or d < best[0]):
                        best = (d, "merge", (i, j))
            if over and len(menu) > 1:
                # DROP a menu size (remnant decompositions only; the
                # quantum always survives, and under a cap a size may only
                # go if every CURRENT group keeps a fitting launch size) —
                # cost mode may drop ANY size, legacy only the smallest
                # (menu is descending: the last index)
                droppable = (range(len(menu) - 1) if self.mode == "cost"
                             else (len(menu) - 1,))
                for di in droppable:
                    m2 = menu[:di] + menu[di + 1:]
                    if not all(gfits(g, m2) for g in groups):
                        continue
                    d = (sum(gcost(g, m2) for g in groups)
                         - sum(gcost(g, menu) for g in groups))
                    if best is None or d < best[0]:
                        best = (d, "drop", di)
            if best is None or (best[0] >= -1e-9 and not over):
                if over:
                    self.warn(
                        f"{len(programs_of(menu))} programs exceed "
                        f"max_buckets={self.max_buckets} — the per-launch "
                        f"pixel cap prevents further merging; expect extra "
                        f"XLA compiles")
                break
            _, lever, payload = best
            if lever == "merge":
                i, j = payload
                groups[i] = groups[i] | groups[j]
                groups.pop(j)
            else:
                menu = menu[:payload] + menu[payload + 1:]
            resort()

        planned = tuple(sorted(
            PlannedGroup(join_of(g),
                         tuple(sorted({pool[i][0] for i in g})),
                         count_of(g),
                         self._parts(join_of(g), count_of(g), menu))
            for g in groups))
        scheduled = (sum(model.area(k) * sum(parts)
                         for k, parts in full_parts.items())
                     + sum(model.area(pg.key) * sum(pg.parts)
                           for pg in planned))
        launches = (sum(len(p) for p in full_parts.values())
                    + sum(len(pg.parts) for pg in planned))
        return Plan(full_parts=full_parts, groups=planned, menu=menu,
                    programs=programs_of(menu),
                    cost=scheduled + model.launch_cost_px * launches,
                    scheduled_px=float(scheduled), launches=launches)

    def plan_with_fallback(self, counts: Dict[Key, int]) -> Plan:
        """``plan`` guarded by the legacy-padding safety net: when no
        pixel cap is in force, never schedule more pixels than the
        pad-every-straggler-to-gbs path would (legacy pads to the FULL
        global batch, which is exactly what a capped cell must not
        launch, so the net is skipped under a cap).  The fallback Plan
        carries the REAL economics of the pad-to-gbs schedule (pixels,
        launches, programs) — these feed the data.planner gauges, which
        must never report a zero-pixel plan for a schedule that launches
        everything."""
        plan = self.plan(counts)
        if self.model.max_launch_px is not None:
            return plan
        legacy = self._legacy_pad_plan(counts)
        if legacy is not None and legacy.cost < plan.cost:
            return legacy
        return plan

    def _legacy_pad_plan(self, counts: Dict[Key, int]) -> Optional[Plan]:
        """The pad-every-straggler-to-gbs schedule as a Plan (the exact
        economics of the path global_schedule falls through to)."""
        from can_tpu.data.batching import _merge_partial_groups

        gbs = max(self.model.menu)
        lc = self.model.launch_cost_px
        partials = [(k, [(k, True)] * (c % gbs))
                    for k, c in sorted(counts.items()) if c % gbs]
        if not partials:
            return None
        merged = _merge_partial_groups(partials, gbs)
        full = {k: (gbs,) * (c // gbs)
                for k, c in sorted(counts.items()) if c >= gbs}
        launches = (sum(len(p) for p in full.values())
                    + sum(-(-len(g) // gbs) for _, g in merged))
        scheduled = (sum(self.model.area(k) * sum(p)
                         for k, p in full.items())
                     + sum(self.model.area(k) * gbs * (-(-len(g) // gbs))
                           for k, g in merged))
        programs = frozenset({(k, gbs) for k in full}
                             | {(k, gbs) for k, _ in merged})
        return Plan(full_parts=full, groups=(), menu=(gbs,),
                    programs=programs, cost=scheduled + lc * launches,
                    scheduled_px=float(scheduled), launches=launches,
                    legacy_fallback=True)


def schedule_coverage(schedule) -> Dict[int, int]:
    """Valid-slot occurrences per item index over a realized schedule —
    the exact-coverage invariant's measurable form.  A correct epoch (or
    an elastic remainder replanned at a new quantum after a shrink)
    covers each of its items EXACTLY once: ``schedule_coverage(sched) ==
    {i: 1 for i in items}``.  Fill slots (valid=False) are excluded — a
    duplicated index with a zero sample mask contributes nothing.  Used
    by the elastic tests and the supervisor's resume-time sanity check;
    pure and jax-free."""
    seen: Dict[int, int] = {}
    for _key, group in schedule:
        for idx, valid in group:
            if valid:
                seen[int(idx)] = seen.get(int(idx), 0) + 1
    return seen


def remnant_menu(gbs: int, quantum: int, *, mode: str = "cost") -> Tuple[int, ...]:
    """Legal launch sizes (global units), descending.

    cost mode: every multiple of the quantum up to the global batch — the
    only hard constraint is dp-divisibility (every size splits evenly
    across hosts and mesh dp shards), so straggler groups can launch at
    their exact size; the program-budget lever drops sizes when compiles
    would exceed ``max_buckets``.  legacy mode: the full batch plus
    quantum * 2^j halvings (the pre-r8 compile-count convenience).
    """
    if mode == "cost":
        return tuple(range(gbs, 0, -quantum))
    menu = {gbs}
    s = quantum
    while s < gbs:
        menu.add(s)
        s *= 2
    return tuple(sorted(menu, reverse=True))
