"""Geometry-adaptive Gaussian ground-truth density maps (offline generation).

Semantics follow the reference generator
(reference: data_preparation/k_nearest_gaussian_kernel.py:14-54):

* per head annotation ``(col, row)``, place a unit delta and blur with an
  isotropic Gaussian of ``sigma = 0.1 * (d1 + d2 + d3)`` where ``d*`` are
  distances to the 3 nearest other heads (KDTree, k=4 including self);
* points outside the image are skipped;
* ``scipy.ndimage.gaussian_filter(mode='constant')`` semantics — mass falling
  outside the image border is lost (no renormalisation).

Two deliberate departures from the reference:

1. **The 1-point case is fixed.** The reference references an undefined
   variable ``gt`` (k_nearest_gaussian_kernel.py:51) and crashes; we use
   ``sigma = mean(image_shape) / 4`` — the value that line was trying to
   compute (the classic MCNN/CSRNet fallback).
2. **Windowed stamping instead of per-point full-image filtering.** The
   reference runs a full-image ``gaussian_filter`` per person —
   O(people x H x W).  Convolving a delta is just the (separable, truncated)
   kernel itself, so we stamp the outer product of two 1-D Gaussian windows
   clipped to the image — identical output (scipy truncates at
   ``truncate * sigma`` anyway), ~1000x faster on dense images.
"""

from __future__ import annotations

import glob
import os
from typing import Sequence

import numpy as np
from scipy.spatial import cKDTree


def _gaussian_kernel_1d(sigma: float, radius: int) -> np.ndarray:
    """Matches scipy.ndimage's Gaussian: sampled, normalised to sum 1."""
    x = np.arange(-radius, radius + 1, dtype=np.float64)
    phi = np.exp(-0.5 * (x / sigma) ** 2)
    return (phi / phi.sum()).astype(np.float64)


def _stamp_gaussian(density: np.ndarray, row: int, col: int, sigma: float,
                    truncate: float = 4.0) -> None:
    """Add a unit-mass truncated Gaussian at (row, col), clipped to bounds.

    Exactly equals ``scipy.ndimage.gaussian_filter(delta, sigma,
    mode='constant', truncate=truncate)`` because filtering a delta yields the
    separable truncated kernel centred on it; 'constant' mode means clipped
    mass is simply lost.
    """
    h, w = density.shape
    radius = int(truncate * float(sigma) + 0.5)
    if radius < 1:
        density[row, col] += 1.0
        return
    k = _gaussian_kernel_1d(sigma, radius)
    r0, r1 = max(0, row - radius), min(h, row + radius + 1)
    c0, c1 = max(0, col - radius), min(w, col + radius + 1)
    kr = k[r0 - (row - radius): r1 - (row - radius)]
    kc = k[c0 - (col - radius): c1 - (col - radius)]
    density[r0:r1, c0:c1] += np.outer(kr, kc)


_native_lib = None
_native_checked = False


def _load_native():
    """ctypes handle to the C++ stamping loop (tools/build_native.py), or
    None — everything works without it, just slower on dense annotations."""
    global _native_lib, _native_checked
    if _native_checked:
        return _native_lib
    _native_checked = True
    import ctypes
    import os

    so = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                      "native", "libdensity_stamp.so")
    if os.path.exists(so):
        try:
            lib = ctypes.CDLL(so)
            d = ctypes.POINTER(ctypes.c_double)
            lib.stamp_gaussians.argtypes = [d, ctypes.c_int64, ctypes.c_int64,
                                            d, d, d, ctypes.c_int64,
                                            ctypes.c_double]
            lib.stamp_gaussians.restype = None
            _native_lib = lib
        except OSError:
            _native_lib = None
    return _native_lib


def gaussian_density_map(points: np.ndarray, shape: Sequence[int], *,
                         k: int = 3, sigma_scale: float = 0.1,
                         truncate: float = 4.0,
                         use_native: bool = True) -> np.ndarray:
    """Geometry-adaptive Gaussian density map.

    points: (P, 2) array of ``(col, row)`` head positions (the ShanghaiTech
      .mat convention, reference k_nearest_gaussian_kernel.py:17,79).
    shape: (H, W) of the image.
    Returns float32 (H, W) density map with sum ~= number of in-bounds heads
    (minus mass clipped at borders).

    The stamping loop runs in the C++ library (can_tpu/native/) when built;
    ``use_native=False`` or a missing .so falls back to numpy — identical
    output either way (tested).
    """
    h, w = int(shape[0]), int(shape[1])
    density = np.zeros((h, w), dtype=np.float64)
    points = np.asarray(points, dtype=np.float64).reshape(-1, 2)
    n = len(points)
    if n == 0:
        return density.astype(np.float32)

    if n > 1:
        tree = cKDTree(points, leafsize=2048)
        # k+1 neighbours: the nearest is the point itself at distance 0.
        distances, _ = tree.query(points, k=min(k + 1, n))
        distances = np.atleast_2d(distances)

    rows, cols, sigmas = [], [], []
    for i, (c, r) in enumerate(points):
        row, col = int(r), int(c)
        if not (0 <= row < h and 0 <= col < w):
            continue  # out-of-bounds annotations skipped (reference :44-46)
        if n > 1:
            # sum of available NN distances, scaled (reference :48-49).
            sigma = float(distances[i][1:].sum()) * sigma_scale
        else:
            sigma = (h + w) / 2.0 / 4.0  # fixed 1-point fallback (bug fix)
        if sigma <= 0:
            sigma = 1.0  # coincident points would give sigma 0
        rows.append(row)
        cols.append(col)
        sigmas.append(sigma)

    lib = _load_native() if use_native else None
    if lib is not None and rows:
        import ctypes

        ra = np.asarray(rows, np.float64)
        ca = np.asarray(cols, np.float64)
        sa = np.asarray(sigmas, np.float64)
        dptr = ctypes.POINTER(ctypes.c_double)
        lib.stamp_gaussians(
            density.ctypes.data_as(dptr), h, w,
            ra.ctypes.data_as(dptr), ca.ctypes.data_as(dptr),
            sa.ctypes.data_as(dptr), len(ra), float(truncate))
    else:
        for row, col, sigma in zip(rows, cols, sigmas):
            _stamp_gaussian(density, row, col, sigma, truncate)
    return density.astype(np.float32)


def _load_mat_points(mat_path: str) -> np.ndarray:
    """Extract (col,row) head annotations from a ShanghaiTech-style .mat
    (layout per reference k_nearest_gaussian_kernel.py:79), tolerating the
    nesting variants different MATLAB exporters produce."""
    import scipy.io as sio

    mat = sio.loadmat(mat_path)
    try:
        pts = np.asarray(mat["image_info"][0, 0][0, 0][0], dtype=np.float64)
        if pts.ndim == 2 and pts.shape[1] == 2:
            return pts
    except (KeyError, IndexError, TypeError, ValueError):
        pass
    # fallback: an (N, 2) numeric array under a recognised annotation key /
    # struct field only — an unconstrained search could silently pick up a
    # [W, H] size pair or bbox corners as "heads"
    for key in _ANNOTATION_KEYS:
        if key in mat:
            found = _find_points(mat[key])
            if found is not None:
                return found
    found = _find_points(mat.get("image_info"))
    if found is None:
        raise ValueError(
            f"no (N, 2) annotation array found in {mat_path} under keys "
            f"{sorted(k for k in mat if not k.startswith('__'))}")
    return found


_ANNOTATION_KEYS = ("annPoints", "points", "location", "locations")


def _find_points(obj):
    if isinstance(obj, np.ndarray):
        if obj.ndim >= 2 and obj.shape[-1] == 2 and obj.size > 0 and \
                np.issubdtype(obj.dtype, np.number):
            return np.asarray(obj, dtype=np.float64).reshape(-1, 2)
        if obj.dtype == object or obj.dtype.names:
            items = obj.flat
            for item in items:
                if obj.dtype.names:
                    for name in obj.dtype.names:
                        got = _find_points(item[name])
                        if got is not None:
                            return got
                else:
                    got = _find_points(item)
                    if got is not None:
                        return got
    elif isinstance(obj, (list, tuple)):
        for item in obj:
            got = _find_points(item)
            if got is not None:
                return got
    return None


def generate_density_maps(image_dirs: Sequence[str], *, k: int = 3,
                          sigma_scale: float = 0.1,
                          verbose: bool = True) -> int:
    """Offline driver: for every ``*.jpg`` under each dir, read its paired
    ``GT_IMG_*.mat`` annotation and write ``*.npy`` density map next to it
    (path scheme per reference k_nearest_gaussian_kernel.py:76-83).

    Returns the number of maps written.
    """
    from PIL import Image

    written = 0
    for path in image_dirs:
        for img_path in sorted(glob.glob(os.path.join(path, "*.jpg"))):
            # Component-wise path construction: blanket str.replace over
            # the ABSOLUTE path rewrote any parent directory containing
            # 'images'/'IMG_'/'.jpg' as a substring, silently reading or
            # writing in unrelated trees (code-review r5).  Only the
            # leaf directory named 'images' and the file's own basename
            # are transformed (reference k_nearest_gaussian_kernel.py:
            # 76-83 scheme).
            img_dir, fname = os.path.split(img_path)
            parent, leaf = os.path.split(img_dir)
            gt_dir = (os.path.join(parent, "ground_truth")
                      if leaf == "images" else img_dir)
            stem = os.path.splitext(fname)[0]
            mat_path = os.path.join(
                gt_dir, ("GT_" + stem if stem.startswith("IMG_") else stem)
                + ".mat")
            with Image.open(img_path) as im:
                w, h = im.size
            points = _load_mat_points(mat_path)
            dmap = gaussian_density_map(points, (h, w), k=k,
                                        sigma_scale=sigma_scale)
            out = os.path.join(gt_dir, stem + ".npy")
            np.save(out, dmap)
            written += 1
            if verbose:
                print(f"{img_path}: {len(points)} heads -> {out} "
                      f"(sum={dmap.sum():.2f})")
    return written
