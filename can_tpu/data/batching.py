"""Static-shape batching for variable-resolution images + host sharding.

The reference handles variable resolution with batch_size=1 and fully dynamic
shapes (reference: train.py:84-91,177) — a non-starter under XLA, where every
distinct shape is a recompile.  TPU-first design:

* **Shape bucketing.** Items are grouped by their post-snap (H, W) — either
  exactly (``pad_multiple=None``: zero padding, bit-exact reference math) or
  rounded up to a multiple (bounded compile count for wild datasets).  Each
  bucket shape compiles once; afterwards every batch of that shape reuses the
  executable.
* **Masking.** A per-image validity flag plus a per-cell mask over the 1/8
  density grid make padded pixels and fill items contribute exactly zero to
  loss/metrics, so MSE-sum and MAE match the reference's per-image math.
* **Lockstep host sharding.** Every process computes the SAME global batch
  schedule from the same seed (the dataset listing is sorted, the shuffle is
  keyed on (seed, epoch)), then materialises only its own slice of each
  global batch.  All hosts therefore step through identical batch counts and
  shapes — the invariant ``jax.make_array_from_process_local_data`` needs —
  which is the role ``DistributedSampler`` plays in the reference
  (train.py:79-88).  Short batches are filled with ``sample_mask=0`` slots
  instead of the reference's wrap-around duplicates, fixing its biased eval
  denominator (train.py:157 divides by ``total_size`` incl. duplicates).
* **Determinism.** The flip RNG is keyed on (seed, epoch, item index), so any
  host resuming at any point reproduces the same stream.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class Batch:
    """One static-shape (per-host slice of a) batch.

    image: (B, H, W, 3) float32, normalised; zero-padded outside each item.
    dmap: (B, H/ds, W/ds, 1) float32 target density.
    pixel_mask: (B, H/ds, W/ds, 1) float32 — 1 on valid density cells.
    sample_mask: (B,) float32 — 1 for real items, 0 for fill slots.
    """

    image: np.ndarray
    dmap: np.ndarray
    pixel_mask: np.ndarray
    sample_mask: np.ndarray

    @property
    def num_valid(self) -> int:
        return int(self.sample_mask.sum())


def pad_batch(items, bucket_hw: Tuple[int, int], batch_size: int,
              valid_flags, ds: int) -> Batch:
    """Assemble variable-size (img, dmap) numpy pairs into one padded Batch."""
    bh, bw = bucket_hw
    gh, gw = bh // ds, bw // ds
    image = np.zeros((batch_size, bh, bw, 3), np.float32)
    dmap = np.zeros((batch_size, gh, gw, 1), np.float32)
    pixel_mask = np.zeros((batch_size, gh, gw, 1), np.float32)
    sample_mask = np.zeros((batch_size,), np.float32)
    for slot, ((img, dm), valid) in enumerate(zip(items, valid_flags)):
        h, w = img.shape[:2]
        image[slot, :h, :w] = img
        dmap[slot, : h // ds, : w // ds] = dm
        pixel_mask[slot, : h // ds, : w // ds] = 1.0
        sample_mask[slot] = float(valid)
    return Batch(image, dmap, pixel_mask, sample_mask)


class ShardedBatcher:
    """Shuffled, shape-bucketed, lockstep-sharded batch iterator.

    dataset: needs ``__len__``, ``snapped_shape(i) -> (H, W)`` and
      ``__getitem__(i, rng) -> (img HWC, dmap hw1)``.
    batch_size: items **per host** per emitted batch; the global batch is
      ``batch_size * process_count``.
    pad_multiple: None → bucket by exact snapped shape (reference-exact
      math); int (multiple of ``ds``) → round H, W up to it (fewer compiles).
    """

    def __init__(self, dataset, batch_size: int, *, shuffle: bool = True,
                 seed: int = 0, process_index: int = 0, process_count: int = 1,
                 pad_multiple: Optional[int] = None, ds: int = 8):
        if pad_multiple is not None and pad_multiple % ds != 0:
            raise ValueError(
                f"pad_multiple ({pad_multiple}) must be a multiple of the "
                f"density downsample factor ({ds})")
        self.dataset = dataset
        self.batch_size = int(batch_size)
        self.shuffle = shuffle
        self.seed = int(seed)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.pad_multiple = pad_multiple
        self.ds = int(ds)
        # snapped shapes are immutable per item: cache them so repeated
        # schedule builds (batches_per_epoch + every epoch) don't re-open
        # every image header
        self._shape_cache: Dict[int, Tuple[int, int]] = {}

    @property
    def dataset_size(self) -> int:
        """True dataset length — the unbiased eval denominator."""
        return len(self.dataset)

    def _bucket_key(self, hw: Tuple[int, int]) -> Tuple[int, int]:
        if self.pad_multiple is None:
            return hw
        m = self.pad_multiple
        return (math.ceil(hw[0] / m) * m, math.ceil(hw[1] / m) * m)

    def global_schedule(self, epoch: int) -> List[Tuple[Tuple[int, int], List[Tuple[int, bool]]]]:
        """Deterministic global batch plan: [(bucket_hw, [(idx, valid)] of
        length global_batch)] — identical on every host for a given
        (seed, epoch)."""
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.default_rng((self.seed, epoch)).permutation(n)
        else:
            order = np.arange(n)
        gbs = self.batch_size * self.process_count
        pending: Dict[Tuple[int, int], List[Tuple[int, bool]]] = {}
        schedule = []
        for idx in order.tolist():
            hw = self._shape_cache.get(idx)
            if hw is None:
                hw = self._shape_cache[idx] = self.dataset.snapped_shape(idx)
            key = self._bucket_key(hw)
            group = pending.setdefault(key, [])
            group.append((idx, True))
            if len(group) == gbs:
                schedule.append((key, group))
                pending[key] = []
        for key, group in pending.items():
            if group:
                # fill dead slots (static shape, zero weight) instead of the
                # reference's wrap-around duplicates.
                group = group + [(group[0][0], False)] * (gbs - len(group))
                schedule.append((key, group))
        return schedule

    def batches_per_epoch(self, epoch: int = 0) -> int:
        return len(self.global_schedule(epoch))

    def epoch(self, epoch: int) -> Iterator[Batch]:
        """Yield this host's slice of each global batch, in schedule order."""
        lo = self.process_index * self.batch_size
        hi = lo + self.batch_size
        for key, group in self.global_schedule(epoch):
            yield self._materialise(key, group[lo:hi], epoch)

    def _materialise(self, key, group, epoch: int) -> Batch:
        items = []
        for idx, _ in group:
            rng = np.random.default_rng((self.seed, epoch, int(idx)))
            items.append(self.dataset.__getitem__(int(idx), rng=rng))
        return pad_batch(items, key, len(group), [v for _, v in group], self.ds)
