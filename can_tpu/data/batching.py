"""Static-shape batching for variable-resolution images + host sharding.

The reference handles variable resolution with batch_size=1 and fully dynamic
shapes (reference: train.py:84-91,177) — a non-starter under XLA, where every
distinct shape is a recompile.  TPU-first design:

* **Shape bucketing.** Items are grouped by their post-snap (H, W) — either
  exactly (``pad_multiple=None``: zero padding, bit-exact reference math),
  rounded up to a multiple (bounded compile count for wild datasets), or
  ``pad_multiple="auto"``: the batcher reads the dataset's shape histogram
  (header-only) and picks the smallest multiple that keeps the number of
  distinct bucket shapes — i.e. XLA compilations — at or under
  ``max_buckets``.  Each bucket shape compiles once; afterwards every batch
  of that shape reuses the executable.  (The reference recompiles nothing
  because torch is eager — but it also gets none of XLA's fusion; bounded
  bucketing is the TPU-native trade.)
* **Masking.** A per-image validity flag plus a per-cell mask over the 1/8
  density grid make padded pixels and fill items contribute exactly zero to
  loss/metrics, so MSE-sum and MAE match the reference's per-image math.
* **Cost-model batch planning.** In ladder+remnant mode the epoch's
  launch plan — per-cell full-batch sizes (lowered under the HBM cap),
  straggler covers at exact quantum-multiple sizes, group merges, and the
  bucket boundaries themselves — is searched by one explicit objective,
  ``area * padded_slots + launch_cost_px * n_launches``, in
  ``data/planner.py`` (r8; ``plan_mode="legacy"`` keeps the pre-r8
  heuristics for A/B).
* **Lockstep host sharding.** Every process computes the SAME global batch
  schedule from the same seed (the dataset listing is sorted, the shuffle is
  keyed on (seed, epoch)), then materialises only its own slice of each
  global batch.  All hosts therefore step through identical batch counts and
  shapes — the invariant ``jax.make_array_from_process_local_data`` needs —
  which is the role ``DistributedSampler`` plays in the reference
  (train.py:79-88).  Short batches are filled with ``sample_mask=0`` slots
  instead of the reference's wrap-around duplicates, fixing its biased eval
  denominator (train.py:157 divides by ``total_size`` incl. duplicates).
* **Determinism.** The flip RNG is keyed on (seed, epoch, item index), so any
  host resuming at any point reproduces the same stream.
"""

from __future__ import annotations

import collections
import dataclasses
import math
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np


@dataclasses.dataclass
class Batch:
    """One static-shape (per-host slice of a) batch.

    image: (B, H, W, 3) float32, normalised; zero-padded outside each item.
    dmap: (B, H/ds, W/ds, 1) float32 target density.
    pixel_mask: (B, H/ds, W/ds, 1) float32 — 1 on valid density cells.
    sample_mask: (B,) float32 — 1 for real items, 0 for fill slots.
    """

    image: np.ndarray
    dmap: np.ndarray
    pixel_mask: np.ndarray
    sample_mask: np.ndarray

    @property
    def num_valid(self) -> int:
        return int(self.sample_mask.sum())


def _ceil_bound(v: int, bounds: Tuple[int, ...]) -> int:
    """Smallest ladder bound >= v (bounds sorted ascending; last covers max)."""
    for b in bounds:
        if b >= v:
            return b
    return bounds[-1]


def snap_to_bucket(hw: Tuple[int, int], *,
                   ladder: Optional[Tuple[Tuple[int, ...], Tuple[int, ...]]] = None,
                   pad_multiple: Optional[Tuple[int, int]] = None,
                   min_bucket_h: Optional[int] = None) -> Tuple[int, int]:
    """Bucket (H, W) for one snapped item shape — the single source of the
    shape→bucket mapping, shared by the offline ``ShardedBatcher`` and the
    online ``serve`` micro-batcher so both paths pad identically.

    ladder: per-axis upper bounds ((H bounds), (W bounds)) — each axis snaps
    up to its smallest covering bound (items above the top bound get the top
    bound; callers size the ladder from their shape distribution).
    pad_multiple: (mh, mw) round-up multiples, used when no ladder is given.
    Neither -> exact shape (zero padding).
    """
    if ladder is not None:
        hb, wb = ladder
        key = (_ceil_bound(hw[0], hb), _ceil_bound(hw[1], wb))
    elif pad_multiple is not None:
        mh, mw = pad_multiple
        key = (math.ceil(hw[0] / mh) * mh, math.ceil(hw[1] / mw) * mw)
    else:
        key = hw
    if min_bucket_h is not None and key[0] < min_bucket_h:
        key = (min_bucket_h, key[1])
    return key


def _merge_partial_groups(partials, gbs: int):
    """Improvement-only pairwise merging of partial batch groups.

    Every partial group pays for ``gbs`` slots at its bucket shape whatever
    its fill; on wild datasets with many buckets the dead slots can cost
    more compute than the padding itself (measured: the bench distribution
    wastes 2x more pixels in dead slots than in padding at 16 buckets).
    Repeatedly merge the pair of groups whose union — at the JOIN bucket
    (elementwise max, so still a ladder grid cell: no new compiles) — costs
    fewer padded pixels than the two groups separately; stop when no merge
    improves.  Deterministic: inputs arrive key-sorted and ties pick the
    lexicographically first pair, so every host computes the same schedule.
    """

    def cost(key, n_items):
        return key[0] * key[1] * gbs * (-(-n_items // gbs))

    partials = [(k, list(g)) for k, g in partials]
    full = []
    while len(partials) > 1:
        best = None
        for i in range(len(partials)):
            ki, gi = partials[i]
            for j in range(i + 1, len(partials)):
                kj, gj = partials[j]
                join = (max(ki[0], kj[0]), max(ki[1], kj[1]))
                gain = (cost(ki, len(gi)) + cost(kj, len(gj))
                        - cost(join, len(gi) + len(gj)))
                if gain > 0 and (best is None or gain > best[0]):
                    best = (gain, i, j, join)
        if best is None:
            break
        _, i, j, join = best
        merged = partials[i][1] + partials[j][1]
        partials = [p for t, p in enumerate(partials) if t not in (i, j)]
        # a strictly-improving merge never overflows gbs: for a+b > gbs the
        # join would cost two batches at >= the average of the two shapes.
        # Guard the invariant anyway (full batches peel off) so a future
        # cost-function tweak can't silently emit oversized groups.
        while len(merged) > gbs:
            full.append((join, merged[:gbs]))
            merged = merged[gbs:]
        if merged:
            partials.append((join, merged))
    return full + partials


def pad_batch(items, bucket_hw: Tuple[int, int], batch_size: int,
              valid_flags, ds: int) -> Batch:
    """Assemble variable-size (img, dmap) numpy pairs into one padded Batch.

    The image buffer keeps the items' dtype: float32 for the normalised
    host path, uint8 for the device-normalised transfer path (where the
    step zeroes padded pixels in normalised space via the upsampled
    pixel_mask, so both paths see identical zero padding)."""
    bh, bw = bucket_hw
    gh, gw = bh // ds, bw // ds
    img_dtype = items[0][0].dtype if items else np.float32
    image = np.zeros((batch_size, bh, bw, 3), img_dtype)
    dmap = np.zeros((batch_size, gh, gw, 1), np.float32)
    pixel_mask = np.zeros((batch_size, gh, gw, 1), np.float32)
    sample_mask = np.zeros((batch_size,), np.float32)
    for slot, ((img, dm), valid) in enumerate(zip(items, valid_flags)):
        h, w = img.shape[:2]
        image[slot, :h, :w] = img
        dmap[slot, : h // ds, : w // ds] = dm
        pixel_mask[slot, : h // ds, : w // ds] = 1.0
        sample_mask[slot] = float(valid)
    return Batch(image, dmap, pixel_mask, sample_mask)


class ShardedBatcher:
    """Shuffled, shape-bucketed, lockstep-sharded batch iterator.

    dataset: needs ``__len__``, ``snapped_shape(i) -> (H, W)`` and
      ``__getitem__(i, rng) -> (img HWC, dmap hw1)``.
    batch_size: items **per host** per emitted batch; the global batch is
      ``batch_size * process_count``.
    pad_multiple: None → bucket by exact snapped shape (reference-exact
      math); int (multiple of ``ds``) → round H, W up to it (fewer compiles).
    """

    def __init__(self, dataset, batch_size: int, *, shuffle: bool = True,
                 seed: int = 0, process_index: int = 0, process_count: int = 1,
                 pad_multiple=None, ds: int = 8, max_buckets: int = 8,
                 min_pad_multiple: Optional[int] = None,
                 min_bucket_h: Optional[int] = None,
                 num_workers: int = 0,
                 remnant_sizes: bool = False,
                 batch_quantum: Optional[int] = None,
                 launch_cost_px: float = 2e6,
                 max_launch_px: Optional[float] = None,
                 plan_mode: str = "cost"):
        if plan_mode not in ("cost", "legacy"):
            raise ValueError(f"unknown plan_mode {plan_mode!r}")
        self.dataset = dataset
        self.batch_size = int(batch_size)
        # "cost": the round-8 cost-model planner (data/planner.py) — exact
        # remnant menus, full-cell batch-size pricing under the HBM cap,
        # merge + local-search packing, and plan-cost-scored ladder grids.
        # "legacy": the pre-r8 heuristics, kept bit-compatible as the
        # ablation baseline (tools/plan_ablation.py) and escape hatch.
        self.plan_mode = plan_mode
        # remnant sub-batches (ladder mode only): emit partial groups at a
        # small menu of sub-batch sizes instead of padding every straggler
        # group to the full global batch — see _partial_plan.  Off by
        # default because legal sub-sizes depend on topology the batcher
        # can't see: every emitted global batch must divide by the mesh's
        # dp axis AND by process_count, which is what ``batch_quantum``
        # (global-batch units; callers pass lcm(dp, process_count))
        # promises.  The CLIs/bench enable it with the right quantum.
        self.remnant_sizes = bool(remnant_sizes)
        self.batch_quantum = int(batch_quantum or process_count or 1)
        # fixed cost of one extra step launch, in pixel-equivalents, for
        # the remnant planner's pixels-vs-launches trade (see _decompose).
        # The default is deliberately conservative (~a 1-2 Mpx image's
        # compute): hosts with sub-ms dispatch can pass ~5e4 to unlock
        # exact splits; the dev tunnel measured ~50 ms/launch (~2 Mpx at
        # the chip's ~42 Mpx/s), where splitting is a net loss
        self.launch_cost_px = float(launch_cost_px)
        # HBM ceiling per launch, in pixels (batch * H * W): bucket cells
        # whose full-batch launch would overflow device memory run at the
        # largest menu size that fits instead (the train step's activation
        # footprint is linear in pixels — cli/common.py max_launch_pixels
        # derives the value from HBM).  Ladder+remnant mode only; None =
        # uncapped.  This is what makes big-batch training runnable on
        # wild datasets whose largest shapes don't fit at the global batch
        # (the reference's only fits-anything answer was batch-1,
        # reference train.py:177).
        self.max_launch_px = (None if max_launch_px is None
                              else float(max_launch_px))
        self._cap_warned: set = set()
        self._plan_cache = None
        # last subset schedule, keyed (epoch, frozenset(include)): the
        # elastic resume asks for the identical subset schedule 2-3
        # times (progress total, epoch(), a possible second shrink) and
        # each build pays an uncached planner run over the subset
        self._subset_cache: Optional[Tuple[Tuple[int, frozenset], list]] = None
        # last FULL epoch schedule, keyed by epoch: batches_per_epoch,
        # the epoch iterator, planner_stats, and the r14 prefetch
        # pricing all ask for the same epoch's schedule — each rebuild
        # is an O(dataset) sort+group, and the schedule is a pure
        # function of (seed, epoch, histogram)
        self._epoch_cache: Optional[Tuple[int, list]] = None
        # host loader threads (the reference's DataLoader num_workers,
        # train.py:90, done with threads: PIL decode / cv2 resize release
        # the GIL, and threads share the process — no pickling, no fork
        # hazards next to a live JAX runtime).  0 = main-thread loading.
        self.num_workers = int(num_workers)
        self._pool: Optional[ThreadPoolExecutor] = None
        self.shuffle = shuffle
        self.seed = int(seed)
        self.process_index = int(process_index)
        self.process_count = int(process_count)
        self.ds = int(ds)
        self.max_buckets = int(max_buckets)
        # snapped shapes are immutable per item: cache them so repeated
        # schedule builds (batches_per_epoch + every epoch) don't re-open
        # every image header
        self._shape_cache: Dict[int, Tuple[int, int]] = {}
        # floor on bucket height (spatial parallelism: each H-shard must own
        # >= 2 feature rows, cli/common.py resolve_sp_padding) — callers
        # pass a value compatible with their pad multiple
        self.min_bucket_h = min_bucket_h
        self.bucket_ladder: Optional[Tuple[Tuple[int, ...], Tuple[int, ...]]] = None
        if self.remnant_sizes:
            gbs = self.batch_size * self.process_count
            if self.batch_quantum % self.process_count:
                raise ValueError(
                    f"batch_quantum ({self.batch_quantum}) must be a multiple "
                    f"of process_count ({self.process_count}) so every host "
                    f"slices an equal share of each sub-batch")
            if gbs % self.batch_quantum:
                raise ValueError(
                    f"global batch ({gbs}) must be a multiple of "
                    f"batch_quantum ({self.batch_quantum})")
        if pad_multiple == "auto":
            pad_multiple = self._resolve_auto_buckets(min_pad_multiple)
        # int -> same multiple both axes; (mh, mw) -> per-axis (spatial
        # parallelism constrains only the sharded H axis, so W keeps the
        # cheaper /ds multiple)
        if isinstance(pad_multiple, int):
            pad_multiple = (pad_multiple, pad_multiple)
        if pad_multiple is not None:
            for m in pad_multiple:
                if m % self.ds != 0:
                    raise ValueError(
                        f"pad_multiple ({pad_multiple}) must be multiples of "
                        f"the density downsample factor ({self.ds})")
        self.pad_multiple = pad_multiple

    def _item_shape(self, idx: int) -> Tuple[int, int]:
        hw = self._shape_cache.get(idx)
        if hw is None:
            hw = self._shape_cache[idx] = self.dataset.snapped_shape(idx)
        return hw

    @staticmethod
    def _axis_bounds(values, k: int, floor: int) -> Tuple[int, ...]:
        """k quantile upper bounds for one axis, rounded up to ``floor``
        multiples (so every bucket H works under spatial sharding too) —
        the coordinate-descent seed."""
        vs = sorted(values)
        n = len(vs)
        bounds = set()
        for i in range(1, k + 1):
            v = vs[-(-i * n // k) - 1]  # ceil(i*n/k)-1: i-th quantile's top
            bounds.add(-(-v // floor) * floor)
        return tuple(sorted(bounds))

    @staticmethod
    def _dp_axis_bounds(values, weights, k: int, floor: int) -> Tuple[int, ...]:
        """EXACT optimal <=k upper bounds for one axis minimising
        ``sum_i weights[i] * bound(values[i])`` (bounds restricted to
        ``floor`` multiples of observed values).  O(k n^2) DP over the n
        distinct candidates, vectorised; n is small (distinct snapped
        extents)."""
        cands = sorted({-(-v // floor) * floor for v in values})
        n = len(cands)
        if n <= k:
            return tuple(cands)
        wsum = {c: 0.0 for c in cands}
        for v, wt in zip(values, weights):
            wsum[-(-v // floor) * floor] += float(wt)
        pre = np.concatenate([[0.0], np.cumsum([wsum[c] for c in cands])])
        c_arr = np.asarray(cands, dtype=np.float64)
        inf = np.inf
        # f[m, j]: min cost covering candidates[0..j] with m bounds, bound at j
        f = np.full((k + 1, n), inf)
        f[1] = c_arr * pre[1:]
        choice = np.zeros((k + 1, n), dtype=np.int64)
        for m in range(2, k + 1):
            # cost(i -> j) = f[m-1, i] + c_j * (pre[j+1] - pre[i+1]), i < j
            prev = f[m - 1][:, None]  # (n, 1) over i
            trans = prev + c_arr[None, :] * (pre[1:][None, :] - pre[1:][:, None])
            trans = np.where(np.tri(n, n, -1, dtype=bool).T, trans, inf)
            choice[m] = np.argmin(trans, axis=0)
            f[m] = trans[choice[m], np.arange(n)]
        m_best = int(np.argmin(f[1:, n - 1])) + 1
        bounds, j, m = [], n - 1, m_best
        while m >= 1:
            bounds.append(cands[j])
            j, m = int(choice[m][j]), m - 1
        return tuple(sorted(bounds))

    def _resolve_auto_buckets(self, min_pad_multiple: Optional[int]) -> Optional[int]:
        """Choose static bucket shapes so each train/eval step compiles at
        most ``max_buckets`` programs.

        Snapped shapes are already multiples of ``ds``, so when the exact
        shape set is small enough, exact bucketing (None) wins: zero
        padding, bit-exact reference loss math.  Otherwise build a
        per-axis quantile ladder: split the H and W histograms into
        kH x kW quantile cells (every (kH, kW) split of the budget is
        scored by its padded-area overhead and the cheapest wins), and pad
        each image up to its cell's (H, W) upper bounds.  This beats any
        single global multiple on wild datasets — buckets concentrate
        where the shapes actually are.
        """
        shapes = [self._item_shape(i) for i in range(len(self.dataset))]
        if not shapes:
            return None
        if min_pad_multiple is None or isinstance(min_pad_multiple, int):
            min_pad_multiple = (min_pad_multiple, min_pad_multiple)
        floors = []
        for m in min_pad_multiple:
            f = max(self.ds, int(m or 0))
            if f % self.ds:
                f = -(-f // self.ds) * self.ds
            floors.append(f)
        floor_h, floor_w = floors
        if (floor_h == floor_w == self.ds
                and len(set(shapes)) <= self.max_buckets):
            return None
        hs = [h for h, _ in shapes]
        ws = [w for _, w in shapes]
        # cost mode + remnant sizes: boundary placement joins the plan
        # search — every (kh, kw) grid with kh*kw <= max_buckets is
        # descended and scored by the FULL plan cost of the schedule it
        # induces (padding AND dead slots AND launches, under the HBM
        # cap), because the padded-area score is blind to how counts
        # split across cells: at b16 a padding-optimal 24-cell ladder
        # leaves ~2.7 items per cell and the remnant covers/merges then
        # cost 3x the padding they saved (BENCH_SUITE_r05, 30.7%
        # schedule overhead).  Other modes keep the padded-area score
        # over budget-saturating grids (pre-r8 behaviour).
        cost_scored = self.plan_mode == "cost" and self.remnant_sizes
        candidates = ((kh, kw)
                      for kh in range(1, self.max_buckets + 1)
                      for kw in ((range(1, self.max_buckets // kh + 1))
                                 if cost_scored
                                 else (self.max_buckets // kh,))
                      if kw >= 1)
        best = None
        seen = set()
        for kh, kw in candidates:
            # seed with quantiles, then coordinate-descend: each axis's
            # bounds are re-solved EXACTLY (weighted 1-D DP) holding the
            # other axis fixed — the weight of an item along H is its
            # current padded W and vice versa, so each pass minimises the
            # true padded area.  Converges in 2-3 passes.
            hb = self._axis_bounds(hs, kh, floor_h)
            wb = self._axis_bounds(ws, kw, floor_w)
            for _ in range(3):
                hb2 = self._dp_axis_bounds(
                    hs, [_ceil_bound(w, wb) for w in ws], kh, floor_h)
                wb2 = self._dp_axis_bounds(
                    ws, [_ceil_bound(h, hb2) for h in hs], kw, floor_w)
                if (hb2, wb2) == (hb, wb):
                    break
                hb, wb = hb2, wb2
            if len(hb) * len(wb) > self.max_buckets or (hb, wb) in seen:
                continue
            seen.add((hb, wb))
            if cost_scored:
                score = self._ladder_plan_cost((hb, wb), shapes)
            else:
                score = sum(_ceil_bound(h, hb) * _ceil_bound(w, wb)
                            for h, w in shapes)
            if best is None or score < best[0]:
                best = (score, hb, wb)
        if best is None:  # budget < any grid: one bucket covering the max
            hb = (-(-max(hs) // floor_h) * floor_h,)
            wb = (-(-max(ws) // floor_w) * floor_w,)
            best = (0, hb, wb)
        _, hb, wb = best
        self.bucket_ladder = (hb, wb)
        return None

    def _ladder_plan_cost(self, ladder, shapes) -> float:
        """Plan cost of the full epoch schedule a candidate ladder would
        induce — the cost-mode score for ``_resolve_auto_buckets``.
        Cell counts are vectorised (the sweep visits ~max_buckets*H(max_
        buckets) candidate grids and may not cost O(n_items) Python per
        grid on large datasets).  Warnings stay silent here (only the
        CHOSEN ladder's plan warns, via _partial_plan)."""
        from can_tpu.sched import offline_planner

        hb, wb = ladder
        hs = np.asarray([h for h, _ in shapes])
        ws = np.asarray([w for _, w in shapes])
        hb_arr = np.asarray(hb)
        wb_arr = np.asarray(wb)
        hi = np.minimum(np.searchsorted(hb_arr, hs), len(hb) - 1)
        wi = np.minimum(np.searchsorted(wb_arr, ws), len(wb) - 1)
        snapped_h = hb_arr[hi]
        if self.min_bucket_h is not None:
            snapped_h = np.maximum(snapped_h, self.min_bucket_h)
        cells, ncell = np.unique(
            np.stack([snapped_h, wb_arr[wi]], axis=1),
            axis=0, return_counts=True)
        counts = {(int(h), int(w)): int(c)
                  for (h, w), c in zip(cells, ncell)}
        planner = offline_planner(self._cost_model(),
                                  max_buckets=self.max_buckets,
                                  mode=self.plan_mode)
        return planner.plan_with_fallback(counts).cost

    def padding_overhead(self) -> float:
        """Fraction of padded-batch pixels that are fill (0 = exact shapes).
        Uses the full dataset histogram, weighting each item by its bucket."""
        shapes = [self._item_shape(i) for i in range(len(self.dataset))]
        if not shapes:
            return 0.0
        item_area = sum(h * w for h, w in shapes)
        bucket_area = sum(bh * bw for bh, bw in map(self._bucket_key, shapes))
        return bucket_area / max(item_area, 1) - 1.0

    def schedule_overhead(self, epoch: int = 0) -> float:
        """TRUE fraction of step compute wasted in this epoch's schedule:
        padded pixels AND dead fill slots, over valid item pixels.  (
        ``padding_overhead`` counts only the per-item padding; on small or
        wildly-shaped datasets the dead slots of partial batches dominate.)
        """
        valid_px = 0
        used_px = 0
        for key, group in self.global_schedule(epoch):
            used_px += key[0] * key[1] * len(group)
            for idx, valid in group:
                if valid:
                    h, w = self._item_shape(idx)
                    valid_px += h * w
        return used_px / max(valid_px, 1) - 1.0

    def describe_buckets(self) -> str:
        """One-line bucket-policy summary for startup telemetry."""
        if self.bucket_ladder is not None:
            hb, wb = self.bucket_ladder
            return f"auto ladder H{list(hb)} x W{list(wb)}"
        if self.pad_multiple is None:
            return "exact shapes"
        mh, mw = self.pad_multiple
        if mh == mw:
            return f"multiple of {mh}"
        return f"H multiple of {mh}, W multiple of {mw}"

    def distinct_shapes(self, epoch: int = 0) -> int:
        """Number of distinct bucket shapes in this epoch's schedule — a
        lower bound on XLA compile count for the train step."""
        return len({key for key, _ in self.global_schedule(epoch)})

    @property
    def dataset_size(self) -> int:
        """True dataset length — the unbiased eval denominator."""
        return len(self.dataset)

    def _bucket_key(self, hw: Tuple[int, int]) -> Tuple[int, int]:
        return snap_to_bucket(hw, ladder=self.bucket_ladder,
                              pad_multiple=self.pad_multiple,
                              min_bucket_h=self.min_bucket_h)

    def _remnant_menu(self) -> Tuple[int, ...]:
        """Legal sub-batch sizes (global units), descending — every size a
        quantum multiple, so it divides cleanly into per-host slices and
        dp shards (batch_quantum contract).  Cost mode: every quantum
        multiple up to the global batch (exact-size remnant launches;
        the program budget prunes).  Legacy: gbs + quantum * 2^j."""
        from can_tpu.data.planner import remnant_menu

        return remnant_menu(self.batch_size * self.process_count,
                            self.batch_quantum, mode=self.plan_mode)

    def _cost_model(self, menu: Optional[Tuple[int, ...]] = None):
        from can_tpu.data.planner import PlanCostModel

        return PlanCostModel(menu=menu or self._remnant_menu(),
                             launch_cost_px=self.launch_cost_px,
                             max_launch_px=self.max_launch_px)

    def _menu_for(self, key: Tuple[int, int],
                  menu: Tuple[int, ...]) -> Tuple[int, ...]:
        """Menu filtered by the per-launch pixel cap for this cell; the
        smallest size always survives (the floor below which the batcher
        cannot subdivide — the quantum).  When even the quantum exceeds
        the cap, the cell launches anyway at the floor size — warned
        loudly ONCE, because the cap's no-OOM promise no longer holds for
        that cell (the alternative, refusing the item, would silently
        drop data)."""
        model = self._cost_model(menu)
        kept = model.fitting(key)
        if self.max_launch_px is not None and not model.fits(key, min(menu)):
            if key not in self._cap_warned:
                self._cap_warned.add(key)
                print(f"[batching] WARNING: bucket {key[0]}x{key[1]} exceeds "
                      f"the per-launch pixel cap even at the minimum batch "
                      f"{min(menu)} ({min(menu) * key[0] * key[1] / 1e6:.1f} "
                      f"Mpx > {self.max_launch_px / 1e6:.1f} Mpx) — "
                      f"launching anyway; expect HBM pressure (shrink "
                      f"batch_quantum or image sizes)")
        return kept

    @staticmethod
    def _decompose(n: int, menu: Tuple[int, ...], area: float = 1.0,
                   launch_cost: float = 0.0) -> Tuple[int, ...]:
        """Exact launch-size cover DP — see ``planner.decompose`` (moved
        there in r8 so the cost model, the ablation tool, and the batcher
        share one implementation; this alias keeps the planner's unit
        surface stable)."""
        from can_tpu.data.planner import decompose

        return decompose(n, menu, area, launch_cost)

    def _cell_counts(self) -> Dict[Tuple[int, int], int]:
        counts = getattr(self, "_cell_counts_cache", None)
        if counts is None:
            counts = self._cell_counts_cache = dict(collections.Counter(
                self._bucket_key(self._item_shape(i))
                for i in range(len(self.dataset))))
        return counts

    def _plan_for_counts(self, counts: Dict[Tuple[int, int], int]):
        """One ``planner.Plan`` for an arbitrary cell-count histogram —
        the full epoch's (cached by ``_partial_plan``) or an elastic
        REMAINDER's (the uncovered items of an interrupted epoch,
        replanned at the new world's quantum; ``global_schedule``'s
        ``include`` path).  A pure function of (counts, cost model,
        budget), so every host derives the identical plan.  Construction
        routes through the scheduling core (``sched.offline_planner`` —
        the r14 one-core refactor); plans are bit-identical to the
        pre-r14 direct ``GlobalPlanner`` (pinned by the legacy
        comparator in tests/test_sched.py)."""
        from can_tpu.sched import offline_planner

        def warn(msg):
            tag = msg[:40]
            if tag not in self._cap_warned:
                self._cap_warned.add(tag)
                print(f"[batching] WARNING: {msg}")

        planner = offline_planner(self._cost_model(),
                                  max_buckets=self.max_buckets,
                                  mode=self.plan_mode, warn=warn)
        return planner.plan_with_fallback(counts)

    def _partial_plan(self):
        """Epoch-invariant launch plan for ladder+remnant mode.

        An item's bucket cell is a pure function of its shape, so each
        cell's item count — hence its full/remnant split — is identical
        in every epoch; only WHICH items fill the slots varies with the
        shuffle.  The plan is therefore computed once from the shape
        histogram by ``planner.GlobalPlanner`` (full-cell batch sizing
        under the HBM cap, remnant menu composition, merge + local-search
        packing, program-budget levers) and cached.  Returns a
        ``planner.Plan``; ``legacy_fallback=True`` means the
        pad-every-straggler-to-gbs path proved cheaper and
        ``global_schedule`` falls through to it.
        """
        if self._plan_cache is not None:
            return self._plan_cache
        self._plan_cache = self._plan_for_counts(self._cell_counts())
        return self._plan_cache

    def program_count(self, epoch: int = 0) -> int:
        """Distinct (bucket shape, batch size) pairs in this epoch's
        schedule — the train step's true XLA compile count (with remnant
        sub-batches, shapes alone undercount)."""
        return len({(key, len(group))
                    for key, group in self.global_schedule(epoch)})

    def planner_stats(self, epoch: int = 0) -> Dict[str, object]:
        """One flat dict of planner decisions + realized schedule
        economics for this epoch — the payload of the ``data.planner``
        telemetry event (live gauges on the /metrics exporter) and the
        plan-ablation bench tier.  Predicted numbers come from the cost
        model; realized ones are re-derived from the emitted schedule, so
        a divergence between the two is a planner bug, not noise (pinned
        by test)."""
        sched = self.global_schedule(epoch)
        used_px = sum(k[0] * k[1] * len(g) for k, g in sched)
        valid_px = sum(h * w for h, w in
                       (self._item_shape(i) for i in range(len(self.dataset))))
        stats = {
            "plan_mode": self.plan_mode,
            "padding_overhead": round(self.padding_overhead(), 4),
            "schedule_overhead": round(used_px / max(valid_px, 1) - 1.0, 4),
            "program_count": len({(k, len(g)) for k, g in sched}),
            "batches_per_epoch": len(sched),
            "realized_px": float(used_px),
            "realized_cost_px": float(used_px
                                      + self.launch_cost_px * len(sched)),
            "launch_cost_px": float(self.launch_cost_px),
            "max_launch_px": self.max_launch_px,
            "max_buckets": self.max_buckets,
        }
        if self.bucket_ladder is not None and self.remnant_sizes:
            plan = self._partial_plan()
            stats.update(
                plan_cost_px=float(plan.cost),
                plan_scheduled_px=float(plan.scheduled_px),
                plan_launches=plan.launches,
                plan_programs=len(plan.programs),
                lowered_cells=plan.lowered_cells,
                lowered_launches=plan.lowered_launches,
                legacy_fallback=plan.legacy_fallback,
                menu_sizes=len(plan.menu),
            )
        return stats

    def global_schedule(self, epoch: int, include: Optional[set] = None
                        ) -> List[Tuple[Tuple[int, int], List[Tuple[int, bool]]]]:
        """Deterministic global batch plan: [(bucket_hw, [(idx, valid)] of
        length global_batch)] — identical on every host for a given
        (seed, epoch).

        ``include`` restricts the plan to a subset of item indices — the
        elastic-resume path: the uncovered REMAINDER of an interrupted
        epoch is replanned (fresh ``_plan_for_counts`` over the subset
        histogram, at THIS batcher's quantum — i.e. the shrunk world's)
        while keeping the epoch's shuffle order, so consumed ∪ scheduled
        covers the epoch exactly once across the transition.  Every host
        passes the same set (derived from the shared elastic manifest)
        and computes the identical plan; the last subset schedule is
        memoised (the resume leg asks for it 2-3 times)."""
        if include is None:
            if self._epoch_cache is not None \
                    and self._epoch_cache[0] == epoch:
                return self._epoch_cache[1]
            sched = self._build_schedule(epoch, None)
            self._epoch_cache = (epoch, sched)
            return sched
        key = (epoch, frozenset(int(i) for i in include))
        if self._subset_cache is not None and self._subset_cache[0] == key:
            return self._subset_cache[1]
        sched = self._build_schedule(epoch, set(key[1]))
        self._subset_cache = (key, sched)
        return sched

    def _build_schedule(self, epoch: int, include: Optional[set]):
        n = len(self.dataset)
        if self.shuffle:
            order = np.random.default_rng((self.seed, epoch)).permutation(n)
        else:
            order = np.arange(n)
        if include is not None:
            include = set(int(i) for i in include)
            order = np.asarray([i for i in order.tolist() if i in include],
                               dtype=np.int64)
        gbs = self.batch_size * self.process_count
        remnant_mode = self.remnant_sizes
        menu = self._remnant_menu() if remnant_mode else None

        plan = None
        if self.bucket_ladder is not None and remnant_mode:
            # remnant sub-batches: the epoch-invariant plan (_partial_plan,
            # a pure function of the shape histogram — identical on every
            # host and in every epoch; the shuffle only decides which
            # concrete items fill the slots) fixes each cell's full-launch
            # sizes AND the straggler groups' join cells + part sizes.
            # An ``include`` subset gets its own (uncached) plan over the
            # subset histogram.  legacy_fallback means the planner proved
            # the pad-every-straggler-to-gbs path cheaper — fall through.
            if include is None:
                plan = self._partial_plan()
            else:
                plan = self._plan_for_counts(dict(collections.Counter(
                    self._bucket_key(self._item_shape(int(i)))
                    for i in order.tolist())))
            if plan.legacy_fallback:
                plan = None
        if plan is not None:
            # stream full launches as cells fill: each cell's planned part
            # sizes are descending, so thresholds are hit in order
            next_full = {k: list(parts)
                         for k, parts in plan.full_parts.items()}
            pending = {}
            schedule = []
            for idx in order.tolist():
                key = self._bucket_key(self._item_shape(idx))
                group = pending.setdefault(key, [])
                group.append((idx, True))
                parts = next_full.get(key)
                if parts and len(group) == parts[0]:
                    schedule.append((key, group))
                    pending[key] = []
                    parts.pop(0)
            for pg in plan.groups:
                items = [it for k in pg.sources for it in pending.get(k, [])]
                pos = 0
                for size in pg.parts:
                    take = items[pos:pos + size]
                    pos += size
                    if len(take) < size:
                        take = take + [(take[0][0], False)] * (size - len(take))
                    schedule.append((pg.key, take))
            return schedule

        full_size = {}  # per-cell full-batch size (pixel cap may shrink it)

        def cell_full(key):
            s = full_size.get(key)
            if s is None:
                s = full_size[key] = (max(self._menu_for(key, menu))
                                      if remnant_mode else gbs)
            return s

        pending: Dict[Tuple[int, int], List[Tuple[int, bool]]] = {}
        schedule = []
        for idx in order.tolist():
            key = self._bucket_key(self._item_shape(idx))
            group = pending.setdefault(key, [])
            group.append((idx, True))
            if len(group) == cell_full(key):
                schedule.append((key, group))
                pending[key] = []
        if self.bucket_ladder is None and self.remnant_sizes:
            # exact / fixed-multiple modes: remnant sizes WITHOUT merging,
            # COVER-ONLY (a single part per straggler group: the smallest
            # menu size that fits it).  Shape joins would break these
            # modes' padding promises, and a multi-part split would mint
            # extra (shape, size) programs — cover-only keeps both
            # invariants: exactly legacy's launch and program counts, with
            # the (shape, cover) program replacing (shape, gbs).  This is
            # what makes small-eval-set batch>1 eval cheap: the reference
            # evaluates at batch 1 with zero waste (test.py:16-35); a
            # 16-image eval split at batch 8 used to be ~70% fill slots
            # here (the round-3 startup hint).
            for key, group in sorted(((k, g) for k, g in pending.items()
                                      if g), key=lambda kg: kg[0]):
                fits = [s for s in self._menu_for(key, menu)
                        if s >= len(group)]
                size = min(fits) if fits else max(self._menu_for(key, menu))
                pos = 0
                while pos < len(group):  # >1 round only under a pixel cap
                    take = group[pos:pos + size]
                    pos += size
                    if len(take) < size:
                        take = take + [(take[0][0], False)] * (size - len(take))
                    schedule.append((key, take))
            return schedule
        partials = sorted(((k, g) for k, g in pending.items() if g),
                          key=lambda kg: kg[0])
        if self.bucket_ladder is not None:
            # ladder mode only: merge straggler groups upward when that
            # costs fewer padded pixels than their dead slots would.  Joins
            # are elementwise maxes of ladder bounds, i.e. grid cells, so
            # the compile bound holds.  Exact mode skips this (a merge
            # would break its zero-padding promise); fixed-multiple mode
            # skips it too — there the join space is the cross product of
            # observed extents and each epoch's shuffle could mint novel
            # shapes, i.e. unbounded mid-run compiles.
            partials = _merge_partial_groups(partials, gbs)
        for key, group in partials:
            if len(group) < gbs:
                # fill dead slots (static shape, zero weight) instead of the
                # reference's wrap-around duplicates.
                group = group + [(group[0][0], False)] * (gbs - len(group))
            schedule.append((key, group))
        return schedule

    def batches_per_epoch(self, epoch: int = 0) -> int:
        return len(self.global_schedule(epoch))

    def epoch(self, epoch: int, include: Optional[set] = None) -> Iterator[Batch]:
        """Yield this host's slice of each global batch, in schedule order.

        With ``num_workers > 0``, item loads (decode + resize + flip) run on
        a thread pool across a sliding window of upcoming batches — both
        intra-batch (wide batches) and inter-batch (batch_size=1, the
        reference's default) parallelism.  Output order and content are
        identical to the serial path: each item's RNG is keyed on
        (seed, epoch, idx), so determinism is independent of thread timing.

        ``include`` yields only the subset schedule (see
        ``global_schedule``) — the elastic remainder of an interrupted
        epoch.  Item RNG keys are unchanged, so a subset item's
        flip/augmentation is bit-identical to the one the uninterrupted
        epoch would have applied.
        """
        def host_slice(group):
            # groups are gbs long, except remnant sub-batches (menu sizes,
            # always a multiple of process_count by the quantum contract)
            sub = len(group) // self.process_count
            lo = self.process_index * sub
            return group[lo:lo + sub]

        schedule = self.global_schedule(epoch, include)
        pool = self._ensure_pool()
        if pool is None:
            for key, group in schedule:
                yield self._materialise(key, host_slice(group), epoch)
            return
        # enough batches in flight to keep every worker busy even at
        # batch_size=1, but bounded so at most `window` decoded batches
        # wait in host RAM
        window = max(2, -(-self.num_workers // max(self.batch_size, 1)) + 1)
        inflight = collections.deque()

        def submit(key, group):
            futs = [pool.submit(self._load_item, int(idx), epoch)
                    for idx, _ in group]
            return key, group, futs

        i = 0
        try:
            while i < len(schedule) or inflight:
                while i < len(schedule) and len(inflight) < window:
                    key, group = schedule[i]
                    inflight.append(submit(key, host_slice(group)))
                    i += 1
                key, group, futs = inflight.popleft()
                items = [f.result() for f in futs]
                yield pad_batch(items, key, len(group),
                                [v for _, v in group], self.ds)
        finally:
            # an abandoned generator (break mid-epoch, error downstream)
            # must not leave up to window*batch_size decode tasks running
            for _, _, futs in inflight:
                for f in futs:
                    f.cancel()

    def close(self) -> None:
        """Shut down the loader thread pool (idempotent).  The batcher
        stays usable — the pool is re-created on the next epoch() — so
        this is a resource release, not a terminal state."""
        pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False, cancel_futures=True)

    def __enter__(self) -> "ShardedBatcher":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        # can-tpu-lint: disable=SWALLOW(interpreter-teardown finalizer; close() is the real, loud API)
        except Exception:
            pass

    def _ensure_pool(self) -> Optional[ThreadPoolExecutor]:
        if self.num_workers > 0 and self._pool is None:
            self._pool = ThreadPoolExecutor(
                max_workers=self.num_workers,
                thread_name_prefix="can_tpu_loader")
        return self._pool

    def _load_item(self, idx: int, epoch: int):
        rng = np.random.default_rng((self.seed, epoch, idx))
        return self.dataset.__getitem__(idx, rng=rng)

    def _materialise(self, key, group, epoch: int) -> Batch:
        items = [self._load_item(int(idx), epoch) for idx, _ in group]
        return pad_batch(items, key, len(group), [v for _, v in group], self.ds)
