from .density import gaussian_density_map, generate_density_maps
from .dataset import CrowdDataset, IMAGENET_MEAN, IMAGENET_STD, normalize_host
from .batching import ShardedBatcher, Batch, pad_batch, snap_to_bucket
from .synthetic import make_synthetic_dataset
from .prefetch import PrefetchPutError, prefetch_to_device
from .prepared import ItemCache, PreparedStore, StaleStoreError, write_store

__all__ = [
    "gaussian_density_map",
    "generate_density_maps",
    "CrowdDataset",
    "IMAGENET_MEAN",
    "IMAGENET_STD",
    "normalize_host",
    "ShardedBatcher",
    "Batch",
    "pad_batch",
    "snap_to_bucket",
    "make_synthetic_dataset",
    "prefetch_to_device",
    "PrefetchPutError",
    "ItemCache",
    "PreparedStore",
    "StaleStoreError",
    "write_store",
]
