"""Shared CLI plumbing: dataset roots, mesh/batch arithmetic, step caches."""

from __future__ import annotations

import os
from typing import Dict, Optional, Tuple

import jax

from can_tpu.parallel import make_mesh


def parse_pad_multiple(value):
    """CLI --pad-multiple value -> ShardedBatcher pad_multiple.

    "auto" (the default): pick from the dataset's shape histogram so the
    step compiles at most ``max_buckets`` programs; "exact"/"none"/"0":
    exact snapped shapes (zero padding, bit-exact reference loss math, but
    one compile per distinct resolution); otherwise an integer multiple.
    """
    if value is None:
        return None
    s = str(value).strip().lower()
    if s == "auto":
        return "auto"
    if s in ("exact", "none", "0"):
        return None
    return int(s)


def resolve_sp_padding(pad_multiple, sp: int):
    """Bucket constraints under spatial parallelism, shared by both CLIs.

    Returns (pad_multiple, min_pad_multiple, min_bucket_h).  Only the
    sharded H axis carries sp constraints (spatial.py shards P(data,
    spatial, None, None)); W keeps the cheaper /8 snap:
    * bucket H must be a multiple of 8*sp so max-pool windows never
      straddle shard boundaries (spatial.py _check_spatial_shapes);
    * bucket H must be >= 16*sp so each shard owns >= 2 feature rows (the
      dilated-conv halo) — short images are padded up instead of crashing
      the step factory mid-eval.
    """
    if sp <= 1:
        return pad_multiple, None, None
    need = 8 * sp
    if pad_multiple is None:  # exact shapes can't guarantee divisibility
        pad_multiple = (need, 8)
    elif isinstance(pad_multiple, int):
        mh = pad_multiple if pad_multiple % need == 0 else (
            -(-pad_multiple // need) * need)
        pad_multiple = (mh, pad_multiple)
    return pad_multiple, (need, None), 16 * sp


def dataset_roots(data_root: str, split: str) -> Tuple[str, str]:
    """ShanghaiTech-style layout (the reference comments these path pairs,
    train.py:49-52): <root>/<split>_data/images + .../ground_truth."""
    base = os.path.join(data_root, f"{split}_data")
    img, gt = os.path.join(base, "images"), os.path.join(base, "ground_truth")
    for p in (img, gt):
        if not os.path.isdir(p):
            raise FileNotFoundError(
                f"expected dataset directory {p} (ShanghaiTech layout: "
                f"<data_root>/{split}_data/{{images,ground_truth}})")
    return img, gt


def resolve_split_roots(split: str, image_root: str, gt_root: str,
                        data_root: str, *,
                        flag_stem: Optional[str] = None) -> Tuple[str, str]:
    """Explicit per-split roots (VisDrone-style layouts, where images and
    density maps live in unrelated trees — the reference hardcodes such a
    pair, train.py:54-57) win over the ShanghaiTech ``data_root``
    convention.  Either give BOTH roots for the split, or a data_root.

    flag_stem: prefix of the caller's flags ("train-"/"test-" in the train
    CLI, "" in the eval CLI) so error messages name flags that exist.
    Pure argument/isdir checks — call straight after parse_args, before any
    runtime/checkpoint work.
    """
    stem = f"{split}-" if flag_stem is None else flag_stem
    if image_root or gt_root:
        if not (image_root and gt_root):
            raise SystemExit(
                f"give both --{stem}image-root and --{stem}gt-root "
                f"(or neither, with --data_root)")
        for p in (image_root, gt_root):
            if not os.path.isdir(p):
                raise SystemExit(f"no such dataset directory: {p}")
        return image_root, gt_root
    if not data_root:
        raise SystemExit(
            f"need --data_root or --{stem}image-root/--{stem}gt-root")
    return dataset_roots(data_root, split)


def build_mesh_and_batch(batch_size: int, sp: int) -> Tuple:
    """Mesh over all devices with ``sp`` spatial shards; returns
    (mesh, per_host_batch, dp).

    ``batch_size`` is PER DATA-PARALLEL REPLICA (the reference's per-GPU
    batch, train.py:177); global batch = batch_size * dp.
    """
    ndev = jax.device_count()
    if ndev % sp:
        raise ValueError(f"--sp {sp} does not divide device count {ndev}")
    dp = ndev // sp
    mesh = make_mesh(dp=dp, sp=sp)
    global_batch = batch_size * dp
    nproc = jax.process_count()
    if global_batch % nproc:
        raise ValueError(
            f"global batch {global_batch} not divisible by process count {nproc}")
    return mesh, global_batch // nproc, dp


def make_inference_forward():
    """Jitted single-image forward that handles both model variants:
    ``fwd(params, image, batch_stats_or_None)`` (shared by the train CLI's
    --show visualization and the test CLI's --show-index)."""
    import jax as _jax

    from can_tpu.models import cannet_apply

    def _fwd(params, x, batch_stats):
        if batch_stats is not None:
            return cannet_apply(params, x, batch_stats=batch_stats,
                                train=False)
        return cannet_apply(params, x)

    return _jax.jit(_fwd)


class SpatialStepCache:
    """Per-image-shape cache of spatial train steps (each H x W bucket shape
    compiles its own shard_map program, mirroring jit's per-shape cache)."""

    def __init__(self, factory):
        self._factory = factory
        self._steps: Dict[Tuple[int, int], object] = {}

    def __call__(self, image_hw: Tuple[int, int]):
        step = self._steps.get(image_hw)
        if step is None:
            step = self._steps[image_hw] = self._factory(image_hw)
        return step


def make_cached_sp_eval_step(mesh, *, compute_dtype=None):
    """Bucket-shape-cached spatial eval step (shared by both CLIs)."""
    from can_tpu.parallel.spatial import make_sp_eval_step

    cache = SpatialStepCache(
        lambda hw: make_sp_eval_step(mesh, hw, compute_dtype=compute_dtype))

    def eval_step(params, batch, batch_stats=None):
        hw = (batch["image"].shape[1], batch["image"].shape[2])
        return cache(hw)(params, batch, batch_stats)

    return eval_step
