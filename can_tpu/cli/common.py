"""Shared CLI plumbing: dataset roots, mesh/batch arithmetic, step caches."""

from __future__ import annotations

import argparse
import dataclasses
import os
from typing import Dict, Optional, Tuple

import jax

from can_tpu.parallel import make_mesh


def parse_pad_multiple(value):
    """CLI --pad-multiple value -> ShardedBatcher pad_multiple.

    "auto" (the default): pick from the dataset's shape histogram so the
    step compiles at most ``max_buckets`` programs; "exact"/"none"/"0":
    exact snapped shapes (zero padding, bit-exact reference loss math, but
    one compile per distinct resolution); otherwise an integer multiple.
    """
    if value is None:
        return None
    s = str(value).strip().lower()
    if s == "auto":
        return "auto"
    if s in ("exact", "none", "0"):
        return None
    return int(s)


def resolve_sp_padding(pad_multiple, sp: int):
    """Bucket constraints under spatial parallelism, shared by both CLIs.

    Returns (pad_multiple, min_pad_multiple, min_bucket_h).  Only the
    sharded H axis carries sp constraints (spatial.py shards P(data,
    spatial, None, None)); W keeps the cheaper /8 snap:
    * bucket H must be a multiple of 8*sp so max-pool windows never
      straddle shard boundaries (spatial.py _check_spatial_shapes);
    * bucket H must be >= 16*sp so each shard owns >= 2 feature rows (the
      dilated-conv halo) — short images are padded up instead of crashing
      the step factory mid-eval.
    """
    if sp <= 1:
        return pad_multiple, None, None
    need = 8 * sp
    if pad_multiple is None:  # exact shapes can't guarantee divisibility
        pad_multiple = (need, 8)
    elif isinstance(pad_multiple, int):
        mh = pad_multiple if pad_multiple % need == 0 else (
            -(-pad_multiple // need) * need)
        pad_multiple = (mh, pad_multiple)
    return pad_multiple, (need, None), 16 * sp


def dataset_roots(data_root: str, split: str) -> Tuple[str, str]:
    """ShanghaiTech-style layout (the reference comments these path pairs,
    train.py:49-52): <root>/<split>_data/images + .../ground_truth."""
    base = os.path.join(data_root, f"{split}_data")
    img, gt = os.path.join(base, "images"), os.path.join(base, "ground_truth")
    for p in (img, gt):
        if not os.path.isdir(p):
            raise FileNotFoundError(
                f"expected dataset directory {p} (ShanghaiTech layout: "
                f"<data_root>/{split}_data/{{images,ground_truth}})")
    return img, gt


def resolve_split_roots(split: str, image_root: str, gt_root: str,
                        data_root: str, *,
                        flag_stem: Optional[str] = None) -> Tuple[str, str]:
    """Explicit per-split roots (VisDrone-style layouts, where images and
    density maps live in unrelated trees — the reference hardcodes such a
    pair, train.py:54-57) win over the ShanghaiTech ``data_root``
    convention.  Either give BOTH roots for the split, or a data_root.

    flag_stem: prefix of the caller's flags ("train-"/"test-" in the train
    CLI, "" in the eval CLI) so error messages name flags that exist.
    Pure argument/isdir checks — call straight after parse_args, before any
    runtime/checkpoint work.
    """
    stem = f"{split}-" if flag_stem is None else flag_stem
    if image_root or gt_root:
        if not (image_root and gt_root):
            raise SystemExit(
                f"give both --{stem}image-root and --{stem}gt-root "
                f"(or neither, with --data_root)")
        for p in (image_root, gt_root):
            if not os.path.isdir(p):
                raise SystemExit(f"no such dataset directory: {p}")
        return image_root, gt_root
    if not data_root:
        raise SystemExit(
            f"need --data_root or --{stem}image-root/--{stem}gt-root")
    return dataset_roots(data_root, split)


def split_prepared_spec(spec: str, split: str) -> str:
    """``--prepared-root`` value -> ``CrowdDataset(prepared=...)`` for one
    split.  'auto'/'off' pass through; a path is a root holding per-split
    stores (``<path>/train``, ``<path>/test`` — what
    ``tools/prepare_data.py --prepared-out`` writes for multi-split runs).
    """
    if spec in ("auto", "off"):
        return spec
    return os.path.join(spec, split)


def build_mesh_and_batch(batch_size: int, sp: int) -> Tuple:
    """Mesh over all devices with ``sp`` spatial shards; returns
    (mesh, per_host_batch, dp).

    ``batch_size`` is PER DATA-PARALLEL REPLICA (the reference's per-GPU
    batch, train.py:177); global batch = batch_size * dp.
    """
    ndev = jax.device_count()
    if ndev % sp:
        raise ValueError(f"--sp {sp} does not divide device count {ndev}")
    dp = ndev // sp
    mesh = make_mesh(dp=dp, sp=sp)
    if jax.process_count() > 1 and sp > 1:
        # The spatial axis must stay WITHIN one host: make_global_batch
        # feeds each host's full-height slabs, so an sp group spanning
        # processes would make make_array_from_process_local_data stitch
        # different hosts' images vertically into one double-height
        # "image" and halo-exchange across the seam — silently wrong
        # gradients (code-review r5).  Verify on the built mesh (exact
        # regardless of create_device_mesh's ordering).
        for row in mesh.devices:
            if len({d.process_index for d in row}) > 1:
                raise ValueError(
                    f"--sp {sp} spans multiple hosts "
                    f"({jax.local_device_count()} local devices/host); "
                    "spatial sharding must stay within one host — lower "
                    "--sp or use more data-parallel replicas")
    global_batch = batch_size * dp
    nproc = jax.process_count()
    if global_batch % nproc:
        raise ValueError(
            f"global batch {global_batch} not divisible by process count {nproc}")
    return mesh, global_batch // nproc, dp


def activation_bytes(batch: int, h: int, w: int, *,
                     bf16: bool = False) -> int:
    """Peak train-step HBM footprint estimate for one CANNet launch.

    Linear in pixels; the constant is MEASURED, not analytic: the r4 OOM
    dump for b16 x 1016x1024 bf16 (16.65 Mpx) showed a 16.97 GiB program —
    ~1030 B/px — dominated by the full-res backward temporaries
    (bf16[B,H,W,64] conv-transpose + select_and_scatter buffers, each with
    2x lane-padding on the 64-channel dim).  jax.checkpoint barely moves
    this peak (the temporaries live INSIDE the rematerialised backward
    segment), which is why the planner's per-launch pixel cap
    (max_launch_pixels), not remat, is the primary fits-in-HBM mechanism.
    Consistent with every observed fit: b16 576x768 (7.5 GiB est) and
    b8 1016x1024 (8.8 GiB est) train fine; b16 1016x1024 (17.6 GiB est)
    OOMs with or without remat.  f32 doubles the bf16 footprint.
    """
    per_px = 1030.0 if bf16 else 2060.0
    return int(batch * h * w * per_px)


# HBM per JAX device by hardware generation — spec constants, not guesses
# (substring-matched against ``device_kind``).  Exists because not every
# PJRT client implements memory_stats(): the axon-tunnelled v5e returns
# nothing, and in the r5 chip run that silently disabled BOTH fits-in-HBM
# mechanisms (max_launch_pixels -> None, remat policy -> never), letting
# the b16 x 1016x1024 varres launch compile at 16.97 GiB and OOM a
# 15.75 GiB chip.  A device whose kind is unknown still returns None.
# NOTE these are the SPEC totals, which are strictly larger than what a
# program can allocate: PJRT reserves a slice for itself before reporting
# ``bytes_limit`` (the r5 v5e OOM dump showed 15.75 GiB usable of the
# 16 GiB spec, ~0.984; other clients reserve a bit more), so
# ``hbm_bytes_for_device_kind`` derates by ``_PJRT_SPEC_DERATE`` rather
# than handing the planner bytes the runtime will never grant.
# ORDERED: lite/cost-optimised variants before their generation's bare
# entry, so "v5lite..." never hits the bare "v5" (v5p) row and "v4i"
# never gets a full v4's 32 GiB.
_PJRT_SPEC_DERATE = 0.97  # spec -> typical usable bytes_limit fraction
_HBM_BY_DEVICE_KIND = (
    ("v5lite", 16 << 30),    # v5e ("TPU v5 lite", "TPU v5litepod-N")
    ("v5e", 16 << 30),
    ("v5p", 95 << 30),
    ("v5", 95 << 30),        # bare "TPU v5" = v5p (v5e always says lite/e)
    ("v6lite", 32 << 30),    # Trillium
    ("v6e", 32 << 30),
    ("v4i", 8 << 30),
    ("v4lite", 8 << 30),
    ("v4", 32 << 30),
    ("v3", 16 << 30),        # per core (= per JAX device)
    ("v2", 8 << 30),
)


# Peak compute / HBM bandwidth per JAX device by hardware generation —
# spec constants for the perf-attribution layer (obs/costs.py), matched
# exactly like _HBM_BY_DEVICE_KIND above (substring, first entry wins,
# lite variants before their generation's bare row).  Units: FLOP/s at the
# bf16 MXU rate, and HBM bytes/s.  v2/v3 rows are PER CORE (= per JAX
# device); v4+ are per chip.  The f32 peak is modelled as bf16/2 — the
# MXU takes bf16 inputs with f32 accumulation, and f32-input matmuls run
# at roughly half rate; an approximation, but MFU consumers only need a
# stable denominator, not a guarantee (the roofline CLASS depends only on
# the ridge ratio, which the /2 preserves).
_PEAK_BY_DEVICE_KIND = (
    ("v5lite", (197e12, 819e9)),   # v5e
    ("v5e", (197e12, 819e9)),
    ("v5p", (459e12, 2765e9)),
    ("v5", (459e12, 2765e9)),      # bare "TPU v5" = v5p (see HBM table)
    ("v6lite", (918e12, 1640e9)),  # Trillium
    ("v6e", (918e12, 1640e9)),
    ("v4i", (138e12, 614e9)),
    ("v4lite", (138e12, 614e9)),
    ("v4", (275e12, 1228e9)),
    ("v3", (61.5e12, 450e9)),      # per core (123 TFLOP/s / 900 GB/s chip)
    ("v2", (22.5e12, 300e9)),
)

# CPU pseudo-peaks: NOMINAL placeholders (≈ a laptop core's order of
# magnitude), flagged nominal=True so every consumer can say "relative
# only".  They exist so the MFU/roofline plumbing is exercisable (and
# tier-1 testable) on the CPU backend — unlike the HBM table, nothing
# here feeds scheduling, so a labelled fiction is acceptable where an
# unlabelled one would not be.
_CPU_NOMINAL_PEAKS = (5e10, 1e10)


@dataclasses.dataclass(frozen=True)
class DevicePeaks:
    """Peak rates for one device kind (the roofline's two ceilings)."""

    flops_bf16: float    # FLOP/s at the bf16 MXU rate
    flops_f32: float     # approximated as bf16/2 (see table note)
    hbm_bytes_s: float   # HBM bandwidth, bytes/s
    source: str          # "spec:<kind>" or "nominal:cpu"
    nominal: bool = False

    def flops(self, compute: str = "f32") -> float:
        return self.flops_bf16 if compute == "bf16" else self.flops_f32

    def ridge(self, compute: str = "f32") -> float:
        """Arithmetic intensity (FLOP/byte) where the roofline bends."""
        return self.flops(compute) / self.hbm_bytes_s


def _match_device_table(kind: str, table):
    """Substring-match a ``device_kind`` against an ordered spec table:
    case-insensitive, spaces stripped, first entry wins (lite variants
    are listed before their generation's bare row).  Single-sourced so
    ``_HBM_BY_DEVICE_KIND`` and ``_PEAK_BY_DEVICE_KIND`` can never
    diverge in matching rules — returns ``(matched_key, value)`` or
    ``(None, None)``."""
    k = kind.lower().replace(" ", "")
    for sub, val in table:
        if sub in k:
            return sub, val
    return None, None


def device_peaks_for_kind(kind: str) -> Optional[DevicePeaks]:
    """Spec peaks for a TPU ``device_kind`` string, or None when the
    generation isn't recognised (same matching rules as
    ``hbm_bytes_for_device_kind``)."""
    sub, val = _match_device_table(kind, _PEAK_BY_DEVICE_KIND)
    if sub is None:
        return None
    flops, bw = val
    return DevicePeaks(flops_bf16=float(flops),
                       flops_f32=float(flops) / 2.0,
                       hbm_bytes_s=float(bw), source=f"spec:{sub}")


def local_device_peaks() -> Optional[DevicePeaks]:
    """Peaks for THIS host's first local device: the spec table on TPU,
    the labelled-nominal CPU entry on the CPU backend (so MFU gauges stay
    exercisable in tests), None anywhere else."""
    try:
        dev = jax.local_devices()[0]
    # can-tpu-lint: disable=SWALLOW(backend init failure degrades to no-peaks; MFU rows go None, documented)
    except Exception:
        return None
    try:
        if dev.platform == "tpu":
            return device_peaks_for_kind(dev.device_kind)
        if dev.platform == "cpu":
            f, bw = _CPU_NOMINAL_PEAKS
            return DevicePeaks(flops_bf16=f, flops_f32=f, hbm_bytes_s=bw,
                               source="nominal:cpu", nominal=True)
    # can-tpu-lint: disable=SWALLOW(unknown device kind degrades to no-peaks; attribution is best-effort)
    except Exception:
        pass
    return None


def hbm_bytes_for_device_kind(kind: str) -> Optional[int]:
    """USABLE HBM bytes for a TPU ``device_kind`` string (spec total
    derated by the typical PJRT reservation, ``_PJRT_SPEC_DERATE`` — a
    real client's ``bytes_limit`` always comes in under spec), or None if
    the generation isn't recognised.  Matched case-insensitively with
    spaces stripped, first entry wins ("TPU v5 lite" and "TPU
    v5litepod-8" both hit "v5lite"; bare "TPU v5" falls through to the
    v5p row)."""
    sub, size = _match_device_table(kind, _HBM_BY_DEVICE_KIND)
    if sub is None:
        return None
    return int(size * _PJRT_SPEC_DERATE)


def device_memory_bytes() -> Optional[int]:
    """Per-LOCAL-device HBM: ``memory_stats()['bytes_limit']`` when the
    PJRT client reports it, else the spec size for the device kind
    (``hbm_bytes_for_device_kind``), else None.  None means 'no device
    memory ceiling' (CPU): there, inventing a number would let a
    fictitious 16 GiB drive real scheduling (launch caps, remat,
    LR-schedule step counts) on backends whose only limit is host RAM.
    TPU generations are different — their HBM is a hardware constant, and
    the spec fallback is what keeps the fits-in-HBM machinery alive on
    clients that don't implement memory_stats (the axon tunnel; see
    _HBM_BY_DEVICE_KIND).

    ``jax.local_devices()``, not ``jax.devices()``: on a multi-host pod
    devices()[0] is non-addressable for every rank but 0, so its
    memory_stats() fails there and ranks would silently diverge on
    whether an HBM cap exists (ADVICE r4, high).  Multi-host callers
    must still AGREE the value — use agreed_device_memory_bytes()."""
    try:
        dev = jax.local_devices()[0]
    # can-tpu-lint: disable=SWALLOW(backend init failure degrades to 'no ceiling', stated below)
    except Exception:
        return None  # backend init failure degrades to 'no ceiling'
    try:
        stats = dev.memory_stats()
        if stats and stats.get("bytes_limit"):
            return int(stats["bytes_limit"])
    # can-tpu-lint: disable=SWALLOW(memory_stats is optional per PJRT client; spec-table fallback follows)
    except Exception:
        pass
    try:
        if dev.platform == "tpu":
            spec = hbm_bytes_for_device_kind(dev.device_kind)
            if spec is None and dev.device_kind not in _WARNED_KINDS:
                _WARNED_KINDS.add(dev.device_kind)
                print(f"[hbm] TPU device_kind {dev.device_kind!r} not in "
                      "the spec table and memory_stats() reports no "
                      "bytes_limit: no HBM cap will be applied",
                      flush=True)
            return spec
    # can-tpu-lint: disable=SWALLOW(spec-table probe is best-effort; 'no HBM cap' is the documented degrade)
    except Exception:
        pass
    return None


_WARNED_KINDS: set = set()


def agreed_device_memory_bytes() -> Optional[int]:
    """device_memory_bytes() agreed across processes (min), for anything
    that feeds the LOCKSTEP schedule: every host must derive the same
    max_launch_px / remat decisions or make_array_from_process_local_data
    deadlocks on mismatched batch plans.  Min is the conservative
    agreement; a host with no ceiling (None) forces None everywhere
    (heterogeneous backends shouldn't invent a cap for the others).
    Collective — call AFTER init_runtime, identically on every host."""
    from can_tpu.parallel import agree_min_value, process_count

    mem = device_memory_bytes()
    if process_count() < 2:
        return mem
    import numpy as _np

    agreed = float(agree_min_value(_np.float64(-1.0 if mem is None else mem)))
    return None if agreed < 0 else int(agreed)


_DETECT = object()  # sentinel: "autodetect HBM" vs an explicit None cap


def max_launch_pixels(*, bf16: bool, ceiling_frac: float = 0.92,
                      hbm_bytes=_DETECT, shards: int = 1) -> Optional[float]:
    """Per-launch pixel budget (batch * H * W, GLOBAL units — the planner
    prices launches in global pixels) for the remnant planner's HBM cap
    (ShardedBatcher max_launch_px), or None on backends with no
    device-memory ceiling (CPU) — there the cap would be fiction and
    would shift batch counts (hence LR schedules) vs the TPU run.

    ``shards``: devices each launch is split across (mesh dp*sp).  The
    train step shards the batch over dp and H over sp, so per-DEVICE
    pixels = global pixels / shards; the B/px constant below is
    per-device (calibrated at dp=sp=1), so the global cap scales by
    ``shards`` — without this, a dp=4 pod would cap launches 4x smaller
    than what fits (ADVICE r4, medium).

    Calibrated to the measured worst case, not the analytic optimum: even
    WITH remat, the b16 x 1016x1024 backward peaked at ~17.2 GiB for
    16.65 Mpx (~1030 B/px: the full-res conv-transpose temporaries plus
    XLA's 2x lane-padding on the 64-channel stem dominate, r4 OOM dump).
    ~1100 B/px (bf16; f32 doubles it) against ``ceiling_frac`` of HBM
    admits every configuration that has been seen to fit (b16 768x1024,
    b8 1016x1024) and rejects the one that OOM'd.  ``hbm_bytes``
    overrides autodetection (tests pin it; multi-host CLIs pass the
    agreed_device_memory_bytes() value so every host caps identically).
    """
    mem = device_memory_bytes() if hbm_bytes is _DETECT else hbm_bytes
    if mem is None:
        return None
    per_px = 1100.0 if bf16 else 2200.0
    return ceiling_frac * mem / per_px * shards


def make_remat_policy(remat_flag: str, *, global_batch: int,
                      bf16: bool, budget_frac: float = 0.80,
                      announce: bool = False,
                      hbm_bytes=_DETECT, shards: int = 1):
    """Per-bucket rematerialisation decision (VERDICT r3 item 3).

    ``--remat on`` / ``off`` force the choice globally; ``auto`` (default)
    enables jax.checkpoint only for bucket shapes whose estimated peak
    footprint exceeds ``budget_frac`` of device HBM — the narrow band
    just under the per-launch pixel cap, where shaving the cross-segment
    activations buys headroom.  Small buckets keep the full-speed
    backward; shapes beyond the cap never launch at that batch at all
    (the planner's max_launch_px runs them at a smaller menu size — the
    reference's only fits-anything answer was batch-1, train.py:177).

    Returns ``policy(image_hw, batch=None) -> bool`` (batch defaults to the
    full global batch; remnant sub-batches pass their smaller actual size,
    so a big-shape straggler can still skip remat).

    ``shards`` (mesh dp*sp): the footprint estimate is for the GLOBAL
    launch but HBM is per-device and the step shards batch over dp / H
    over sp, so the estimate is divided by ``shards`` before comparing —
    otherwise dp>1 meshes over-trigger remat (ADVICE r4, medium).
    Multi-host callers pass hbm_bytes=agreed_device_memory_bytes().
    """
    if remat_flag in ("on", "off"):
        return lambda hw, batch=None: remat_flag == "on"
    mem = device_memory_bytes() if hbm_bytes is _DETECT else hbm_bytes
    if mem is None:
        # no device-memory ceiling reported (CPU backend): auto-remat
        # would be keyed to a made-up number — keep the fast backward
        return lambda hw, batch=None: False
    budget = int(mem * budget_frac)

    def policy(hw, batch=None):
        b = batch or global_batch
        need = activation_bytes(b, hw[0], hw[1], bf16=bf16) // shards > budget
        if need and announce and (b, hw) not in policy._said:
            policy._said.add((b, hw))
            print(f"[remat] bucket {hw[0]}x{hw[1]} (batch {b}): activation "
                  f"estimate exceeds {budget_frac:.0%} of HBM -> "
                  f"rematerialising backward for this bucket")
        return need

    policy._said = set()
    return policy


MODEL_MPX_PER_S = 42.0  # CANNet bf16 train-step device rate (v5e measured:
# 94.9 img/s x 0.442 Mpx at 576x768) — converts dispatch ms to the
# pixel-equivalents the remnant planner prices launches in

# Per-launch cost in the DEVICE regime: what one extra launch costs when
# dispatch is overlapped with compute (steps enqueued back-to-back, the
# loop's windowed fetch amortising the sync) — the regime the bench
# suite's steady-state compute numbers and a healthy prefetching train
# loop run in.  The pixel-independent device work per launch is chiefly
# the optimizer update (~300 MB param/momentum traffic ≈ 0.4 ms ≈ 0.017
# Mpx on v5e, r5 calibration note above) plus executable switch + infeed
# bookkeeping; 0.05 Mpx (~1.2 ms) is that with ~3x slack.  This is NOT
# the dispatch-bound number: a host whose launches serialize on an RPC
# (the 96 ms axon tunnel ⇒ ~4 Mpx) must price with --launch-cost-mpx
# auto / the 2.0 default instead.  The distinction matters: the r5 bench
# planned its varres schedule at tunnel pricing (2.0) and then quoted
# the steady-state compute rate — paying 30.7% pixel overhead (b16) to
# economise launches that regime gets nearly free (VERDICT r5 item 7).
DEVICE_LAUNCH_COST_MPX = 0.05


def measure_launch_cost_mpx(*, probes: int = 30,
                            device_rate_mpx_s: float = MODEL_MPX_PER_S) -> float:
    """Measure per-launch dispatch overhead and convert to Mpx-equivalents
    (the remnant planner's unit).  Times a tiny jitted op, BLOCKING on
    each call (device_get inside the loop): JAX enqueues dispatches ahead
    of execution, so an unblocked loop would hide the per-launch
    round-trip on exactly the high-latency tunnels 'auto' exists to
    detect (ADVICE r4).  Each probe measures the full dispatch+completion
    path with near-zero compute; the median is the fixed launch cost.
    Note this is an UPPER bound on what the train loop pays per launch:
    the loop fetches metrics once per ``check_every`` window (8 steps),
    amortising the completion sync, while the dispatch-path cost (the
    tunnel's measured ~50 ms RPC, r4 diag_remnant) is paid per launch
    regardless — so on the hosts where 'auto' matters the bound is
    tight, and elsewhere both numbers sit in the planner's flat region.

    Calibration status (r5, tools/launch_cost_probe.py + the plan-space
    sweep in CHANGES.md): the probe measures DISPATCH only; a real train
    step also pays pixel-independent device work each launch — chiefly
    the optimizer update (~300 MB of param/momentum traffic ≈ 0.4 ms ≈
    0.015 Mpx-equivalents on v5e) plus argument marshaling.  That
    omission cannot change a plan: the remnant planner's decisions are
    flat across [0, 0.05] Mpx and across [1, 4] Mpx on the bench
    distribution; the sensitive band (0.1-1 Mpx ≈ 2.5-25 ms) is exactly
    where dispatch dominates and the probe measures the dominant term
    directly.  So: no correction applied, by measurement rather than
    hope.  Costs one trivial compile at startup.
    """
    import statistics
    import time

    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda x: x + 1.0)
    x = jnp.zeros(())
    float(jax.device_get(f(x)))  # compile + settle
    times = []
    for _ in range(probes):
        t0 = time.perf_counter()
        float(jax.device_get(f(x)))
        times.append(time.perf_counter() - t0)
    return statistics.median(times) * device_rate_mpx_s


def parse_launch_cost(value):
    """argparse type for --launch-cost-mpx: 'auto' or a float — validated
    AT PARSE TIME (a typo'd value must not cost a multi-host rendezvous,
    same contract as the path checks)."""
    s = str(value).strip().lower()
    if s == "auto":
        return "auto"
    try:
        return float(value)
    except ValueError:
        raise argparse.ArgumentTypeError(
            f"expected 'auto' or a number, got {value!r}")


def resolve_launch_cost_px(spec, *, announce: bool = False) -> float:
    """CLI --launch-cost-mpx value (parse_launch_cost output) -> planner
    pixel units.  'auto' measures the host's dispatch overhead
    (measure_launch_cost_mpx) and, on multi-host runs, averages it across
    processes with ``reduce_value`` so every host prices launches
    identically — the remnant planner's lockstep schedule depends on all
    hosts computing the SAME plan.  A number is used as given (default
    2.0 ~= the dev tunnel's measured ~50 ms/launch).  Call AFTER
    init_runtime."""
    if spec == "auto":
        import numpy as _np

        from can_tpu.parallel import process_count, reduce_value

        mpx = measure_launch_cost_mpx()
        if process_count() > 1:
            mpx = float(reduce_value(_np.float32(mpx), average=True))
        if announce:
            print(f"[planner] measured launch overhead ~"
                  f"{mpx / MODEL_MPX_PER_S * 1e3:.1f} ms/launch -> "
                  f"launch cost {mpx:.2f} Mpx"
                  + (" (mean across hosts)" if process_count() > 1 else ""))
        return mpx * 1e6
    return float(spec) * 1e6


def make_bucketed_train_step(apply_fn, optimizer, mesh, *, compute_dtype,
                             policy, health_metrics: bool = False):
    """Data-parallel train step with per-bucket remat dispatch: two jitted
    step objects (remat on/off); jit caches per batch shape under each, so
    every bucket runs the cheapest variant the ``policy`` (make_remat_policy)
    allows.  Shared by the train CLI and bench_suite so the bench measures
    exactly the CLI's dispatch.  health_metrics: in-program grad/update
    norms for the run-health layer (default off — identical programs)."""
    from can_tpu.parallel import make_dp_train_step

    steps = {flag: make_dp_train_step(apply_fn, optimizer, mesh,
                                      compute_dtype=compute_dtype,
                                      remat=flag,
                                      health_metrics=health_metrics)
             for flag in (False, True)}

    def train_step(state, batch):
        shape = batch["image"].shape
        return steps[policy(tuple(shape[1:3]), batch=shape[0])](state, batch)

    # cost-ledger seam (obs/costs.py): the jitted step this batch would
    # dispatch to, so a ProgramCostLedger can AOT-read cost_analysis()
    # through the remat dispatch closure
    train_step.jit_for = lambda state, batch: steps[
        policy(tuple(batch["image"].shape[1:3]),
               batch=batch["image"].shape[0])]
    return train_step


def make_inference_forward():
    """Jitted single-image forward that handles both model variants:
    ``fwd(params, image, batch_stats_or_None)`` (shared by the train CLI's
    --show visualization and the test CLI's --show-index)."""
    import jax as _jax

    from can_tpu.models import cannet_apply

    def _fwd(params, x, batch_stats):
        if batch_stats is not None:
            return cannet_apply(params, x, batch_stats=batch_stats,
                                train=False)
        return cannet_apply(params, x)

    return _jax.jit(_fwd)


class SpatialStepCache:
    """Per-image-shape cache of spatial train steps (each H x W bucket shape
    compiles its own shard_map program, mirroring jit's per-shape cache)."""

    def __init__(self, factory):
        self._factory = factory
        self._steps: Dict[Tuple[int, int], object] = {}

    def __call__(self, image_hw: Tuple[int, int]):
        step = self._steps.get(image_hw)
        if step is None:
            step = self._steps[image_hw] = self._factory(image_hw)
        return step


def make_cached_sp_eval_step(mesh, *, compute_dtype=None):
    """Bucket-shape-cached spatial eval step (shared by both CLIs)."""
    from can_tpu.parallel.spatial import make_sp_eval_step

    cache = SpatialStepCache(
        lambda hw: make_sp_eval_step(mesh, hw, compute_dtype=compute_dtype))

    def eval_step(params, batch, batch_stats=None):
        hw = (batch["image"].shape[1], batch["image"].shape[2])
        return cache(hw)(params, batch, batch_stats)

    # cost-ledger seam, as in make_bucketed_train_step
    eval_step.jit_for = lambda params, batch, batch_stats=None: cache(
        (batch["image"].shape[1], batch["image"].shape[2]))
    return eval_step
