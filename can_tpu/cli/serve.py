"""Online serving CLI: load a checkpoint, warm up the bucket programs,
answer count/density requests over HTTP.

The reference repo has no request-level inference at all (test.py is batch
evaluation of a directory); this is the front door the ROADMAP's
"serves heavy traffic" north star needs.  Checkpoint loading — Orbax dir,
reference ``.pth``, or converted ``.npz`` — is shared with the eval CLI
(``cli/test.py::load_params``), so anything you can evaluate you can serve.

    python -m can_tpu.cli.serve --torch-pth epoch_354.pth \
        --bucket-shapes 384x512,512x768,768x1024 --max-batch 8 \
        --max-wait-ms 5 --port 8000

    curl -X POST --data-binary @img.npy \
        'http://127.0.0.1:8000/predict?deadline_ms=200'
"""

from __future__ import annotations

import argparse
import re
from typing import List, Tuple


def parse_bucket_shapes(spec: str) -> List[Tuple[int, int]]:
    """'384x512,512x768' -> [(384, 512), (512, 768)] (validated /8)."""
    shapes = []
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        m = re.fullmatch(r"(\d+)x(\d+)", part)
        if not m:
            raise argparse.ArgumentTypeError(
                f"bad bucket shape {part!r} (want HxW, e.g. 384x512)")
        h, w = int(m.group(1)), int(m.group(2))
        if h % 8 or w % 8:
            raise argparse.ArgumentTypeError(
                f"bucket shape {h}x{w} must be multiples of 8 (the "
                f"density grid)")
        shapes.append((h, w))
    if not shapes:
        raise argparse.ArgumentTypeError("no bucket shapes given")
    return sorted(set(shapes))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="CANNet online serving")
    # checkpoint source — same flags and conflict rules as the eval CLI
    p.add_argument("--checkpoint-dir", type=str, default=None,
                   help="Orbax checkpoint dir (default ./checkpoints)")
    p.add_argument("--epoch", type=int, default=None,
                   help="checkpoint epoch (default: best by MAE, else latest)")
    p.add_argument("--torch-pth", type=str, default="",
                   help="serve a REFERENCE torch checkpoint directly")
    p.add_argument("--params-npz", type=str, default="",
                   help="serve a tools/import_torch_checkpoint.py .npz")
    p.add_argument("--syncBN", action="store_true",
                   help="checkpoint is the BatchNorm model variant")
    p.add_argument("--seed", type=int, default=0)
    # serving policy
    p.add_argument("--bucket-shapes", type=parse_bucket_shapes,
                   default=parse_bucket_shapes("384x512,512x768,768x1024"),
                   help="comma-separated HxW bucket ladder; requests snap "
                        "UP to the smallest covering shape per axis — one "
                        "XLA program each, all compiled at startup")
    p.add_argument("--max-batch", type=int, default=8,
                   help="requests per micro-batch (every launch pads to "
                        "exactly this, so batch size is static)")
    p.add_argument("--max-wait-ms", type=float, default=5.0,
                   help="latency CAP on batching: the priced flush "
                        "deadline (can_tpu/sched) never waits past this; "
                        "with --flush-policy timer it is the fixed flush "
                        "deadline itself (pre-r14 behaviour)")
    p.add_argument("--menu-budget", type=int, default=None,
                   help="launch sizes per (bucket, dtype) in the priced "
                        "sub-batch menu (can_tpu/sched.select_menu; "
                        "default 3): a 2-request flush launches a 2-slot "
                        "program instead of padding to --max-batch; all "
                        "menu sizes are compiled at warmup.  1 = the "
                        "single max-batch program")
    p.add_argument("--flush-policy", type=str, default="priced",
                   choices=["priced", "timer"],
                   help="priced: a group flushes the moment waiting "
                        "longer cannot beat launch-cost amortization "
                        "given its arrival rate and deadline slack; "
                        "timer: the fixed --max-wait-ms deadline "
                        "(pre-r14)")
    p.add_argument("--dispatch-order", type=str, default="priced",
                   choices=["priced", "fifo"],
                   help="fleet work-queue order: priced = cheapest-"
                        "feasible-first under deadline pressure with a "
                        "starvation age bound (can_tpu/sched.pick_work); "
                        "fifo = pre-r14 pure FIFO")
    p.add_argument("--queue-capacity", type=int, default=64,
                   help="hard bound on queued requests (beyond: queue_full)")
    p.add_argument("--high-water", type=int, default=None,
                   help="queue depth that starts load shedding "
                        "(backpressure rejects until half-drained); "
                        "default: 3/4 of capacity")
    p.add_argument("--deadline-ms", type=float, default=None,
                   help="default per-request deadline (expired requests "
                        "are rejected, never dispatched); requests may "
                        "override per call")
    p.add_argument("--replicas", type=int, default=1,
                   help="serve-engine replicas, one per device of the "
                        "mesh (>= 2 builds the FleetEngine: work-stealing "
                        "dispatch, quarantine-on-failure, blue/green "
                        "/rollout; 1 keeps the single-engine service)")
    p.add_argument("--serve-dtype", type=str, default="f32",
                   choices=["f32", "bf16", "int8"],
                   help="predict-program mode (serve/quant.py): f32 = "
                        "bit-parity with offline evaluate(); bf16 = bf16 "
                        "params+compute at MXU rate; int8 = weight-only "
                        "post-training quantization (per-channel scales, "
                        "f32 accumulation, 4x smaller resident params) — "
                        "each priced by the committed parity ladder")
    p.add_argument("--bf16", action="store_true",
                   help="LEGACY bf16 compute with f32 params (counts "
                        "shift ~1e-3 relative); superseded by "
                        "--serve-dtype bf16, conflict if both given")
    # self-healing fleet (ISSUE 13; all fleet-mode only)
    p.add_argument("--aot-bundle", type=str, default="",
                   help="load AOT-serialized predict executables from "
                        "this bundle dir (serve/aot.py): warmup, "
                        "resurrection, and scale-up DESERIALIZE instead "
                        "of compiling — seconds to ready, zero new "
                        "compiles; a stale bundle (params/dtype/jax "
                        "mismatch) is refused with the axis named")
    p.add_argument("--aot-bake", type=str, default="",
                   help="after warmup, serialize the compiled predict "
                        "grid for EVERY device into this bundle dir "
                        "(written beside the checkpoint is the "
                        "convention) and keep serving — the artifact "
                        "--aot-bundle loads on the next start")
    p.add_argument("--autoscale-max", type=int, default=0,
                   help="enable the autoscaler with this replica "
                        "ceiling (> --replicas; 0 = off): the fleet "
                        "grows on sustained queue depth / p99-over-"
                        "deadline / SLO burn and shrinks when idle, "
                        "with hysteresis + cooldown — zero-drop "
                        "transitions either way")
    p.add_argument("--autoscale-min", type=int, default=None,
                   help="autoscaler floor (default: --replicas)")
    p.add_argument("--autoscale-interval-s", type=float, default=1.0,
                   help="autoscaler evaluation period")
    p.add_argument("--probe-cooldown-s", type=float, default=5.0,
                   help="probation cooldown before a quarantined "
                        "replica's first health probe (backoff doubles "
                        "per failed probe, jittered)")
    p.add_argument("--watchdog-slack", type=float, default=10.0,
                   help="hang-watchdog deadline = cost-ledger expected "
                        "execute time x this slack (per bucket)")
    p.add_argument("--watchdog-default-s", type=float, default=30.0,
                   help="hang-watchdog deadline before any timing "
                        "exists (or without a ledger)")
    # per-stream sessions (serve/streams.py)
    p.add_argument("--stream-ttl-s", type=float, default=300.0,
                   help="evict a stream session idle this long (host-"
                        "side state: count/density EWMA, frame sequence, "
                        "sticky replica pin — clients opt in per request "
                        "with ?stream_id=...&frame_seq=N)")
    p.add_argument("--degrade-policy", type=str, default="priced",
                   choices=["priced", "off"],
                   help="priced: the per-stream degradation ladder — "
                        "full inference -> frame-skip (answer from the "
                        "session EWMA, labelled degraded+staleness, no "
                        "launch) -> reject, driven by arrival rate vs "
                        "the sched core's priced drain cost with "
                        "hysteresis; off: sessions + sticky routing + "
                        "sequence hygiene only, never skip a frame")
    p.add_argument("--max-body-mb", type=float, default=64.0,
                   help="HTTP 413 any POST body over this many MiB "
                        "BEFORE reading it (one unbounded multi-GB "
                        "upload would OOM the serve host)")
    p.add_argument("--u8-warmup", action="store_true",
                   help="also pre-compile uint8-input programs, for "
                        "clients POSTing ?raw=1 (pixels stay bytes on the "
                        "wire and into HBM; normalise-on-device, like the "
                        "train CLI's --u8-input)")
    # front end
    p.add_argument("--host", type=str, default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    # plumbing shared with the other CLIs
    p.add_argument("--platform", type=str, default="default",
                   choices=["default", "cpu", "tpu"])
    p.add_argument("--compile-cache", type=str, default="auto",
                   help="persistent XLA compilation-cache dir ('auto' = "
                        "~/.cache/can_tpu/xla, 'off' disables) — makes "
                        "warm restarts deserialise the bucket programs "
                        "instead of recompiling")
    p.add_argument("--telemetry-dir", type=str, default="",
                   help="write serve.request/serve.batch/serve.reject "
                        "JSONL here (tools/telemetry_report.py summarises)")
    p.add_argument("--telemetry-heartbeat-s", type=float, default=60.0)
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus-text /metrics + /healthz on this "
                        "port (0 = ephemeral): the service's /stats "
                        "counters (requests, rejects, queue depth, "
                        "latency percentiles) in the SAME format and "
                        "labels as the train CLI's gauges — one scrape "
                        "config covers training and serving")
    p.add_argument("--metrics-host", type=str, default="127.0.0.1",
                   help="bind address for --metrics-port")
    p.add_argument("--collector-push", type=str, default="",
                   metavar="URL",
                   help="stream telemetry to a FleetCollector "
                        "(can_tpu.cli.collect) at URL — best-effort "
                        "batched JSONL over HTTP (see the train CLI)")
    p.add_argument("--incident-dir", type=str, default="",
                   help="arm the incident layer: a replica quarantine, a "
                        "fast SLO burn, or a SIGTERM dumps a "
                        "self-contained bundle (flight-recorder ring + "
                        "gauges + live serve stats + stacks) here — see "
                        "the train CLI / obs/incidents.py")
    p.add_argument("--slo-spec", type=str, default="",
                   help="JSON SLO spec (slo_spec.json): serve p99 vs "
                        "deadline, reject rate, ... evaluated live as "
                        "multi-window burn rates; can_tpu_slo_* gauges "
                        "on /metrics are the autoscaler's signal")
    return p.parse_args(argv)


def _run_config_for(checkpoint_dir, torch_pth, params_npz):
    """Run config for the drift guard — imported .pth/.npz checkpoints
    carry none, so the guard degrades to skipped for them (same as
    resume).  One helper so serve-time and rollout-time agree forever."""
    from can_tpu.utils import load_run_config

    if torch_pth or params_npz:
        return None
    return load_run_config(checkpoint_dir)


def make_rollout_loader(base_args):
    """Checkpoint loader for the HTTP /rollout endpoint: a JSON source
    spec (same keys as the CLI flags) -> (params, batch_stats,
    run_config).  Reuses the eval CLI's validated loading path, so
    anything you can serve you can roll out."""
    import argparse as _ap

    def load(spec: dict):
        from can_tpu.cli.test import load_params, validate_params_source

        allowed = {"checkpoint_dir", "epoch", "params_npz", "torch_pth",
                   "syncBN"}
        unknown = set(spec) - allowed
        if unknown:
            raise ValueError(f"unknown rollout keys: {sorted(unknown)} "
                             f"(allowed: {sorted(allowed)})")
        # an imported-source spec (torch_pth / params_npz) must NOT
        # inherit the serving checkpoint_dir: validate_params_source
        # rejects the combination, which would 409 every such rollout
        imported = bool(spec.get("torch_pth") or spec.get("params_npz"))
        ns = _ap.Namespace(
            # default to the SERVING run's directory (a bare {"epoch": N}
            # rolls forward within it), exactly like syncBN below — an
            # unrelated ./checkpoints fallback could silently flip the
            # fleet to a different run's weights
            checkpoint_dir=spec.get(
                "checkpoint_dir",
                None if imported else base_args.checkpoint_dir),
            epoch=spec.get("epoch"),
            torch_pth=spec.get("torch_pth", ""),
            params_npz=spec.get("params_npz", ""),
            syncBN=bool(spec.get("syncBN", base_args.syncBN)),
            seed=base_args.seed)
        try:
            validate_params_source(ns)
            params, batch_stats = load_params(ns)
        except SystemExit as e:
            # the loading path speaks CLI (SystemExit); over HTTP that
            # must become a 409-able error, not a dead handler thread
            raise ValueError(str(e)) from None
        run_config = _run_config_for(ns.checkpoint_dir, ns.torch_pth,
                                     ns.params_npz)
        return params, batch_stats, run_config

    return load


def build_service(args, telemetry=None):
    """Engine + service from parsed args (no networking) — the seam the
    tests and bench drive; ``main`` adds HTTP around it."""
    import jax.numpy as jnp
    import numpy as np

    from can_tpu.cli.test import load_params
    from can_tpu.serve import CountService, FleetEngine, ServeEngine

    if args.bf16 and args.serve_dtype != "f32":
        raise SystemExit("--bf16 is the legacy f32-params/bf16-compute "
                         "mode; with --serve-dtype use the mode itself "
                         "(drop --bf16)")
    if args.replicas < 1:
        raise SystemExit(f"--replicas must be >= 1, got {args.replicas}")
    if args.menu_budget is not None and not 1 <= args.menu_budget <= 8:
        # the exact menu search is combinatorial in the budget: bound it
        # HERE (and cleanly), before the checkpoint load — past ~8 sizes
        # the expected-cost curve is flat and the search is just heat
        raise SystemExit(f"--menu-budget must be in [1, 8], got "
                         f"{args.menu_budget}")
    if args.stream_ttl_s <= 0:
        raise SystemExit(f"--stream-ttl-s must be positive, got "
                         f"{args.stream_ttl_s}")
    if args.max_body_mb <= 0:
        raise SystemExit(f"--max-body-mb must be positive, got "
                         f"{args.max_body_mb}")
    fleet_only = ["--aot-bundle", "--aot-bake", "--autoscale-max"]
    if args.replicas <= 1 and (args.aot_bundle or args.aot_bake
                               or args.autoscale_max):
        raise SystemExit(f"{'/'.join(fleet_only)} need fleet mode "
                         f"(--replicas >= 2)")
    if args.autoscale_max and args.autoscale_max <= args.replicas:
        raise SystemExit(f"--autoscale-max ({args.autoscale_max}) must "
                         f"exceed --replicas ({args.replicas})")
    if args.autoscale_max and args.autoscale_min is not None:
        # validate BEFORE the checkpoint load: AutoscalePolicy would
        # reject these anyway, but only after minutes of load+warmup
        if not 1 <= args.autoscale_min <= args.autoscale_max:
            raise SystemExit(
                f"--autoscale-min ({args.autoscale_min}) must be in "
                f"[1, --autoscale-max={args.autoscale_max}]")
    params, batch_stats = load_params(args)
    compute_dtype = jnp.bfloat16 if args.bf16 else None
    if args.replicas > 1:
        from can_tpu.serve import AotStaleError

        run_config = _run_config_for(args.checkpoint_dir, args.torch_pth,
                                     args.params_npz)
        try:
            engine = FleetEngine(
                params, batch_stats, replicas=args.replicas,
                serve_dtype=args.serve_dtype,
                compute_dtype=compute_dtype,
                telemetry=telemetry, run_config=run_config,
                aot_bundle=args.aot_bundle or None,
                probe_cooldown_s=args.probe_cooldown_s,
                watchdog_slack=args.watchdog_slack,
                watchdog_default_s=args.watchdog_default_s,
                dispatch_order=args.dispatch_order)
        except AotStaleError as e:
            # a stale bundle silently falling back to minutes of
            # compiles defeats the flag's whole point: refuse, name the
            # axis, point at the re-bake
            raise SystemExit(f"--aot-bundle refused: {e}")
    else:
        engine = ServeEngine(params, batch_stats,
                             serve_dtype=args.serve_dtype,
                             compute_dtype=compute_dtype,
                             telemetry=telemetry)
    high_water = (args.high_water if args.high_water is not None
                  else max(1, (3 * args.queue_capacity) // 4))
    shapes = args.bucket_shapes
    ladder = (tuple(sorted({h for h, _ in shapes})),
              tuple(sorted({w for _, w in shapes})))
    service = CountService(engine, max_batch=args.max_batch,
                           max_wait_ms=args.max_wait_ms,
                           queue_capacity=args.queue_capacity,
                           high_water=high_water,
                           default_deadline_ms=args.deadline_ms,
                           bucket_ladder=ladder, telemetry=telemetry,
                           menu_budget=args.menu_budget,
                           flush_policy=args.flush_policy,
                           stream_ttl_s=args.stream_ttl_s,
                           degrade_policy=args.degrade_policy,
                           max_body_mb=args.max_body_mb)
    if args.replicas > 1:
        # the /rollout endpoint's checkpoint loader (fleet only: a single
        # engine has no staging replica to warm on)
        service.rollout_loader = make_rollout_loader(args)
    # the ladder's cross product is the compile universe; warm it ALL so
    # no live request ever pays a compile
    grid = [(h, w) for h in ladder[0] for w in ladder[1]]
    dtypes = (np.float32, np.uint8) if args.u8_warmup else (np.float32,)
    try:
        report = service.warmup(grid, dtypes=dtypes)
    except Exception as e:
        from can_tpu.serve import AotStaleError

        if isinstance(e, AotStaleError):
            # warmup re-checks the batch-geometry axes (max_batch,
            # bucket grid) the constructor can't know yet — same clean
            # refusal as a construction-time mismatch
            raise SystemExit(f"--aot-bundle refused: {e}")
        raise
    reps = f" x {args.replicas} replicas" if args.replicas > 1 else ""
    aot = " [AOT]" if args.replicas > 1 and args.aot_bundle else ""
    print(f"[serve] warmup: {report['compiles']} programs over "
          f"{report['shapes']} bucket shapes{reps} "
          f"[{args.serve_dtype}]{aot} in {report['seconds']:.1f}s")
    if args.replicas > 1 and args.aot_bake:
        manifest = engine.bake_aot(args.aot_bake)
        engine.load_aot(args.aot_bake)  # this run heals from it too
        print(f"[serve] AOT bundle: {len(manifest['programs'])} programs "
              f"over {len(engine._devices_all)} devices -> "
              f"{args.aot_bake} ({manifest['bake_seconds']:.1f}s)")
    if args.replicas > 1 and args.autoscale_max:
        from can_tpu.serve import Autoscaler, AutoscalePolicy

        policy = AutoscalePolicy(
            min_replicas=(args.autoscale_min
                          if args.autoscale_min is not None
                          else args.replicas),
            max_replicas=args.autoscale_max,
            p99_high_s=(args.deadline_ms / 1e3
                        if args.deadline_ms else None),
            interval_s=args.autoscale_interval_s)
        gauges = getattr(telemetry, "_gauge_sink", None)
        service.autoscaler = Autoscaler(service, policy, gauges=gauges)
        print(f"[serve] autoscaler armed: {policy.min_replicas}.."
              f"{policy.max_replicas} replicas, "
              f"eval every {policy.interval_s:g}s")
    return service


def main(argv=None) -> int:
    args = parse_args(argv)
    from can_tpu.cli.test import validate_params_source

    validate_params_source(args)  # the corrected sentinel logic, shared
    from can_tpu.cli.train import (
        apply_compile_cache,
        apply_platform,
        build_telemetry,
        validate_incident_args,
    )
    from can_tpu.parallel import init_runtime, process_index, shutdown_runtime
    from can_tpu.serve import serve_http

    validate_incident_args(args)
    apply_platform(args)
    init_runtime()
    apply_compile_cache(args, announce=True)
    telemetry, heartbeat, exporter = build_telemetry(
        args, host_id=process_index(), trace_window=None)
    try:
        service = build_service(args, telemetry=telemetry)
        if exporter is not None:
            # serve's counters in the same scrape as the bus gauges
            exporter.add_stats_source("serve", service.stats)
        with service:
            httpd = serve_http(service, host=args.host, port=args.port)
            endpoints = "POST /predict, GET /healthz, GET /stats"
            if args.replicas > 1:
                endpoints += ", POST /rollout"
            print(f"[serve] listening on http://{args.host}:{args.port} "
                  f"({endpoints})")
            try:
                httpd.serve_forever()
            except KeyboardInterrupt:
                print("[serve] shutting down")
            finally:
                httpd.server_close()
        return 0
    finally:
        from can_tpu.obs import shutdown_telemetry

        # deterministic order shared with the SIGTERM path (lifecycle.py)
        shutdown_telemetry(telemetry, heartbeat=heartbeat,
                           exporter=exporter)
        shutdown_runtime()


if __name__ == "__main__":
    raise SystemExit(main())
