"""Fleet collector daemon CLI — the observability plane's aggregation
point (``can_tpu/obs/collector.py``).

    python -m can_tpu.cli.collect runs/exp1/ --spec slo_spec.json \
        --port 9900 --snapshot-dir runs/exp1-fleet/

One process joins every host's telemetry live — tailing the run dir's
``telemetry.host*.jsonl`` files AND accepting HTTP ``POST /ingest``
batches from remote hosts started with ``--collector-push`` — and
serves:

* ``GET /metrics``   — federated Prometheus text: per-host gauges with
  a ``host`` label, fleet rollups, per-host clock skew, and the GLOBAL
  SLO burn (``can_tpu_slo_burn_global{objective,window_s}``) computed
  by ONE engine over the skew-corrected ts-merged stream;
* ``GET /fleet/status`` — machine-readable fleet liveness + counters;
* silent-host detection ("no data ≠ healthy"): a host whose corrected
  heartbeat goes stale raises a ``fleet.host`` event, an incident
  bundle (with ``--incident-dir``), and a dead-host signal file (with
  ``--emit-signal`` — the same obs/signals.py grammar the elastic
  supervisor polls, so detection drives the fleet's shrink reaction).

``--snapshot-dir`` archives everything ingested plus a ``collector.json``
manifest recording the MEASURED per-host clock offsets; pointing
``tools/slo_report.py`` at that snapshot replays the live global burn
bit-identically, and ``tools/trace_export.py`` renders skew-corrected
cross-host timelines from it.

Pure host-side code — no JAX import, runs on any box that can reach the
run dir or be reached by the pushing hosts.
"""

from __future__ import annotations

import argparse
import json
import signal
import sys
import time


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("run_dir", nargs="?", default="",
                   help="directory of telemetry.host*.jsonl to tail "
                        "(optional — push-only fleets omit it)")
    p.add_argument("--spec", default="",
                   help="SLO spec JSON (slo_spec.json) for the global "
                        "burn engine; omit to collect without grading")
    p.add_argument("--listen", default="127.0.0.1",
                   help="bind address (0.0.0.0 for remote pushers)")
    p.add_argument("--port", type=int, default=0,
                   help="HTTP port for /metrics, /fleet/status and "
                        "POST /ingest (0 = ephemeral)")
    p.add_argument("--interval-s", type=float, default=2.0,
                   help="tail-poll / liveness-check interval")
    p.add_argument("--stale-after-s", type=float, default=180.0,
                   help="corrected heartbeat age that marks a host "
                        "stale (~3x the hosts' heartbeat interval)")
    p.add_argument("--snapshot-dir", default="",
                   help="archive ingested telemetry + collector.json "
                        "manifest here (must differ from run_dir); the "
                        "offline-replay artifact for tools/slo_report.py "
                        "and tools/trace_export.py")
    p.add_argument("--incident-dir", default="",
                   help="dump incident bundles here on stale hosts and "
                        "fast global SLO burn (obs/incidents.py)")
    p.add_argument("--emit-signal", metavar="DIR", default="",
                   help="write a dead-host signal file (obs/signals.py "
                        "schema) into DIR when a host goes stale — the "
                        "directory an elastic supervisor polls")
    p.add_argument("--json", action="store_true",
                   help="print the final /fleet/status document as JSON "
                        "on exit (after the drain)")
    args = p.parse_args(argv)

    # import after parsing: --help must not pay for anything
    from can_tpu.obs.collector import FleetCollector
    from can_tpu.obs.slo import load_slo_spec

    spec = None
    if args.spec:
        try:
            spec = load_slo_spec(args.spec)
        except (OSError, ValueError) as e:
            print(f"collect: bad spec: {e}", file=sys.stderr)
            return 2
    try:
        collector = FleetCollector(
            spec, run_dir=args.run_dir, snapshot_dir=args.snapshot_dir,
            stale_after_s=args.stale_after_s,
            signal_dir=args.emit_signal, incident_dir=args.incident_dir,
            host=args.listen, port=args.port,
            poll_interval_s=args.interval_s)
    except ValueError as e:
        print(f"collect: {e}", file=sys.stderr)
        return 2
    collector.start()
    print(f"[collect] /metrics + /fleet/status + POST /ingest on "
          f"http://{collector.host}:{collector.port}"
          + (f", tailing {args.run_dir}" if args.run_dir else "")
          + (f", snapshots -> {args.snapshot_dir}"
             if args.snapshot_dir else ""), flush=True)
    # a supervisor stops the daemon with SIGTERM: that must reach the
    # drain below (watermark release + final snapshot with
    # ``drained: true``), exactly like ^C — not die mid-archive
    rc = 0

    def _on_term(signum, frame):
        raise SystemExit(128 + signum)

    signal.signal(signal.SIGTERM, _on_term)
    try:
        while True:
            time.sleep(3600.0)  # poll/HTTP threads do the work
    except KeyboardInterrupt:
        pass
    except SystemExit as e:
        rc = e.code or 0
    finally:
        collector.close(drain=True)
    if args.json:
        print(json.dumps(collector.status()))
    return rc


if __name__ == "__main__":
    raise SystemExit(main())
