"""Evaluation / inference CLI — the reference's ``test.py`` re-done.

``cal_mae`` (reference test.py:10-35) → dataset MAE/MSE from a checkpoint;
``estimate_density_map`` (test.py:38-62) → save a single image's predicted
density map.  Paths come from flags instead of the reference's hardcoded
ShanghaiA locations (test.py:67-69).
"""

from __future__ import annotations

import argparse

import jax
import jax.numpy as jnp
import numpy as np

from can_tpu.cli.common import (
    build_mesh_and_batch,
    make_cached_sp_eval_step,
    parse_pad_multiple,
    resolve_launch_cost_px,
    resolve_split_roots,
    resolve_sp_padding,
)
from can_tpu.data import CrowdDataset, ShardedBatcher
from can_tpu.models import cannet_apply, cannet_init, init_batch_stats
from can_tpu.parallel import (
    init_runtime,
    make_dp_eval_step,
    make_global_batch,
    process_count,
    process_index,
    shutdown_runtime,
)
from can_tpu.train import create_train_state, evaluate, make_lr_schedule, make_optimizer
from can_tpu.utils import CheckpointManager, save_density_visualization


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="CANNet TPU evaluation")
    p.add_argument("--data_root", type=str, default="",
                   help="ShanghaiTech-layout root "
                        "(<root>/<split>_data/{images,ground_truth})")
    p.add_argument("--image-root", type=str, default="",
                   help="explicit image dir (VisDrone-style layouts); "
                        "pair with --gt-root")
    p.add_argument("--gt-root", type=str, default="")
    p.add_argument("--split", type=str, default="test", choices=["train", "test"])
    # default=None sentinel, resolved to ./checkpoints AFTER conflict
    # checks: the --torch-pth conflict must key on "flag was provided",
    # not on the literal default string (ADVICE r5 — an explicit
    # `--checkpoint-dir ./checkpoints` used to slip through)
    p.add_argument("--checkpoint-dir", type=str, default=None,
                   help="Orbax checkpoint dir (default ./checkpoints)")
    p.add_argument("--epoch", type=int, default=None,
                   help="checkpoint epoch (default: best by MAE, else latest)")
    p.add_argument("--torch-pth", type=str, default="",
                   help="evaluate a REFERENCE torch checkpoint directly "
                        "(e.g. the published epoch_354.pth, reference "
                        "test.py:69) — imported via utils/torch_import.py, "
                        "no prior conversion needed")
    p.add_argument("--params-npz", type=str, default="",
                   help="evaluate a tools/import_torch_checkpoint.py .npz "
                        "(torch-free path)")
    p.add_argument("--batch-size", type=int, default=1,
                   help="images per data-parallel replica")
    p.add_argument("--sp", type=int, default=1,
                   help="spatial (image-height) shards per replica — for "
                        "images too large for one chip (UCF-QNRF scale); "
                        "forces bucket shapes to multiples of 8*sp")
    p.add_argument("--pad-multiple", type=parse_pad_multiple, default="exact",
                   help="'exact' (default): per-resolution compiles but "
                        "bit-exact boundary math — eval is the parity "
                        "oracle, so correctness beats compile time here; "
                        "'auto' bounds compiled shapes (padding shifts the "
                        "conv boundary, perturbing edge-adjacent cells); "
                        "or an int multiple")
    p.add_argument("--bf16", action="store_true")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--show-index", type=int, default=None,
                   help="also save a density-map visualization of this item")
    p.add_argument("--out-dir", type=str, default="./eval_out")
    p.add_argument("--platform", type=str, default="default",
                   choices=["default", "cpu", "tpu"])
    p.add_argument("--syncBN", action="store_true",
                   help="checkpoint is the BatchNorm model variant")
    p.add_argument("--u8-input", action="store_true",
                   help="ship uint8 pixels, normalise on device (see train "
                        "CLI; pixels differ by u8 resize rounding, so keep "
                        "the default f32 for bit-exact paper numbers)")
    p.add_argument("--num-workers", type=int, default=None,
                   help="host data-loading threads (default: min(8, cpus); "
                        "0 = main thread)")
    p.add_argument("--prepared-root", type=str, default="auto",
                   help="prepared 1/8-density store: 'auto' (default) "
                        "probes <gt_root>/prepared and falls back to the "
                        "legacy decode when absent/stale; 'off' disables; "
                        "a path points at a root holding per-split stores "
                        "(<path>/<split>, the train CLI's and "
                        "--prepared-out's layout) and MUST validate "
                        "(numerics are bit-identical either way — see "
                        "tools/prepare_data.py --prepared)")
    p.add_argument("--item-cache-mb", type=float, default=0.0,
                   help="bounded in-RAM LRU over decoded items, in MB "
                        "(0 = off).  A single eval pass decodes each "
                        "unique item once regardless — this pays off for "
                        "fill-slot duplicates and for callers that loop "
                        "evaluations in one process")
    p.add_argument("--compile-cache", type=str, default="auto",
                   help="persistent XLA compilation-cache dir ('auto' = "
                        "~/.cache/can_tpu/xla, 'off' disables)")
    p.add_argument("--profile-dir", type=str, default="",
                   help="jax.profiler trace output dir (with --trace-steps)")
    p.add_argument("--trace-steps", type=str, default="",
                   help="trace WINDOW by eval-batch range, START:STOP "
                        "slice semantics, into --profile-dir")
    p.add_argument("--telemetry-dir", type=str, default="",
                   help="write structured telemetry JSONL here (same "
                        "schema as the train CLI; one file per host)")
    p.add_argument("--telemetry-heartbeat-s", type=float, default=60.0,
                   help="heartbeat event interval (with --telemetry-dir)")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus-text /metrics + /healthz on this "
                        "port during the eval (0 = ephemeral; see the "
                        "train CLI — long high-res evals are worth "
                        "watching too)")
    p.add_argument("--metrics-host", type=str, default="127.0.0.1",
                   help="bind address for --metrics-port")
    p.add_argument("--collector-push", type=str, default="",
                   metavar="URL",
                   help="stream telemetry to a FleetCollector "
                        "(can_tpu.cli.collect) at URL — best-effort "
                        "batched JSONL over HTTP (see the train CLI)")
    p.add_argument("--incident-dir", type=str, default="",
                   help="arm the incident layer: flight-recorder ring + "
                        "trigger-dumped bundles + SIGTERM/preemption "
                        "hook (see the train CLI; long high-res evals "
                        "die to preemption too)")
    p.add_argument("--slo-spec", type=str, default="",
                   help="JSON SLO spec evaluated live as multi-window "
                        "burn rates (see the train CLI / slo_spec.json)")
    p.add_argument("--max-buckets", type=int, default=24,
                   help="compile budget for --pad-multiple auto (distinct "
                        "(shape x batch-size) programs)")
    p.add_argument("--no-remnant-batches", action="store_true",
                   help="with --pad-multiple auto, pad straggler groups to "
                        "the full batch instead of emitting smaller "
                        "sub-batches (see train CLI)")
    from can_tpu.cli.common import parse_launch_cost

    p.add_argument("--launch-cost-mpx", type=parse_launch_cost, default=2.0,
                   help="per-launch cost for the remnant planner, in "
                        "megapixel-equivalents, or 'auto' to measure this "
                        "host's dispatch overhead (see train CLI)")
    return p.parse_args(argv)


def validate_params_source(args) -> None:
    """Reject conflicting/invalid checkpoint-source flags, then resolve the
    ``--checkpoint-dir`` default.  Shared by the eval and serve CLIs (both
    load params through :func:`load_params`); pure arg validation — safe
    to run before any runtime init."""
    import os as _os

    if args.torch_pth and args.params_npz:
        raise SystemExit("give --torch-pth OR --params-npz, not both")
    if (args.torch_pth or args.params_npz) and args.syncBN:
        raise SystemExit("--torch-pth/--params-npz hold the reference "
                         "model (no BatchNorm); drop --syncBN")
    # imported params are a complete model: checkpoint-selection flags
    # would be silently ignored, so reject them like the conflicts above
    if (args.torch_pth or args.params_npz) and args.epoch is not None:
        raise SystemExit("--epoch selects an Orbax checkpoint epoch; it "
                         "does not apply to --torch-pth/--params-npz")
    if (args.torch_pth or args.params_npz) \
            and args.checkpoint_dir is not None:
        raise SystemExit("--checkpoint-dir is ignored with "
                         "--torch-pth/--params-npz; drop one of them")
    for p in (args.torch_pth, args.params_npz):
        if p and not _os.path.isfile(p):
            raise SystemExit(f"no such checkpoint file: {p}")
    if args.checkpoint_dir is None:
        args.checkpoint_dir = "./checkpoints"


def load_params(args):
    """Restore (params, batch_stats) from the checkpoint manager (best epoch
    by default), or import reference/converted weights directly."""
    if args.torch_pth or args.params_npz:
        if args.torch_pth:
            from can_tpu.utils.torch_import import load_torch_checkpoint

            params = load_torch_checkpoint(args.torch_pth)
            print(f"[load] reference torch checkpoint {args.torch_pth}")
        else:
            from can_tpu.utils.torch_import import load_params_npz

            params = load_params_npz(args.params_npz)
            print(f"[load] imported params {args.params_npz}")
        return params, None
    params = cannet_init(jax.random.key(args.seed), batch_norm=args.syncBN)
    optimizer = make_optimizer(make_lr_schedule(1e-7))
    state = create_train_state(params, optimizer, init_batch_stats(params))
    ckpt = CheckpointManager(args.checkpoint_dir)
    epoch = args.epoch
    if epoch is None:
        epoch = ckpt.best_epoch()
    if epoch is None:  # no metrics recorded: fall back to latest
        epoch = ckpt.latest_epoch()
    state = ckpt.restore(state, epoch=epoch)
    ckpt.close()
    print(f"[load] epoch {epoch} from {args.checkpoint_dir}")
    return state.params, state.batch_stats


def main(argv=None) -> int:
    args = parse_args(argv)
    # pure arg/path validation BEFORE runtime init / checkpoint restore
    img_root, gt_root = resolve_split_roots(
        args.split, args.image_root, args.gt_root, args.data_root,
        flag_stem="")
    validate_params_source(args)
    if args.item_cache_mb < 0:
        raise SystemExit("--item-cache-mb must be >= 0")
    from can_tpu.cli.train import (
        apply_compile_cache,
        apply_platform,
        build_telemetry,
        resolve_num_workers,
        validate_incident_args,
        validate_trace_args,
    )

    trace_window = validate_trace_args(args)
    validate_incident_args(args)
    apply_platform(args)
    init_runtime()
    apply_compile_cache(args)
    telemetry, heartbeat, exporter = build_telemetry(
        args, host_id=process_index(), trace_window=trace_window)
    # loop instrumentation only when something consumes it (see train CLI)
    loop_tel = telemetry if (args.telemetry_dir or trace_window
                             or exporter is not None or args.incident_dir
                             or args.slo_spec) else None
    try:
        params, batch_stats = load_params(args)
        compute_dtype = jnp.bfloat16 if args.bf16 else None
        from can_tpu.data import ItemCache, StaleStoreError

        item_cache = (ItemCache(int(args.item_cache_mb * 1e6))
                      if args.item_cache_mb > 0 else None)
        from can_tpu.cli.common import split_prepared_spec

        try:
            ds = CrowdDataset(img_root, gt_root, gt_downsample=8,
                              phase="test", u8_output=args.u8_input,
                              prepared=split_prepared_spec(
                                  args.prepared_root, args.split),
                              item_cache=item_cache)
        except StaleStoreError as e:
            raise SystemExit(f"--prepared-root {args.prepared_root}: {e}")
        telemetry.emit("data.prepared", split=args.split,
                       **ds.prepared_note)
        if process_index() == 0:
            note = ds.prepared_note
            print(f"[data] prepared store: "
                  f"{'on' if note['active'] else 'legacy(' + str(note['reason']) + ')'}")
        # per-host slice of the lockstep schedule, like the train CLI —
        # without this a multi-host pod would feed every image
        # process_count times
        mesh, host_batch, dp = build_mesh_and_batch(args.batch_size, args.sp)
        # params device-resident + replicated ONCE: the imported-checkpoint
        # paths return host numpy trees, and feeding those to the jitted
        # eval step would re-upload all ~74 MB of weights EVERY batch
        # (review r5) — ruinous on a ~50 ms-dispatch tunnel.  No-op cost
        # for the already-resident Orbax path.
        from can_tpu.parallel import replicated_sharding

        params = jax.device_put(params, replicated_sharding(mesh))
        if batch_stats is not None:
            batch_stats = jax.device_put(batch_stats,
                                         replicated_sharding(mesh))
        pad_multiple, min_pad, min_bucket_h = resolve_sp_padding(
            args.pad_multiple, args.sp)
        if args.sp > 1 and pad_multiple != args.pad_multiple:
            # never silently trade away the exact-shape default: sp changes
            # the reported numbers' boundary math, so say so
            print(f"[data] sp={args.sp}: bucket H padded to multiples of "
                  f"{8 * args.sp} (exact shapes can't shard)")
        import math as _math

        batcher = ShardedBatcher(ds, host_batch, shuffle=False,
                                 pad_multiple=pad_multiple,
                                 min_pad_multiple=min_pad,
                                 min_bucket_h=min_bucket_h,
                                 process_index=process_index(),
                                 process_count=process_count(),
                                 num_workers=resolve_num_workers(args),
                                 max_buckets=args.max_buckets,
                                 remnant_sizes=not args.no_remnant_batches,
                                 batch_quantum=_math.lcm(dp, process_count()),
                                 launch_cost_px=resolve_launch_cost_px(
                                     args.launch_cost_mpx))
        if process_index() == 0:
            # main-process-only: the telemetry re-scans every image header,
            # and a pod would otherwise emit one duplicate line per process
            sched = batcher.schedule_overhead(0)
            pad = batcher.padding_overhead()
            print(f"[data] buckets={batcher.describe_buckets()} -> "
                  f"{batcher.distinct_shapes(0)} distinct batch shapes "
                  f"(padding overhead {pad:.1%}, "
                  f"schedule overhead {sched:.1%})")
            # fill-slot component alone (schedule_overhead also contains
            # per-item padding, which a smaller batch would NOT fix)
            fill = (1 + sched) / (1 + pad) - 1
            if fill > 0.5:
                if not args.no_remnant_batches:
                    # remnant covers already shrank every launch to the
                    # smallest legal size, so what remains is the batch
                    # quantum: each launch must split across the dp mesh
                    # axis and every host
                    print(f"[data] hint: batch fill slots add {fill:.0%} "
                          f"compute — the per-launch floor is "
                          f"{batcher.batch_quantum} images "
                          f"(lcm of dp={dp} and {process_count()} "
                          f"host(s)); a tiny eval set can't fill it "
                          f"(evaluate on fewer devices to lower the "
                          f"floor)")
                else:
                    print(f"[data] hint: batch fill slots add {fill:.0%} "
                          "compute (small eval set spread over many "
                          "shapes at this batch size) — drop "
                          "--no-remnant-batches or use a smaller "
                          "--batch-size")
        if args.sp > 1:
            eval_step = make_cached_sp_eval_step(mesh,
                                                 compute_dtype=compute_dtype)
        else:
            eval_step = make_dp_eval_step(cannet_apply, mesh,
                                          compute_dtype=compute_dtype)
        try:
            from can_tpu.sched import prefetch_depth_for

            metrics = evaluate(eval_step, params, batcher.epoch(0),
                               put_fn=lambda b: make_global_batch(
                                   b, mesh, spatial=args.sp > 1),
                               dataset_size=batcher.dataset_size,
                               show_progress=True, batch_stats=batch_stats,
                               telemetry=loop_tel,
                               prefetch=prefetch_depth_for(batcher))
        finally:
            batcher.close()
        telemetry.emit("epoch", step=0, phase="eval", mae=metrics["mae"],
                       mse=metrics["mse"], num_images=metrics["num_images"])
        if item_cache is not None:
            telemetry.emit("data.cache", step=0, **item_cache.stats())
        print(f"[result] images={metrics['num_images']} "
              f"MAE={metrics['mae']:.3f} MSE={metrics['mse']:.3f}")

        if args.show_index is not None and jax.process_index() == 0:
            # rank-0 only: every rank running this branch would (a) build
            # the sp viz mesh from GLOBAL devices non-addressable off
            # host 0 and crash, and (b) race identical PNG writes over
            # shared storage (code-review r5)
            from can_tpu.data import normalize_host

            img, gt = ds[args.show_index]
            img = normalize_host(img)  # no-op for the f32 path
            if args.sp > 1:
                # H-sharded forward — the image may not fit one chip (the
                # reason --sp was requested); pad H to the sp constraints
                # and crop the density map back.  BN checkpoints ride along:
                # eval-mode BN consumes replicated running stats.
                from can_tpu.parallel import make_mesh
                from can_tpu.parallel.spatial import make_spatial_apply

                h0, w0 = img.shape[:2]
                need = 8 * args.sp
                ph = max(-(-h0 // need) * need, 16 * args.sp)
                pimg = np.zeros((ph, w0, 3), np.float32)
                pimg[:h0] = img
                # one image: a dp=1 x sp viz mesh (the eval mesh shards the
                # batch dim over dp, which a single image can't fill)
                # LOCAL devices: rank 0 cannot address other hosts' chips
                viz_mesh = make_mesh(jax.local_devices()[:args.sp], dp=1,
                                     sp=args.sp)
                fwd = make_spatial_apply(viz_mesh, (ph, w0),
                                         compute_dtype=compute_dtype)
                # params live on the eval mesh; rehome them for the viz mesh
                host_params = jax.device_get(params)
                host_stats = (jax.device_get(batch_stats)
                              if batch_stats is not None else None)
                et = np.asarray(fwd(host_params, jnp.asarray(pimg)[None],
                                    host_stats))[0]
                et = et[: h0 // 8]
            else:
                from can_tpu.cli.common import make_inference_forward

                # host copies: the eval loop may have committed params to
                # the global mesh; a rank-local jit must not consume them
                host_params = jax.device_get(params)
                host_stats = (jax.device_get(batch_stats)
                              if batch_stats is not None else None)
                et = np.asarray(make_inference_forward()(
                    host_params, jnp.asarray(img)[None], host_stats))[0]
            paths = save_density_visualization(
                img, gt, et, args.out_dir,
                tag=f"{args.split}_{args.show_index}")
            print(f"[viz] wrote {paths}")
        return 0
    finally:
        from can_tpu.obs import shutdown_telemetry

        # deterministic order shared with the SIGTERM path (lifecycle.py)
        shutdown_telemetry(telemetry, heartbeat=heartbeat,
                           exporter=exporter)
        shutdown_runtime()  # the reference leaks its process group (SURVEY §3.1)


if __name__ == "__main__":
    raise SystemExit(main())
