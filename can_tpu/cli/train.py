"""Distributed training CLI — the reference's ``train.py`` re-done TPU-first.

Reference launch (README.md:24-26):
    python -m torch.distributed.launch --nproc_per_node=N --use_env train.py
TPU launch: ONE command per host (chips are addressed through the mesh, not
one process per accelerator):
    python -m can_tpu.cli.train --data_root ... [--sp K] [--bf16]

Flag-compatibility with reference train.py:175-195, with its dead/broken
flags made real:
* ``--data_root`` actually selects the dataset (reference parses it but
  hardcodes VisDrone paths, train.py:49-57);
* ``--lrf`` is a real cosine decay to lr*lrf (reference parses, never uses);
* ``--seed`` gives full reproducibility (reference seeds only CUDA with
  time.time(), train.py:66,71);
* ``--syncBN`` trains the real BatchNorm variant of the model with
  cross-replica statistics (the reference's flag is a no-op because its
  CANNet has no BN layers, SURVEY §2);
* eval MAE uses the true dataset size (reference divides by the
  padding-inflated sampler total, train.py:157).
"""

from __future__ import annotations

import argparse
import datetime
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np

from can_tpu.cli.common import (
    SpatialStepCache,
    build_mesh_and_batch,
    make_cached_sp_eval_step,
    make_remat_policy,
    parse_pad_multiple,
    resolve_launch_cost_px,
    resolve_split_roots,
    resolve_sp_padding,
)
from can_tpu.data import CrowdDataset, ShardedBatcher
from can_tpu.models import (
    cannet_apply,
    cannet_init,
    init_batch_stats,
    load_vgg16_frontend,
)
from can_tpu.parallel import (
    init_runtime,
    is_main_process,
    make_dp_eval_step,
    make_global_batch,
    process_count,
    process_index,
    shutdown_runtime,
)
from can_tpu.parallel.spatial import make_sp_train_step
from can_tpu.train import (
    NonFiniteLossError,
    create_train_state,
    evaluate,
    make_lr_schedule,
    make_optimizer,
    train_one_epoch,
)
from can_tpu.utils import CheckpointManager, MetricLogger, profile_trace


def parse_args(argv=None):
    p = argparse.ArgumentParser(description="CANNet TPU distributed training")
    # reference-compatible flags (train.py:175-195)
    p.add_argument("--epochs", type=int, default=500)
    p.add_argument("--batch-size", type=int, default=1,
                   help="images per data-parallel replica (reference: per GPU)")
    p.add_argument("--lr", type=float, default=1e-7)
    p.add_argument("--lrf", type=float, default=1.0,
                   help="final lr fraction for cosine decay (1.0 = constant)")
    p.add_argument("--syncBN", action="store_true",
                   help="train the BatchNorm variant of CANNet; batch stats "
                        "are computed over the global sharded batch, i.e. "
                        "cross-replica synchronized (the reference's flag is "
                        "a no-op because its model has no BN layers)")
    p.add_argument("--wandb", action="store_true")
    p.add_argument("--show", action="store_true",
                   help="save eval sample density visualizations")
    p.add_argument("--data_root", type=str, default="",
                   help="ShanghaiTech-layout root "
                        "(<root>/<split>_data/{images,ground_truth})")
    # VisDrone-style layouts: images and density maps in unrelated trees
    # (the reference hardcodes such a pair, train.py:54-57)
    p.add_argument("--train-image-root", type=str, default="")
    p.add_argument("--train-gt-root", type=str, default="")
    p.add_argument("--test-image-root", type=str, default="")
    p.add_argument("--test-gt-root", type=str, default="")
    p.add_argument("--init_checkpoint", "--init-checkpoint", type=str,
                   default="",
                   help="checkpoint dir to resume from (latest epoch); "
                        "underscore spelling is the reference's, dashed "
                        "alias matches this CLI's convention")
    p.add_argument("--init-torch-pth", type=str, default="",
                   help="warm-start params from a REFERENCE torch "
                        "checkpoint (e.g. the published epoch_354.pth) — "
                        "the reference's --init_checkpoint .pth workflow "
                        "(its train.py:98-102,113), but with STRICT layout "
                        "validation instead of strict=False; params only "
                        "(optimizer/step start fresh)")
    # TPU-native knobs
    p.add_argument("--checkpoint-dir", type=str, default="./checkpoints")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--sp", type=int, default=1,
                   help="spatial (image-height) shards per replica")
    p.add_argument("--pad-multiple", type=parse_pad_multiple, default="auto",
                   help="bucket H,W up to this multiple; 'auto' (default) "
                        "picks the smallest multiple that bounds the number "
                        "of distinct compiled shapes; 'exact' buckets by "
                        "exact snapped shape (zero padding, unbounded "
                        "compiles on wild datasets)")
    p.add_argument("--bf16", action="store_true",
                   help="bfloat16 compute (f32 params/accumulation on TPU; "
                        "on cpu/gpu backends bf16 may accumulate at lower "
                        "precision)")
    p.add_argument("--u8-input", action="store_true",
                   help="ship uint8 pixels to the device and normalise "
                        "inside the compiled step: 4x less host->device "
                        "traffic, XLA fuses the normalise into the first "
                        "conv (pixels differ from the f32 path only by u8 "
                        "rounding in the resize)")
    p.add_argument("--remat", nargs="?", const="on", default="auto",
                   choices=["auto", "on", "off"],
                   help="rematerialise the forward in backward "
                        "(jax.checkpoint): ~1/3 more FLOPs for far less "
                        "activation HBM. 'auto' (default) enables it per "
                        "bucket shape, only where the activation estimate "
                        "would overflow HBM (cli/common.py "
                        "make_remat_policy); bare --remat forces it on, "
                        "'off' disables")
    p.add_argument("--vgg16-npz", type=str, default="",
                   help="pretrained VGG-16 frontend .npz (tools/convert_vgg16.py)")
    p.add_argument("--eval-interval", type=int, default=1,
                   help="evaluate+checkpoint every N epochs (>= 1; the "
                        "final epoch always evaluates)")
    p.add_argument("--profile-dir", type=str, default="")
    p.add_argument("--trace-steps", type=str, default="",
                   help="jax.profiler trace WINDOW by run-local step range, "
                        "START:STOP slice semantics (e.g. 10:13 = steps "
                        "10..12) into --profile-dir — instead of the "
                        "whole-run trace a bare --profile-dir captures")
    p.add_argument("--telemetry-dir", type=str, default="",
                   help="write structured telemetry JSONL here (one "
                        "telemetry.host{k}.jsonl per host: compile / "
                        "step_window / stall / memory / heartbeat / epoch "
                        "events; summarize with tools/telemetry_report.py)")
    p.add_argument("--telemetry-heartbeat-s", type=float, default=60.0,
                   help="heartbeat event interval (with --telemetry-dir): "
                        "a hung run leaves a last-known-good timestamp; "
                        "<= 0 disables the heartbeat thread")
    p.add_argument("--metrics-port", type=int, default=None,
                   help="serve Prometheus-text /metrics + /healthz on this "
                        "port (0 = ephemeral): live step/loss/grad-norm "
                        "gauges, compile/stall/alert counters — fed by an "
                        "in-memory sink on the telemetry bus; also enables "
                        "the run-health detectors (health.alert events). "
                        "Default off: no exporter thread, no extra "
                        "instrumentation")
    p.add_argument("--metrics-host", type=str, default="127.0.0.1",
                   help="bind address for --metrics-port (0.0.0.0 to let "
                        "a fleet scraper reach every host)")
    p.add_argument("--collector-push", type=str, default="",
                   metavar="URL",
                   help="stream this host's telemetry to a FleetCollector "
                        "(can_tpu.cli.collect) at URL as batched JSONL "
                        "over HTTP POST /ingest — live fleet-level "
                        "gauges, global SLO burn, clock-skew-corrected "
                        "liveness.  Best-effort: a dead collector costs "
                        "dropped batches (counted), never the run")
    p.add_argument("--incident-dir", type=str, default="",
                   help="arm the incident layer (obs/incidents.py): a "
                        "flight-recorder ring retains the last N events "
                        "of telemetry, and any trigger — NaN/stall-budget "
                        "health alert, replica quarantine, unhandled loop "
                        "exception, SIGTERM/preemption — dumps a "
                        "self-contained bundle (ring + gauges + cost "
                        "ledger + all-thread stacks + device memory + "
                        "run config) into this directory, rate-limited "
                        "and retention-bounded.  Default off: no "
                        "recorder, no signal hook")
    p.add_argument("--slo-spec", type=str, default="",
                   help="JSON SLO spec (see slo_spec.json): objectives "
                        "evaluated live as multi-window error-budget "
                        "burn rates over the telemetry stream — slo.burn "
                        "events, can_tpu_slo_* gauges on /metrics, and "
                        "incident bundles on fast burn (with "
                        "--incident-dir).  Grade a finished run with "
                        "tools/slo_report.py")
    p.add_argument("--max-steps-per-epoch", type=int, default=0,
                   help="truncate epochs (smoke tests); 0 = full epoch")
    p.add_argument("--platform", type=str, default="default",
                   choices=["default", "cpu", "tpu"],
                   help="force a JAX platform (cpu + "
                        "XLA_FLAGS=--xla_force_host_platform_device_count=N "
                        "gives an N-device virtual mesh)")
    p.add_argument("--num-workers", type=int, default=None,
                   help="host data-loading threads per process (decode + "
                        "resize + pad; the reference's DataLoader "
                        "num_workers, train.py:90). Default: min(8, cpus); "
                        "0 = load in the main thread")
    p.add_argument("--prepared-root", type=str, default="auto",
                   help="prepared 1/8-density store (tools/prepare_data.py "
                        "--prepared): 'auto' (default) probes each split's "
                        "<gt_root>/prepared and falls back to the legacy "
                        "decode path when absent/stale; 'off' disables; a "
                        "path points at a root holding per-split stores "
                        "(<path>/train, <path>/test) and MUST validate")
    p.add_argument("--item-cache-mb", type=float, default=0.0,
                   help="bounded in-RAM LRU over fully-decoded items, in "
                        "MB (shared across train+test splits; 0 = off): "
                        "datasets that fit decode once, then epochs serve "
                        "from memory — counters land as data.cache "
                        "telemetry events")
    p.add_argument("--allow-config-change", action="store_true",
                   help="permit resuming (--init_checkpoint) with "
                        "schedule-bearing flags (lr/lrf/epochs/batch/seed/"
                        "syncBN/bf16) that differ from the ones the "
                        "checkpoint was trained with — without this flag, "
                        "drift is an error, not a silent schedule break")
    p.add_argument("--max-buckets", type=int, default=24,
                   help="compile budget for --pad-multiple auto: max "
                        "distinct batch shapes per step. More buckets = "
                        "less padding (straggler merging keeps the number "
                        "of shapes actually compiled well under the "
                        "budget), and the persistent compilation cache "
                        "makes the one-time bill cheap. Measured on the "
                        "bench distribution: 8 -> 41.5, 16 -> 50.4, "
                        "24 -> 56.3 img/s")
    p.add_argument("--s2d-stem", action="store_true",
                   help="space-to-depth the VGG stem: fold the 3-channel "
                        "first conv into (H/2, W/2, 12) packed space so its "
                        "contraction uses 108 of the MXU's 128 K-lanes "
                        "instead of 27 — numerically identical "
                        "(ops/conv.py fold_stem_kernel); dp path only")
    p.add_argument("--no-remnant-batches", action="store_true",
                   help="disable remnant sub-batches: with --pad-multiple "
                        "auto, straggler groups normally run at a small "
                        "menu of static sub-batch sizes (near-zero dead "
                        "slots; each (shape x size) program counts against "
                        "--max-buckets) instead of padding to the full "
                        "global batch")
    from can_tpu.cli.common import parse_launch_cost

    p.add_argument("--launch-cost-mpx", type=parse_launch_cost, default=2.0,
                   help="fixed cost of one extra step launch, in "
                        "megapixel-equivalents, for the remnant planner's "
                        "pixels-vs-launches trade. The conservative "
                        "default (~50 ms at the chip's measured rate) "
                        "suits high-dispatch-latency links; 'auto' "
                        "measures this host's dispatch overhead at "
                        "startup (sub-ms dispatch unlocks exact "
                        "straggler splits)")
    p.add_argument("--bn-impl", choices=("twopass", "onepass", "pallas"),
                   default="onepass",
                   help="SyncBN batch-moments path (only meaningful with "
                        "--syncBN): 'onepass' (default) computes per-channel "
                        "(sum, sumsq, count) in one read of each BN layer's "
                        "feature map and issues ONE packed collective per "
                        "layer — measured strictly fewer HBM bytes per "
                        "lowered program than 'twopass' (the original "
                        "mean-then-variance math, kept bit-compatible for "
                        "A/B, mirroring --plan-mode legacy); 'pallas' "
                        "additionally fuses the mask multiply into a TPU "
                        "kernel (ops/pallas_bn.py; jnp fallback off-TPU / "
                        "unsupported shapes)")
    p.add_argument("--plan-mode", choices=("cost", "legacy"), default="cost",
                   help="batch-plan search: 'cost' (default) plans bucket "
                        "boundaries, per-cell batch sizes, and remnant "
                        "menus jointly under one cost model "
                        "(area*slots + launch_cost*launches, HBM cap "
                        "respected); 'legacy' is the pre-r8 heuristic "
                        "planner, kept for A/B comparison")
    p.add_argument("--elastic-dir", type=str, default="",
                   help="arm elastic shrink-and-continue training "
                        "(parallel/elastic.py): a shared signal directory "
                        "(shared FS on a pod) polled for preemption "
                        "leave/dead files — written by a preempted host's "
                        "SIGTERM hook or tools/run_monitor.py "
                        "--emit-signal.  On an agreed signal, all hosts "
                        "checkpoint at a bounded barrier, leavers exit "
                        "cleanly, survivors re-rendezvous at the shrunk "
                        "world, the planner replans the interrupted "
                        "epoch's remaining items, lr/global-batch rescale "
                        "with dp, and training continues — recorded as "
                        "one elastic.transition telemetry event.  "
                        "Default off: no hook, no per-step polling")
    p.add_argument("--elastic-check-every", type=int, default=4,
                   help="steps between elastic agreement polls (each is "
                        "one small host allgather at world > 1; smaller "
                        "reacts faster, larger costs less)")
    p.add_argument("--compile-cache", type=str, default="auto",
                   help="persistent XLA compilation-cache dir ('auto' = "
                        "~/.cache/can_tpu/xla, 'off' disables): warm "
                        "restarts skip the per-bucket-shape compile bill")
    return p.parse_args(argv)


def apply_platform(args) -> None:
    if args.platform != "default":
        jax.config.update("jax_platforms", args.platform)


def validate_trace_args(args):
    """Parse ``--trace-steps`` (SystemExit on malformed specs, BEFORE any
    runtime init) and require the trace destination."""
    from can_tpu.obs import parse_trace_steps

    try:
        window = parse_trace_steps(getattr(args, "trace_steps", ""))
    except ValueError as e:
        raise SystemExit(str(e))
    if window and not args.profile_dir:
        raise SystemExit("--trace-steps needs --profile-dir (the trace's "
                         "output directory)")
    return window


def validate_incident_args(args) -> None:
    """Pure arg/path validation for the incident/SLO flags — run BEFORE
    any runtime init (a typo'd spec must not cost a multi-host
    rendezvous, the same contract as the dataset path checks).  Shared
    by all three CLIs."""
    spec_path = getattr(args, "slo_spec", "")
    if spec_path:
        from can_tpu.obs.slo import load_slo_spec

        try:
            # stash the PARSED spec: build_telemetry runs after
            # init_runtime, and re-reading the file there would reopen
            # the post-rendezvous failure window this validation closes
            # (a spec replaced mid-launch on a shared FS)
            args._slo_spec_parsed = load_slo_spec(spec_path)
        except OSError as e:
            raise SystemExit(f"--slo-spec: cannot read {spec_path}: {e}")
        except ValueError as e:
            raise SystemExit(f"--slo-spec: {e}")
    incident_dir = getattr(args, "incident_dir", "")
    if incident_dir:
        import os as _os

        try:
            _os.makedirs(incident_dir, exist_ok=True)
        except OSError as e:
            raise SystemExit(f"--incident-dir: cannot create "
                             f"{incident_dir}: {e}")


def build_telemetry(args, *, host_id: int, trace_window, logger=None,
                    install_signals: bool = True):
    """The CLIs' shared wiring: per-host JSONL sink (``--telemetry-dir``),
    MetricLogger adapter (epoch scalars keep flowing to stdout/wandb
    unchanged), optional step-range trace window, heartbeat thread, and —
    with ``--metrics-port`` — an in-memory gauge sink plus the live
    Prometheus exporter (obs/exporter.py).  ``--incident-dir`` adds the
    flight recorder + IncidentManager (+ the SIGTERM/preemption hook,
    unless ``install_signals=False`` — in-process tests must not retarget
    the interpreter's signal table); ``--slo-spec`` adds the SLO
    burn-rate engine.  Returns
    ``(telemetry, heartbeat_or_None, exporter_or_None)`` — tear the
    stack down with ``obs.shutdown_telemetry`` (one deterministic order
    for clean exit and SIGTERM alike).

    ``--collector-push URL`` adds a best-effort push sink streaming the
    bus to a FleetCollector; ``CAN_TPU_HOST_ID`` overrides the host id
    on every emitted event (several processes on one machine all read
    ``process_index() == 0`` — the fleet view needs them distinct)."""
    from can_tpu import obs

    env_hid = os.environ.get("CAN_TPU_HOST_ID", "")
    if env_hid:
        try:
            host_id = int(env_hid)
        except ValueError:
            raise SystemExit(f"CAN_TPU_HOST_ID: not an int: {env_hid!r}")
    trace = (obs.StepTraceWindow(args.profile_dir, *trace_window)
             if trace_window else None)
    extra = [obs.MetricLoggerSink(logger)] if logger is not None else []
    collector_url = getattr(args, "collector_push", "")
    if collector_url:
        extra.append(obs.CollectorPushSink(collector_url))
    exporter = None
    gauges = None
    metrics_port = getattr(args, "metrics_port", None)
    incident_dir = getattr(args, "incident_dir", "")
    slo_spec_path = getattr(args, "slo_spec", "")
    if metrics_port is not None or incident_dir or slo_spec_path:
        # the gauge sink exists for ANY of its three consumers: the
        # scrape endpoint, the bundle's gauges.json snapshot, and the
        # SLO layer's can_tpu_slo_* exports
        gauges = obs.GaugeSink()
        extra.append(gauges)
    if metrics_port is not None:
        exporter = obs.MetricsExporter(
            gauges, host=getattr(args, "metrics_host", "127.0.0.1"),
            port=metrics_port).start()
        print(f"[metrics] /metrics + /healthz on "
              f"http://{exporter.host}:{exporter.port}")
    recorder = None
    if incident_dir:
        recorder = obs.FlightRecorder()
        extra.append(recorder)
    if args.telemetry_dir:
        tel = obs.open_host_telemetry(args.telemetry_dir, host_id=host_id,
                                      extra_sinks=extra, trace=trace)
    else:
        tel = obs.Telemetry(extra, host_id=host_id, trace=trace)
    # the gauge sink rides the bus handle (like .ledger/.spans): the
    # serve CLI's autoscaler reads can_tpu_slo_alerting from it
    tel._gauge_sink = gauges
    # performance-attribution collaborators ride the same arming rule as
    # the loop instrumentation: any consumer (JSONL artifact, live
    # /metrics scraper, trace window, incident recorder, SLO engine)
    # arms the cost ledger + span tracer; a default run constructs
    # neither, so nothing new can touch its hot path.  The ledger prices
    # MFU against the run's COMPUTE dtype.
    if (args.telemetry_dir or exporter is not None or trace_window
            or incident_dir or slo_spec_path):
        tel.ledger = obs.ProgramCostLedger(
            compute="bf16" if getattr(args, "bf16", False) else "f32")
        tel.spans = obs.SpanTracer(tel)
    run_config = {k: v for k, v in vars(args).items()
                  if isinstance(v, (str, int, float, bool, type(None)))}
    if slo_spec_path:
        # the spec validate_incident_args already parsed (pre-init, so
        # a bad file can't cost a rendezvous); loaded here only for
        # callers that skipped validation.  Watcher order vs the
        # incident manager is irrelevant — slo.burn alerts reach it
        # through the bus's own watcher fan-out.
        spec = getattr(args, "_slo_spec_parsed", None)
        if spec is None:
            spec = obs.load_slo_spec(slo_spec_path)
        tel.watchers.append(obs.SloEngine(spec, tel))
    if incident_dir:
        manager = obs.IncidentManager(tel, recorder,
                                      incident_dir=incident_dir,
                                      gauges=gauges,
                                      run_config=run_config,
                                      host_id=host_id)
        tel.watchers.append(manager)
        tel.incidents = manager
        if install_signals:
            # SIGTERM/preemption: dump + flush a bundle, then SystemExit
            # into the CLI's finally -> shutdown_telemetry (same order
            # as a clean exit); None off the main thread
            obs.install_sigterm_handler(manager)
    tel.emit("run", config=run_config)
    # heartbeat whenever an artifact OR a live consumer wants liveness:
    # the exporter's last_heartbeat_ts gauge is the probe's staleness
    # signal, the ring's heartbeat tail dates a preempted bundle, and
    # heartbeats drive SLO evaluation on otherwise-quiet runs
    hb = (obs.Heartbeat(tel, args.telemetry_heartbeat_s)
          if (args.telemetry_dir or exporter is not None or incident_dir
              or slo_spec_path) else None)
    return tel, hb, exporter


def apply_compile_cache(args, *, announce: bool = False) -> None:
    from can_tpu.utils import enable_compilation_cache

    spec = getattr(args, "compile_cache", "auto")
    cache_dir = enable_compilation_cache(None if spec == "auto" else spec)
    if announce and cache_dir:
        print(f"[xla] persistent compilation cache at {cache_dir}")


def resolve_num_workers(args) -> int:
    if getattr(args, "num_workers", None) is not None:
        return max(0, args.num_workers)
    return min(8, os.cpu_count() or 1)


def main(argv=None) -> int:
    args = parse_args(argv)
    # pure arg/path validation BEFORE any runtime init: a typo'd path must
    # not cost a multi-host rendezvous
    if args.eval_interval < 1:
        # 0 conventionally means 'off' elsewhere, but here it would
        # ZeroDivisionError only AFTER a full epoch trained with nothing
        # checkpointed (code-review r5) — reject before any work
        raise SystemExit("--eval-interval must be >= 1 (the final epoch "
                         "always evaluates; large values approximate "
                         "'rarely')")
    if args.elastic_check_every < 1:
        raise SystemExit("--elastic-check-every must be >= 1")
    train_img, train_gt = resolve_split_roots(
        "train", args.train_image_root, args.train_gt_root, args.data_root)
    test_img, test_gt = resolve_split_roots(
        "test", args.test_image_root, args.test_gt_root, args.data_root)
    if args.init_torch_pth:
        if args.syncBN:
            raise SystemExit("--init-torch-pth holds the reference model "
                             "(no BatchNorm); drop --syncBN")
        if args.vgg16_npz:
            raise SystemExit("--init-torch-pth already contains the trained "
                             "frontend; drop --vgg16-npz")
        if args.init_checkpoint:
            raise SystemExit("--init-torch-pth (fresh warm-start) and "
                             "--init_checkpoint (full-state resume) "
                             "conflict — the resume would silently replace "
                             "the warm-started params; pick one")
        if not os.path.isfile(args.init_torch_pth):
            raise SystemExit(f"no such checkpoint file: {args.init_torch_pth}")
    if args.item_cache_mb < 0:
        raise SystemExit("--item-cache-mb must be >= 0")
    # resume-config guard (pure file reading, BEFORE any runtime init):
    # a schedule-bearing flag that silently differs from the checkpoint's
    # run breaks the cosine schedule / data order the resumed state
    # assumes — fail here unless the drift is explicitly allowed
    run_cfg = {"lr": args.lr, "lrf": args.lrf, "epochs": args.epochs,
               "batch_size": args.batch_size, "seed": args.seed,
               "syncBN": bool(args.syncBN), "bf16": bool(args.bf16)}
    from can_tpu.utils.checkpoint import (
        ConfigDriftError,
        check_resume_config,
        has_checkpoint,
        load_run_config,
        save_run_config,
    )

    if args.init_checkpoint:
        from can_tpu.parallel.elastic import load_manifest as _el_manifest

        saved_cfg = load_run_config(args.init_checkpoint)
        # guard only REAL resumes: a config with no checkpoint beside it
        # (a run that crashed before its first save) cold-starts, and a
        # cold start has no restored schedule to protect.  A preemption
        # BEFORE the first epoch save leaves no integer step dir but DOES
        # leave an elastic manifest + shrink checkpoint — that mid-epoch
        # state's schedule needs the guard every bit as much (elastic is
        # a world change, never a licence for schedule drift).
        # world_size itself is checked POST-init (dp is unknown before
        # devices exist) with the elastic allowance — strip it here
        resumable = (has_checkpoint(args.init_checkpoint)
                     or _el_manifest(args.init_checkpoint) is not None)
        if saved_cfg is not None and resumable:
            sched_cfg = {k: v for k, v in saved_cfg.items()
                         if k != "world_size"}
            try:
                drifted = check_resume_config(sched_cfg, run_cfg,
                                              allow=args.allow_config_change)
            except ConfigDriftError as e:
                raise SystemExit(f"{e} (pass --allow-config-change to "
                                 "resume with the new schedule anyway)")
            if drifted:
                print(f"[resume] config drift allowed: {', '.join(drifted)}")
    trace_window = validate_trace_args(args)
    validate_incident_args(args)
    # per-step instrumentation is on when ANY consumer exists: JSONL
    # artifact, trace window, live /metrics scraper, incident recorder,
    # or SLO engine.  Known before any runtime work so the step builders
    # can compile the health scalars in; a default run keeps the exact
    # pre-PR programs.
    instrument = bool(args.telemetry_dir or trace_window
                      or args.metrics_port is not None
                      or args.incident_dir or args.slo_spec)
    apply_platform(args)
    topo = init_runtime()
    # the elastic supervisor's SIGTERM hook: installed AFTER init_runtime
    # (jax.distributed.initialize registers XLA's own preemption notifier
    # at initialize, clobbering handlers installed earlier) and BEFORE
    # the incident manager's (build_telemetry, inside the generation
    # loop): the manager then dumps the preemption bundle FIRST and
    # chains here — which sets the leaving flag and RETURNS, spending the
    # grace window on the shrink choreography instead of exiting
    # mid-collective
    supervisor = None
    if args.elastic_dir:
        from can_tpu.parallel.elastic import ElasticSupervisor

        supervisor = ElasticSupervisor(
            args.elastic_dir, check_every=args.elastic_check_every)
        supervisor.install_signal_hook()
    apply_compile_cache(args, announce=is_main_process())
    if is_main_process():
        print(f"[runtime] {topo}")
        print(f"[start] {datetime.datetime.now():%Y-%m-%d %H:%M:%S}")
        if args.syncBN:
            print("[model] BatchNorm variant; stats sync across replicas "
                  f"via global-batch reductions (moments path: "
                  f"{args.bn_impl})")
    return _run_elastic_generations(
        args, run_cfg, topo, supervisor=supervisor,
        trace_window=trace_window, instrument=instrument,
        split_roots=(train_img, train_gt, test_img, test_gt),
        save_run_config=save_run_config,
        check_resume_config=check_resume_config)


def _run_elastic_generations(args, run_cfg, topo, *, supervisor,
                             trace_window, instrument, split_roots,
                             save_run_config, check_resume_config) -> int:
    """The generation loop: build the world, train; on an agreed elastic
    shrink, checkpoint + tear down + re-rendezvous and loop — every
    iteration is one runtime generation (parallel/runtime.py).  The
    telemetry stack and datasets are built once and survive transitions;
    everything device-bound (mesh, steps, batchers, state) is rebuilt
    per generation.  Pre-elastic runs execute exactly one iteration."""
    from can_tpu.parallel import elastic as el
    from can_tpu.utils.checkpoint import CheckpointIOError, ConfigDriftError

    train_img, train_gt, test_img, test_gt = split_roots
    compute_dtype = jnp.bfloat16 if args.bf16 else None
    pad_multiple, min_pad, min_bucket_h = resolve_sp_padding(
        args.pad_multiple, args.sp)

    from can_tpu.cli.common import split_prepared_spec
    from can_tpu.data import ItemCache, StaleStoreError

    # datasets + item cache are world-INDEPENDENT (host-side decode):
    # built once, they survive elastic transitions — only device-bound
    # objects rebuild per generation
    item_cache = (ItemCache(int(args.item_cache_mb * 1e6))
                  if args.item_cache_mb > 0 else None)
    try:
        train_ds = CrowdDataset(train_img, train_gt, gt_downsample=8,
                                phase="train", u8_output=args.u8_input,
                                prepared=split_prepared_spec(
                                    args.prepared_root, "train"),
                                item_cache=item_cache)
        test_ds = CrowdDataset(test_img, test_gt, gt_downsample=8,
                               phase="test", u8_output=args.u8_input,
                               prepared=split_prepared_spec(
                                   args.prepared_root, "test"),
                               item_cache=item_cache)
    except StaleStoreError as e:
        raise SystemExit(f"--prepared-root {args.prepared_root}: {e}")
    num_workers = resolve_num_workers(args)

    # cross-generation context: the telemetry stack is built by the FIRST
    # generation and survives transitions (the elastic.transition event
    # rides the same bus as everything else); pending_manifest hands the
    # shrink record from the dying generation to the next iteration
    ctx = {"telemetry": None, "heartbeat": None, "exporter": None,
           "logger": None, "pending_manifest": None, "best_mae": None,
           "generations": 0}

    def run_generation():
        """One runtime generation: build the world at the CURRENT
        process_count/device set, (elastic-)resume, train.  Returns
        ("done"|"abort", rc) or ("reform", None) or ("leave", rc)."""
        from can_tpu.utils.checkpoint import has_checkpoint, load_run_config

        ctx["generations"] += 1
        first_gen = ctx["generations"] == 1
        main_proc = is_main_process()
        mesh, host_batch, dp = build_mesh_and_batch(args.batch_size, args.sp)
        # SyncBN moments path (ops/bn_moments.py): built only for
        # --syncBN so a default run constructs nothing new — its lowered
        # step must stay byte-identical (tests/test_batchnorm.py)
        bn_ops = None
        if args.syncBN:
            from can_tpu.ops.bn_moments import make_bn_ops

            if args.bn_impl == "pallas":
                if args.sp == 1 and dp > 1:
                    # pallas_call has no GSPMD partitioning rule: under
                    # the jit-sharded dp step it would force a gather;
                    # inside the sp shard_map body it composes fine
                    raise SystemExit(
                        "--bn-impl pallas needs --sp > 1 (the kernel "
                        "runs per-device inside shard_map) or a single "
                        "device; use onepass for the GSPMD data-parallel "
                        "step")
                bn_ops = make_bn_ops("pallas",
                                     interpret=jax.default_backend() != "tpu")
            else:
                bn_ops = make_bn_ops(args.bn_impl)
        if args.sp > 1 and main_proc and first_gen and pad_multiple != "auto":
            print(f"[data] sp={args.sp}: padding H,W to multiples of "
                  f"{pad_multiple}")
        import math as _math

        # legal remnant sub-batch sizes must split evenly across hosts
        # AND across the mesh's dp axis (make_global_batch shards the
        # leading dim).  The quantum is a property of THIS generation's
        # world: after a shrink the planner replans under the new one.
        quantum = _math.lcm(dp, process_count())
        common = dict(seed=args.seed, process_index=process_index(),
                      process_count=process_count(),
                      pad_multiple=pad_multiple,
                      min_pad_multiple=min_pad, min_bucket_h=min_bucket_h,
                      num_workers=num_workers, max_buckets=args.max_buckets,
                      remnant_sizes=not args.no_remnant_batches,
                      batch_quantum=quantum, plan_mode=args.plan_mode,
                      launch_cost_px=resolve_launch_cost_px(
                          args.launch_cost_mpx,
                          announce=main_proc and first_gen))
        # HBM agreed across hosts (min) ONCE PER GENERATION: both the
        # launch cap and the remat policy must be identical on every host
        # or the lockstep schedule deadlocks (ADVICE r4)
        from can_tpu.cli.common import agreed_device_memory_bytes

        hbm = agreed_device_memory_bytes()
        ndev = dp * args.sp  # devices per launch
        if not args.no_remnant_batches:
            # HBM cap per launch: bucket cells too big for the full
            # global batch run at a smaller menu size instead of OOMing
            from can_tpu.cli.common import max_launch_pixels

            train_common = dict(common,
                                max_launch_px=max_launch_pixels(
                                    bf16=args.bf16, hbm_bytes=hbm,
                                    shards=ndev))
        else:
            train_common = common
        train_batcher = ShardedBatcher(train_ds, host_batch, shuffle=True,
                                       **train_common)
        test_batcher = ShardedBatcher(test_ds, host_batch, shuffle=False,
                                      **common)
        if main_proc:
            print(f"[data] train={len(train_ds)} test={len(test_ds)} "
                  f"host_batch={host_batch} dp={dp} sp={args.sp} "
                  f"workers={num_workers}")
            # compile-count telemetry: every distinct bucket shape
            # compiles its own executable — the first-epoch compile bill
            for tag, b in (("train", train_batcher), ("test", test_batcher)):
                n = b.distinct_shapes(0)
                print(f"[data] {tag}: buckets={b.describe_buckets()} -> "
                      f"{n} distinct batch shapes, "
                      f"{b.program_count(0)} (shape x size) programs "
                      f"(plan={b.plan_mode}, "
                      f"padding overhead {b.padding_overhead():.1%}, "
                      f"schedule overhead {b.schedule_overhead(0):.1%})")
                if n > 4 * b.max_buckets:
                    print(f"[data] WARNING: {n} shapes will each compile "
                          f"a program; use --pad-multiple auto to bound "
                          f"this")

        # identical init on every host by construction: same seed/key
        params = cannet_init(jax.random.key(args.seed),
                             batch_norm=args.syncBN)
        if args.vgg16_npz:
            params = load_vgg16_frontend(params, args.vgg16_npz)
            if main_proc and first_gen:
                print(f"[init] loaded pretrained VGG-16 frontend from "
                      f"{args.vgg16_npz}")
        if args.init_torch_pth:
            # the reference's .pth warm-start — params from the torch
            # checkpoint, optimizer/step fresh; deterministic file read
            # on every host => identical init holds
            from can_tpu.utils.torch_import import load_torch_checkpoint

            params = load_torch_checkpoint(args.init_torch_pth)
            if main_proc and first_gen:
                print(f"[init] warm-started params from reference "
                      f"checkpoint {args.init_torch_pth}")

        # the epoch-0 count is exact for EVERY epoch (the plan is a pure
        # function of the shape histogram), so the cosine schedule's
        # endpoint lands exactly on the last step.  After an elastic
        # shrink this recomputes at dp': world_size=dp' IS the linear
        # lr-rescaling rule, and total_steps re-prices the remaining run
        # at the new schedule granularity — both recorded in the
        # elastic.transition event.
        steps_per_epoch = train_batcher.batches_per_epoch(0)
        # priced prefetch depth (the scheduling core's 4th consumer):
        # tiny launches amortise the per-launch dispatch overhead over
        # little compute and need a deeper host pipeline; the historical
        # depth=2 is exactly what the pricing returns for normal batches
        from can_tpu.sched import prefetch_depth_for

        prefetch = prefetch_depth_for(train_batcher)
        # computed ONCE per generation: the depth is a pure function of
        # the batcher's epoch-invariant schedule, and global_schedule(0)
        # is an O(dataset) rebuild — not something the per-epoch eval
        # block should pay
        eval_prefetch = prefetch_depth_for(test_batcher)
        schedule = make_lr_schedule(args.lr, world_size=dp,
                                    total_steps=args.epochs * steps_per_epoch,
                                    lrf=args.lrf)
        optimizer = make_optimizer(schedule)
        state = create_train_state(params, optimizer,
                                   init_batch_stats(params))

        ckpt = CheckpointManager(args.checkpoint_dir)
        # NOTE: the run config (incl. this generation's world_size) is
        # persisted AFTER resume resolution — on an in-place resume
        # (--init_checkpoint == --checkpoint-dir) writing it first would
        # overwrite the saved world_size the drift check below is about
        # to read, neutering the guard

        # -- resume resolution -------------------------------------------
        # priority: an in-process shrink manifest (the generation that
        # just dissolved), else — first generation only — a live elastic
        # manifest in --init_checkpoint (cold restart after preemption),
        # else the normal latest-epoch resume.
        manifest = None
        resumed_from = None
        manifest_dir = None
        start_epoch = 0
        resumed_best = ctx["best_mae"]
        include = None
        if ctx["pending_manifest"] is not None:
            manifest = ctx["pending_manifest"]
            ctx["pending_manifest"] = None
            resumed_from = "in_process"
            manifest_dir = args.checkpoint_dir
        elif first_gen and args.init_checkpoint:
            probe = CheckpointManager(args.init_checkpoint)
            try:
                latest = probe.latest_epoch()
                m = el.load_manifest(args.init_checkpoint)
                if el.manifest_is_live(m, latest):
                    manifest = m
                    resumed_from = "cold_restart"
                    manifest_dir = args.init_checkpoint
                    resumed_best = probe.best_metric()
                    # the drift guard with the ELASTIC allowance: the
                    # live manifest is the permit for a dp-only world
                    # change — anything else would have failed the
                    # schedule-key check pre-init
                    saved_cfg = load_run_config(args.init_checkpoint)
                    if (saved_cfg is not None
                            and "world_size" in saved_cfg):
                        drifted = check_resume_config(
                            {"world_size": saved_cfg["world_size"]},
                            {"world_size": dp},
                            allow=args.allow_config_change,
                            allow_elastic=True)
                        if drifted and main_proc:
                            print(f"[elastic] world drift permitted by "
                                  f"the live transition manifest: "
                                  f"world_size "
                                  f"{saved_cfg['world_size']} -> {dp}")
                else:
                    # the drift guard's world check: a saved world_size
                    # that differs from this world is only legal when an
                    # elastic transition explains it
                    saved_cfg = load_run_config(args.init_checkpoint)
                    if (saved_cfg is not None
                            and has_checkpoint(args.init_checkpoint)
                            and "world_size" in saved_cfg):
                        try:
                            check_resume_config(
                                {"world_size": saved_cfg["world_size"]},
                                {"world_size": dp},
                                allow=args.allow_config_change,
                                allow_elastic=False)
                        except ConfigDriftError as e:
                            raise SystemExit(
                                f"{e} — the checkpoint trained at a "
                                f"different world size and no live "
                                f"elastic manifest explains the change "
                                f"(pass --allow-config-change to resume "
                                f"on the new world anyway)")
                    if latest is not None:
                        state = probe.restore(state)
                        start_epoch = latest + 1
                        # carry the prior leg's best forward so
                        # [best]/[done] report the RUN's best
                        resumed_best = probe.best_metric()
                        if main_proc:
                            print(f"[resume] epoch {latest} from "
                                  f"{args.init_checkpoint}"
                                  + (f" (best so far {resumed_best:.3f})"
                                     if resumed_best is not None else ""))
                    elif main_proc:
                        print(f"[resume] no checkpoint in "
                              f"{args.init_checkpoint}; cold start")
            finally:
                # the restore manager must not stay alive for the whole
                # run — its stale step/metrics view aliases ckpt's
                # directory on an in-place resume (code-review r5)
                probe.close()
        if manifest is not None:
            # elastic resume: restore the EXACT mid-epoch state from the
            # shrink checkpoint, replan the interrupted epoch's remaining
            # items at this world's quantum (exact coverage: consumed ∪
            # remaining = the epoch, pinned by tests), rescale via the
            # dp'-built schedule above
            emgr = CheckpointManager(
                os.path.join(manifest_dir, el.ELASTIC_SUBDIR))
            try:
                state = emgr.restore(state,
                                     epoch=int(manifest["transition_id"]))
            finally:
                emgr.close()
            start_epoch = int(manifest["epoch"])
            rem = el.remaining_items(manifest, len(train_ds))
            include = set(rem) if rem else None
            if not rem:
                start_epoch += 1  # interrupted exactly at the epoch end
            if supervisor is not None:
                # inherit the transition's host bookkeeping (rank
                # re-numbering + handled leavers) so a stale signal file
                # cannot re-trigger the shrink this manifest records
                supervisor.adopt_manifest(manifest)
            if main_proc:
                w_old = manifest["world_old"]
                print(f"[elastic] resuming generation "
                      f"{manifest['generation']} transition: epoch "
                      f"{manifest['epoch']} step {manifest['steps_done']}"
                      f", world {w_old['processes']}proc/dp{w_old['dp']}"
                      f" -> {process_count()}proc/dp{dp}, "
                      f"{len(rem)} item(s) remaining ({resumed_from})")
        if main_proc:
            # persist the schedule-bearing config + this generation's
            # world beside the checkpoints (AFTER the resume resolution
            # read the previous one): the NEXT resume checks flag drift,
            # and a dp-only world change is legal exactly when an
            # elastic manifest explains it
            save_run_config(args.checkpoint_dir,
                            dict(run_cfg, world_size=dp))

        apply_fn = cannet_apply
        if args.s2d_stem:
            if args.sp > 1:
                raise SystemExit("--s2d-stem is dp-path only (the sp "
                                 "step builds its own sharded apply)")
            import functools

            apply_fn = functools.partial(cannet_apply, s2d_stem=True)
        if bn_ops is not None and args.sp == 1:
            import functools

            from can_tpu.models.cannet import LocalOps

            # the BN-moments seam rides LocalOps beside context_fused;
            # dp-path only (the sp step takes bn_ops directly)
            apply_fn = functools.partial(apply_fn,
                                         ops=LocalOps(bn_ops=bn_ops))
        remat_policy = make_remat_policy(args.remat,
                                         global_batch=args.batch_size * dp,
                                         bf16=args.bf16,
                                         announce=main_proc and first_gen,
                                         hbm_bytes=hbm, shards=ndev)
        if args.sp > 1:
            cache = SpatialStepCache(
                lambda hw: make_sp_train_step(optimizer, mesh, hw,
                                              compute_dtype=compute_dtype,
                                              remat=remat_policy(hw),
                                              health_metrics=instrument,
                                              bn_ops=bn_ops))

            def train_step(state, batch):
                return cache(tuple(batch["image"].shape[1:3]))(state, batch)

            # cost-ledger seam: the underlying jitted step for these
            # args, so cost_analysis() reads through the closure
            train_step.jit_for = lambda state, batch: cache(
                tuple(batch["image"].shape[1:3]))
            eval_step = make_cached_sp_eval_step(
                mesh, compute_dtype=compute_dtype)
        else:
            from can_tpu.cli.common import make_bucketed_train_step

            train_step = make_bucketed_train_step(
                apply_fn, optimizer, mesh, compute_dtype=compute_dtype,
                policy=remat_policy, health_metrics=instrument)
            eval_step = make_dp_eval_step(apply_fn, mesh,
                                          compute_dtype=compute_dtype)
        # batches are H-sharded when sp > 1 (train and eval both)
        put = lambda b: make_global_batch(b, mesh, spatial=args.sp > 1)

        if first_gen:
            ctx["logger"] = MetricLogger(
                use_wandb=args.wandb, enabled=main_proc,
                name=f"bs{args.batch_size}x{dp}", config=vars(args),
                run_id_file=os.path.join(args.checkpoint_dir,
                                         "wandb_run_id.txt"))
            # telemetry: per-host JSONL (+ MetricLogger adapter),
            # heartbeat thread, and the step-range trace trigger — built
            # ONCE; elastic transitions keep emitting into the same bus
            ctx["telemetry"], ctx["heartbeat"], ctx["exporter"] = \
                build_telemetry(args, host_id=process_index(),
                                trace_window=trace_window,
                                logger=ctx["logger"])
            if supervisor is not None:
                supervisor.telemetry = ctx["telemetry"]
            # prepared-store status: one data.prepared event per split
            for split, d in (("train", train_ds), ("test", test_ds)):
                ctx["telemetry"].emit("data.prepared", split=split,
                                      **d.prepared_note)
            if main_proc:
                print("[data] prepared store: " + " ".join(
                    f"{split}={'on' if d.prepared_note['active'] else 'legacy(' + str(d.prepared_note['reason']) + ')'}"
                    for split, d in (("train", train_ds),
                                     ("test", test_ds))))
        if not first_gen:
            # a transition may have promoted a DIFFERENT host to main
            # (the old rank 0 left): the once-constructed logger follows
            # the role, or stdout/wandb epoch rows silently stop for the
            # rest of the run.  (A wandb stream stays owned by the
            # original main if it left — re-initialising a wandb run
            # mid-process isn't supported; stdout rows resume.)
            ctx["logger"].enabled = main_proc
        telemetry = ctx["telemetry"]
        if telemetry.ledger is not None:
            # the drift gauge's denominator: the launch cost THIS run's
            # plans were priced at
            telemetry.ledger.plan_launch_cost_px = common["launch_cost_px"]
        if manifest is not None:
            # the transition record: world change + rescaling, exactly
            # once per transition (survivor leg or cold restart).
            # Through the supervisor when armed — its transitions
            # counter then covers cold restarts too
            topo_now = {"generation": runtime_generation(),
                        "process_count": process_count()}
            emitter = (supervisor.emit_transition
                       if supervisor is not None else None)
            if emitter is None:
                def emitter(m, t, **kw):
                    el.emit_transition(telemetry, m, t, **kw)
            emitter(manifest, topo_now, new_dp=dp,
                    remaining=0 if include is None else len(include),
                    global_batch_new=host_batch * process_count(),
                    resumed_from=resumed_from)
        # the LOOPS are instrumented only when something consumes
        # per-step data: the default run's hot path stays byte-identical
        loop_tel = telemetry if instrument else None
        from can_tpu.obs import HealthMonitor

        health = HealthMonitor(telemetry) if loop_tel is not None else None
        best_mae = (float("inf") if resumed_best is None
                    else float(resumed_best))
        world_closed = False  # elastic branch closes early, pre-reform
        try:
            with profile_trace(None if trace_window
                               else (args.profile_dir or None)):
                for epoch in range(start_epoch, args.epochs):
                    inc = include if epoch == start_epoch else None
                    total = (steps_per_epoch if inc is None else
                             len(train_batcher.global_schedule(epoch, inc)))
                    batches = train_batcher.epoch(epoch, inc)
                    if args.max_steps_per_epoch:
                        import itertools

                        batches = itertools.islice(
                            batches, args.max_steps_per_epoch)
                    on_step = (supervisor.step_hook(epoch)
                               if supervisor is not None else None)
                    try:
                        state, stats = train_one_epoch(
                            train_step, state, batches, put_fn=put,
                            epoch=epoch, show_progress=main_proc,
                            total=total, telemetry=loop_tel,
                            health=health, on_step=on_step,
                            prefetch=prefetch)
                    except el.ElasticInterrupt as interrupt:
                        # the agreed shrink point: flush any in-flight
                        # async save FIRST (its arrays must reach disk
                        # while the old world's backends are alive),
                        # checkpoint at a bounded barrier, then leave or
                        # re-form
                        ckpt.wait()
                        sched = train_batcher.global_schedule(epoch, inc)
                        # prior coverage exists only while TRAINING the
                        # resumed remainder itself (inc is not None): a
                        # manifest whose remainder was empty bumped
                        # start_epoch, and its consumed set belongs to
                        # the FINISHED epoch, not this one
                        prior = (manifest.get("consumed", ())
                                 if manifest is not None
                                 and inc is not None else ())
                        new_manifest = supervisor.shrink(
                            interrupt, state=interrupt.state, epoch=epoch,
                            checkpoint_dir=args.checkpoint_dir,
                            schedule=sched, dp=dp, sp=args.sp,
                            batch_size=host_batch, prior_consumed=prior)
                        ctx["best_mae"] = (None if best_mae == float("inf")
                                           else best_mae)
                        # device-bound teardown BEFORE leave/reform:
                        # reform() resets the PJRT backends, and the
                        # generation's finally must not wait on Orbax
                        # ops whose arrays' backend no longer exists
                        train_batcher.close()
                        test_batcher.close()
                        ckpt.close()
                        world_closed = True
                        if process_index() in new_manifest["leavers"]:
                            if main_proc:
                                print("[elastic] leaving after shrink "
                                      "checkpoint (preempted)")
                            return ("leave", supervisor.leave())
                        supervisor.reform(new_manifest)
                        ctx["pending_manifest"] = new_manifest
                        return ("reform", None)
                    # every epoch: loss, throughput, shape count
                    epoch_metrics = {
                        "train_loss": stats.loss,
                        "lr": float(schedule(int(state.step))),
                        "img_per_s": round(stats.img_per_s, 2),
                        "epoch_s": round(stats.seconds, 2),
                        "distinct_shapes": stats.distinct_shapes,
                    }

                    # always evaluate+checkpoint the FINAL epoch too
                    eval_epoch = ((epoch + 1) % args.eval_interval == 0
                                  or epoch == args.epochs - 1)
                    if eval_epoch:
                        metrics = evaluate(
                            eval_step, state.params, test_batcher.epoch(0),
                            put_fn=put,
                            dataset_size=test_batcher.dataset_size,
                            batch_stats=state.batch_stats,
                            telemetry=loop_tel,
                            prefetch=eval_prefetch)
                        mae = metrics["mae"]
                        epoch_metrics.update(mae=mae, mse=metrics["mse"])
                    # through the bus: MetricLoggerSink forwards scalars
                    # to stdout/wandb; JSONL records the epoch event
                    telemetry.emit("epoch", step=epoch, **epoch_metrics)
                    telemetry.emit("data.planner", step=epoch,
                                   realized_programs=stats.programs,
                                   **train_batcher.planner_stats(epoch))
                    if item_cache is not None:
                        telemetry.emit("data.cache", step=epoch,
                                       **item_cache.stats())
                    if eval_epoch:
                        ckpt.save(epoch, state, mae=mae,
                                  extra={"mse": metrics["mse"]})
                        if mae < best_mae:
                            best_mae = mae
                            ctx["best_mae"] = best_mae
                            if main_proc:
                                print(f"[best] epoch {epoch}: "
                                      f"MAE {mae:.3f}")
                        if args.show and main_proc:
                            _save_sample_viz(args, state, test_ds, epoch,
                                             ctx["logger"])
        except NonFiniteLossError as e:
            print(f"[abort] {e}", file=sys.stderr)
            return ("abort", 1)
        except CheckpointIOError as e:
            # the typed give-up after exhausted retries: one incident
            # bundle (when armed), then a clean abort — the run cannot
            # promise resumability without its checkpoint
            inc_mgr = getattr(telemetry, "incidents", None)
            if inc_mgr is not None:
                inc_mgr.on_exception(e, phase="checkpoint")
            print(f"[abort] {e}", file=sys.stderr)
            return ("abort", 1)
        finally:
            if not world_closed:
                train_batcher.close()
                test_batcher.close()
                ckpt.wait()
                ckpt.close()
        ctx["best_mae"] = None if best_mae == float("inf") else best_mae
        if main_proc:
            print(f"[done] best MAE {best_mae:.3f}")
        return ("done", 0)

    from can_tpu.parallel.runtime import generation as runtime_generation

    try:
        while True:
            outcome, rc = run_generation()
            if outcome != "reform":
                return rc
            # else: a new generation formed — loop rebuilds the world
    finally:
        # one deterministic teardown order for clean exit, abort, leave,
        # AND the SIGTERM path (obs/lifecycle.py): heartbeat ->
        # watchers+sinks -> exporter; then the supervisor's signal hook
        # and the runtime (idempotent after a leave)
        if ctx["telemetry"] is not None:
            from can_tpu.obs import shutdown_telemetry

            shutdown_telemetry(ctx["telemetry"], heartbeat=ctx["heartbeat"],
                               exporter=ctx["exporter"])
        if ctx["logger"] is not None:
            ctx["logger"].finish()
        if supervisor is not None:
            supervisor.close()
        shutdown_runtime()  # the reference never calls its cleanup()



_viz_forward = None  # module-level so repeat shapes hit the jit cache


def _save_sample_viz(args, state, test_ds, epoch, logger) -> None:
    from can_tpu.utils import save_density_visualization

    global _viz_forward
    if _viz_forward is None:
        from can_tpu.cli.common import make_inference_forward

        _viz_forward = make_inference_forward()
    from can_tpu.data import normalize_host

    idx = int(np.random.default_rng((args.seed, epoch)).integers(len(test_ds)))
    img, gt = test_ds[idx]
    img = normalize_host(img)  # no-op for the f32 path
    # This runs on rank 0 ONLY, so it must not issue a computation over
    # the globally-committed params (unmatched multi-host computation =
    # error or pod wedge, code-review r5): pull the replicated params to
    # host (a local read of addressable shards) and jit over local
    # arrays instead.
    host_params = jax.device_get(state.params)
    host_stats = (jax.device_get(state.batch_stats)
                  if state.batch_stats is not None else None)
    et = _viz_forward(host_params, jnp.asarray(img)[None], host_stats)
    out_dir = os.path.join(args.checkpoint_dir, "temp")
    paths = save_density_visualization(img, gt, np.asarray(et)[0], out_dir,
                                       tag=f"epoch{epoch}")
    logger.log_images(paths, caption=f"epoch {epoch}", step=epoch)


if __name__ == "__main__":
    raise SystemExit(main())
