"""Instrumentation sources: where the events come from.

Each source measures one TPU-specific failure mode the loop comments used
to only WARN about:

* ``RecompileTracker`` — silent recompiles.  Every new ``(shape, dtype)``
  batch signature hitting a jitted step costs a trace+lower+compile on the
  calling thread; before this, ``EpochStats.distinct_shapes`` was a bare
  count with no timing or attribution.
* ``StallClock`` — input-pipeline starvation: seconds the consumer spent
  blocked waiting for ``prefetch_to_device``'s next batch.
* ``device_memory_snapshot`` / ``emit_memory`` — HBM pressure from
  in-flight staged batches, via PJRT ``memory_stats()`` where the client
  implements it (host RSS as the always-available fallback: CPU and the
  axon tunnel report no device stats).
* ``Heartbeat`` — a liveness timestamp every N seconds from a daemon
  thread, so a hung run leaves a last-known-good timestamp in the artifact
  instead of a file that just stops.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional


class RecompileTracker:
    """Wrap a (jitted) step callable; attribute each NEW batch signature.

    The first call carrying an unseen ``(name, shape, dtype)`` signature is
    timed end-to-end and emitted as a ``compile`` event: under jit the
    first call with a new signature blocks on trace + lower + compile
    before dispatching, so its wall time IS the compile bill (plus one
    dispatch — noise next to any real compile).  Signatures live in
    ``telemetry.signature_registry[name]``, not on the wrapper, so
    re-wrapping the step every epoch doesn't re-attribute old shapes.

    ``batch_arg``: positional index of the batch dict in the wrapped
    callable's signature (1 for ``train_step(state, batch)`` and
    ``eval_step(params, batch, ...)``).

    ``last_first_call`` is True right after a call that hit a new
    signature — callers timing steps around this wrapper use it to keep
    compile wall time OUT of their steady-state step distribution (it is
    already fully accounted by the ``compile`` event; recording it twice
    would let one 10 s compile masquerade as the step p95/max)."""

    def __init__(self, fn: Callable, telemetry, *, name: str = "step",
                 batch_arg: int = 1):
        from can_tpu.train.steps import batch_signature

        self._fn = fn
        self._tel = telemetry
        self._name = name
        self._batch_arg = batch_arg
        self._signature = batch_signature
        self._seen = telemetry.signature_registry.setdefault(name, {})
        self.last_first_call = False

    def jit_for(self, *args):
        """The underlying jitted callable for these args — the same hook
        the bucketed/spatial dispatch closures expose, so the cost ledger
        and the HLO auditor (``obs.costs.resolve_jit``) can lower the
        EXACT program this wrapper dispatches.  Chains through a wrapped
        callable that itself exposes ``jit_for``."""
        inner = getattr(self._fn, "jit_for", None)
        return inner(*args) if inner is not None else self._fn

    def __call__(self, *args):
        sig = self._signature(args[self._batch_arg])
        if sig in self._seen:
            self.last_first_call = False
            return self._fn(*args)
        self.last_first_call = True
        t0 = time.perf_counter()
        out = self._fn(*args)
        dt = time.perf_counter() - t0
        self._seen[sig] = dt
        payload = dict(name=self._name, signature=[list(s) for s in sig],
                       seconds=round(dt, 4), n_signatures=len(self._seen))
        # perf-attribution hook: with a ProgramCostLedger on the bus
        # (Telemetry.ledger, armed by the CLIs), the new signature's XLA
        # cost_analysis() flops/bytes are read at compile time and ride
        # this same compile event; backends that report nothing degrade
        # to the bare payload (the ledger never raises into the step)
        ledger = getattr(self._tel, "ledger", None)
        if ledger is not None:
            cost = ledger.register(self._name, sig, fn=self._fn, args=args)
            if cost is not None:
                payload.update(cost)
        self._tel.emit("compile", **payload)
        return out


class StallClock:
    """Accumulates time a consumer spent BLOCKED on its input pipeline.

    ``prefetch_to_device(..., stall=clock)`` adds to it only when the next
    batch's future wasn't already done — i.e. genuine starvation, not the
    cost of the (already overlapped) load itself."""

    __slots__ = ("seconds", "count")

    def __init__(self):
        self.seconds = 0.0
        self.count = 0

    def add(self, dt: float) -> None:
        self.seconds += dt
        self.count += 1


def device_memory_snapshot() -> dict:
    """Best-effort memory accounting: per-local-device PJRT stats where the
    client implements ``memory_stats()`` (real TPUs), host RSS always.

    ``jax.local_devices()``, not ``jax.devices()``: on a pod, non-local
    devices' stats are unreadable off their host (ADVICE r4)."""
    devices = []
    try:
        import jax

        for d in jax.local_devices():
            rec = {"id": d.id, "platform": d.platform}
            try:
                stats = d.memory_stats()
            # can-tpu-lint: disable=SWALLOW(memory_stats is optional per PJRT client; the device row still lands)
            except Exception:
                stats = None
            if stats:
                for key in ("bytes_in_use", "peak_bytes_in_use",
                            "bytes_limit", "largest_alloc_size"):
                    if key in stats:
                        rec[key] = int(stats[key])
            devices.append(rec)
    # can-tpu-lint: disable=SWALLOW(backend not initialised / unreachable: host RSS still lands)
    except Exception:
        pass  # backend not initialised / unreachable: host RSS still lands
    snap = {"devices": devices, "host_rss_mb": _host_rss_mb()}
    return snap


def _host_rss_mb() -> Optional[float]:
    try:
        import resource

        rss_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        return round(rss_kb / 1024.0, 1)  # linux reports KiB
    # can-tpu-lint: disable=SWALLOW(resource module is unix-only; None row is the degrade)
    except Exception:  # pragma: no cover — non-unix
        return None


def emit_memory(telemetry, *, step: Optional[int] = None,
                where: str = "") -> None:
    """One ``memory`` event: epoch boundaries and on-demand probes."""
    telemetry.emit("memory", step=step, where=where,
                   **device_memory_snapshot())


class Heartbeat:
    """Daemon thread emitting a ``heartbeat`` event every ``interval_s``.

    One event fires immediately at start (the last-known-good baseline a
    short run still records), then every interval until ``close()``.
    Payload carries the run-local step counter, so a wedged run's artifact
    says how far it got, not just when it died — plus a monotonic ``seq``
    and the process-start ``start_ts``, so a reader of an APPENDED file
    (same run dir, new process) can tell a restarted process (``start_ts``
    changes, ``seq`` resets) from a resumed stream (``tools/run_monitor.py``
    counts the restarts).  ``interval_s <= 0`` disables the thread entirely
    (NOT a floor — a 0 interval flooding ~100 fsync'd events/second into
    the file would be worse than none)."""

    def __init__(self, telemetry, interval_s: float = 60.0,
                 *, start: bool = True):
        self._tel = telemetry
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._t0 = time.time()
        self._seq = 0
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="can-tpu-heartbeat")
        if start and self.interval_s > 0:
            self._thread.start()

    def _run(self) -> None:
        while True:
            self._tel.emit("heartbeat",
                           uptime_s=round(time.time() - self._t0, 3),
                           seq=self._seq, start_ts=round(self._t0, 3))
            self._seq += 1
            if self._stop.wait(self.interval_s):
                return

    def close(self) -> None:
        self._stop.set()
        if self._thread.is_alive():  # pragma: no branch
            self._thread.join(timeout=5.0)
