"""can_tpu.obs — structured telemetry: event bus, sources, trace windows.

Quickstart (what the CLIs wire up from ``--telemetry-dir``)::

    from can_tpu import obs

    tel = obs.open_host_telemetry(out_dir, host_id=process_index())
    hb = obs.Heartbeat(tel, interval_s=60)
    try:
        state, stats = train_one_epoch(step, state, batches,
                                       put_fn=put, telemetry=tel)
        tel.emit("epoch", step=epoch, train_loss=stats.loss)
    finally:
        hb.close()
        tel.close()

Every layer that does device work takes an optional ``telemetry`` and
stays zero-cost when it is None — the hot path never pays for
observability it didn't ask for.
"""

from .bus import (
    EVENT_KINDS,
    JsonlSink,
    MetricLoggerSink,
    StdoutSink,
    Telemetry,
    open_host_telemetry,
)
from .collector import COLLECTOR_HOST_ID, CollectorPushSink, FleetCollector
from .costs import ProgramCostLedger
from .exporter import GaugeSink, MetricsExporter, aggregate_fleet, render_stats
from .flightrec import FlightRecorder
from .health import (
    EwmaMadDetector,
    HealthMonitor,
    PlateauDetector,
    ThroughputDetector,
)
from .incidents import IncidentManager, install_sigterm_handler
from .lifecycle import shutdown_telemetry, supervised_loop
from .report import format_report, read_events, read_events_counted, summarize
from .sources import (
    Heartbeat,
    RecompileTracker,
    StallClock,
    device_memory_snapshot,
    emit_memory,
)
from .slo import SloEngine, SloObjective, SloSpec, grade_events, load_slo_spec
from .spans import SpanTracer
from .trace import StepTraceWindow, parse_trace_steps

__all__ = [
    "COLLECTOR_HOST_ID",
    "CollectorPushSink",
    "EVENT_KINDS",
    "FleetCollector",
    "EwmaMadDetector",
    "FlightRecorder",
    "GaugeSink",
    "Heartbeat",
    "HealthMonitor",
    "IncidentManager",
    "JsonlSink",
    "MetricLoggerSink",
    "MetricsExporter",
    "PlateauDetector",
    "ProgramCostLedger",
    "RecompileTracker",
    "SloEngine",
    "SloObjective",
    "SloSpec",
    "SpanTracer",
    "StallClock",
    "StdoutSink",
    "StepTraceWindow",
    "Telemetry",
    "ThroughputDetector",
    "device_memory_snapshot",
    "emit_memory",
    "aggregate_fleet",
    "format_report",
    "grade_events",
    "install_sigterm_handler",
    "load_slo_spec",
    "open_host_telemetry",
    "parse_trace_steps",
    "read_events",
    "read_events_counted",
    "render_stats",
    "shutdown_telemetry",
    "supervised_loop",
    "summarize",
]
