"""Deterministic telemetry teardown, shared by every CLI and exit path.

Before this helper each CLI hand-ordered its own close calls, and the
order MATTERS: a heartbeat thread still emitting into closing sinks
races file closes; an exporter shut before the final SLO evaluation
never exposes the run's last burn values; sinks closed before the
watchers flush lose the final ``slo.burn`` / signal-restore work.  The
one correct order is:

1. **heartbeat** — stop the only background EMITTER first, so nothing
   new enters the bus while it drains.
2. **telemetry.close()** — which itself closes watchers (final SLO
   evaluation lands its last events in the still-open sinks; the
   incident manager restores any signal handlers) and THEN the sinks.
3. **exporter** — last, so a scraper polling through the shutdown can
   still read the final gauge values the watcher flush just produced
   (the ``GaugeSink`` is in-memory; it outlives the bus harmlessly).

Both exits use it: the clean path (CLI ``finally``) and the SIGTERM
path (``obs/incidents.py`` dumps the bundle in the handler, raises
``SystemExit``, and the same ``finally`` runs the same order).
Idempotent — a double call (signal during teardown) is a no-op.
"""

from __future__ import annotations


def shutdown_telemetry(telemetry, *, heartbeat=None, exporter=None) -> None:
    """Close a ``build_telemetry`` stack in the documented order.  Every
    argument may be None; every step is individually guarded so one
    failing close cannot leak the others."""
    for step in (
        (lambda: heartbeat.close()) if heartbeat is not None else None,
        (lambda: telemetry.close()) if telemetry is not None else None,
        (lambda: exporter.close()) if exporter is not None else None,
    ):
        if step is None:
            continue
        try:
            step()
        except Exception as e:  # noqa: BLE001 — teardown must finish
            print(f"[telemetry] teardown step failed "
                  f"({type(e).__name__}: {e}); continuing", flush=True)


def supervised_loop(stop, interval_s: float, tick, label: str) -> None:
    """The daemon-supervisor loop body shared by the fleet maintenance
    thread and the autoscaler: ``tick()`` every ``interval_s`` until
    ``stop`` (a ``threading.Event``) is set, surviving any single sick
    tick under the sink contract — warn once per FAILURE STREAK (a
    recovery re-arms the warning), never kill the loop."""
    warned = False
    while not stop.wait(interval_s):
        try:
            tick()
            warned = False
        except Exception as e:  # noqa: BLE001 — the supervisor must
            # outlive any single sick tick
            if not warned:
                warned = True
                print(f"[{label}] tick failed ({type(e).__name__}: {e});"
                      f" kept — will retry next interval", flush=True)
