"""Cross-stack span tracing: trace_id/span trees on the telemetry bus.

The latency percentiles (serve) and step windows (train) say HOW LONG;
nothing says WHERE the milliseconds went for one request or one step
window.  Spans close that gap with the smallest possible mechanism: each
span is one ``trace.span`` event on the existing bus —

    payload: {trace_id, span_id, parent_id, name, start_s, duration_s,
              ...attrs}

— so spans inherit the bus's sinks, per-host files, crash semantics, and
report tooling, and ``tools/trace_export.py`` converts them to
Chrome/Perfetto trace-event JSON offline.

Clock discipline: ``start_s`` is in the EMITTER's clock (the serve path
uses the service's monotonic clock so fake-clock tests stay
deterministic; the train loop uses ``time.perf_counter`` stamps it
already takes).  All spans of one run share a base, which is all the
export needs — it normalises to the file's earliest span.  Parents may be
emitted after their children (a root span's duration isn't known until it
ends); consumers must not assume emission order.

The tracer is armed exactly like the ledger: ``Telemetry.spans`` is None
unless a CLI consumer exists, and every producer guards with
``getattr(telemetry, "spans", None)`` — zero cost on default runs.
"""

from __future__ import annotations

import itertools
import os
from typing import Optional


class SpanTracer:
    """Mints ids and emits ``trace.span`` events.

    Ids carry the pid plus a short random tag so traces from several
    hosts/processes joined into one artifact can't collide — pid alone
    is not enough: two containerised replicas typically BOTH run as
    pid 1.  The per-process counter keeps ids cheap within a run.
    Thread-safe: ``itertools.count`` is atomic under CPython, and
    emission goes through the bus's own lock.
    """

    def __init__(self, telemetry, *, prefix: Optional[str] = None):
        self._tel = telemetry
        self.prefix = (prefix if prefix is not None
                       else f"{os.getpid():x}{os.urandom(2).hex()}")
        self._ids = itertools.count(1)

    def new_trace_id(self, hint: str = "") -> str:
        tag = f"{hint}-" if hint else ""
        return f"{tag}{self.prefix}-{next(self._ids):x}"

    def new_span_id(self) -> str:
        return f"s{self.prefix}-{next(self._ids):x}"

    def emit(self, *, trace_id: str, name: str, start: float, end: float,
             span_id: Optional[str] = None, parent_id: Optional[str] = None,
             step: Optional[int] = None, **attrs) -> str:
        """Emit one completed span; returns its span_id (pre-mint with
        ``new_span_id()`` to emit children before their parent)."""
        sid = span_id if span_id is not None else self.new_span_id()
        self._tel.emit("trace.span", step=step, trace_id=trace_id,
                       span_id=sid, parent_id=parent_id, name=name,
                       start_s=round(float(start), 6),
                       duration_s=round(max(float(end) - float(start),
                                            0.0), 6),
                       **attrs)
        return sid
