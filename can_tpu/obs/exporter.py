"""Prometheus-text ``/metrics`` + ``/healthz`` for live runs.

The JSONL artifact answers "what happened"; a scrape endpoint answers
"what is happening".  ``GaugeSink`` is an ordinary bus sink — it derives
in-memory gauges/counters from the SAME events every other sink sees (no
new instrumentation, no extra hot-path work beyond a dict update per
event) — and ``MetricsExporter`` serves them over a stdlib
``ThreadingHTTPServer`` (the ``serve/service.py`` pattern: threads hold
blocked scrapers; the run owns the device).

One scrape config covers training AND serving: the serve CLI registers
``CountService.stats()`` as an extra source, so its request/reject/queue
counters come out in the same Prometheus text at the same port.

Exposition format (text/plain; version=0.0.4)::

    # TYPE can_tpu_loss gauge
    can_tpu_loss 0.1234
    # TYPE can_tpu_events_total counter
    can_tpu_events_total{kind="step_window"} 42

Nothing here touches the default path: no ``--metrics-port``, no
``GaugeSink``, no server thread.
"""

from __future__ import annotations

import json
import math
import statistics
import threading
from typing import Callable, Dict, Optional, Tuple

_PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

# serve/service.py stats() keys that are monotonic counts (rendered with
# the Prometheus ``_total`` suffix); the rest of the dict is gauges
_SERVE_COUNTER_KEYS = frozenset(
    {"submitted", "completed", "rejected", "batches", "batch_slots",
     "batch_valid", "compile_count", "failures"})


def _fmt_value(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    f = float(v)
    if math.isnan(f):
        return "NaN"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    return repr(f) if isinstance(v, float) else str(v)


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _labelled_block(by_name: Dict[str, list], mtype: str) -> list:
    lines = []
    for name in sorted(by_name):
        lines.append(f"# TYPE {name} {mtype}")
        for labels, v in sorted(by_name[name], key=lambda kv: kv[0]):
            if labels:
                lab = ",".join(f'{k}="{str(val)}"' for k, val in labels)
                lines.append(f"{name}{{{lab}}} {_fmt_value(v)}")
            else:
                lines.append(f"{name} {_fmt_value(v)}")
    return lines


def render_prometheus(gauges: Dict[str, float],
                      counters: Dict[Tuple[str, tuple], float],
                      labelled_gauges: Optional[
                          Dict[Tuple[str, tuple], float]] = None) -> str:
    """One exposition block: gauges, then counters.  Labelled maps key on
    ``(name, ((label, value), ...))``.  A name appearing both plain and
    labelled (the fleet's service-wide vs per-replica ``generation``)
    renders as ONE group under ONE ``# TYPE`` line — the Prometheus text
    parser rejects a second TYPE line for the same metric, and that would
    void the whole scrape."""
    by_name: Dict[str, list] = {}
    for name in sorted(gauges):
        v = gauges[name]
        if v is None:
            continue
        by_name.setdefault(name, []).append(((), v))
    if labelled_gauges:
        for (name, labels), v in labelled_gauges.items():
            by_name.setdefault(name, []).append((labels, v))
    lines = _labelled_block(by_name, "gauge")
    by_name = {}
    for (name, labels), v in counters.items():
        by_name.setdefault(name, []).append((labels, v))
    lines += _labelled_block(by_name, "counter")
    return "\n".join(lines) + "\n" if lines else ""


class GaugeSink:
    """Bus sink -> in-memory Prometheus state.

    Gauges (last value wins): run-local ``can_tpu_step``, the per-window
    ``can_tpu_loss`` / ``can_tpu_grad_norm`` / ``can_tpu_update_norm``
    means the loop folds into ``step_window`` events, window median step
    time, per-epoch scalars (``can_tpu_train_loss``, ``can_tpu_mae``,
    ...), heartbeat timestamp, peak HBM / host RSS.  Counters: events by
    kind, steps/images, compiles (+seconds), stall seconds, health alerts
    by signal+kind.  Thread-safe: the bus emits under its own lock from
    several threads, and scrape threads read concurrently."""

    def __init__(self, prefix: str = "can_tpu"):
        self.prefix = prefix
        # RLock: the SIGTERM bundle's gauge snapshot may interrupt the
        # main thread inside emit()'s own critical section — same-thread
        # re-entry must succeed (see obs/incidents.py)
        self._lock = threading.RLock()
        self._gauges: Dict[str, float] = {}
        self._counters: Dict[Tuple[str, tuple], float] = {}
        # labelled gauges (the SLO layer's per-objective/window burns):
        # key (name, ((label, value), ...)), rendered in the same group
        # as any same-named plain gauge
        self._labelled: Dict[Tuple[str, tuple], float] = {}

    # -- bus sink protocol ----------------------------------------------
    def emit(self, event: dict) -> None:
        kind = event.get("kind", "?")
        p = event.get("payload", {})
        pre = self.prefix
        with self._lock:
            self._count((f"{pre}_events_total", (("kind", kind),)))
            if kind == "step_window":
                if event.get("step") is not None:
                    self._gauges[f"{pre}_step"] = event["step"]
                self._count((f"{pre}_steps_total", ()),
                            float(p.get("steps", 0)))
                self._count((f"{pre}_images_total", ()),
                            float(p.get("images", 0.0)))
                samples = p.get("samples_s", ())
                if samples:
                    self._gauges[f"{pre}_step_time_p50_s"] = float(
                        statistics.median(samples))
                for key in ("loss", "grad_norm", "update_norm"):
                    if key in p:
                        self._gauges[f"{pre}_{key}"] = float(p[key])
            elif kind == "compile":
                self._count((f"{pre}_compiles_total", ()))
                self._count((f"{pre}_compile_seconds_total", ()),
                            float(p.get("seconds", 0.0)))
            elif kind == "stall":
                self._count((f"{pre}_stall_seconds_total", ()),
                            float(p.get("seconds", 0.0)))
            elif kind == "epoch":
                if event.get("step") is not None:
                    self._gauges[f"{pre}_epoch"] = event["step"]
                for k, v in p.items():
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        self._gauges[f"{pre}_{_sanitize(k)}"] = float(v)
            elif kind == "heartbeat":
                self._gauges[f"{pre}_last_heartbeat_ts"] = event.get("ts")
            elif kind == "memory":
                for d in p.get("devices", ()):
                    for key in ("peak_bytes_in_use", "bytes_in_use"):
                        if key in d:
                            g = f"{pre}_peak_hbm_bytes"
                            self._gauges[g] = max(
                                self._gauges.get(g, 0), int(d[key]))
                            break
                rss = p.get("host_rss_mb")
                if rss is not None:
                    self._gauges[f"{pre}_host_rss_mb"] = float(rss)
            elif kind == "health.alert":
                self._count((f"{pre}_health_alerts_total",
                             (("signal", str(p.get("signal", "?"))),
                              ("kind", str(p.get("alert", "?"))))))
            elif kind == "serve.request":
                # stream degradation visibility: EWMA-served answers
                # count (vs the fresh-inference total riding
                # events_total{kind="serve.request"}) and the last
                # served staleness — the live view of the ladder's
                # "degrade instead of drown" contract
                if p.get("degraded"):
                    self._count((f"{pre}_stream_degraded_total", ()))
                    if p.get("staleness_s") is not None:
                        self._gauges[f"{pre}_stream_staleness_s"] = \
                            float(p["staleness_s"])
            elif kind == "stream.session":
                if p.get("active") is not None:
                    # sampled exactly when the session set changes or
                    # snapshots (the serve.batch queue-depth discipline)
                    self._gauges[f"{pre}_stream_sessions"] = \
                        float(p["active"])
                if str(p.get("state")) == "evicted":
                    self._count((f"{pre}_stream_evictions_total", ()))
            elif kind == "stream.degrade":
                # one ladder rung TRANSITION (not one degraded answer)
                self._count((f"{pre}_stream_degrade_total",
                             (("rung", str(p.get("rung", "?"))),)))
            elif kind == "stream.repin":
                self._count((f"{pre}_stream_repins_total", ()))
            elif kind == "serve.batch":
                # scheduler economics (can_tpu/sched): per-flush fill %
                # and dead slots, plus the predicted-vs-realized launch
                # cost the core's invariant rides on — a mismatch count
                # above zero is a scheduling bug, live on the scrape
                if p.get("fill_pct") is not None:
                    self._gauges[f"{pre}_sched_fill_pct"] = \
                        float(p["fill_pct"])
                self._count((f"{pre}_sched_batches_total", ()))
                self._count((f"{pre}_sched_slots_total", ()),
                            float(p.get("size", 0)))
                self._count((f"{pre}_sched_padded_slots_total", ()),
                            float(p.get("padded_slots", 0)))
                pred = p.get("predicted_cost_px")
                real = p.get("realized_cost_px")
                if pred is not None and real is not None:
                    self._count((f"{pre}_sched_predicted_cost_px_total",
                                 ()), float(pred))
                    self._count((f"{pre}_sched_realized_cost_px_total",
                                 ()), float(real))
                    from can_tpu.sched.core import costs_match

                    if not costs_match(pred, real):
                        self._count(
                            (f"{pre}_sched_cost_mismatch_total", ()))
            elif kind == "data.planner":
                # batch-planner economics (ShardedBatcher.planner_stats):
                # padding/schedule overhead, program + lowered-launch
                # counts, plan cost — numeric payload entries become
                # can_tpu_planner_* gauges (last epoch wins)
                for k, v in p.items():
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool) and v is not None:
                        self._gauges[f"{pre}_planner_{_sanitize(k)}"] = \
                            float(v)
            elif kind == "fleet.rollout":
                self._count((f"{pre}_fleet_rollouts_total", ()))
                if "generation" in p:
                    self._gauges[f"{pre}_fleet_generation"] = \
                        float(p["generation"])
            elif kind == "fleet.replica":
                # state transitions: count quarantines per replica (flip
                # events re-announce "active" and are not failures); a
                # watchdog wedge is the hang flavour of the same loss
                if str(p.get("state")) in ("quarantined", "wedged"):
                    self._count((f"{pre}_fleet_quarantines_total",
                                 (("replica", str(p.get("replica", "?"))),)))
            elif kind == "fleet.scale":
                # one autoscale/manual add/remove transition; the live
                # count gauge rides the event (sampled exactly when it
                # changes, the serve.batch queue-depth discipline)
                self._count((f"{pre}_fleet_scale_events_total",
                             (("direction",
                               str(p.get("direction", "?"))),)))
                if p.get("live") is not None:
                    self._gauges[f"{pre}_fleet_live_replicas"] = \
                        float(p["live"])
            elif kind == "fleet.resurrect":
                self._count((f"{pre}_fleet_resurrections_total",
                             (("replica", str(p.get("replica", "?"))),)))
                if p.get("live") is not None:
                    self._gauges[f"{pre}_fleet_live_replicas"] = \
                        float(p["live"])
            elif kind == "fleet.probe":
                self._count((f"{pre}_fleet_probes_total",
                             (("ok", "1" if p.get("ok") else "0"),)))
            elif kind == "slo.burn":
                # one objective's multi-window burn evaluation
                # (obs/slo.py): per-window burns and the alerting state
                # become labelled gauges — the admission / scale-up
                # signal an autoscaler scrapes — and alert transitions
                # count.  A window below min_samples has burn None and
                # emits nothing (absence beats a fake zero).
                name = str(p.get("objective", "?"))
                for w, info in (p.get("windows") or {}).items():
                    burn = (info.get("burn")
                            if isinstance(info, dict) else None)
                    if burn is not None:
                        self._labelled[(f"{pre}_slo_burn",
                                        (("objective", name),
                                         ("window_s", str(w))))] = \
                            float(burn)
                self._labelled[(f"{pre}_slo_alerting",
                                (("objective", name),))] = \
                    1.0 if p.get("alerting") else 0.0
                if p.get("alerting"):
                    self._count((f"{pre}_slo_alerts_total",
                                 (("objective", name),)))
            elif kind == "fleet.host":
                # a HOST-level liveness transition (obs/collector.py):
                # stale = heartbeats older than the bound on the
                # skew-corrected clock — "no data ≠ healthy".  The live
                # counts ride the event (sampled exactly when the set
                # changes, the serve.batch queue-depth discipline)
                self._count((f"{pre}_fleet_host_transitions_total",
                             (("state", str(p.get("state", "?"))),)))
                if p.get("live") is not None:
                    self._gauges[f"{pre}_fleet_hosts_live"] = \
                        float(p["live"])
                if p.get("stale") is not None:
                    self._gauges[f"{pre}_fleet_hosts_stale"] = \
                        float(p["stale"])
            elif kind == "collector.ingest":
                # one collector ingest batch accepted for one host:
                # events/torn-line counts by host label, transport
                # (tail|push) recorded as its own counter dimension
                host = str(p.get("host", "?"))
                self._count((f"{pre}_collector_events_total",
                             (("host", host),)),
                            float(p.get("events", 0)))
                if p.get("torn"):
                    self._count((f"{pre}_collector_torn_total",
                                 (("host", host),)),
                                float(p["torn"]))
            elif kind == "incident.bundle":
                self._count((f"{pre}_incidents_total",
                             (("reason", str(p.get("reason", "?"))),)))
            elif kind == "perf.summary":
                # performance-attribution aggregates (obs/costs.py
                # ProgramCostLedger.summary): the payload keys are already
                # gauge-shaped (mfu_weighted, roofline_*_bound,
                # launch_cost_mpx_empirical, launch_cost_drift, ...), so
                # numeric entries map verbatim to can_tpu_<key>; the
                # per-program "detail" list and string provenance are for
                # the JSONL/report, not the scrape
                for k, v in p.items():
                    if isinstance(v, (int, float)) \
                            and not isinstance(v, bool):
                        self._gauges[f"{pre}_{_sanitize(k)}"] = float(v)

    def close(self) -> None:
        pass  # in-memory only; the exporter's lifecycle is the CLI's

    # -- reads -----------------------------------------------------------
    def _count(self, key: Tuple[str, tuple], by: float = 1.0) -> None:
        self._counters[key] = self._counters.get(key, 0) + by

    def alerts_total(self) -> int:
        with self._lock:
            return int(sum(v for (name, _), v in self._counters.items()
                           if name == f"{self.prefix}_health_alerts_total"))

    def render(self) -> str:
        with self._lock:
            return render_prometheus(dict(self._gauges),
                                     dict(self._counters),
                                     dict(self._labelled))

    def snapshot(self) -> dict:
        """JSON-ready point-in-time copy of every gauge and counter —
        what an incident bundle freezes (obs/incidents.py): the same
        values a /metrics scrape would have shown at the moment of
        death, without needing the exporter to still be alive."""
        with self._lock:
            return {
                "gauges": dict(self._gauges),
                "labelled_gauges": [
                    {"name": n, "labels": dict(labels), "value": v}
                    for (n, labels), v in sorted(self._labelled.items())],
                "counters": [
                    {"name": n, "labels": dict(labels), "value": v}
                    for (n, labels), v in sorted(self._counters.items())],
            }


# how a fleet rollup folds one gauge across hosts (obs/collector.py's
# federated /metrics): "sum" for capacity-like gauges where the fleet
# value is the total, "last" for stream-position gauges where the most
# recently heartbeating host is the truth, "max" (the default) for
# watermarks and progress.  Counters always sum — they are totals by
# construction.
DEFAULT_FLEET_AGG: Dict[str, str] = {
    "can_tpu_stream_sessions": "sum",
    "can_tpu_fleet_live_replicas": "sum",
    "can_tpu_host_rss_mb": "sum",
    "can_tpu_loss": "last",
    "can_tpu_step_time_p50_s": "last",
}


def aggregate_fleet(snapshots: Dict[int, dict], *, label: str = "host",
                    agg: Optional[Dict[str, str]] = None
                    ) -> Tuple[Dict[str, float],
                               Dict[Tuple[str, tuple], float],
                               Dict[Tuple[str, tuple], float]]:
    """Fold per-host ``GaugeSink.snapshot()`` dicts into one federated
    exposition: every per-host sample re-emitted with a ``host`` label,
    PLUS one plain fleet rollup per gauge/counter family.  Returns
    ``(gauges, counters, labelled_gauges)`` shaped for
    :func:`render_prometheus` — which renders a family's plain rollup
    and its host-labelled members under ONE ``# TYPE`` line (the PR-8
    dup-TYPE rule, now extended to host-labelled families).

    Rollups: counters sum; gauges follow ``agg`` (name -> sum|max|last,
    over :data:`DEFAULT_FLEET_AGG`, default max), where "last" takes the
    value from the host with the newest heartbeat.  Per-host LABELLED
    gauges (per-objective burns etc.) are host-labelled but not rolled
    up — cross-host aggregates of those need real cross-host arithmetic
    (the collector's global SLO engine), not a per-name fold."""
    rules = dict(DEFAULT_FLEET_AGG)
    rules.update(agg or {})
    gauges: Dict[str, float] = {}
    counters: Dict[Tuple[str, tuple], float] = {}
    labelled: Dict[Tuple[str, tuple], float] = {}
    # hosts ordered oldest-heartbeat first, so for "last" the newest
    # heartbeat's value lands last and wins the fold
    def _hb(item):
        hid, snap = item
        hb = (snap.get("gauges") or {}).get("can_tpu_last_heartbeat_ts")
        return (hb if isinstance(hb, (int, float)) else float("-inf"),
                hid)
    ordered = sorted(snapshots.items(), key=_hb)
    for hid, snap in ordered:
        hl = (label, str(hid))
        for name, v in sorted((snap.get("gauges") or {}).items()):
            if v is None:
                continue
            labelled[(name, (hl,))] = v
            rule = rules.get(name, "max")
            if rule == "sum":
                gauges[name] = gauges.get(name, 0.0) + float(v)
            elif rule == "last":
                gauges[name] = v
            else:
                gauges[name] = (v if name not in gauges
                                else max(gauges[name], v))
        for row in snap.get("labelled_gauges") or ():
            labels = tuple(sorted(dict(row.get("labels") or {},
                                       **{label: str(hid)}).items()))
            labelled[(row["name"], labels)] = row["value"]
        for row in snap.get("counters") or ():
            base = dict(row.get("labels") or {})
            base.pop(label, None)
            labels = tuple(sorted({**base, label: str(hid)}.items()))
            counters[(row["name"], labels)] = \
                counters.get((row["name"], labels), 0.0) + row["value"]
            roll = tuple(sorted(base.items()))
            counters[(row["name"], roll)] = \
                counters.get((row["name"], roll), 0.0) + row["value"]
    return gauges, counters, labelled


def render_stats(stats: dict, *, prefix: str = "can_tpu_serve",
                 counter_keys=_SERVE_COUNTER_KEYS) -> str:
    """Flat numeric stats dict -> Prometheus text (serve's ``/stats``
    counters in the same scrape).  Count-like keys get ``_total``; bools
    become 0/1 gauges; Nones and other nested values are skipped — EXCEPT
    the fleet's ``"replicas"`` sub-dicts, whose numeric entries become
    per-replica LABELLED lines (``can_tpu_serve_batches_total{replica=
    "k"}``), so one scrape shows which replica is serving, quarantined,
    or lagging a rollout generation."""
    gauges: Dict[str, float] = {}
    counters: Dict[Tuple[str, tuple], float] = {}
    labelled_gauges: Dict[Tuple[str, tuple], float] = {}
    for k, v in stats.items():
        if k == "replicas" and isinstance(v, dict):
            for rk, sub in v.items():
                if not isinstance(sub, dict):
                    continue
                label = (("replica", str(rk)),)
                for sk, sv in sub.items():
                    if sv is None or not isinstance(sv, (int, float, bool)):
                        continue
                    name = f"{prefix}_{_sanitize(sk)}"
                    if sk in counter_keys and not isinstance(sv, bool):
                        counters[(f"{name}_total", label)] = sv
                    else:  # quarantined/generation: state gauges
                        labelled_gauges[(name, label)] = sv
            continue
        if v is None or not isinstance(v, (int, float, bool)):
            continue
        name = f"{prefix}_{_sanitize(k)}"
        if k in counter_keys and not isinstance(v, bool):
            counters[(f"{name}_total", ())] = v
        else:
            gauges[name] = v
    return render_prometheus(gauges, counters, labelled_gauges)


class MetricsExporter:
    """The scrape endpoint: ``GET /metrics`` (gauge sink + every
    registered stats source) and ``GET /healthz`` (liveness + the alert
    counter, so a probe can distinguish "up" from "up but screaming").

    ``port=0`` binds an ephemeral port (tests); ``.port`` is the bound
    one.  ``start()`` launches a daemon thread — scrapes must never block
    the train loop, and a hung scraper dies with the process."""

    def __init__(self, gauges: GaugeSink, *, host: str = "127.0.0.1",
                 port: int = 0):
        self.gauges = gauges
        self.host = host
        self.port = int(port)
        self._sources: Dict[str, Callable[[], dict]] = {}
        self._httpd = None
        self._thread: Optional[threading.Thread] = None

    def add_stats_source(self, prefix: str,
                         stats_fn: Callable[[], dict]) -> None:
        """Expose a flat numeric stats dict (e.g. ``CountService.stats``)
        as ``can_tpu_<prefix>_*`` lines in the same scrape."""
        self._sources[prefix] = stats_fn

    def render(self) -> str:
        parts = [self.gauges.render()]
        for prefix, fn in sorted(self._sources.items()):
            try:
                parts.append(render_stats(fn(),
                                          prefix=f"can_tpu_{prefix}"))
            except Exception as e:  # noqa: BLE001 — a dead source must
                # not kill the scrape: the OTHER metrics still matter
                parts.append(f"# source {prefix} failed: "
                             f"{type(e).__name__}\n")
        return "".join(p for p in parts if p)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "MetricsExporter":
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # scrapes are not news
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from urllib.parse import urlparse

                path = urlparse(self.path).path
                if path == "/metrics":
                    self._send(200, exporter.render().encode(),
                               _PROM_CONTENT_TYPE)
                elif path == "/healthz":
                    body = json.dumps(
                        {"ok": True,
                         "alerts_total": exporter.gauges.alerts_total()})
                    self._send(200, body.encode(), "application/json")
                else:
                    self._send(404, json.dumps(
                        {"error": f"no such path: {path}"}).encode(),
                        "application/json")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port=0
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True,
                                        name="can-tpu-metrics-exporter")
        self._thread.start()
        return self

    def close(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
