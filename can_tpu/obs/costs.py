"""ProgramCostLedger: per-program XLA cost attribution — MFU, roofline
class, and the empirical launch-cost fit.

Until this module, the MFU plateau (~60%, VERDICT r5) and the planner's
``DEVICE_LAUNCH_COST_MPX`` constant were argued from one-off hand math in
``tools/ablate_mfu.py`` — no running system could say, per compiled
program, how many FLOPs it executes, how many HBM bytes it moves, or
whether it is compute- or bandwidth-bound.  The ledger closes that gap by
joining three data sources the stack already has:

* **compile time** — ``obs.RecompileTracker`` fires once per new
  ``(shape, dtype)`` signature; when a ledger is attached to the telemetry
  bus (``Telemetry.ledger``), the tracker calls :meth:`register`, which
  AOT-lowers the SAME jitted callable and reads
  ``compiled.cost_analysis()`` flops / "bytes accessed".  Backends that
  don't report cost analysis degrade to ``None`` rows — the ledger never
  raises into the step path.  The extra ``lower().compile()`` rides the
  compile event (already the slow path) and is a persistent-cache hit on
  backends with the XLA compilation cache armed.
* **steady state** — ``StepTimer`` per-shape wall totals (train/eval) and
  serve per-batch execute times (``CountService``) land via
  :meth:`observe` / :meth:`observe_timer`, giving each program a measured
  seconds-per-launch with first-call compiles already excluded upstream.
* **the device peak table** — ``cli.common.local_device_peaks`` (spec
  FLOP/s + HBM GB/s per device kind; a labelled-NOMINAL entry on CPU so
  the plumbing stays testable) turns flops/seconds into MFU and
  flops/bytes into a roofline class against the ridge intensity.

The launch-cost fit closes the loop with the PR-5 planner: the
``PlanCostModel`` prices a launch as ``area * slots + launch_cost_px``;
in time units that is ``seconds = px / rate + launch_overhead_s``.  A
weighted least-squares line through the measured (pixels, mean seconds)
points recovers both terms, and the intercept re-expressed in the
planner's unit is the EMPIRICAL ``DEVICE_LAUNCH_COST_MPX`` —
``launch_cost_drift`` (empirical / planned) is the model-drift gauge that
says when the constant in ``cli/common.py`` has gone stale.

Everything surfaces as ``perf.summary`` events (per-epoch in the loops,
periodic in serve): numeric payload keys become ``can_tpu_mfu_*`` /
``can_tpu_roofline_*`` / ``can_tpu_launch_cost_*`` gauges via the
exporter's ``GaugeSink``, and the ``detail`` rows feed
``tools/telemetry_report.py`` and the bench suite's perf tier.  A run
without telemetry constructs no ledger — the default hot path is
untouched.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

ROOFLINE_COMPUTE = "compute"
ROOFLINE_MEMORY = "memory"
ROOFLINE_UNKNOWN = "unknown"

# Timing-trust rule: serve execute times are FENCED (a device->host fetch
# closes every measured window), so one launch is already honest.  The
# train loop's per-shape samples are host-side dispatch intervals (the
# window-flush step absorbs the device sync — loop.py's documented
# bias): an individual sample can be wildly short, but the pipeline is
# rate-limited, so the MEAN converges on the true step time as launches
# accumulate.  Unfenced programs therefore need this many launches
# before their mean feeds MFU / the launch-cost fit; below it the row
# reports mean_s but refuses to synthesize utilisation from it (the r9
# bring-up saw a 1-launch program "achieve" 600x MFU this way).
MIN_UNFENCED_LAUNCHES = 4


def extract_image_signature(signature) -> Tuple[tuple, str]:
    """``train.steps.batch_signature`` triples -> (image shape, dtype).

    The image tensor carries the pixels every cost in this module is
    normalised by; batches without an ``image`` entry fall back to the
    largest-shape tensor (so the ledger still keys sanely on exotic
    batch dicts)."""
    best = None
    for name, shape, dtype in signature:
        if name == "image":
            return tuple(shape), str(dtype)
        size = 1
        for d in shape:
            size *= int(d)
        if best is None or size > best[0]:
            best = (size, tuple(shape), str(dtype))
    if best is None:
        return (), "?"
    return best[1], best[2]


def resolve_jit(fn, args):
    """The lowerable jitted callable behind ``fn`` for these ``args``:
    ``jax.jit`` objects pass through, wrapped dispatchers (the bucketed/
    spatial step closures, ``obs.RecompileTracker``) expose ``jit_for``
    returning the underlying jit.  Shared by the cost ledger and the HLO
    auditor (``can_tpu.analysis.hlo_audit``) so both reach the SAME
    program an operator's step actually runs."""
    picker = getattr(fn, "jit_for", None)
    return picker(*args) if picker is not None else fn


def cost_analysis_of(fn, args) -> Optional[Tuple[Optional[float],
                                                 Optional[float]]]:
    """(flops, bytes accessed) for the program ``fn(*args)`` compiles to,
    or None when the backend/callable can't say.

    ``fn`` is usually a ``jax.jit`` object (``.lower`` exists); wrapped
    dispatchers (the bucketed/spatial step closures) expose ``jit_for``
    returning the underlying jitted callable for these args.  The
    ``lower().compile()`` here is a SECOND compile of a program jit just
    built — acceptable because it happens once per signature on the
    already-slow compile path, and the persistent compilation cache (CLI
    default) turns it into a deserialise.  Never raises."""
    try:
        target = resolve_jit(fn, args)
        lower = getattr(target, "lower", None)
        if lower is None:
            return None
        ca = lower(*args).compile().cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else None
        if not ca:
            return None
        flops = ca.get("flops")
        byts = ca.get("bytes accessed")
        flops = float(flops) if flops is not None and flops > 0 else None
        byts = float(byts) if byts is not None and byts > 0 else None
        if flops is None and byts is None:
            return None
        return flops, byts
    # can-tpu-lint: disable=SWALLOW(attribution must never kill a run; None row is the degrade)
    except Exception:  # noqa: BLE001 — attribution must never kill a run
        return None


@dataclasses.dataclass
class ProgramCost:
    """One compiled program's ledger row (mutable: timings accumulate)."""

    name: str                 # step name ("train_step", "serve_predict", …)
    shape: tuple              # image shape (B, H, W, C)
    dtype: str                # image dtype string
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None
    launches: int = 0
    seconds: float = 0.0
    fenced: bool = True  # ANDed over observations; see MIN_UNFENCED_LAUNCHES

    @property
    def timing_reliable(self) -> bool:
        return bool(self.launches) and (self.fenced or
                                        self.launches >=
                                        MIN_UNFENCED_LAUNCHES)

    @property
    def pixels(self) -> Optional[int]:
        if len(self.shape) < 3:
            return None
        return int(self.shape[0]) * int(self.shape[1]) * int(self.shape[2])

    @property
    def mean_s(self) -> Optional[float]:
        return self.seconds / self.launches if self.launches else None

    @property
    def intensity(self) -> Optional[float]:
        """Arithmetic intensity, FLOP per HBM byte."""
        if not self.flops or not self.bytes_accessed:
            return None
        return self.flops / self.bytes_accessed


class ProgramCostLedger:
    """The join: per-program cost analysis x timings x device peaks.

    compute: "bf16" or "f32" — selects the peak-FLOP/s ceiling MFU is
      quoted against (the run's compute dtype, not the transfer dtype).
    peaks: a ``cli.common.DevicePeaks``; default autodetects the local
      device (None on unknown backends — MFU rows go None, flops/bytes
      and the launch-cost fit still work).
    plan_launch_cost_px: the planner's configured launch cost (pixel
      units) — the denominator of the ``launch_cost_drift`` gauge; the
      train CLI sets it to the resolved ``--launch-cost-mpx``.

    Thread-safety: ``register`` runs on whatever thread hits the compile
    (train loop / serve batcher), ``observe`` on loop or batcher threads,
    snapshots on scrape threads — one lock covers the record table.
    """

    def __init__(self, *, compute: str = "f32", peaks=None,
                 plan_launch_cost_px: Optional[float] = None):
        if peaks is None:
            from can_tpu.cli.common import local_device_peaks

            peaks = local_device_peaks()
        self.peaks = peaks
        self.compute = compute if compute in ("bf16", "f32") else "f32"
        self.plan_launch_cost_px = plan_launch_cost_px
        import threading

        self._lock = threading.Lock()
        self._programs: Dict[Tuple[str, tuple, str], ProgramCost] = {}

    # -- compile-time registration (RecompileTracker hook) ---------------
    def register(self, name: str, signature, *, fn=None, args=(),
                 cost=None) -> Optional[dict]:
        """Record a newly compiled signature; returns ``{"flops",
        "bytes_accessed"}`` when the backend reported them (the tracker
        folds these into the ``compile`` event payload).  ``cost`` is a
        (flops, bytes) override — the test seam and the path for callers
        that already hold a compiled object."""
        shape, dtype = extract_image_signature(signature)
        if cost is None and fn is not None:
            cost = cost_analysis_of(fn, args)
        with self._lock:
            rec = self._programs.setdefault(
                (name, shape, dtype), ProgramCost(name, shape, dtype))
            if cost is not None and rec.flops is None:
                rec.flops, rec.bytes_accessed = cost
        if cost is None:
            return None
        # only the keys the backend actually reported: a half-reporting
        # client must not put literal Nones into the compile payload
        out = {}
        if cost[0] is not None:
            out["flops"] = cost[0]
        if cost[1] is not None:
            out["bytes_accessed"] = cost[1]
        return out or None

    # -- steady-state timing ---------------------------------------------
    def observe(self, name: str, shape, seconds: float, n: int = 1,
                *, dtype: Optional[str] = None,
                fenced: bool = True) -> None:
        """Add ``n`` launches totalling ``seconds`` for the program with
        this image ``shape`` (compile first-calls excluded by the caller,
        exactly as for the step reservoirs).  ``dtype`` disambiguates when
        one shape was compiled at several image dtypes (serve passes it;
        the train loop runs one dtype per run, so shape alone resolves —
        ties go to the most recently registered record).  ``fenced=False``
        marks dispatch-biased samples (the train loop's async intervals):
        those only feed MFU once MIN_UNFENCED_LAUNCHES accumulate."""
        shape = tuple(shape)
        with self._lock:
            rec = None
            if dtype is not None:
                rec = self._programs.get((name, shape, dtype))
            if rec is None:
                matches = [r for (n_, s_, _), r in self._programs.items()
                           if n_ == name and s_ == shape]
                rec = matches[-1] if matches else None
            if rec is None:
                rec = self._programs[(name, shape, dtype or "?")] = \
                    ProgramCost(name, shape, dtype or "?")
            rec.launches += int(n)
            rec.seconds += float(seconds)
            rec.fenced = rec.fenced and bool(fenced)

    def observe_timer(self, name: str, timer) -> None:
        """Fold a ``StepTimer``'s per-shape totals in (the loops call this
        at epoch boundaries with their per-epoch timers).  Loop samples
        are host-side dispatch intervals — unfenced by construction."""
        for shape, (n, total) in timer.shape_totals().items():
            self.observe(name, shape, total, n, fenced=False)

    # -- snapshots --------------------------------------------------------
    def _peak_flops(self) -> Optional[float]:
        return self.peaks.flops(self.compute) if self.peaks else None

    def roofline_of(self, rec: ProgramCost) -> str:
        inten = rec.intensity
        if inten is None or self.peaks is None:
            return ROOFLINE_UNKNOWN
        return (ROOFLINE_COMPUTE
                if inten >= self.peaks.ridge(self.compute)
                else ROOFLINE_MEMORY)

    def _snapshot(self) -> List[ProgramCost]:
        """Consistent point-in-time copy of every registered program —
        the unit rows(), launch_cost_fit() and the summary share so one
        emitted event can never disagree with itself."""
        with self._lock:
            recs = sorted(self._programs.values(),
                          key=lambda r: (r.name, r.shape, r.dtype))
            return [dataclasses.replace(r) for r in recs]

    def rows(self, _snapshot: Optional[List[ProgramCost]] = None
             ) -> List[dict]:
        """Per-program dicts, sorted by (name, shape): flops/bytes,
        intensity, roofline class, launches, mean seconds, MFU and
        bandwidth utilisation against the peak table."""
        peak_f = self._peak_flops()
        peak_bw = self.peaks.hbm_bytes_s if self.peaks else None
        recs = self._snapshot() if _snapshot is None else _snapshot
        out = []
        for r in recs:
            mean_s = r.mean_s
            trust = r.timing_reliable
            mfu = (r.flops / (mean_s * peak_f)
                   if trust and r.flops and mean_s and peak_f else None)
            bw_util = (r.bytes_accessed / (mean_s * peak_bw)
                       if trust and r.bytes_accessed and mean_s and peak_bw
                       else None)
            out.append({
                "name": r.name, "shape": list(r.shape), "dtype": r.dtype,
                "flops": r.flops, "bytes_accessed": r.bytes_accessed,
                "pixels": r.pixels,
                "intensity": (round(r.intensity, 4)
                              if r.intensity is not None else None),
                "roofline": self.roofline_of(r),
                "launches": r.launches,
                "mean_s": round(mean_s, 6) if mean_s is not None else None,
                "total_s": round(r.seconds, 4),
                "timing_reliable": trust,
                "mfu": round(mfu, 4) if mfu is not None else None,
                "bw_util": round(bw_util, 4) if bw_util is not None else None,
            })
        return out

    def launch_cost_fit(self, name: Optional[str] = None, *,
                        _snapshot: Optional[List[ProgramCost]] = None
                        ) -> Optional[dict]:
        """Weighted least-squares of mean seconds-per-launch against
        pixels-per-launch over the timed programs (optionally one step
        ``name``): ``seconds = px / rate + overhead``.  Needs >= 2
        distinct pixel sizes and a positive slope; returns the realized
        device rate, the fixed per-launch overhead, and that overhead in
        the planner's Mpx unit (clamped at 0 — a negative intercept is
        measurement noise, reported raw in ``intercept_s``).  Only
        timing-reliable programs contribute (see MIN_UNFENCED_LAUNCHES):
        one dispatch-biased point would swing the intercept wildly."""
        if _snapshot is None:
            _snapshot = self._snapshot()
        pts = [(r.pixels, r.mean_s, r.launches)
               for r in _snapshot
               if (name is None or r.name == name)
               and r.pixels and r.mean_s and r.timing_reliable]
        if len({px for px, _, _ in pts}) < 2:
            return None
        sw = sum(n for _, _, n in pts)
        mx = sum(n * px for px, _, n in pts) / sw
        my = sum(n * s for _, s, n in pts) / sw
        sxx = sum(n * (px - mx) ** 2 for px, _, n in pts)
        sxy = sum(n * (px - mx) * (s - my) for px, s, n in pts)
        if sxx <= 0 or sxy <= 0:
            return None
        slope = sxy / sxx            # seconds per pixel
        intercept = my - slope * mx  # fixed seconds per launch
        mpx = max(intercept / slope, 0.0) / 1e6
        out = {
            "rate_mpx_s": round(1.0 / slope / 1e6, 4),
            "intercept_s": round(intercept, 6),
            "launch_cost_mpx_empirical": round(mpx, 4),
            "fit_points": len(pts),
        }
        if self.plan_launch_cost_px:
            out["launch_cost_drift"] = round(
                mpx / (self.plan_launch_cost_px / 1e6), 4)
        return out

    def _aggregate(self, rows: List[dict],
                   snapshot: Optional[List[ProgramCost]] = None) -> dict:
        """Aggregate payload derived from ONE rows() snapshot (so an
        emitted summary always agrees with its own detail): weighted MFU
        over timed programs, roofline class counts over all registered
        programs, the launch-cost fit, and the peak-table provenance.
        Keys are named for the exporter: numeric entries become
        ``can_tpu_<key>`` gauges verbatim."""
        out: dict = {"perf_programs": len(rows)}
        for cls in (ROOFLINE_COMPUTE, ROOFLINE_MEMORY, ROOFLINE_UNKNOWN):
            out[f"roofline_{cls}_bound" if cls != ROOFLINE_UNKNOWN
                else "roofline_unknown"] = sum(
                    1 for r in rows if r["roofline"] == cls)
        timed = [r for r in rows if r["mfu"] is not None and r["total_s"]]
        if timed:
            wsum = sum(r["total_s"] for r in timed)
            out["mfu_weighted"] = round(
                sum(r["mfu"] * r["total_s"] for r in timed) / wsum, 4)
            out["mfu_best"] = max(r["mfu"] for r in timed)
            out["mfu_worst"] = min(r["mfu"] for r in timed)
        # launch-cost fit PER step family, never pooled: train_step is
        # fwd+bwd+optimizer while eval/serve are fwd-only, so their
        # seconds-per-pixel slopes differ ~3x and a pooled regression
        # reports a bogus intercept (hence bogus drift) even when every
        # family matches the planner constant exactly.  The Mpx unit is
        # itself family-relative (overhead seconds x that family's own
        # rate), and the planner prices TRAIN launches — so the drift
        # gauge comes from "train_step" whenever it has a fit, with the
        # best-constrained other family as the fallback (serve-only
        # deployments still get an empirical rate/overhead, labelled).
        best_name = best_fit = None
        for n in sorted({r["name"] for r in rows}):
            f = self.launch_cost_fit(n, _snapshot=snapshot)
            if f is None:
                continue
            if n == "train_step":
                best_name, best_fit = n, f
                break
            if best_fit is None or f["fit_points"] > best_fit["fit_points"]:
                best_name, best_fit = n, f
        if best_fit is not None:
            out.update(best_fit)
            out["launch_cost_fit_name"] = best_name
        if self.peaks is not None:
            out["peak_flops"] = self._peak_flops()
            out["peak_hbm_bytes_s"] = self.peaks.hbm_bytes_s
            out["peak_nominal"] = int(self.peaks.nominal)
            out["peak_source"] = self.peaks.source
        return out

    def summary(self) -> dict:
        snap = self._snapshot()
        return self._aggregate(self.rows(snap), snap)

    def emit_summary(self, telemetry, *, step: Optional[int] = None,
                     phase: str = "") -> dict:
        """One ``perf.summary`` event: the aggregate payload (gauge feed)
        plus the per-program ``detail`` rows (report/bench feed), both —
        including the launch-cost fit — from the same snapshot."""
        snap = self._snapshot()
        rows = self.rows(snap)
        payload = self._aggregate(rows, snap)
        telemetry.emit("perf.summary", step=step, phase=phase,
                       detail=rows, **payload)
        return payload
