"""FlightRecorder: a bounded ring-buffer sink — the black box.

The JSONL sink records everything forever; the exporter holds the latest
gauge values; NEITHER answers "what were the last thirty seconds of this
process's life" at the moment something dies.  A preempted host has a few
hundred milliseconds between SIGTERM and SIGKILL, a quarantined replica's
context is scattered across a multi-GB artifact, and a NaN abort's
interesting window is the steps right BEFORE the alert.  The recorder
keeps exactly that window in memory: one bounded ring per event kind
(chatty kinds — spans, step windows — cannot evict the rare ones — the
alert that explains the crash), appended O(1) from the bus's sink
fan-out and snapshotted wholesale into an incident bundle
(``obs/incidents.py``) when a trigger fires.

Cost discipline: the recorder is an ordinary bus sink, so a default run
(``telemetry=None``) never constructs one and pays nothing; an armed run
pays one deque append per event behind a single uncontended lock (the
lock exists for the snapshot path — ``collections.deque`` iteration
raises if a concurrent append mutates it mid-copy).  No serialisation,
no I/O, no per-event allocation beyond the event dict the bus already
built.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from typing import Dict, List, Optional

#: default events kept per kind; chatty kinds get their own caps below
DEFAULT_CAPACITY = 256

#: per-kind capacity overrides: high-rate kinds keep a deeper window
#: (a serve box does hundreds of requests/spans per second; 256 would be
#: under a second of context), metronome kinds keep a shallow one (64
#: heartbeats IS the liveness tail — more adds nothing)
DEFAULT_KIND_CAPACITY = {
    "trace.span": 1024,
    "serve.request": 1024,
    "serve.batch": 512,
    "step_window": 512,
    "heartbeat": 64,
}


class FlightRecorder:
    """Per-kind bounded rings over the telemetry stream.

    ``capacity``: default events kept per kind; ``kind_capacity`` maps
    kind -> its own cap (merged over :data:`DEFAULT_KIND_CAPACITY`).
    ``retain_s``: optional age bound applied at SNAPSHOT time (the ring
    itself is count-bounded — pruning by age per append would make the
    hot path O(evictions)); None keeps everything the rings hold.
    """

    def __init__(self, *, capacity: int = DEFAULT_CAPACITY,
                 kind_capacity: Optional[Dict[str, int]] = None,
                 retain_s: Optional[float] = None):
        self.capacity = int(capacity)
        self.kind_capacity = dict(DEFAULT_KIND_CAPACITY)
        if kind_capacity:
            self.kind_capacity.update(kind_capacity)
        self.retain_s = retain_s
        # RLock: the SIGTERM handler's snapshot may interrupt the main
        # thread INSIDE emit()'s critical section (signals run on the
        # main thread between bytecodes) — same-thread re-entry must
        # succeed or the preemption dump deadlocks (obs/incidents.py)
        self._lock = threading.RLock()
        self._rings: Dict[str, deque] = {}
        self._seen: Dict[str, int] = {}

    # -- bus sink protocol ------------------------------------------------
    def emit(self, event: dict) -> None:
        kind = event.get("kind", "?")
        with self._lock:
            ring = self._rings.get(kind)
            if ring is None:
                cap = max(1, int(self.kind_capacity.get(kind,
                                                        self.capacity)))
                ring = self._rings[kind] = deque(maxlen=cap)
            ring.append(event)
            self._seen[kind] = self._seen.get(kind, 0) + 1

    def close(self) -> None:
        pass  # in-memory only; the bundle dump is the flush

    # -- reads ------------------------------------------------------------
    def snapshot(self, *, now: Optional[float] = None) -> List[dict]:
        """Every retained event, merged across kinds and sorted by the
        bus wall-clock ``ts`` (stable, so same-ts events keep their
        per-kind order).  ``now`` + ``retain_s`` bound the age; events
        without a numeric ts are kept (age unknowable, and dropping them
        would hide exactly the malformed event worth seeing)."""
        with self._lock:
            events = [e for ring in self._rings.values() for e in ring]
        if self.retain_s is not None and now is not None:
            floor = now - self.retain_s
            events = [e for e in events
                      if not isinstance(e.get("ts"), (int, float))
                      or e["ts"] >= floor]
        return sorted(events,
                      key=lambda e: (e.get("ts")
                                     if isinstance(e.get("ts"), (int, float))
                                     else 0.0))

    def stats(self) -> Dict[str, dict]:
        """Per-kind accounting for the bundle manifest: kept / seen /
        evicted / capacity.  ``evicted = seen - kept`` is exact because
        the rings only ever drop from the head on overflow."""
        with self._lock:
            return {kind: {"kept": len(ring),
                           "seen": self._seen.get(kind, 0),
                           "evicted": self._seen.get(kind, 0) - len(ring),
                           "capacity": ring.maxlen}
                    for kind, ring in sorted(self._rings.items())}

    def dump(self, path: str, *, now: Optional[float] = None) -> int:
        """Write the snapshot as telemetry-schema JSONL (the SAME format
        the per-host files use, so ``run_monitor`` / ``trace_export`` /
        ``telemetry_report`` read a ring dump with zero changes).
        Returns the event count."""
        events = self.snapshot(now=now)
        with open(path, "w") as f:
            for e in events:
                f.write(json.dumps(e) + "\n")
        return len(events)
