"""Multi-host telemetry join: discovery, incremental tailing, clock-skew
offsets, and ts-merge — the ONE implementation every fleet-level reader
shares.

Before this module, three tools each carried their own copy of "find the
``telemetry.host{k}.jsonl`` files, read them with torn-line counting,
merge by timestamp": ``tools/run_monitor.py`` (liveness), ``tools/
slo_report.py`` (grading), ``tools/trace_export.py`` (flame views).  The
live ``FleetCollector`` (obs/collector.py) is a fourth consumer — and the
one for which drift would be fatal, because its correctness oracle is
"the offline replay of the same files grades bit-identically".  So the
join lives here once, and a cross-tool consistency test pins all four to
it.

Clock-skew model (shared by the live and offline paths):

* every host stamps events with ITS OWN wall clock (``obs/bus.py``
  ``clock=time.time``); hosts drift, so a raw ts-merge interleaves
  wrongly and staleness-vs-newest-event lets a fast clock mask a dead
  peer;
* a per-host OFFSET (``offset_s > 0`` ⇒ that host's clock runs fast) is
  subtracted before any merge or staleness judgement:
  ``corrected = ts - offset``;
* offline, with no receive-time to compare against, the offset is
  estimated from the first heartbeat per host against the fleet median
  (hosts start together far more reliably than their clocks agree — the
  same anchor ``trace_export`` always used for span re-anchoring); the
  live collector measures it directly (heartbeat ts vs receive time) and
  records it in its snapshot manifest, which then WINS over estimation;
* offsets within ``snap_s`` of zero snap to exactly ``0.0``: ordinary
  emit jitter is not skew, and a snapped offset keeps single-clock
  fixtures byte-identical through the corrected path.

Pure host-side code — no JAX import (tools run on any machine the
artifacts were copied to).
"""

from __future__ import annotations

import glob
import json
import os
import re
import statistics
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from can_tpu.obs.report import read_events_counted

HOST_FILE_RE = re.compile(r"telemetry\.host(\d+)\.jsonl$")

#: offsets smaller than this are measurement noise, not skew — snapped
#: to 0.0 so the corrected path is a no-op on single-clock runs.
DEFAULT_SNAP_S = 30.0

#: manifest name marking a directory as a FleetCollector snapshot
#: (written last, atomically — same contract as incident bundles).
COLLECTOR_MANIFEST = "collector.json"
COLLECTOR_SCHEMA = "can_tpu.collector.v1"


def host_file_name(host_id: int) -> str:
    return f"telemetry.host{int(host_id)}.jsonl"


def discover_host_files(run_dir: str) -> Dict[int, str]:
    """``host_id -> path`` for every per-host file in ``run_dir``,
    sorted by host id (the canonical concatenation order)."""
    hosts: Dict[int, str] = {}
    for path in glob.glob(os.path.join(run_dir, "telemetry.host*.jsonl")):
        m = HOST_FILE_RE.search(path)
        if m:
            hosts[int(m.group(1))] = path
    return dict(sorted(hosts.items()))


def read_host_events(paths: Dict[int, str]
                     ) -> Tuple[Dict[int, list], Dict[int, int]]:
    """Read every per-host file with torn-line counting
    (``read_events_counted`` semantics: a complete line that fails to
    decode is counted skipped, never silently dropped)."""
    events: Dict[int, list] = {}
    skipped: Dict[int, int] = {}
    for hid in sorted(paths):
        events[hid], skipped[hid] = read_events_counted(paths[hid])
    return events, skipped


def corrected_ts(ts: float, offset: float) -> float:
    """THE skew correction — one expression, imported by both the live
    collector and the offline replay so the floats are bit-identical."""
    return ts - offset


def apply_offsets(events: Iterable[dict], offset: float) -> List[dict]:
    """Skew-correct one host's events (shallow copies; the zero-offset
    path returns the originals untouched so single-clock runs replay
    byte-identically)."""
    if not offset:
        return list(events)
    out = []
    for e in events:
        ts = e.get("ts")
        if isinstance(ts, (int, float)):
            e = dict(e, ts=corrected_ts(float(ts), offset))
        out.append(e)
    return out


def join_events(events_by_host: Dict[int, Sequence[dict]],
                offsets: Optional[Dict[int, float]] = None) -> List[dict]:
    """Concatenate per-host streams in sorted-host order with offsets
    applied.  This IS the merge contract: downstream consumers that need
    time order stable-sort by ``ts``, so equal timestamps resolve to
    (host, line) order — exactly what the live collector's
    ``(corrected_ts, host, seq)`` release key reproduces."""
    offsets = offsets or {}
    out: List[dict] = []
    for hid in sorted(events_by_host):
        out.extend(apply_offsets(events_by_host[hid],
                                 float(offsets.get(hid, 0.0))))
    return out


def first_heartbeat_ts(events: Iterable[dict]) -> Optional[float]:
    """First heartbeat timestamp in stream order (the offline skew
    anchor — NOT min over ts, so a restarted host anchors at its
    original start)."""
    for e in events:
        if e.get("kind") == "heartbeat" \
                and isinstance(e.get("ts"), (int, float)):
            return float(e["ts"])
    return None


def snap_offset(offset: float, *, snap_s: float = DEFAULT_SNAP_S) -> float:
    return 0.0 if abs(offset) < snap_s else float(offset)


def estimate_offsets(first_ts_by_host: Dict[int, Optional[float]], *,
                     snap_s: float = DEFAULT_SNAP_S) -> Dict[int, float]:
    """Post-hoc skew estimate: each host's first heartbeat against the
    fleet median first heartbeat.  Median, not min — one fast clock
    should read as "that host is fast", not as "everyone else is slow".
    A host without heartbeats gets offset 0 (nothing to anchor on)."""
    anchors = {h: t for h, t in first_ts_by_host.items() if t is not None}
    if len(anchors) < 2:
        return {h: 0.0 for h in first_ts_by_host}
    med = statistics.median(anchors.values())
    return {h: (snap_offset(anchors[h] - med, snap_s=snap_s)
                if h in anchors else 0.0)
            for h in first_ts_by_host}


def corrected_staleness(last_ts: Optional[float], offset: float,
                        now: float) -> Optional[float]:
    """Age of a host's newest (heartbeat) event on the CORRECTED
    clock — the one liveness rule both ``run_monitor`` modes and the
    live collector route through, so a host whose fast clock inflates
    its raw timestamps cannot mask a dead peer (or read live while
    dead)."""
    if last_ts is None:
        return None
    return now - corrected_ts(float(last_ts), offset)


# --- collector snapshots -------------------------------------------------
def is_collector_snapshot(path: str) -> bool:
    return os.path.isfile(os.path.join(path, COLLECTOR_MANIFEST))


def load_collector_manifest(path: str) -> Optional[dict]:
    """The snapshot manifest, or None when absent/torn (the collector
    writes it atomically via tmp+rename, so a partial read means a torn
    copy, not a torn write)."""
    mpath = os.path.join(path, COLLECTOR_MANIFEST)
    try:
        with open(mpath) as f:
            m = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return m if isinstance(m, dict) else None


def collector_offsets(manifest: Optional[dict]) -> Dict[int, float]:
    """Measured per-host clock offsets from a snapshot manifest — these
    WIN over post-hoc estimation (the collector saw receive times; the
    estimator only guesses from start alignment)."""
    out: Dict[int, float] = {}
    for hid, h in ((manifest or {}).get("hosts") or {}).items():
        try:
            out[int(hid)] = float((h or {}).get("clock_offset_s", 0.0))
        except (TypeError, ValueError):
            out[int(hid)] = 0.0
    return out


def resolve_offsets(run_dir: str,
                    events_by_host: Dict[int, Sequence[dict]], *,
                    snap_s: float = DEFAULT_SNAP_S) -> Dict[int, float]:
    """The offset source for a directory of per-host files: a collector
    snapshot's measured offsets when present, else the post-hoc
    first-heartbeat estimate."""
    if is_collector_snapshot(run_dir):
        measured = collector_offsets(load_collector_manifest(run_dir))
        return {h: float(measured.get(h, 0.0)) for h in events_by_host}
    return estimate_offsets(
        {h: first_heartbeat_ts(evs) for h, evs in events_by_host.items()},
        snap_s=snap_s)


def resolve_telemetry_source(target: str) -> Tuple[List[str], str]:
    """Shared path resolution for the offline tools: a telemetry JSONL
    file -> [it]; an incident bundle dir -> its ring dump; a run dir or
    collector snapshot -> its per-host files.  Returns ``(paths,
    source_kind)`` with kind in ``{"file", "bundle", "snapshot",
    "run"}``.  Raises ``SystemExit`` (usage-class) on an empty/missing
    target — callers map it to exit 2."""
    # local import: incidents pulls in nothing heavy, but keeping the
    # module-level deps minimal keeps join importable everywhere
    from can_tpu.obs.incidents import (
        MANIFEST_NAME,
        bundle_ring_path,
        is_bundle_dir,
    )
    if os.path.isdir(target):
        if is_bundle_dir(target):
            try:
                return [bundle_ring_path(target)], "bundle"
            except ValueError as e:
                raise SystemExit(str(e))
        paths = [p for _, p in sorted(discover_host_files(target).items())]
        if not paths:
            raise SystemExit(
                f"no telemetry.host*.jsonl files (or {MANIFEST_NAME} / "
                f"{COLLECTOR_MANIFEST}) in {target}")
        return paths, ("snapshot" if is_collector_snapshot(target)
                       else "run")
    if not os.path.isfile(target):
        raise SystemExit(f"no such file or directory: {target}")
    return [target], "file"


def load_joined_events(target: str, *, estimate: bool = False,
                       snap_s: float = DEFAULT_SNAP_S
                       ) -> Tuple[List[dict], int, dict]:
    """One-call join for the offline tools: resolve ``target``, read
    with torn-line counting, skew-correct, concatenate.  Returns
    ``(events, skipped_lines, meta)`` with ``meta = {"kind", "offsets",
    "paths"}``.

    Offset policy: a collector snapshot's MEASURED offsets always
    apply; post-hoc ESTIMATION is opt-in (``estimate=True``) — liveness
    and trace re-anchoring want it (a fast clock must not mask a dead
    peer), but SLO grading of a plain run dir must not re-time events on
    a guess (a legitimately staggered start is not clock skew), so
    ``slo_report`` leaves it off."""
    paths, kind = resolve_telemetry_source(target)
    if kind in ("run", "snapshot"):
        hosts = discover_host_files(target)
        events_by_host, skipped = read_host_events(hosts)
        if kind == "snapshot":
            measured = collector_offsets(load_collector_manifest(target))
            offsets = {h: float(measured.get(h, 0.0))
                       for h in events_by_host}
        elif estimate:
            offsets = estimate_offsets(
                {h: first_heartbeat_ts(evs)
                 for h, evs in events_by_host.items()}, snap_s=snap_s)
        else:
            offsets = {h: 0.0 for h in events_by_host}
        return (join_events(events_by_host, offsets),
                sum(skipped.values()),
                {"kind": kind, "offsets": offsets,
                 "paths": [hosts[h] for h in sorted(hosts)]})
    events: List[dict] = []
    skipped_n = 0
    for p in paths:
        evs, sk = read_events_counted(p)
        events.extend(evs)
        skipped_n += sk
    return events, skipped_n, {"kind": kind, "offsets": {},
                               "paths": paths}


class HostTail:
    """Incremental JSONL reader: remembers the byte offset and keeps a
    partial trailing line in a buffer, so each poll costs O(new bytes)
    instead of re-parsing a multi-day run's whole file.  A line without
    its newline yet is a write IN PROGRESS, not a torn tail — it stays
    buffered until complete (only a decode failure on a COMPLETE line
    counts as skipped).  File truncation (rotation) resets the tail.

    Two consumption styles: ``run_monitor --follow`` re-reads the
    cumulative ``events`` list each poll; the live collector calls
    ``drain()`` to take ownership of just the new events (bounded
    memory — the collector archives them, it must not also hoard
    them)."""

    def __init__(self, path: str):
        self.path = path
        self.offset = 0
        self._buf = ""
        self.events: list = []
        self.skipped = 0

    def poll(self) -> None:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return  # transiently unreadable; next poll retries
        if size < self.offset:  # truncated/rotated underneath us
            self.offset, self._buf = 0, ""
            self.events, self.skipped = [], 0
        with open(self.path) as f:
            f.seek(self.offset)
            chunk = f.read()
            self.offset = f.tell()
        *lines, self._buf = (self._buf + chunk).split("\n")
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                self.events.append(json.loads(line))
            except json.JSONDecodeError:
                self.skipped += 1

    def drain(self) -> list:
        """Take the accumulated events (clears the list, keeps the byte
        offset and partial-line buffer — the tail keeps tailing)."""
        out, self.events = self.events, []
        return out
