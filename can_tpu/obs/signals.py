"""Elastic preemption signal files: the monitor ↔ supervisor interface.

One tiny JSON file per (kind, host) in a shared directory is how
detection and reaction COMPOSE without a new daemon: ``tools/
run_monitor.py --emit-signal`` writes a ``dead`` file when a host's
heartbeat goes stale, a preempted host's SIGTERM hook writes its own
``leave`` file, and the elastic supervisor (parallel/elastic.py) polls
the directory from its per-step hook — whoever detects first, the
reaction path is the same.  ``stay`` files carry a survivor's
re-rendezvous address through a shrink.

Writes are atomic (tmp + rename) so a reader never sees a torn file;
foreign/undecodable JSON is skipped on read.  This module lives in
``can_tpu.obs`` (not beside the supervisor) because it must be
importable with ZERO jax — run_monitor's contract is pure host-side
file reading, runnable on any machine the artifacts were copied to.
"""

from __future__ import annotations

import json
import os
import time
from typing import Iterable, List, Optional, Set

SIGNAL_SCHEMA = "can_tpu.elastic.signal.v1"
SIGNAL_KINDS = ("leave", "dead", "stay")


def signal_path(signal_dir: str, kind: str, host_id: int) -> str:
    return os.path.join(signal_dir, f"signal-{kind}-h{int(host_id)}.json")


def write_signal(signal_dir: str, *, kind: str, host_id: int, reason: str,
                 detail: Optional[dict] = None,
                 ts: Optional[float] = None) -> str:
    """One machine-readable elastic signal file, written atomically.

    * ``leave`` — a host announces its own preemption (SIGTERM hook);
    * ``dead``  — an external monitor declares a host dead
      (``run_monitor --emit-signal``);
    * ``stay``  — a survivor advertises its re-rendezvous address during
      a shrink (consumed by ``elastic.reform_coordinator``).
    """
    if kind not in SIGNAL_KINDS:
        raise ValueError(f"unknown signal kind {kind!r} "
                         f"(known: {', '.join(SIGNAL_KINDS)})")
    os.makedirs(signal_dir, exist_ok=True)
    path = signal_path(signal_dir, kind, host_id)
    doc = {"schema": SIGNAL_SCHEMA, "kind": kind, "host_id": int(host_id),
           "reason": str(reason), "ts": time.time() if ts is None else ts,
           "detail": detail or {}}
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)
    return path


def read_signals(signal_dir: str) -> List[dict]:
    """Every valid signal file in the dir, sorted by filename.  Torn or
    foreign JSON is skipped (atomic writes make torn rare; skipping is
    the correct read for a shared directory)."""
    out = []
    try:
        names = sorted(os.listdir(signal_dir))
    except OSError:
        return []
    for name in names:
        if not (name.startswith("signal-") and name.endswith(".json")):
            continue
        try:
            with open(os.path.join(signal_dir, name)) as f:
                doc = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if isinstance(doc, dict) and doc.get("schema") == SIGNAL_SCHEMA:
            out.append(doc)
    return out


def leaver_hosts(signals: Iterable[dict]) -> Set[int]:
    """Hosts that leave/dead signals name — a host's local contribution
    to the fleet's shrink agreement mask."""
    return {int(s["host_id"]) for s in signals
            if s.get("kind") in ("leave", "dead")
            and isinstance(s.get("host_id"), int)}
