"""Run-health detectors: watch a training run while it is ALIVE.

The bus (obs/bus.py) records what happened; nothing watched the stream
until after the fact — a diverging loss, a dying input pipeline, or a
silent throughput regression was a post-mortem discovery in the JSONL.
This module turns the per-window metric fetch the loop already does into
live ``health.alert`` events (same bus, same sinks, so alerts land in the
JSONL, the /metrics exporter, and ``tools/run_monitor.py`` alike).

Signals and detector kinds:

* ``loss``       — ``spike`` (EWMA+MAD outlier), ``plateau`` (no EWMA
                   improvement for ``plateau_patience`` steps), ``nan``
                   (the abort path: emitted BEFORE ``NonFiniteLossError``
                   propagates, so the artifact says why the run died).
* ``grad_norm``  — ``spike`` and ``nan_precursor`` (a non-finite or
                   exploding gradient norm usually precedes the NaN loss
                   by a window; the norm is computed INSIDE the jitted
                   step — see ``train/steps.py make_train_step``
                   ``health_metrics`` — so it rides the existing windowed
                   fetch with zero extra device syncs).
* ``step_time``  — ``throughput_regression``: the window's median
                   steady-state step time vs a rolling baseline of recent
                   windows (compile first-calls are already excluded from
                   the samples, so a new bucket shape is not a
                   regression).
* ``input``      — ``stall_budget``: the epoch's ``stall`` accounting
                   escalated to an alert when starvation exceeds a budget
                   fraction of the epoch.

All thresholds are scale-free (MAD multiples / relative fractions): the
detectors never need to know whether the loss is 1e-4 or 1e4.  Alert
storms are bounded by a per-(signal, kind) cooldown — repeats inside the
cooldown window are counted (``suppressed`` in ``health.summary``), not
emitted.
"""

from __future__ import annotations

import collections
import math
import statistics
from typing import Optional

_EPS = 1e-12


class EwmaMadDetector:
    """EWMA baseline + MAD scale over one scalar stream.

    ``update(x)`` returns None, or an anomaly dict when ``x`` deviates
    from the EWMA by more than ``k`` MADs (after ``warmup`` samples).
    The MAD is floored at ``rel_floor`` of the baseline magnitude so a
    near-constant stream (synthetic data, converged runs) doesn't alert
    on femto-scale jitter.  The baseline keeps adapting THROUGH spikes
    (an EWMA tracks level shifts; a one-off outlier barely moves it),
    and residuals are recorded unconditionally so the scale estimate
    reflects the stream as it actually is.
    """

    def __init__(self, *, alpha: float = 0.15, k: float = 8.0,
                 warmup: int = 8, window: int = 64,
                 rel_floor: float = 1e-3):
        self.alpha = float(alpha)
        self.k = float(k)
        self.warmup = int(warmup)
        self.rel_floor = float(rel_floor)
        self.ewma: Optional[float] = None
        self.n = 0
        self._resid = collections.deque(maxlen=int(window))

    def _mad(self) -> float:
        return statistics.median(self._resid)

    def update(self, x: float) -> Optional[dict]:
        x = float(x)
        if not math.isfinite(x):
            return None  # non-finite is the caller's nan_precursor path
        verdict = None
        if self.ewma is None:
            self.ewma = x
        else:
            resid = abs(x - self.ewma)
            if self.n >= self.warmup and self._resid:
                scale = max(self._mad(),
                            self.rel_floor * max(abs(self.ewma), _EPS))
                deviation = resid / max(scale, _EPS)
                if deviation > self.k:
                    verdict = {"alert": "spike", "value": x,
                               "baseline": self.ewma,
                               "deviation": round(deviation, 2)}
            self._resid.append(resid)
            self.ewma += self.alpha * (x - self.ewma)
        self.n += 1
        return verdict


class PlateauDetector:
    """Fires once when the EWMA of a to-be-minimised series stops
    improving: no new best better than ``tol`` (relative) for
    ``patience`` consecutive updates.  Re-arms after a genuine
    improvement, so a run that un-sticks and re-sticks alerts again."""

    def __init__(self, *, alpha: float = 0.05, patience: int = 200,
                 tol: float = 1e-3, warmup: int = 20):
        self.alpha = float(alpha)
        self.patience = int(patience)
        self.tol = float(tol)
        self.warmup = int(warmup)
        self.ewma: Optional[float] = None
        self.best: Optional[float] = None
        self.since_best = 0
        self.n = 0
        self._fired = False

    def update(self, x: float) -> Optional[dict]:
        x = float(x)
        if not math.isfinite(x):
            return None
        self.ewma = x if self.ewma is None else (
            self.ewma + self.alpha * (x - self.ewma))
        self.n += 1
        if self.n < self.warmup:
            self.best = self.ewma
            return None
        if self.best is None or self.ewma < self.best * (1.0 - self.tol):
            self.best = min(self.best, self.ewma) \
                if self.best is not None else self.ewma
            self.since_best = 0
            self._fired = False
            return None
        self.since_best += 1
        if self.since_best >= self.patience and not self._fired:
            self._fired = True
            return {"alert": "plateau", "value": self.ewma,
                    "baseline": self.best, "stuck_for": self.since_best}
        return None


class ThroughputDetector:
    """Median window step-time vs a rolling baseline of recent windows.

    ``update(median_step_s)`` alerts after ``consec`` consecutive windows
    slower than ``(1 + frac)`` times the rolling-median baseline — a
    sustained regression (thermal throttling, a neighbour stealing host
    CPU, a degraded ICI link), not one noisy window.  The baseline deque
    only ingests NON-regressing windows, so a persistent slowdown cannot
    talk its way into the baseline and silence itself."""

    def __init__(self, *, frac: float = 0.25, consec: int = 3,
                 warmup: int = 3, window: int = 16):
        self.frac = float(frac)
        self.consec = int(consec)
        self.warmup = int(warmup)
        self._base = collections.deque(maxlen=int(window))
        self._slow = 0

    def baseline(self) -> Optional[float]:
        if len(self._base) < self.warmup:
            return None
        return statistics.median(self._base)

    def update(self, median_step_s: float) -> Optional[dict]:
        x = float(median_step_s)
        if not math.isfinite(x) or x <= 0:
            return None
        base = self.baseline()
        if base is not None and x > base * (1.0 + self.frac):
            self._slow += 1
            if self._slow == self.consec:
                return {"alert": "throughput_regression", "value": x,
                        "baseline": base,
                        "slowdown": round(x / base, 3),
                        "windows": self._slow}
            return None
        self._slow = 0
        self._base.append(x)
        return None


class HealthMonitor:
    """Joins the detectors to the bus: one per-run object, fed from the
    train loop's existing windowed metric fetch (``train/loop.py``).

    Emits ``health.alert`` events (payload: signal, kind, value,
    baseline, epoch, ...) and one ``health.summary`` per epoch (alert
    counts by ``signal/kind``, suppressed repeats, last baselines).
    Everything here is host-side arithmetic on already-fetched scalars —
    no device work, no extra syncs; when telemetry is off the loop never
    constructs a monitor and the hot path is untouched.
    """

    #: a spiking value beyond this multiple of its baseline is classed
    #: nan_precursor rather than spike — the "about to overflow" regime
    #: (a ratio, not a MAD count: low-jitter series make MADs tiny, and a
    #: 10% wobble must not read as impending divergence)
    NAN_PRECURSOR_RATIO = 10.0

    def __init__(self, telemetry, *, spike_k: float = 8.0,
                 warmup: int = 8, plateau_patience: int = 200,
                 plateau_tol: float = 1e-3, regress_frac: float = 0.25,
                 regress_consec: int = 3, stall_budget_frac: float = 0.15,
                 cooldown: int = 50):
        self.telemetry = telemetry
        self.stall_budget_frac = float(stall_budget_frac)
        self.cooldown = int(cooldown)
        self._loss = EwmaMadDetector(k=spike_k, warmup=warmup)
        self._grad = EwmaMadDetector(k=spike_k, warmup=warmup)
        self._plateau = PlateauDetector(patience=plateau_patience,
                                        tol=plateau_tol)
        self._rate = ThroughputDetector(frac=regress_frac,
                                        consec=regress_consec)
        self._updates = 0
        self._last_emit: dict = {}  # (signal, kind) -> update index
        self.alerts_total = 0
        self.suppressed_total = 0
        self._counts: dict = {}  # "signal/kind" -> count (incl. suppressed)

    # -- alert fan-out ---------------------------------------------------
    def _alert(self, signal: str, verdict: dict, *, epoch: int,
               step: Optional[int] = None, rate_limit: bool = True,
               **extra) -> None:
        """``rate_limit=False`` for alerts that are already naturally
        bounded (once per epoch / terminal): the cooldown counts per-STEP
        updates, so a short epoch would wrongly swallow them."""
        key = (signal, verdict["alert"])
        tag = f"{signal}/{verdict['alert']}"
        self._counts[tag] = self._counts.get(tag, 0) + 1
        last = self._last_emit.get(key)
        if rate_limit and last is not None \
                and self._updates - last < self.cooldown:
            self.suppressed_total += 1
            return
        self._last_emit[key] = self._updates
        self.alerts_total += 1
        self.telemetry.emit("health.alert", step=step, signal=signal,
                            epoch=epoch, **verdict, **extra)

    def _classify(self, verdict: dict) -> dict:
        """Upgrade a spike verdict to nan_precursor when the value has
        left its baseline's decade — the explosion regime, not noise."""
        base = abs(verdict.get("baseline") or 0.0)
        if abs(verdict["value"]) > self.NAN_PRECURSOR_RATIO * max(base, _EPS):
            return dict(verdict, alert="nan_precursor")
        return verdict

    # -- feed points (called by train/loop.py) ---------------------------
    def on_step_metrics(self, *, loss_per_img: float,
                        grad_norm: Optional[float] = None,
                        update_norm: Optional[float] = None,
                        epoch: int, step: Optional[int] = None) -> None:
        """One fetched step's scalars.  Called inside the metric-flush
        window, so detection lags the device by at most ``check_every``
        steps — the same staleness the NaN abort already has."""
        self._updates += 1
        if grad_norm is not None:
            if not math.isfinite(grad_norm):
                self._alert("grad_norm",
                            {"alert": "nan_precursor", "value": grad_norm,
                             "baseline": self._grad.ewma}, epoch=epoch,
                            step=step)
            else:
                v = self._grad.update(grad_norm)
                if v is not None:
                    self._alert("grad_norm", self._classify(v), epoch=epoch,
                                step=step, update_norm=update_norm)
        v = self._loss.update(loss_per_img)
        if v is not None:
            self._alert("loss", self._classify(v), epoch=epoch, step=step)
        v = self._plateau.update(loss_per_img)
        if v is not None:
            self._alert("loss", v, epoch=epoch, step=step)

    def on_window(self, samples, *, epoch: int, phase: str = "train") -> None:
        """One metric-flush window's steady-state step-time samples (the
        list ``step_window`` events carry; compiles already excluded)."""
        if not samples:
            return
        v = self._rate.update(statistics.median(float(x) for x in samples))
        if v is not None:
            self._alert("step_time", v, epoch=epoch, phase=phase)

    def on_stall(self, *, seconds: float, frac: float, epoch: int,
                 phase: str = "train") -> None:
        """Escalate the epoch's stall accounting: starvation beyond the
        budget fraction means the chip waited on the host — an alert, not
        just a row in the post-mortem table."""
        if frac > self.stall_budget_frac:
            # at most once per epoch by construction — never step-cooled
            # (epochs shorter than the cooldown would silently swallow a
            # persistent starvation condition)
            self._alert("input", {"alert": "stall_budget",
                                  "value": round(frac, 4),
                                  "baseline": self.stall_budget_frac,
                                  "seconds": round(seconds, 3)},
                        epoch=epoch, phase=phase, rate_limit=False)

    def on_nonfinite(self, loss: float, *, epoch: int,
                     step: Optional[int] = None) -> None:
        """The abort path: called by the loop's flush right BEFORE it
        raises ``NonFiniteLossError``, so the alert is on the bus (and
        flushed to the JSONL) when the process dies.  Never rate-limited:
        a dying run's last event must not be swallowed by a cooldown."""
        tag = "loss/nan"
        self._counts[tag] = self._counts.get(tag, 0) + 1
        self.alerts_total += 1
        self.telemetry.emit("health.alert", step=step, signal="loss",
                            alert="nan", value=loss, epoch=epoch)

    def epoch_summary(self, epoch: int) -> None:
        """One ``health.summary`` per epoch: the rollup the monitor and
        the report table read without replaying every alert."""
        self.telemetry.emit(
            "health.summary", epoch=epoch,
            alerts_total=self.alerts_total,
            suppressed=self.suppressed_total,
            counts=dict(sorted(self._counts.items())),
            loss_ewma=self._loss.ewma,
            grad_norm_ewma=self._grad.ewma,
            step_time_baseline_s=self._rate.baseline())
