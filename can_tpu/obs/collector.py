"""Live fleet observability plane: cross-host ingest, global SLO burn,
skew-corrected liveness, federated /metrics.

Every fleet-level question used to be answered POST-HOC: run_monitor,
slo_report and trace_export join the per-host ``telemetry.host*.jsonl``
after the fact, and every live gauge/burn is per host.  The
:class:`FleetCollector` is the live join — one daemon that:

* **ingests** per-host telemetry two ways: tailing local host files
  (the ``obs/join.py`` :class:`~can_tpu.obs.join.HostTail` incremental
  machinery — O(new bytes) per poll, in-progress lines buffered), and an
  HTTP ``POST /ingest`` endpoint for hosts without a shared filesystem
  (batched JSONL, shipped by :class:`CollectorPushSink` riding the
  emitting host's own bus);
* **estimates clock skew** per host: each heartbeat's ``ts`` against the
  collector's receive clock; the offset freezes at the median of the
  first few samples (snapped to zero under ``snap_s`` — emit latency is
  not skew) and is subtracted before ANY merge or liveness judgement,
  surfaced as ``can_tpu_host_clock_skew_s{host}``;
* **evaluates GLOBAL SLO burn** by releasing the joined stream in
  ``(corrected_ts, host, seq)`` order — a watermark merge: events are
  held until every live host has reported past them — into ONE
  ``obs/slo.py`` engine.  The correctness oracle: replaying the
  snapshot's host files offline through ``slo_report`` (which applies
  the manifest's recorded offsets) grades BIT-IDENTICALLY — same
  ``slo.burn`` payload sequence, same verdict — because the release
  order reproduces exactly the offline stable-sort-by-ts of the files
  concatenated in host order, and both sides share the same feed/tail/
  aggregate code (``slo.replay_evals`` / ``slo.aggregate_grade``).
  Burn evaluation rides the EVENT clock, never the wall clock — a
  quiet fleet stops evaluating, exactly like the replay;
* **detects silent hosts**: heartbeat staleness on the CORRECTED clock
  (``join.corrected_staleness``) past ``stale_after_s`` marks the host
  stale — "no data ≠ healthy" — emitting one edge-triggered
  ``fleet.host`` event (incident bundle via ``obs/incidents.py``) and,
  when ``signal_dir`` is set, the same ``dead`` signal file grammar
  ``run_monitor --emit-signal`` writes, so detection drives the elastic
  shrink reaction with no new plumbing.  A stale host drops out of the
  watermark so the live stream keeps flowing without it;
* **bounds memory**: per-host gauges are O(metrics), recent raw events
  ride a per-host :class:`~can_tpu.obs.flightrec.FlightRecorder` ring
  (chatty kinds capped), and the pre-watermark hold queue force-freezes
  a host's offset at ``pending_cap`` so an unfrozen host cannot hold
  events hostage;
* **serves**: ``GET /metrics`` — per-host labelled samples + fleet
  rollups (``obs/exporter.py`` ``aggregate_fleet``; one ``# TYPE`` per
  family) + ``can_tpu_fleet_hosts_live`` / ``can_tpu_slo_burn_global
  {objective,window_s}`` — plus ``GET /fleet/status`` (JSON) and
  ``GET /healthz``.

Known limit (documented, not silent): a host that backfills OLD
timestamps after being marked stale feeds late relative to the offline
sort; the snapshot replay remains the ground truth for grading.

Snapshots: with ``snapshot_dir`` set, every ingested event is archived
verbatim to ``telemetry.host{k}.jsonl`` beside the collector's own bus
(``fleet.jsonl``) and an atomically-replaced ``collector.json`` manifest
(measured offsets, host states, counts) — a self-contained artifact that
``run_monitor`` / ``slo_report`` / ``trace_export`` all recognise via
``obs/join.py``.

Pure host-side code — no JAX import; the collector runs on any box that
can reach the hosts' files or be reached by their push sinks.
"""

from __future__ import annotations

import json
import os
import statistics
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from can_tpu.obs.bus import JsonlSink, Telemetry
from can_tpu.obs.exporter import (
    _PROM_CONTENT_TYPE,
    GaugeSink,
    aggregate_fleet,
    render_prometheus,
)
from can_tpu.obs.flightrec import FlightRecorder
from can_tpu.obs.join import (
    COLLECTOR_MANIFEST,
    COLLECTOR_SCHEMA,
    DEFAULT_SNAP_S,
    HostTail,
    corrected_staleness,
    corrected_ts,
    discover_host_files,
    host_file_name,
    snap_offset,
)
from can_tpu.obs.signals import write_signal
from can_tpu.obs.slo import SloEngine, aggregate_grade, tail_evaluate

#: the collector's own bus host id — outside the real host-id space, so
#: fleet.jsonl events are never confused with host 0's.
COLLECTOR_HOST_ID = -1


class _HostState:
    """Everything the collector tracks per ingesting host."""

    def __init__(self, host_id: int, transport: str, now: float):
        self.host_id = int(host_id)
        self.transport = transport          # "tail" | "push" (first seen)
        self.first_seen = now
        self.seq = 0                        # ingest order within host
        self.pending: deque = deque()       # (seq, raw event) pre-release
        self.offset: Optional[float] = None  # frozen clock offset (s)
        self.samples: List[float] = []      # pre-freeze skew samples
        self.last_raw_ts: Optional[float] = None
        self.last_hb_raw_ts: Optional[float] = None
        self.stale = False
        self.staleness_s: Optional[float] = None
        self.events = 0
        self.torn = 0
        self.fed = 0
        self.gauge_errors = 0
        self.gauges = GaugeSink()           # per-host live gauges (raw ts)
        self.ring = FlightRecorder()        # bounded recent-event window
        self.tail: Optional[HostTail] = None
        self.tail_skipped_seen = 0
        self.archive = None                 # snapshot file handle

    def provisional_offset(self, snap_s: float) -> float:
        """The frozen offset, or the best current estimate (median of
        the samples so far) — what liveness uses before freeze."""
        if self.offset is not None:
            return self.offset
        if self.samples:
            return snap_offset(statistics.median(self.samples),
                               snap_s=snap_s)
        return 0.0


class FleetCollector:
    """The daemon.  Construct, then either ``start()`` (HTTP server +
    poll thread) or drive ``poll(now=...)`` manually (tests inject the
    clock).  ``drain()`` force-releases everything and tail-evaluates —
    after it, ``grade()`` is the final verdict the offline replay must
    match."""

    def __init__(self, spec=None, *, run_dir: str = "",
                 snapshot_dir: str = "", stale_after_s: float = 180.0,
                 snap_s: float = DEFAULT_SNAP_S, freeze_after: int = 3,
                 reorder_slack_s: float = 1.0, pending_cap: int = 4096,
                 signal_dir: str = "", incident_dir: str = "",
                 host: str = "127.0.0.1", port: int = 0,
                 poll_interval_s: float = 2.0, prefix: str = "can_tpu",
                 clock: Callable[[], float] = time.time):
        if run_dir and snapshot_dir and \
                os.path.abspath(run_dir) == os.path.abspath(snapshot_dir):
            raise ValueError(
                "snapshot_dir must differ from run_dir — archiving into "
                "the tailed directory would re-ingest the archive")
        self.spec = spec
        self.run_dir = run_dir
        self.snapshot_dir = snapshot_dir
        self.stale_after_s = float(stale_after_s)
        self.snap_s = float(snap_s)
        self.freeze_after = max(1, int(freeze_after))
        self.reorder_slack_s = float(reorder_slack_s)
        self.pending_cap = max(1, int(pending_cap))
        self.signal_dir = signal_dir
        self.host = host
        self.port = int(port)
        self.poll_interval_s = float(poll_interval_s)
        self.prefix = prefix
        self._clock = clock
        self._lock = threading.RLock()
        self._hosts: Dict[int, _HostState] = {}
        self._evals: List[Tuple[float, dict]] = []
        self._last_payload: Dict[str, dict] = {}
        self._fed = 0
        self._last_fed_ts: Optional[float] = None
        self._torn_unattributed = 0
        self._drained = False
        self._stop: Optional[threading.Event] = None
        self._thread: Optional[threading.Thread] = None
        self._httpd = None
        self._http_thread: Optional[threading.Thread] = None
        if self.snapshot_dir:
            os.makedirs(self.snapshot_dir, exist_ok=True)
        # the collector's OWN bus: fleet.host / collector.ingest /
        # slo.burn land in fleet gauges, a bounded ring, fleet.jsonl
        # (named so a snapshot replay never mistakes it for host data),
        # and — via the watcher list — the incident manager
        self.fleet_gauges = GaugeSink(prefix)
        self.recorder = FlightRecorder()
        sinks: list = [self.fleet_gauges, self.recorder]
        if self.snapshot_dir:
            sinks.append(JsonlSink(os.path.join(self.snapshot_dir,
                                                "fleet.jsonl")))
        self.tel = Telemetry(sinks, host_id=COLLECTOR_HOST_ID, clock=clock)
        self.incidents = None
        if incident_dir:
            from can_tpu.obs.incidents import IncidentManager

            self.incidents = IncidentManager(
                self.tel, self.recorder, incident_dir=incident_dir,
                gauges=self.fleet_gauges, host_id=COLLECTOR_HOST_ID,
                clock=clock)
            self.tel.watchers.append(self.incidents)
        # ONE global engine over the merged stream; its slo.burn
        # emissions ride the fleet bus (gauges, ring, incident trigger).
        # It is NOT a bus watcher — only released host events feed it.
        self.engine = SloEngine(spec, telemetry=self.tel) if spec else None

    # -- ingest -----------------------------------------------------------
    def _host_locked(self, host_id: int, transport: str,
                     now: float) -> _HostState:
        st = self._hosts.get(int(host_id))
        if st is None:
            st = _HostState(host_id, transport, now)
            if self.snapshot_dir:
                st.archive = open(os.path.join(
                    self.snapshot_dir, host_file_name(host_id)), "a")
            self._hosts[int(host_id)] = st
        return st

    def _ingest_locked(self, st: _HostState, events, now: float) -> int:
        n = 0
        for e in events:
            if not isinstance(e, dict):
                st.torn += 1
                continue
            n += 1
            st.events += 1
            st.seq += 1
            ts = e.get("ts")
            if isinstance(ts, (int, float)) and not isinstance(ts, bool):
                fts = float(ts)
                st.last_raw_ts = (fts if st.last_raw_ts is None
                                  else max(st.last_raw_ts, fts))
                if e.get("kind") == "heartbeat":
                    st.last_hb_raw_ts = (fts if st.last_hb_raw_ts is None
                                         else max(st.last_hb_raw_ts, fts))
                    if st.offset is None:
                        # the skew measurement: host clock vs ours, at
                        # the least-buffered event the host emits
                        st.samples.append(fts - now)
                        if len(st.samples) >= self.freeze_after:
                            st.offset = snap_offset(
                                statistics.median(st.samples),
                                snap_s=self.snap_s)
            try:
                st.gauges.emit(e)
            except Exception as ex:  # noqa: BLE001 — one malformed
                # payload must not kill ingest; the event still archives
                # and feeds the engine (which type-guards its samples)
                st.gauge_errors += 1
                if st.gauge_errors == 1:
                    print(f"[collector] host {st.host_id} gauge update "
                          f"failed: {type(ex).__name__}: {ex}", flush=True)
            st.ring.emit(e)
            if st.archive is not None:
                st.archive.write(json.dumps(e) + "\n")
            st.pending.append((st.seq, e))
            if len(st.pending) >= self.pending_cap and st.offset is None:
                # bounded hold: a host that never heartbeats cannot keep
                # the fleet's merge (or our memory) hostage
                st.offset = st.provisional_offset(self.snap_s)
        return n

    def ingest_events(self, host_id: int, events, *,
                      transport: str = "push", torn: int = 0,
                      now: Optional[float] = None) -> int:
        """Ingest one batch for one host (the push handler and the tail
        poll both land here).  Returns the number of events accepted."""
        now = self._clock() if now is None else now
        with self._lock:
            st = self._host_locked(host_id, transport, now)
            st.torn += int(torn)
            n = self._ingest_locked(st, events, now)
        if n or torn:
            self.tel.emit("collector.ingest", host=int(host_id), events=n,
                          torn=int(torn), transport=transport)
        return n

    def ingest_push(self, body: bytes) -> dict:
        """``POST /ingest`` body: batched JSONL (one bus event per
        line), grouped by each event's own ``host_id``.  Undecodable
        lines are counted torn — unattributed when the line never parsed
        far enough to name a host.  The push CLIENT ships whole lines
        (``CollectorPushSink``); in-progress-line buffering is the tail
        transport's job (``HostTail``)."""
        text = body.decode("utf-8", errors="replace")
        by_host: Dict[int, list] = {}
        torn = 0
        for line in text.split("\n"):
            line = line.strip()
            if not line:
                continue
            try:
                e = json.loads(line)
            except json.JSONDecodeError:
                torn += 1
                continue
            if not isinstance(e, dict):
                torn += 1
                continue
            try:
                hid = int(e.get("host_id", 0))
            except (TypeError, ValueError):
                torn += 1
                continue
            by_host.setdefault(hid, []).append(e)
        accepted = 0
        for hid in sorted(by_host):
            accepted += self.ingest_events(hid, by_host[hid],
                                           transport="push")
        if torn:
            with self._lock:
                self._torn_unattributed += torn
        return {"accepted": accepted, "torn": torn,
                "hosts": sorted(by_host)}

    # -- the poll loop ----------------------------------------------------
    def poll(self, now: Optional[float] = None) -> None:
        """One collector iteration: advance the tails, judge liveness,
        release the watermark batch into the global engine, refresh the
        snapshot manifest.  Tests drive this directly with an injected
        ``now``; ``start()``'s thread loops it."""
        now = self._clock() if now is None else now
        ingests: List[Tuple[int, int, int]] = []
        with self._lock:
            if self.run_dir:
                for hid, path in discover_host_files(self.run_dir).items():
                    st = self._host_locked(hid, "tail", now)
                    if st.tail is None or st.tail.path != path:
                        st.tail = HostTail(path)
                        st.tail_skipped_seen = 0
                    st.tail.poll()
                    new = st.tail.drain()
                    delta = st.tail.skipped - st.tail_skipped_seen
                    if delta < 0:  # rotation reset the tail's counter
                        delta = st.tail.skipped
                    st.tail_skipped_seen = st.tail.skipped
                    if new or delta:
                        self._ingest_locked(st, new, now)
                        st.torn += delta
                        ingests.append((hid, len(new), delta))
            transitions = self._liveness_locked(now)
            batch = self._release_locked()
        for hid, n, delta in ingests:
            self.tel.emit("collector.ingest", host=hid, events=n,
                          torn=delta, transport="tail")
        for t in transitions:
            self.tel.emit("fleet.host", **t)
            if t["state"] == "stale" and self.signal_dir:
                # the exact grammar run_monitor --emit-signal writes, so
                # the elastic supervisor's reaction needs no new wiring
                path = write_signal(
                    self.signal_dir, kind="dead", host_id=t["host"],
                    reason="heartbeat_stale",
                    detail={"staleness_s": t["staleness_s"],
                            "source": "collector"}, ts=now)
                print(f"[collector] dead-host signal -> {path}",
                      flush=True)
        with self._lock:
            self._feed_locked(batch)
        self._write_manifest(now)

    def _liveness_locked(self, now: float) -> List[dict]:
        """Edge-triggered host state transitions on the skew-corrected
        clock.  A host with NO timestamped data yet ages from its first
        contact — silence is never health."""
        out = []
        for hid in sorted(self._hosts):
            st = self._hosts[hid]
            ref = (st.last_hb_raw_ts if st.last_hb_raw_ts is not None
                   else st.last_raw_ts)
            if ref is None:
                staleness = now - st.first_seen
            else:
                staleness = corrected_staleness(
                    ref, st.provisional_offset(self.snap_s), now)
            st.staleness_s = staleness
            stale = staleness > self.stale_after_s
            if stale != st.stale:
                st.stale = stale
                out.append({"host": hid,
                            "state": "stale" if stale else "live",
                            "staleness_s": round(staleness, 3),
                            "transport": st.transport})
        if out:
            live = sum(1 for s in self._hosts.values() if not s.stale)
            for t in out:
                t["live"] = live
                t["stale"] = len(self._hosts) - live
        return out

    def _release_locked(self, drain: bool = False) -> List[tuple]:
        """The watermark merge.  Watermark = min over live frozen hosts
        of (newest corrected ts − reorder slack): nothing releases until
        every host still counted on has reported past it, so the release
        order — sorted ``(corrected_ts, host, seq)`` — reproduces the
        offline stable-sort exactly.  Stale hosts drop out of the
        minimum (their silence must not dam the fleet); an unfrozen host
        with pending events blocks until it freezes (bounded by
        ``pending_cap``)."""
        marks = []
        for st in self._hosts.values():
            if st.stale:
                continue
            if st.offset is None:
                if st.pending and not drain:
                    return []
                continue
            if st.last_raw_ts is not None:
                marks.append(corrected_ts(st.last_raw_ts, st.offset))
        if drain:
            wm = float("inf")
        elif not marks:
            return []
        else:
            wm = min(marks) - self.reorder_slack_s
        batch = []
        for hid, st in self._hosts.items():
            if drain and st.offset is None:
                st.offset = st.provisional_offset(self.snap_s)
            off = st.offset if st.offset is not None else 0.0
            while st.pending:
                seq, e = st.pending[0]
                ts = e.get("ts")
                if not isinstance(ts, (int, float)) \
                        or isinstance(ts, bool):
                    # archived + gauged already; the engine feed skips
                    # non-timestamped events exactly like the replay
                    st.pending.popleft()
                    continue
                cts = corrected_ts(float(ts), off)
                if cts > wm:
                    break
                st.pending.popleft()
                batch.append((cts, hid, seq, e))
        batch.sort(key=lambda t: (t[0], t[1], t[2]))
        return batch

    def _feed_locked(self, batch: List[tuple]) -> None:
        for cts, hid, seq, e in batch:
            # zero-offset events pass through UNTOUCHED (int ts stays
            # int), matching join.apply_offsets — the replay side
            ev = e if e.get("ts") == cts else dict(e, ts=cts)
            self._fed += 1
            self._last_fed_ts = cts
            self._hosts[hid].fed += 1
            if self.engine is None:
                continue
            out = self.engine.on_event(ev)
            if out:
                for p in out:
                    self._evals.append((cts, p))
                    self._last_payload[str(p.get("objective"))] = p

    def drain(self, now: Optional[float] = None) -> None:
        """Terminal flush: freeze every offset, release ALL pending in
        global sorted order, then tail-evaluate at the last fed ts —
        mirroring ``slo.replay_evals`` exactly, so ``grade()`` after a
        drain is what the offline replay of the snapshot computes."""
        now = self._clock() if now is None else now
        with self._lock:
            batch = self._release_locked(drain=True)
            self._feed_locked(batch)
            last_ts = self._last_fed_ts
            self._drained = True
        if self.engine is not None and last_ts is not None:
            payloads = tail_evaluate(self.engine, last_ts)
            with self._lock:
                for p in payloads:
                    self._evals.append((last_ts, p))
                    self._last_payload[str(p.get("objective"))] = p
        self._write_manifest(now)

    # -- verdicts ---------------------------------------------------------
    def evals(self) -> List[Tuple[float, dict]]:
        """Every ``(eval_ts, slo.burn payload)`` so far, in feed order —
        the sequence the bit-identity oracle compares."""
        with self._lock:
            return list(self._evals)

    def grade(self) -> Optional[dict]:
        """The live verdict, through the SAME ``aggregate_grade`` the
        offline ``slo_report`` uses.  Call after ``drain()`` for a final
        grade; mid-run it grades what has been released so far."""
        if self.engine is None:
            return None
        with self._lock:
            evals = list(self._evals)
            fed = self._fed
        return aggregate_grade(self.spec, evals,
                               self.engine.run_totals(), n_events=fed)

    # -- snapshot ---------------------------------------------------------
    def _host_row_locked(self, st: _HostState) -> dict:
        return {
            "clock_offset_s": st.provisional_offset(self.snap_s),
            "offset_frozen": st.offset is not None,
            "skew_samples": len(st.samples),
            "state": "stale" if st.stale else "live",
            "staleness_s": (round(st.staleness_s, 3)
                            if st.staleness_s is not None else None),
            "transport": st.transport,
            "events": st.events,
            "torn": st.torn,
            "fed": st.fed,
            "pending": len(st.pending),
            "last_ts": st.last_raw_ts,
            "last_heartbeat_ts": st.last_hb_raw_ts,
        }

    def _write_manifest(self, now: Optional[float] = None) -> Optional[str]:
        """Atomic ``collector.json`` refresh (tmp + rename — the
        manifest-written-last contract: a reader that sees it sees a
        consistent snapshot; the archives were flushed first)."""
        if not self.snapshot_dir:
            return None
        with self._lock:
            for st in self._hosts.values():
                if st.archive is not None:
                    st.archive.flush()
            doc = {
                "schema": COLLECTOR_SCHEMA,
                "ts": self._clock() if now is None else now,
                "stale_after_s": self.stale_after_s,
                "snap_s": self.snap_s,
                "reorder_slack_s": self.reorder_slack_s,
                "drained": self._drained,
                "objectives": ([o.name for o in self.spec.objectives]
                               if self.spec else []),
                "hosts": {str(hid): self._host_row_locked(st)
                          for hid, st in sorted(self._hosts.items())},
                "counts": {
                    "events": sum(s.events for s in self._hosts.values()),
                    "torn": sum(s.torn for s in self._hosts.values()),
                    "torn_unattributed": self._torn_unattributed,
                    "fed": self._fed,
                    "evaluations": len(self._evals),
                },
            }
        path = os.path.join(self.snapshot_dir, COLLECTOR_MANIFEST)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        return path

    # -- reads ------------------------------------------------------------
    def status(self) -> dict:
        """The ``/fleet/status`` document."""
        with self._lock:
            live = sum(1 for s in self._hosts.values() if not s.stale)
            return {
                "hosts": {str(hid): self._host_row_locked(st)
                          for hid, st in sorted(self._hosts.items())},
                "hosts_live": live,
                "hosts_stale": len(self._hosts) - live,
                "events": sum(s.events for s in self._hosts.values()),
                "torn": (sum(s.torn for s in self._hosts.values())
                         + self._torn_unattributed),
                "fed": self._fed,
                "evaluations": len(self._evals),
                "drained": self._drained,
                "slo": {name: {"alerting": p.get("alerting"),
                               "burn_max": p.get("burn_max"),
                               "windows": p.get("windows")}
                        for name, p in sorted(self._last_payload.items())},
            }

    def render_metrics(self) -> str:
        """The federated exposition: per-host labelled samples + fleet
        rollups (one ``# TYPE`` per family), collector vitals, and the
        GLOBAL burn — ``can_tpu_slo_burn_global{objective,window_s}``
        from the one engine that saw the merged stream (a per-host fold
        cannot compute a cross-host quantile; this can)."""
        pre = self.prefix
        with self._lock:
            snaps = {hid: st.gauges.snapshot()
                     for hid, st in self._hosts.items()}
            g, c, lg = aggregate_fleet(snaps)
            live = sum(1 for s in self._hosts.values() if not s.stale)
            g[f"{pre}_fleet_hosts_live"] = float(live)
            g[f"{pre}_fleet_hosts_stale"] = float(len(self._hosts) - live)
            g[f"{pre}_collector_pending_events"] = float(
                sum(len(s.pending) for s in self._hosts.values()))
            c[(f"{pre}_collector_fed_events_total", ())] = float(self._fed)
            if self._torn_unattributed:
                c[(f"{pre}_collector_torn_unattributed_total", ())] = \
                    float(self._torn_unattributed)
            for hid, st in sorted(self._hosts.items()):
                hl = (("host", str(hid)),)
                lg[(f"{pre}_host_clock_skew_s", hl)] = \
                    float(st.provisional_offset(self.snap_s))
                if st.staleness_s is not None:
                    lg[(f"{pre}_host_staleness_s", hl)] = \
                        round(float(st.staleness_s), 3)
                lg[(f"{pre}_host_stale", hl)] = 1.0 if st.stale else 0.0
                c[(f"{pre}_collector_events_total", hl)] = float(st.events)
                if st.torn:
                    c[(f"{pre}_collector_torn_total", hl)] = \
                        float(st.torn)
            for name, p in sorted(self._last_payload.items()):
                ol = ("objective", name)
                for w, info in (p.get("windows") or {}).items():
                    if isinstance(info, dict) \
                            and info.get("burn") is not None:
                        lg[(f"{pre}_slo_burn_global",
                            (ol, ("window_s", str(w))))] = \
                            float(info["burn"])
                lg[(f"{pre}_slo_alerting_global", (ol,))] = \
                    1.0 if p.get("alerting") else 0.0
        return render_prometheus(g, c, lg)

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "FleetCollector":
        """HTTP endpoints + the poll loop, both daemon threads."""
        self._start_server()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="can-tpu-fleet-collector")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll()
            except Exception as e:  # noqa: BLE001 — the plane must
                # outlive one bad poll; the failure itself is the news
                print(f"[collector] poll failed: {type(e).__name__}: {e}",
                      flush=True)

    def _start_server(self) -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        col = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, fmt, *args):  # scrapes are not news
                pass

            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                from urllib.parse import urlparse

                path = urlparse(self.path).path
                if path == "/metrics":
                    self._send(200, col.render_metrics().encode(),
                               _PROM_CONTENT_TYPE)
                elif path == "/fleet/status":
                    self._send(200, json.dumps(col.status()).encode(),
                               "application/json")
                elif path == "/healthz":
                    s = col.status()
                    body = json.dumps({"ok": True,
                                       "hosts_live": s["hosts_live"],
                                       "hosts_stale": s["hosts_stale"]})
                    self._send(200, body.encode(), "application/json")
                else:
                    self._send(404, json.dumps(
                        {"error": f"no such path: {path}"}).encode(),
                        "application/json")

            def do_POST(self):
                from urllib.parse import urlparse

                if urlparse(self.path).path != "/ingest":
                    self._send(404, json.dumps(
                        {"error": "POST /ingest only"}).encode(),
                        "application/json")
                    return
                try:
                    n = int(self.headers.get("Content-Length") or 0)
                    res = col.ingest_push(self.rfile.read(n))
                except Exception as e:  # noqa: BLE001 — a bad request
                    # must answer 400, not kill the handler thread
                    self._send(400, json.dumps(
                        {"error": f"{type(e).__name__}: {e}"}).encode(),
                        "application/json")
                    return
                self._send(200, json.dumps(res).encode(),
                           "application/json")

        self._httpd = ThreadingHTTPServer((self.host, self.port), Handler)
        self.port = self._httpd.server_address[1]  # resolve port=0
        self._http_thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="can-tpu-collector-http")
        self._http_thread.start()

    def close(self, *, drain: bool = True) -> None:
        """Stop the loop, take a final poll, drain (final grade +
        manifest), shut the server, close the archives and the bus."""
        if self._stop is not None:
            self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10.0)
            self._thread = None
        try:
            self.poll()
        except Exception as e:  # noqa: BLE001 — teardown still proceeds
            print(f"[collector] final poll failed: {type(e).__name__}: "
                  f"{e}", flush=True)
        if drain:
            self.drain()
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._http_thread is not None:
            self._http_thread.join(timeout=5.0)
            self._http_thread = None
        with self._lock:
            for st in self._hosts.values():
                if st.archive is not None:
                    st.archive.close()
                    st.archive = None
        self.tel.close()


class CollectorPushSink:
    """Bus sink that ships events to a :class:`FleetCollector`'s
    ``/ingest`` over HTTP — the no-shared-filesystem transport.  An
    ordinary sink (``obs.Telemetry([..., CollectorPushSink(url)])`` or
    the CLIs' ``--collector-push``): ``emit()`` serialises under a lock
    into a bounded queue (drop-OLDEST with a counter when full — recent
    telemetry outranks old); a daemon flusher batches JSONL ``POST``\\ s
    via stdlib urllib.  Failures drop the batch with a counter and warn
    once per failure streak (the bus's sink discipline) — the emitting
    run must never block or die on the collector's availability.
    ``close()`` stops the flusher after a final flush attempt."""

    def __init__(self, url: str, *, capacity: int = 4096,
                 flush_interval_s: float = 0.5, batch_max: int = 500,
                 timeout_s: float = 5.0):
        if "://" not in url:
            url = "http://" + url
        self.url = url.rstrip("/")
        self.capacity = max(1, int(capacity))
        self.flush_interval_s = float(flush_interval_s)
        self.batch_max = max(1, int(batch_max))
        self.timeout_s = float(timeout_s)
        self.dropped = 0
        self.pushed_events = 0
        self.push_failures = 0
        self._warned = False
        self._q: deque = deque()
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="can-tpu-collector-push")
        self._thread.start()

    # -- bus sink protocol ------------------------------------------------
    def emit(self, event: dict) -> None:
        try:
            line = json.dumps(event)
        except (TypeError, ValueError):
            self.dropped += 1  # unserialisable event: counted, not fatal
            return
        with self._lock:
            if len(self._q) >= self.capacity:
                self._q.popleft()
                self.dropped += 1
            self._q.append(line)
        self._wake.set()

    def close(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=2 * self.timeout_s + 5.0)

    # -- the flusher ------------------------------------------------------
    def _run(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.flush_interval_s)
            self._wake.clear()
            self._flush()
        self._flush()  # final flush after stop — close()'s last chance

    def _flush(self) -> None:
        while True:
            with self._lock:
                if not self._q:
                    return
                batch = [self._q.popleft()
                         for _ in range(min(len(self._q), self.batch_max))]
            data = ("\n".join(batch) + "\n").encode()
            req = urllib.request.Request(
                self.url + "/ingest", data=data,
                headers={"Content-Type": "application/x-ndjson"},
                method="POST")
            try:
                with urllib.request.urlopen(req,
                                            timeout=self.timeout_s) as r:
                    r.read()
            except OSError as e:  # URLError subclasses OSError
                self.push_failures += 1
                self.dropped += len(batch)
                if not self._warned:
                    self._warned = True
                    print(f"[collector-push] POST {self.url}/ingest "
                          f"failed ({e}); dropping batches until it "
                          f"recovers", flush=True)
                return
            self.pushed_events += len(batch)
            self._warned = False
