"""Summarize a telemetry JSONL into the numbers an operator asks first.

``tools/telemetry_report.py`` is the CLI; this module is the importable
(and tier-1-tested) core: read events, aggregate, format one table.
Tolerant by design — unknown kinds are counted and otherwise ignored, and
a truncated last line (a run killed mid-write) is skipped, because the
reader's job is post-mortem triage of exactly such runs.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional, Tuple

import numpy as np


def read_events_counted(path: str) -> Tuple[List[dict], int]:
    """Read a telemetry JSONL, returning ``(events, skipped_lines)``.

    A run killed mid-write leaves a torn final line — exactly the runs
    this reader triages — so undecodable lines are skipped, but COUNTED:
    the note distinguishes "clean artifact" from "crashed mid-event"
    (and more than one skip flags real corruption, not a torn tail)."""
    events = []
    skipped = 0
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                events.append(json.loads(line))
            except json.JSONDecodeError:
                skipped += 1  # torn tail write of a killed run
    return events, skipped


def read_events(path: str) -> List[dict]:
    return read_events_counted(path)[0]


def _percentile(samples: List[float], q: float) -> Optional[float]:
    if not samples:
        return None
    return float(np.percentile(np.asarray(samples, np.float64), q))


def summarize(events: Iterable[dict]) -> dict:
    """Aggregate one host's event stream.  Step-time percentiles pool the
    raw per-step samples every ``step_window`` event carries, so they are
    exact over the run, not a merge of per-window approximations."""
    events = list(events)
    by_kind: dict = {}
    samples: List[float] = []
    steps = 0
    images = 0.0
    compile_s = 0.0
    stall_s = 0.0
    stall_events = 0
    peak_hbm = None
    peak_rss_mb = None
    first_ts = None
    last_ts = None
    last_heartbeat_ts = None
    epochs = set()
    serve_lat: List[float] = []
    serve_queue_wait: List[float] = []
    serve_device: List[float] = []
    serve_rejects: dict = {}
    serve_batches = 0
    serve_slots = 0
    serve_valid = 0
    serve_queue_depth_max = None
    # stream sessions (serve/streams.py): degraded answers off
    # serve.request, lifecycle/ladder/pin counts off the stream.* kinds
    stream_degraded = 0
    stream_staleness: List[float] = []
    stream_sessions_last = None
    stream_evictions = 0
    stream_degrade_by_rung: dict = {}
    stream_repins = 0
    # scheduling core (can_tpu/sched): per-flush economics off serve.batch
    sched_padded = 0
    sched_pred_px = 0.0
    sched_real_px = 0.0
    sched_mismatches = 0
    perf_last: Optional[dict] = None
    span_names: dict = {}
    fleet_rollouts = 0
    fleet_generation = None
    fleet_quarantines: dict = {}
    fleet_states: dict = {}
    fleet_scale = {"up": 0, "down": 0}
    fleet_live_last = None
    fleet_resurrections = 0
    fleet_probes = {"ok": 0, "failed": 0}
    fleet_ttfr_last = None
    fleet_host_states: dict = {}
    fleet_host_stale_events = 0
    collector_ingested = 0
    collector_torn = 0
    cache_last: Optional[dict] = None
    planner_last: Optional[dict] = None
    prepared_splits: dict = {}
    alerts: dict = {}
    health_last: Optional[dict] = None
    incidents_by_reason: dict = {}
    incident_last: Optional[dict] = None
    slo_last: dict = {}
    slo_alert_events = 0
    elastic_transitions = 0
    elastic_last: Optional[dict] = None
    for e in events:
        kind = e.get("kind", "?")
        by_kind[kind] = by_kind.get(kind, 0) + 1
        ts = e.get("ts")
        if isinstance(ts, (int, float)):
            first_ts = ts if first_ts is None else min(first_ts, ts)
            last_ts = ts if last_ts is None else max(last_ts, ts)
        p = e.get("payload", {})
        if kind == "step_window":
            steps += int(p.get("steps", 0))
            images += float(p.get("images", 0.0))
            samples.extend(float(s) for s in p.get("samples_s", ()))
        elif kind == "compile":
            compile_s += float(p.get("seconds", 0.0))
        elif kind == "stall":
            stall_s += float(p.get("seconds", 0.0))
            stall_events += int(p.get("count", 0))
        elif kind == "memory":
            for d in p.get("devices", ()):
                for key in ("peak_bytes_in_use", "bytes_in_use"):
                    if key in d:
                        v = int(d[key])
                        peak_hbm = v if peak_hbm is None else max(peak_hbm, v)
                        break
            rss = p.get("host_rss_mb")
            if rss is not None:
                peak_rss_mb = (rss if peak_rss_mb is None
                               else max(peak_rss_mb, rss))
        elif kind == "heartbeat":
            last_heartbeat_ts = (ts if last_heartbeat_ts is None
                                 else max(last_heartbeat_ts, ts))
        elif kind == "epoch":
            if e.get("step") is not None:
                epochs.add(int(e["step"]))
        elif kind == "serve.request":
            if "latency_s" in p:
                serve_lat.append(float(p["latency_s"]))
            if "queue_wait_s" in p:
                serve_queue_wait.append(float(p["queue_wait_s"]))
            if "device_s" in p:
                serve_device.append(float(p["device_s"]))
            if p.get("degraded"):
                stream_degraded += 1
                if p.get("staleness_s") is not None:
                    stream_staleness.append(float(p["staleness_s"]))
        elif kind == "serve.batch":
            serve_batches += 1
            serve_slots += int(p.get("size", 0))
            serve_valid += int(p.get("valid", 0))
            sched_padded += int(p.get("padded_slots", 0))
            if p.get("predicted_cost_px") is not None:
                from can_tpu.sched.core import costs_match

                sched_pred_px += float(p["predicted_cost_px"])
                sched_real_px += float(p.get("realized_cost_px", 0.0))
                if not costs_match(p["predicted_cost_px"],
                                   p.get("realized_cost_px", 0.0)):
                    sched_mismatches += 1
            depth = p.get("queue_depth")
            if depth is not None:
                d = int(depth)
                serve_queue_depth_max = (
                    d if serve_queue_depth_max is None
                    else max(serve_queue_depth_max, d))
        elif kind == "serve.reject":
            reason = str(p.get("reason", "?"))
            serve_rejects[reason] = (serve_rejects.get(reason, 0)
                                     + int(p.get("count", 1)))
        elif kind == "health.alert":
            tag = f"{p.get('signal', '?')}/{p.get('alert', '?')}"
            alerts[tag] = alerts.get(tag, 0) + 1
        elif kind == "health.summary":
            health_last = p  # per-epoch rollup: the last wins
        elif kind == "data.cache":
            cache_last = p  # counters are cumulative: the last wins
        elif kind == "data.planner":
            planner_last = p  # plan is epoch-invariant: the last wins
        elif kind == "data.prepared":
            split = str(p.get("split", "?"))
            prepared_splits[split] = ("on" if p.get("active")
                                      else f"legacy({p.get('reason', '?')})")
        elif kind == "fleet.rollout":
            fleet_rollouts += 1
            if p.get("generation") is not None:
                g = int(p["generation"])
                fleet_generation = (g if fleet_generation is None
                                    else max(fleet_generation, g))
        elif kind == "fleet.replica":
            rk = str(p.get("replica", "?"))
            fleet_states[rk] = str(p.get("state", "?"))  # last state wins
            if p.get("state") in ("quarantined", "wedged"):
                fleet_quarantines[rk] = fleet_quarantines.get(rk, 0) + 1
        elif kind == "fleet.scale":
            d = str(p.get("direction", "?"))
            fleet_scale[d] = fleet_scale.get(d, 0) + 1
            if p.get("live") is not None:
                fleet_live_last = int(p["live"])
            if p.get("time_to_first_ready_s") is not None:
                fleet_ttfr_last = float(p["time_to_first_ready_s"])
        elif kind == "fleet.resurrect":
            fleet_resurrections += 1
            if p.get("live") is not None:
                fleet_live_last = int(p["live"])
        elif kind == "fleet.probe":
            fleet_probes["ok" if p.get("ok") else "failed"] += 1
        elif kind == "fleet.host":
            hk = str(p.get("host", "?"))
            fleet_host_states[hk] = str(p.get("state", "?"))  # last wins
            if p.get("state") == "stale":
                fleet_host_stale_events += 1
        elif kind == "collector.ingest":
            collector_ingested += int(p.get("events", 0))
            collector_torn += int(p.get("torn", 0))
        elif kind == "stream.session":
            if p.get("active") is not None:
                stream_sessions_last = int(p["active"])
            if p.get("state") == "evicted":
                stream_evictions += 1
        elif kind == "stream.degrade":
            rung = str(p.get("rung", "?"))
            stream_degrade_by_rung[rung] = \
                stream_degrade_by_rung.get(rung, 0) + 1
        elif kind == "stream.repin":
            stream_repins += 1
        elif kind == "incident.bundle":
            reason = str(p.get("reason", "?"))
            incidents_by_reason[reason] = \
                incidents_by_reason.get(reason, 0) + 1
            incident_last = p  # the freshest bundle is the triage entry
        elif kind == "slo.burn":
            slo_last[str(p.get("objective", "?"))] = p  # last eval wins
            if p.get("alerting"):
                slo_alert_events += 1
        elif kind == "elastic.transition":
            elastic_transitions += 1
            elastic_last = p  # the newest world formation wins
        elif kind == "perf.summary":
            perf_last = p  # the ledger is cumulative: the last wins
        elif kind == "trace.span":
            name = str(p.get("name", "?"))
            span_names[name] = span_names.get(name, 0) + 1
    wall_s = (last_ts - first_ts) if first_ts is not None else None
    return {
        "events": len(events),
        "by_kind": dict(sorted(by_kind.items())),
        "steps": steps,
        "images": images,
        "epochs": len(epochs),
        "wall_s": round(wall_s, 3) if wall_s is not None else None,
        "step_p50_s": _percentile(samples, 50),
        "step_p95_s": _percentile(samples, 95),
        "step_max_s": max(samples) if samples else None,
        "recompiles": by_kind.get("compile", 0),
        "compile_s": round(compile_s, 3),
        "stall_s": round(stall_s, 3),
        "stall_events": stall_events,
        "peak_hbm_bytes": peak_hbm,
        "peak_host_rss_mb": peak_rss_mb,
        "heartbeats": by_kind.get("heartbeat", 0),
        "last_heartbeat_ts": last_heartbeat_ts,
        # online serving (can_tpu/serve); zeros/Nones for offline runs
        "serve_requests": by_kind.get("serve.request", 0),
        "serve_latency_p50_s": _percentile(serve_lat, 50),
        "serve_latency_p95_s": _percentile(serve_lat, 95),
        "serve_latency_max_s": max(serve_lat) if serve_lat else None,
        "serve_batches": serve_batches,
        "serve_mean_fill": (round(serve_valid / serve_slots, 4)
                            if serve_slots else None),
        "serve_rejects": sum(serve_rejects.values()),
        "serve_rejects_by_reason": dict(sorted(serve_rejects.items())),
        "serve_queue_depth_max": serve_queue_depth_max,
        # scheduling core (can_tpu/sched); Nones/zeros pre-r14 artifacts
        "sched_fill_pct": (round(100.0 * serve_valid / serve_slots, 2)
                           if serve_slots else None),
        "sched_padded_slots": sched_padded,
        "sched_predicted_cost_px": round(sched_pred_px, 1),
        "sched_realized_cost_px": round(sched_real_px, 1),
        "sched_cost_mismatches": sched_mismatches,
        # per-request breakdown (from the span timestamps; Nones pre-r9)
        "serve_queue_wait_p50_s": _percentile(serve_queue_wait, 50),
        "serve_queue_wait_p95_s": _percentile(serve_queue_wait, 95),
        "serve_device_p95_s": _percentile(serve_device, 95),
        # stream sessions (serve/streams.py); zeros/Nones pre-stream
        "stream_sessions": stream_sessions_last,
        "stream_degraded": stream_degraded,
        "stream_staleness_p95_s": _percentile(stream_staleness, 95),
        "stream_degrade_transitions": dict(
            sorted(stream_degrade_by_rung.items())),
        "stream_repins": stream_repins,
        "stream_evictions": stream_evictions,
        # serving fleet (can_tpu/serve/fleet.py); zeros/empty single-engine
        "fleet_rollouts": fleet_rollouts,
        "fleet_generation": fleet_generation,
        "fleet_quarantines": sum(fleet_quarantines.values()),
        "fleet_replica_states": dict(sorted(fleet_states.items())),
        # self-healing layer (ISSUE 13): scale transitions, probation
        # probes, resurrections, last time-to-first-ready
        "fleet_scale_up": fleet_scale.get("up", 0),
        "fleet_scale_down": fleet_scale.get("down", 0),
        "fleet_live_replicas": fleet_live_last,
        "fleet_resurrections": fleet_resurrections,
        "fleet_probes_ok": fleet_probes["ok"],
        "fleet_probes_failed": fleet_probes["failed"],
        "fleet_ttfr_last_s": fleet_ttfr_last,
        # fleet observability plane (obs/collector.py): per-HOST
        # liveness transitions + ingest totals; empty/zero off-collector
        "fleet_host_states": dict(sorted(fleet_host_states.items())),
        "fleet_host_stale_events": fleet_host_stale_events,
        "collector_ingested": collector_ingested,
        "collector_torn": collector_torn,
        # host data pipeline (can_tpu/data/prepared.py); Nones/empty offline
        "prepared_splits": dict(sorted(prepared_splits.items())),
        "cache_hits": cache_last.get("hits") if cache_last else None,
        "cache_misses": cache_last.get("misses") if cache_last else None,
        "cache_hit_rate": cache_last.get("hit_rate") if cache_last else None,
        "cache_bytes": cache_last.get("bytes") if cache_last else None,
        "cache_capacity_bytes": (cache_last.get("capacity_bytes")
                                 if cache_last else None),
        "cache_evictions": (cache_last.get("evictions")
                            if cache_last else None),
        # batch planner (can_tpu/data/planner.py); Nones when not emitted
        "planner_mode": planner_last.get("plan_mode") if planner_last else None,
        "planner_padding_overhead": (planner_last.get("padding_overhead")
                                     if planner_last else None),
        "planner_schedule_overhead": (planner_last.get("schedule_overhead")
                                      if planner_last else None),
        "planner_programs": (planner_last.get("program_count")
                             if planner_last else None),
        "planner_lowered_launches": (planner_last.get("lowered_launches")
                                     if planner_last else None),
        "planner_realized_programs": (planner_last.get("realized_programs")
                                      if planner_last else None),
        # run-health layer (can_tpu/obs/health.py); zeros/Nones when off
        "health_alerts": sum(alerts.values()),
        "health_alerts_by_kind": dict(sorted(alerts.items())),
        "health_suppressed": (health_last.get("suppressed")
                              if health_last else None),
        # performance attribution (can_tpu/obs/costs.py + spans.py);
        # Nones/zeros when the ledger/tracer were never armed
        "perf_programs": perf_last.get("perf_programs") if perf_last else None,
        "perf_mfu_weighted": (perf_last.get("mfu_weighted")
                              if perf_last else None),
        "perf_mfu_best": perf_last.get("mfu_best") if perf_last else None,
        "perf_mfu_worst": perf_last.get("mfu_worst") if perf_last else None,
        "perf_roofline_compute": (perf_last.get("roofline_compute_bound")
                                  if perf_last else None),
        "perf_roofline_memory": (perf_last.get("roofline_memory_bound")
                                 if perf_last else None),
        "perf_roofline_unknown": (perf_last.get("roofline_unknown")
                                  if perf_last else None),
        "perf_launch_cost_mpx": (perf_last.get("launch_cost_mpx_empirical")
                                 if perf_last else None),
        "perf_launch_cost_drift": (perf_last.get("launch_cost_drift")
                                   if perf_last else None),
        "perf_peak_nominal": (bool(perf_last.get("peak_nominal"))
                              if perf_last else None),
        "trace_spans": by_kind.get("trace.span", 0),
        "trace_spans_by_name": dict(sorted(span_names.items())),
        # incident layer (can_tpu/obs/incidents.py + slo.py); zeros/empty
        # when never armed
        "incidents": sum(incidents_by_reason.values()),
        "incidents_by_reason": dict(sorted(incidents_by_reason.items())),
        "incident_last_path": (incident_last.get("path")
                               if incident_last else None),
        # elastic transitions (parallel/elastic.py); zeros/Nones when the
        # run never shrank
        "elastic_transitions": elastic_transitions,
        "elastic_last": (None if elastic_last is None else {
            "epoch": elastic_last.get("epoch"),
            "steps_done": elastic_last.get("steps_done"),
            "processes_old": elastic_last.get("processes_old"),
            "processes_new": elastic_last.get("processes_new"),
            "dp_old": elastic_last.get("dp_old"),
            "dp_new": elastic_last.get("dp_new"),
            "lr_scale": elastic_last.get("lr_scale"),
            "remaining_items": elastic_last.get("remaining_items"),
            "reason": elastic_last.get("reason"),
        }),
        "slo_objectives": {
            name: {"burn_min": p.get("burn_min"),
                   "burn_max": p.get("burn_max"),
                   "alerting": bool(p.get("alerting")),
                   "run_good": p.get("run_good"),
                   "run_bad": p.get("run_bad")}
            for name, p in sorted(slo_last.items())},
        "slo_alert_events": slo_alert_events,
    }


def _fmt(v, unit: str = "") -> str:
    if v is None:
        return "-"
    if isinstance(v, float):
        return f"{v:.4g}{unit}"
    return f"{v}{unit}"


def format_report(summary: dict, *, title: str = "telemetry") -> str:
    """One aligned two-column table; the whole contract of the CLI tool."""
    gib = (summary["peak_hbm_bytes"] / 2**30
           if summary["peak_hbm_bytes"] is not None else None)
    rows = [
        ("events", _fmt(summary["events"])),
        ("kinds", " ".join(f"{k}={n}"
                           for k, n in summary["by_kind"].items()) or "-"),
        ("epochs", _fmt(summary["epochs"])),
        ("steps", _fmt(summary["steps"])),
        ("images", _fmt(summary["images"])),
        ("wall", _fmt(summary["wall_s"], " s")),
        ("step p50", _fmt(summary["step_p50_s"], " s")),
        ("step p95", _fmt(summary["step_p95_s"], " s")),
        ("step max", _fmt(summary["step_max_s"], " s")),
        ("recompiles", _fmt(summary["recompiles"])),
        ("compile time", _fmt(summary["compile_s"], " s")),
        ("input stall", _fmt(summary["stall_s"], " s")),
        ("peak HBM", _fmt(round(gib, 3) if gib is not None else None,
                          " GiB")),
        ("peak host RSS", _fmt(summary["peak_host_rss_mb"], " MB")),
        ("heartbeats", _fmt(summary["heartbeats"])),
    ]
    if summary.get("prepared_splits"):
        rows.append(("prepared store",
                     " ".join(f"{k}={v}" for k, v in
                              summary["prepared_splits"].items())))
    if summary.get("cache_hits") is not None:
        cap = summary.get("cache_capacity_bytes")
        rows += [
            ("item cache", f"hits={summary['cache_hits']} "
                           f"misses={summary['cache_misses']} "
                           f"hit_rate={_fmt(summary['cache_hit_rate'])}"),
            ("item cache bytes",
             f"{_fmt(summary['cache_bytes'])} / {_fmt(cap)}"
             f" (evictions={_fmt(summary['cache_evictions'])})"),
        ]
    if summary.get("planner_schedule_overhead") is not None:
        rows.append(
            ("batch planner",
             f"mode={summary['planner_mode']} "
             f"padding={_fmt(summary['planner_padding_overhead'])} "
             f"schedule={_fmt(summary['planner_schedule_overhead'])} "
             f"programs={_fmt(summary['planner_programs'])}"
             + (f" (realized {summary['planner_realized_programs']})"
                if summary.get("planner_realized_programs") is not None
                else "")
             + (f" lowered={summary['planner_lowered_launches']}"
                if summary.get("planner_lowered_launches") else "")))
    if summary.get("perf_programs"):
        nominal = " (NOMINAL peak)" if summary.get("perf_peak_nominal") else ""
        rows.append(
            ("perf MFU",
             f"weighted={_fmt(summary['perf_mfu_weighted'])} "
             f"best={_fmt(summary['perf_mfu_best'])} "
             f"worst={_fmt(summary['perf_mfu_worst'])} "
             f"programs={summary['perf_programs']}{nominal}"))
        rows.append(
            ("perf roofline",
             f"compute={_fmt(summary['perf_roofline_compute'])} "
             f"memory={_fmt(summary['perf_roofline_memory'])} "
             f"unknown={_fmt(summary['perf_roofline_unknown'])}"))
        if summary.get("perf_launch_cost_mpx") is not None:
            rows.append(
                ("perf launch cost",
                 f"empirical={_fmt(summary['perf_launch_cost_mpx'])} Mpx"
                 + (f" drift={_fmt(summary['perf_launch_cost_drift'])}x"
                    if summary.get("perf_launch_cost_drift") is not None
                    else "")))
    if summary.get("trace_spans"):
        names = summary.get("trace_spans_by_name") or {}
        rows.append(("trace spans",
                     f"{summary['trace_spans']} ("
                     + " ".join(f"{k}={n}" for k, n in names.items()) + ")"))
    if summary.get("elastic_transitions"):
        e = summary.get("elastic_last") or {}
        rows.append(
            ("elastic",
             f"transitions={summary['elastic_transitions']} "
             f"last: epoch {_fmt(e.get('epoch'))} "
             f"step {_fmt(e.get('steps_done'))} "
             f"world {_fmt(e.get('processes_old'))}proc/"
             f"dp{_fmt(e.get('dp_old'))} -> "
             f"{_fmt(e.get('processes_new'))}proc/"
             f"dp{_fmt(e.get('dp_new'))} "
             f"lr x{_fmt(e.get('lr_scale'))} "
             f"remaining={_fmt(e.get('remaining_items'))} "
             f"({e.get('reason', '?')})"))
    if summary.get("incidents"):
        by_reason = summary.get("incidents_by_reason") or {}
        rows.append(("incidents",
                     " ".join(f"{k}={n}" for k, n in by_reason.items())))
        if summary.get("incident_last_path"):
            rows.append(("last bundle", summary["incident_last_path"]))
    if summary.get("slo_objectives"):
        parts = []
        for name, o in summary["slo_objectives"].items():
            burn = o.get("burn_max")
            tag = _fmt(burn) if burn is not None else "-"
            parts.append(f"{name}={tag}"
                         + ("(ALERT)" if o.get("alerting") else ""))
        rows.append(("SLO burn (max)", " ".join(parts)))
        if summary.get("slo_alert_events"):
            rows.append(("SLO alert evals",
                         _fmt(summary["slo_alert_events"])))
    if summary.get("health_alerts"):
        by_kind = summary.get("health_alerts_by_kind") or {}
        rows.append(("health alerts",
                     " ".join(f"{k}={n}" for k, n in by_kind.items())))
        if summary.get("health_suppressed"):
            rows.append(("alerts suppressed",
                         _fmt(summary["health_suppressed"])))
    if summary.get("serve_requests") or summary.get("serve_rejects"):
        rejects = summary.get("serve_rejects_by_reason") or {}
        rows += [
            ("serve requests", _fmt(summary["serve_requests"])),
            ("serve p50", _fmt(summary["serve_latency_p50_s"], " s")),
            ("serve p95", _fmt(summary["serve_latency_p95_s"], " s")),
            ("serve max", _fmt(summary["serve_latency_max_s"], " s")),
            ("serve batches", _fmt(summary["serve_batches"])),
            ("serve mean fill", _fmt(summary["serve_mean_fill"])),
            ("serve rejects", " ".join(f"{k}={n}"
                                       for k, n in rejects.items()) or "0"),
            ("serve queue max", _fmt(summary["serve_queue_depth_max"])),
        ]
        if summary.get("serve_queue_wait_p95_s") is not None:
            rows.append(
                ("serve breakdown",
                 f"queue_wait p95={_fmt(summary['serve_queue_wait_p95_s'])} s"
                 f" device p95={_fmt(summary['serve_device_p95_s'])} s"))
        if summary.get("sched_fill_pct") is not None:
            # the scheduling core's per-flush economics (can_tpu/sched):
            # fill %, dead slots, and the predicted==realized invariant
            mism = summary.get("sched_cost_mismatches", 0)
            rows.append(
                ("scheduler",
                 f"fill={_fmt(summary['sched_fill_pct'])}% "
                 f"padded_slots={summary['sched_padded_slots']} "
                 f"predicted={_fmt(summary['sched_predicted_cost_px'])}px "
                 f"realized={_fmt(summary['sched_realized_cost_px'])}px "
                 + ("predicted==realized" if not mism
                    else f"MISMATCHES={mism}")))
    if (summary.get("stream_sessions") is not None
            or summary.get("stream_degraded")
            or summary.get("stream_repins")):
        by_rung = summary.get("stream_degrade_transitions") or {}
        rungs = (" transitions: " + " ".join(f"{k}={n}" for k, n
                                             in by_rung.items())
                 if by_rung else "")
        rows.append(
            ("streams",
             f"sessions={_fmt(summary.get('stream_sessions'))} "
             f"degraded={summary['stream_degraded']} "
             f"staleness p95={_fmt(summary['stream_staleness_p95_s'], ' s')} "
             f"repins={summary['stream_repins']} "
             f"evictions={summary['stream_evictions']}" + rungs))
    if (summary.get("fleet_rollouts") or summary.get("fleet_quarantines")
            or summary.get("fleet_replica_states")):
        states = summary.get("fleet_replica_states") or {}
        rows.append(
            ("serving fleet",
             f"rollouts={summary['fleet_rollouts']} "
             f"generation={_fmt(summary.get('fleet_generation'))} "
             f"quarantines={summary['fleet_quarantines']}"
             + ((" replicas: "
                 + " ".join(f"r{k}={v}" for k, v in states.items()))
                if states else "")))
    if (summary.get("fleet_resurrections") or summary.get("fleet_scale_up")
            or summary.get("fleet_scale_down")
            or summary.get("fleet_probes_ok")
            or summary.get("fleet_probes_failed")):
        rows.append(
            ("fleet healing",
             f"resurrections={summary['fleet_resurrections']} "
             f"probes ok={summary['fleet_probes_ok']}/"
             f"failed={summary['fleet_probes_failed']} "
             f"scale up={summary['fleet_scale_up']}/"
             f"down={summary['fleet_scale_down']}"
             + (f" live={summary['fleet_live_replicas']}"
                if summary.get("fleet_live_replicas") is not None else "")
             + (f" ttfr={_fmt(summary['fleet_ttfr_last_s'])} s"
                if summary.get("fleet_ttfr_last_s") is not None else "")))
    if (summary.get("fleet_host_states")
            or summary.get("collector_ingested")
            or summary.get("collector_torn")):
        hosts = summary.get("fleet_host_states") or {}
        rows.append(
            ("fleet hosts",
             f"ingested={summary.get('collector_ingested', 0)} "
             f"torn={summary.get('collector_torn', 0)} "
             f"stale events={summary.get('fleet_host_stale_events', 0)}"
             + ((" hosts: "
                 + " ".join(f"h{k}={v}" for k, v in hosts.items()))
                if hosts else "")))
    width = max(len(k) for k, _ in rows)
    lines = [f"# {title}"]
    lines += [f"{k.ljust(width)}  {v}" for k, v in rows]
    return "\n".join(lines)
