"""Declarative SLOs + multi-window burn rates over the telemetry bus.

The stack exports every signal an autoscaler or pager needs — serve
latency, reject counts, step times, stall fractions, MFU — but only as
raw gauges: "p99 vs deadline" existed as two numbers an operator had to
eyeball.  This module adds the formal layer: a JSON spec declares
OBJECTIVES (a good/bad predicate over one event stream plus a target
good-fraction), and the engine evaluates each as an error-budget BURN
RATE over several sliding windows:

    burn(window) = bad_fraction(window) / (1 - target)

Burn 1.0 means spending the budget exactly at the sustainable rate;
burn 10 means ten times too fast.  An objective ALERTS when its burn
meets ``burn_alert`` on EVERY window — the classic multi-window AND: the
short window proves the problem is happening NOW, the long window proves
it is not a blip (Google SRE workbook ch. 5).  Alerts ride the bus as
``slo.burn`` events, which:

* become ``can_tpu_slo_*`` gauges via ``GaugeSink`` — the scrape-able
  admission/scale-up signal ROADMAP item 2 consumes;
* trigger an incident bundle on fast burn (``obs/incidents.py``);
* land in the JSONL, where ``tools/slo_report.py`` replays a finished
  run against the same spec (same arithmetic, event-time clock) and
  exits nonzero on violation — the CI shape of an SLO.

Spec schema (see the committed ``slo_spec.json``)::

    {"version": 1, "eval_interval_s": 30,
     "objectives": [
       {"name": "serve_p99_deadline",
        "event": "serve.request",      # bus kind sampled
        "field": "latency_s",          # numeric payload key; a LIST
                                       #   field (samples_s) contributes
                                       #   one sample per element; null
                                       #   = each event is one good
        "op": "<=", "threshold": 2.0,  # good when value op threshold
        "bad_kinds": ["serve.reject"], # kinds counted bad (payload
                                       #   "count", default 1)
        "target": 0.95,                # required good fraction
        "windows_s": [60, 300],        # burn windows, short -> long
        "burn_alert": 10.0,            # alert at >= this on ALL windows
        "min_samples": 10}]}           # per window, else burn undefined

The engine is a ``Telemetry.watchers`` entry: it samples every event
(host-side dict reads, no device work), and evaluation is TIME-GATED on
the event stream's own clock — heartbeats keep it live on an otherwise
quiet run, and no new thread exists.  Everything is keyed on event
``ts``, so the offline replay is bit-identical to the live evaluation.
"""

from __future__ import annotations

import dataclasses
import json
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

_OPS = ("<=", ">=")


@dataclasses.dataclass(frozen=True)
class SloObjective:
    """One declared objective (see the module docstring for semantics)."""

    name: str
    event: str
    target: float
    field: Optional[str] = None
    op: str = "<="
    threshold: Optional[float] = None
    bad_kinds: Tuple[str, ...] = ()
    windows_s: Tuple[float, ...] = (60.0, 300.0)
    burn_alert: float = 10.0
    min_samples: int = 10
    description: str = ""

    @property
    def budget(self) -> float:
        """The error budget: allowed bad fraction."""
        return 1.0 - self.target

    def good(self, value: float) -> bool:
        if self.op == "<=":
            return value <= self.threshold
        return value >= self.threshold


def parse_slo_spec(doc: dict) -> "SloSpec":
    """Validate a spec document; raises ``ValueError`` naming the exact
    field (a typo'd spec must fail at CLI-validation time, before any
    runtime init — the path-check contract)."""
    if not isinstance(doc, dict):
        raise ValueError("spec must be a JSON object")
    if doc.get("version") != 1:
        raise ValueError(f"unsupported spec version {doc.get('version')!r} "
                         "(expected 1)")
    objs = doc.get("objectives")
    if not isinstance(objs, list) or not objs:
        raise ValueError("spec needs a non-empty 'objectives' list")
    seen = set()
    out = []
    for i, o in enumerate(objs):
        where = f"objectives[{i}]"
        if not isinstance(o, dict):
            raise ValueError(f"{where}: must be an object")
        name = o.get("name")
        if not name or not isinstance(name, str):
            raise ValueError(f"{where}: needs a string 'name'")
        if name in seen:
            raise ValueError(f"{where}: duplicate objective name {name!r}")
        seen.add(name)
        event = o.get("event")
        if not event or not isinstance(event, str):
            raise ValueError(f"{where} ({name}): needs a string 'event' "
                             "(the bus kind sampled)")
        target = o.get("target")
        if not isinstance(target, (int, float)) or not 0.0 < target < 1.0:
            raise ValueError(f"{where} ({name}): 'target' must be a "
                             "fraction in (0, 1)")
        field = o.get("field")
        if field is not None and not isinstance(field, str):
            raise ValueError(f"{where} ({name}): 'field' must be a string "
                             "payload key or null")
        op = o.get("op", "<=")
        if op not in _OPS:
            raise ValueError(f"{where} ({name}): 'op' must be one of "
                             f"{_OPS}")
        threshold = o.get("threshold")
        if field is not None and not isinstance(threshold, (int, float)):
            raise ValueError(f"{where} ({name}): a value objective "
                             "(field set) needs a numeric 'threshold'")
        windows = o.get("windows_s", [60, 300])
        if (not isinstance(windows, list) or not windows
                or not all(isinstance(w, (int, float)) and w > 0
                           for w in windows)):
            raise ValueError(f"{where} ({name}): 'windows_s' must be a "
                             "non-empty list of positive seconds")
        bad_kinds = o.get("bad_kinds", [])
        if not isinstance(bad_kinds, list) \
                or not all(isinstance(k, str) for k in bad_kinds):
            raise ValueError(f"{where} ({name}): 'bad_kinds' must be a "
                             "list of event kinds")
        out.append(SloObjective(
            name=name, event=event, target=float(target), field=field,
            op=op,
            threshold=(float(threshold)
                       if isinstance(threshold, (int, float)) else None),
            bad_kinds=tuple(bad_kinds),
            windows_s=tuple(sorted(float(w) for w in windows)),
            burn_alert=float(o.get("burn_alert", 10.0)),
            min_samples=int(o.get("min_samples", 10)),
            description=str(o.get("description", ""))))
    interval = doc.get("eval_interval_s", 30.0)
    if not isinstance(interval, (int, float)) or interval <= 0:
        raise ValueError("'eval_interval_s' must be positive seconds")
    return SloSpec(objectives=tuple(out), eval_interval_s=float(interval))


def load_slo_spec(path: str) -> "SloSpec":
    """Read + validate a spec file; ``ValueError`` on unparsable JSON so
    callers handle one exception family for 'bad spec'."""
    with open(path) as f:
        try:
            doc = json.load(f)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}: not valid JSON ({e})") from None
    try:
        return parse_slo_spec(doc)
    except ValueError as e:
        raise ValueError(f"{path}: {e}") from None


@dataclasses.dataclass(frozen=True)
class SloSpec:
    objectives: Tuple[SloObjective, ...]
    eval_interval_s: float = 30.0
    version: int = 1


class _ObjectiveState:
    """Sliding sample log + run totals for one objective."""

    def __init__(self, obj: SloObjective):
        self.obj = obj
        self.samples: deque = deque()  # (ts, good_n, bad_n)
        self.total_good = 0
        self.total_bad = 0
        self.last_value: Optional[float] = None

    def add(self, ts: float, good: int, bad: int) -> None:
        self.samples.append((ts, good, bad))
        self.total_good += good
        self.total_bad += bad

    def prune(self, now: float) -> None:
        floor = now - max(self.obj.windows_s)
        while self.samples and self.samples[0][0] < floor:
            self.samples.popleft()

    def window_counts(self, now: float, window_s: float) -> Tuple[int, int]:
        floor = now - window_s
        good = bad = 0
        for ts, g, b in reversed(self.samples):
            if ts < floor:
                break
            good += g
            bad += b
        return good, bad

    def burn(self, now: float, window_s: float) -> dict:
        """Burn over one window: ``bad_frac / budget``, or None below
        ``min_samples`` (an empty window must read as "not enough data",
        never as "healthy" OR "violating")."""
        good, bad = self.window_counts(now, window_s)
        n = good + bad
        out = {"good": good, "bad": bad, "samples": n, "burn": None}
        if n >= self.obj.min_samples:
            out["burn"] = round((bad / n) / max(self.obj.budget, 1e-9), 4)
        return out


class SloEngine:
    """The evaluator: a bus watcher maintaining per-objective windows.

    telemetry: where ``slo.burn`` events go (None for offline replay —
    :func:`grade_events` reads the returned payloads directly).
    Thread-safe: sampling happens on whichever thread emits, evaluation
    payloads are computed under the lock and emitted outside it (the
    emission re-enters the watcher list; the refreshed ``_last_eval``
    time gate makes that re-entry a no-op).
    """

    def __init__(self, spec: SloSpec, telemetry=None):
        self.spec = spec
        self._tel = telemetry
        self._lock = threading.Lock()
        self._state = {o.name: _ObjectiveState(o) for o in spec.objectives}
        self._last_eval: Optional[float] = None
        self.alerts_total = 0

    # -- sampling ---------------------------------------------------------
    def _sample(self, obj: SloObjective, st: _ObjectiveState,
                kind: str, ts: float, payload: dict) -> None:
        if kind == obj.event:
            if obj.field is None:
                st.add(ts, 1, 0)  # each event is one good; bad_kinds count
                return
            v = payload.get(obj.field)
            values = v if isinstance(v, (list, tuple)) else (v,)
            good = bad = 0
            last = None
            for x in values:
                if not isinstance(x, (int, float)) or isinstance(x, bool):
                    continue
                last = float(x)
                if obj.good(last):
                    good += 1
                else:
                    bad += 1
            if good or bad:
                st.add(ts, good, bad)
                st.last_value = last
        elif kind in obj.bad_kinds:
            n = payload.get("count", 1)
            n = int(n) if isinstance(n, (int, float)) else 1
            st.add(ts, 0, max(n, 1))

    def on_event(self, event: dict) -> Optional[List[dict]]:
        """``Telemetry.watchers`` hook.  Samples the event, and — when
        ``eval_interval_s`` has elapsed on the EVENT clock — evaluates,
        emits, and returns the evaluation payloads (live callers ignore
        the return; the offline replay collects it)."""
        kind = event.get("kind", "")
        if kind.startswith("slo.") or kind.startswith("incident."):
            return None  # our own output must not feed our input
        ts = event.get("ts")
        if not isinstance(ts, (int, float)):
            return None
        with self._lock:
            for st in self._state.values():
                self._sample(st.obj, st, kind, float(ts), event.get(
                    "payload", {}))
            if self._last_eval is None:
                # anchor the gate at the first event; evaluating a
                # single-sample stream would only emit noise
                self._last_eval = float(ts)
                return None
            due = float(ts) - self._last_eval >= self.spec.eval_interval_s
            if due:
                # claim the interval INSIDE the lock: two threads
                # emitting just past the boundary must not both see
                # `due` and double-evaluate (double slo.burn events,
                # inflated alert counters)
                self._last_eval = float(ts)
        if not due:
            return None
        return self.evaluate(float(ts))

    # -- evaluation -------------------------------------------------------
    def evaluate(self, now: float) -> List[dict]:
        """Compute every objective's multi-window burn at ``now``; emit
        one ``slo.burn`` event per objective that has ever sampled (a
        spec may declare serve objectives a train run never feeds — those
        stay silent rather than emitting empty noise forever)."""
        with self._lock:
            self._last_eval = now
            payloads = []
            for name, st in self._state.items():
                st.prune(now)
                if st.total_good + st.total_bad == 0:
                    continue
                obj = st.obj
                windows = {str(int(w)): st.burn(now, w)
                           for w in obj.windows_s}
                burns = [w["burn"] for w in windows.values()
                         if w["burn"] is not None]
                alerting = (len(burns) == len(windows) and bool(burns)
                            and all(b >= obj.burn_alert for b in burns))
                if alerting:
                    self.alerts_total += 1
                payloads.append({
                    "objective": name,
                    "target": obj.target,
                    "op": obj.op,
                    "threshold": obj.threshold,
                    "burn_alert": obj.burn_alert,
                    "windows": windows,
                    "burn_max": max(burns) if burns else None,
                    "burn_min": min(burns) if burns else None,
                    "alerting": alerting,
                    "last_value": st.last_value,
                    "run_good": st.total_good,
                    "run_bad": st.total_bad,
                })
        if self._tel is not None:
            # outside the lock: the emit fans back through the watcher
            # list (incident trigger on alerting burns) and into sinks
            for p in payloads:
                self._tel.emit("slo.burn", **p)
        return payloads

    def run_totals(self) -> Dict[str, Tuple[int, int]]:
        """(good, bad) over the whole run per objective — the offline
        grader's budget check (never pruned)."""
        with self._lock:
            return {name: (st.total_good, st.total_bad)
                    for name, st in self._state.items()}

    def close(self) -> None:
        """Final evaluation at the last seen event time, so a run's tail
        window is graded and the last ``slo.burn`` is in the artifact."""
        with self._lock:
            last = self._last_eval
        if last is not None:
            self.evaluate(last)


def replay_evals(events: Sequence[dict], spec: SloSpec,
                 engine: Optional[SloEngine] = None
                 ) -> Tuple[SloEngine, List[Tuple[float, dict]]]:
    """The offline feed loop, factored so the fleet collector's
    bit-identity oracle IS this code: sort by ``ts`` (Python's stable
    sort — equal timestamps keep input order, which for concatenated
    per-host files means (host, line) order), feed every ts-carrying
    event, tail-evaluate at the final event time unless that event
    itself just evaluated.  Returns the engine and every
    ``(eval_ts, payload)`` pair."""
    if engine is None:
        engine = SloEngine(spec, telemetry=None)
    ordered = sorted((e for e in events
                      if isinstance(e.get("ts"), (int, float))),
                     key=lambda e: e["ts"])
    evals: List[Tuple[float, dict]] = []
    for e in ordered:
        out = engine.on_event(e)
        if out:
            evals.extend((e["ts"], p) for p in out)
    if ordered:
        # tail evaluation at the final event time — unless the final
        # event itself just evaluated (double-counting its alerts)
        last_ts = float(ordered[-1]["ts"])
        evals.extend((last_ts, p)
                     for p in tail_evaluate(engine, last_ts))
    engine._replay_events = len(ordered)
    return engine, evals


def tail_evaluate(engine: SloEngine, last_ts: float) -> List[dict]:
    """Final evaluation at ``last_ts`` — a no-op when the last event
    already evaluated there (the exact rule :func:`replay_evals` uses;
    the live collector's drain calls this so its closing evaluation is
    bit-identical to the offline tail)."""
    with engine._lock:
        already = (engine._last_eval is not None
                   and engine._last_eval >= last_ts)
    return [] if already else engine.evaluate(last_ts)


def grade_events(events: Sequence[dict], spec: SloSpec) -> dict:
    """Offline replay: feed a finished run's events (any order; sorted
    here by ``ts``) through the SAME engine arithmetic, collect every
    evaluation, and grade two ways:

    * **fast burn** — any evaluation where an objective alerted: the
      violation names the objective and its windows (the live pager
      would have fired there).
    * **budget** — the run-total bad fraction exceeds the objective's
      error budget (needs ``min_samples`` total): the run as a whole
      blew its objective even if no single window alerted.

    Returns ``{"objectives": {...}, "violations": [...],
    "evaluations": n, "events": n}`` — ``tools/slo_report.py`` renders
    it and exits 1 on any violation."""
    engine, evals = replay_evals(events, spec)
    return aggregate_grade(spec, evals, engine.run_totals(),
                           n_events=engine._replay_events)


def aggregate_grade(spec: SloSpec, evals: Sequence[Tuple[float, dict]],
                    totals: Dict[str, Tuple[int, int]], *,
                    n_events: int) -> dict:
    """Fold evaluation payloads + run totals into the grade dict —
    shared verbatim by :func:`grade_events` (offline) and the fleet
    collector's live verdict (``obs/collector.py``), so "live == replay"
    is a property of the inputs, never of two graders drifting."""
    objectives: dict = {}
    violations: List[dict] = []
    for obj in spec.objectives:
        good, bad = totals.get(obj.name, (0, 0))
        n = good + bad
        worst: Dict[str, float] = {}
        alert_evals = 0
        first_alert_ts = None
        for ts, p in evals:
            if p["objective"] != obj.name:
                continue
            if p["alerting"]:
                alert_evals += 1
                if first_alert_ts is None:
                    first_alert_ts = ts
            for w, info in p["windows"].items():
                if info["burn"] is not None:
                    worst[w] = max(worst.get(w, 0.0), info["burn"])
        bad_frac = (bad / n) if n else None
        row = {
            "samples": n, "good": good, "bad": bad,
            "bad_frac": round(bad_frac, 6) if bad_frac is not None else None,
            "budget": round(obj.budget, 6),
            "target": obj.target,
            "worst_burn": {w: worst[w] for w in sorted(worst)},
            "alert_evaluations": alert_evals,
            "graded": n >= obj.min_samples,
        }
        objectives[obj.name] = row
        if alert_evals:
            widest = max(obj.windows_s)
            violations.append({
                "objective": obj.name, "kind": "fast_burn",
                "window": "+".join(str(int(w)) for w in obj.windows_s),
                "burn": max(worst.values()) if worst else None,
                "burn_alert": obj.burn_alert,
                "first_at_ts": first_alert_ts,
                "evaluations": alert_evals,
                "detail": (f"burn >= {obj.burn_alert} on every window "
                           f"(up to {int(widest)}s) in {alert_evals} "
                           f"evaluation(s)"),
            })
        elif row["graded"] and bad_frac is not None \
                and bad_frac > obj.budget:
            violations.append({
                "objective": obj.name, "kind": "budget", "window": "run",
                "bad_frac": round(bad_frac, 6),
                "budget": round(obj.budget, 6),
                "detail": (f"run bad fraction {bad_frac:.4g} exceeds the "
                           f"{obj.budget:.4g} error budget "
                           f"(target {obj.target})"),
            })
    return {"objectives": objectives, "violations": violations,
            "evaluations": len(evals), "events": n_events}
