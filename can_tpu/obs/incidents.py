"""IncidentManager: trigger -> one self-contained incident bundle.

The bus records everything and the health layer alerts live, but when a
process actually dies — NaN abort, quarantined replica, preemption
SIGTERM, an exception unwinding through the loop — the context an
operator needs is scattered: the last seconds of telemetry are in a
multi-GB JSONL (or an unflushed buffer), the gauge values are gone with
the exporter, and the Python stacks are gone with the process.  An
incident bundle is one directory holding all of it, written AT the
moment of the trigger:

    incident-<ms>-h<host>-<reason>/
        ring.jsonl      last-N-events flight-recorder dump (bus schema —
                        readable by run_monitor / trace_export /
                        telemetry_report unchanged)
        gauges.json     GaugeSink snapshot (incl. can_tpu_slo_* burns)
        costs.json      ProgramCostLedger rows (per-program MFU/roofline)
        stacks.txt      every Python thread's stack
        memory.json     device-memory + host-RSS snapshot
        incident.json   manifest — schema, reason, severity, run config,
                        exception traceback, ring accounting, extra info
                        sources.  Written LAST, so a bundle torn by
                        SIGKILL mid-write reads as absent, never as
                        trusted-but-partial (the prepared-store rule).

Triggers (wired as a ``Telemetry.watchers`` entry — watchers run after
sink fan-out and OUTSIDE the bus lock, so a trigger may itself emit):

* ``health.alert`` with ``alert`` in nan / stall_budget — the run-health
  layer's "this run is dying / starving" verdicts (obs/health.py; the
  nan alert is emitted BEFORE ``NonFiniteLossError`` unwinds, so the
  bundle exists when the process exits).
* ``fleet.replica`` quarantine — a serving replica just failed out of
  dispatch (serve/fleet.py).
* ``slo.burn`` with ``alerting`` — a fast SLO burn (obs/slo.py).
* :meth:`on_exception` — an unhandled loop exception, called by
  ``train/loop.py`` before the stack unwinds.
* :meth:`on_signal` — SIGTERM/preemption, via
  :func:`install_sigterm_handler`: dump + flush, then chain to the
  previous handler (or raise ``SystemExit`` so the CLI ``finally``
  teardown runs — obs/lifecycle.py).

Bounded by construction: per-reason rate limiting (a NaN alert storm or
a flapping replica writes ONE bundle per cooldown, with suppressed
repeats counted into the next manifest) and directory retention (oldest
bundles beyond ``max_bundles`` are deleted before each write).  A bundle
write failure warns and returns None — incident capture must never kill
the run it is documenting.

This module imports neither jax nor anything that does (the memory
snapshot import is lazy) — bundle reading tools stay runnable anywhere.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import signal
import sys
import threading
import time
import traceback
from typing import Callable, Dict, Optional

BUNDLE_SCHEMA = "can_tpu.incident.v1"
MANIFEST_NAME = "incident.json"
RING_NAME = "ring.jsonl"

#: health.alert payload ``alert`` values that dump a bundle (spikes and
#: plateaus are advisories; nan and stall_budget are the run dying)
TRIGGER_ALERTS = ("nan", "stall_budget")

_SLUG_RE = re.compile(r"[^a-z0-9]+")


def _slug(reason: str) -> str:
    return _SLUG_RE.sub("-", str(reason).lower()).strip("-") or "unknown"


def all_thread_stacks() -> str:
    """Every Python thread's current stack, named — what a post-mortem
    debugger would ask for first on a hang or a deadlocked teardown."""
    names = {t.ident: t.name for t in threading.enumerate()}
    lines = []
    for ident, frame in sorted(sys._current_frames().items()):
        lines.append(f"--- thread {names.get(ident, '?')} (ident {ident}) ---")
        lines.extend(line.rstrip("\n")
                     for line in traceback.format_stack(frame))
    return "\n".join(lines) + "\n"


def read_manifest(bundle_dir: str) -> Optional[dict]:
    """The bundle's manifest, or None when absent/torn (a dump killed
    before its final write is NOT a bundle — manifest-last contract)."""
    path = os.path.join(bundle_dir, MANIFEST_NAME)
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    return doc if isinstance(doc, dict) else None


def is_bundle_dir(path: str) -> bool:
    """A directory with a manifest IS a bundle (torn dumps have none)."""
    return os.path.isfile(os.path.join(path, MANIFEST_NAME))


def bundle_ring_path(bundle_dir: str) -> str:
    """The bundle's ring dump path — the single resolver every reading
    tool shares (slo_report, trace_export), so a bundle-layout change
    cannot diverge them.  Raises ``ValueError`` when the bundle carries
    no ring (dumped without a flight recorder)."""
    ring = os.path.join(bundle_dir, RING_NAME)
    if not os.path.isfile(ring):
        raise ValueError(f"incident bundle {bundle_dir} has no "
                         f"{RING_NAME} (dumped without a flight "
                         f"recorder?)")
    return ring


class IncidentManager:
    """Owns the incident directory; dumps a bundle per trigger.

    telemetry: the bus (the manager emits ``incident.bundle`` events and
      reads the run-local step + the armed ledger off it).
    recorder: a :class:`~can_tpu.obs.flightrec.FlightRecorder` sharing
      the same bus (its snapshot IS the bundle's ring.jsonl); None skips
      the ring section.
    gauges: a ``GaugeSink`` to snapshot (None skips).
    run_config: the CLI's schedule-bearing flag dict, recorded verbatim.
    rate_limit_s / max_bundles: the storm bounds described above.
    """

    def __init__(self, telemetry, recorder=None, *, incident_dir: str,
                 gauges=None, run_config: Optional[dict] = None,
                 rate_limit_s: float = 60.0, max_bundles: int = 16,
                 host_id: int = 0, clock: Callable[[], float] = time.time):
        if not incident_dir:
            raise ValueError("incident_dir is required")
        os.makedirs(incident_dir, exist_ok=True)
        self._tel = telemetry
        self.recorder = recorder
        self.gauges = gauges
        self.run_config = run_config
        self.incident_dir = incident_dir
        self.rate_limit_s = float(rate_limit_s)
        self.max_bundles = max(1, int(max_bundles))
        self.host_id = int(host_id)
        self._clock = clock
        # RLock: a signal landing while THIS thread is mid-trigger must
        # be able to re-enter (signals run on the main thread); the
        # per-reason rate limiter still bounds the work
        self._lock = threading.RLock()
        self._last: Dict[str, float] = {}       # reason -> last dump ts
        self._suppressed: Dict[str, int] = {}   # reason -> rate-limited count
        self._info_sources: Dict[str, Callable[[], dict]] = {}
        self._restore_signals: Optional[Callable[[], None]] = None
        self.bundles_written = 0

    # -- collaborators ----------------------------------------------------
    def add_info_source(self, name: str, fn: Callable[[], dict]) -> None:
        """Fold ``fn()`` into every future bundle's manifest under
        ``info[name]`` (e.g. the serve CLI registers
        ``CountService.stats`` so a bundle carries live queue depth and
        per-replica health).  Failures are recorded, not raised."""
        self._info_sources[name] = fn

    # -- trigger entry points --------------------------------------------
    def on_event(self, event: dict) -> None:
        """``Telemetry.watchers`` hook: runs after sink fan-out, outside
        the bus lock (so the triggering event is already in the ring,
        and the bundle's own ``incident.bundle`` emission cannot
        deadlock).  ``incident.*`` kinds are ignored by construction —
        a bundle must not trigger a bundle."""
        kind = event.get("kind", "")
        if kind.startswith("incident."):
            return
        p = event.get("payload", {})
        if kind == "health.alert" and p.get("alert") in TRIGGER_ALERTS:
            self.trigger(f"health_{p.get('alert')}", detail=p)
        elif kind == "fleet.replica" and p.get("state") == "quarantined":
            self.trigger("fleet_quarantine", detail=p)
        elif kind == "fleet.host" and p.get("state") == "stale":
            # a silent HOST (obs/collector.py liveness rule): the moment
            # "no data ≠ healthy" fires is exactly when its recent
            # telemetry is worth freezing
            self.trigger("fleet_host_stale", detail=p)
        elif kind == "slo.burn" and p.get("alerting"):
            self.trigger(f"slo_{p.get('objective', '?')}", detail=p,
                         severity="warning")

    def on_exception(self, exc: BaseException, **context) -> Optional[str]:
        """An unhandled loop exception (``train/loop.py`` calls this
        before re-raising): the bundle records the traceback while the
        frames are still live."""
        return self.trigger("exception", exc=exc, detail=context or None)

    def on_signal(self, signum: int) -> Optional[str]:
        """The preemption path: dump + flush before the process dies."""
        try:
            name = signal.Signals(signum).name.lower()
        except ValueError:
            name = str(signum)
        return self.trigger(f"signal_{name}", severity="preemption",
                            detail={"signum": int(signum)})

    def close(self) -> None:
        """Teardown: restore any installed signal handlers.  No bundle —
        a clean exit is not an incident."""
        if self._restore_signals is not None:
            self._restore_signals()
            self._restore_signals = None

    # -- the dump ---------------------------------------------------------
    def trigger(self, reason: str, *, detail: Optional[dict] = None,
                exc: Optional[BaseException] = None,
                severity: str = "error") -> Optional[str]:
        """Rate-limited bundle dump; returns the bundle path, or None
        when suppressed (cooldown) or the write failed."""
        now = self._clock()
        with self._lock:
            last = self._last.get(reason)
            if last is not None and now - last < self.rate_limit_s:
                self._suppressed[reason] = \
                    self._suppressed.get(reason, 0) + 1
                return None
            try:
                path, manifest = self._dump(reason, now, detail=detail,
                                            exc=exc, severity=severity)
            except Exception as e:  # noqa: BLE001 — capture must never
                # kill the run it documents; the failure itself is news.
                # The cooldown is NOT consumed: a transient I/O failure
                # must not suppress the next trigger's retry, or a
                # recoverable hiccup loses the incident entirely
                print(f"[incident] bundle write FAILED for {reason!r}: "
                      f"{type(e).__name__}: {e}", flush=True)
                return None
            self._last[reason] = now  # only a WRITTEN bundle cools down
            self.bundles_written += 1
            suppressed = dict(sorted(self._suppressed.items()))
        # outside the manager lock: the emit fans out to sinks AND back
        # through the watcher list (where on_event ignores incident.*)
        self._tel.emit("incident.bundle", reason=reason, severity=severity,
                       path=path, ring_events=manifest.get("ring_events", 0),
                       suppressed=suppressed)
        return path

    def _existing_bundles(self):
        out = []
        try:
            for name in os.listdir(self.incident_dir):
                if name.startswith("incident-"):
                    full = os.path.join(self.incident_dir, name)
                    if os.path.isdir(full):
                        out.append(full)
        except OSError:
            return []
        return sorted(out)

    def _dump(self, reason, now, *, detail, exc, severity):
        # retention FIRST: the directory never exceeds max_bundles even
        # transiently (bundle names sort by their ms timestamp, so the
        # oldest are the lexicographic head)
        existing = self._existing_bundles()
        for stale in existing[: max(0, len(existing) - self.max_bundles + 1)]:
            shutil.rmtree(stale, ignore_errors=True)
        base = (f"incident-{int(now * 1000):013d}-h{self.host_id}"
                f"-{_slug(reason)}")
        path = os.path.join(self.incident_dir, base)
        n = 1
        while os.path.exists(path):  # same-ms retrigger (fake clocks)
            n += 1
            path = os.path.join(self.incident_dir, f"{base}.{n}")
        os.makedirs(path)
        files = []
        errors = {}

        def section(name, fn):
            try:
                fn()
                files.append(name)
            except Exception as e:  # noqa: BLE001 — one failing section
                # (a half-dead gauge source) must not lose the others;
                # the manifest records what is missing and why
                errors[name] = f"{type(e).__name__}: {e}"

        ring_events = 0
        if self.recorder is not None:
            def _ring():
                nonlocal ring_events
                ring_events = self.recorder.dump(
                    os.path.join(path, RING_NAME), now=now)
            section(RING_NAME, _ring)
        if self.gauges is not None:
            section("gauges.json", lambda: self._write_json(
                path, "gauges.json", self.gauges.snapshot()))
        ledger = getattr(self._tel, "ledger", None)
        if ledger is not None:
            section("costs.json", lambda: self._write_json(
                path, "costs.json", {"programs": ledger.rows(),
                                     "summary": ledger.summary()}))
        section("stacks.txt", lambda: self._write_text(
            path, "stacks.txt", all_thread_stacks()))
        section("memory.json", lambda: self._write_memory(path))
        info = {}
        for name, fn in sorted(self._info_sources.items()):
            try:
                info[name] = fn()
            except Exception as e:  # noqa: BLE001 — a dead stats source
                # is itself incident context, recorded in place
                info[name] = {"error": f"{type(e).__name__}: {e}"}
        manifest = {
            "schema": BUNDLE_SCHEMA,
            "reason": reason,
            "severity": severity,
            "ts": now,
            "time_utc": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime(now)),
            "host_id": self.host_id,
            "pid": os.getpid(),
            "step": getattr(self._tel, "step", None),
            "run_config": self.run_config,
            "detail": detail,
            "exception": (None if exc is None else {
                "type": type(exc).__name__,
                "message": str(exc),
                "traceback": traceback.format_exception(
                    type(exc), exc, exc.__traceback__),
            }),
            "ring_events": ring_events,
            "ring_stats": (self.recorder.stats()
                           if self.recorder is not None else None),
            "suppressed": dict(sorted(self._suppressed.items())),
            "info": info,
            "files": sorted(files),
            "section_errors": errors,
        }
        # manifest LAST: its presence is the bundle's validity bit
        self._write_json(path, MANIFEST_NAME, manifest)
        return path, manifest

    @staticmethod
    def _write_json(bundle: str, name: str, doc) -> None:
        with open(os.path.join(bundle, name), "w") as f:
            json.dump(doc, f, indent=1, default=str)

    @staticmethod
    def _write_text(bundle: str, name: str, text: str) -> None:
        with open(os.path.join(bundle, name), "w") as f:
            f.write(text)

    @staticmethod
    def _write_memory(bundle: str) -> None:
        from can_tpu.obs.sources import device_memory_snapshot

        IncidentManager._write_json(bundle, "memory.json",
                                    device_memory_snapshot())


def install_sigterm_handler(manager: IncidentManager,
                            signums=(signal.SIGTERM,)):
    """Arm the preemption hook: on each signal, dump a bundle (the JSONL
    sinks flush per event, so the ``incident.bundle`` record is on disk
    too), then chain to the previously installed handler — or, when the
    previous disposition was the default, raise ``SystemExit(128+n)`` so
    the CLI's ``finally`` teardown (``obs/lifecycle.py``) runs the same
    deterministic close order as a clean exit.

    Returns a ``restore()`` callable (also stored on the manager, so
    ``manager.close()`` restores), or None when not on the main thread
    (``signal.signal`` is main-thread-only; a library consumer embedding
    this off-main simply gets no signal hook, never a crash)."""
    previous: dict = {}
    installed: list = []

    def _handler(signum, frame):
        manager.on_signal(signum)
        prev = previous.get(signum)
        if callable(prev):
            prev(signum, frame)
        else:
            raise SystemExit(128 + signum)

    try:
        for s in signums:
            previous[s] = signal.signal(s, _handler)
            installed.append(s)
    except ValueError:  # not the main thread: roll back what we set
        for s in installed:
            try:
                signal.signal(s, previous[s]
                              if previous[s] is not None else signal.SIG_DFL)
            # can-tpu-lint: disable=SWALLOW(rollback is best-effort off the main thread; install already failed)
            except (ValueError, TypeError):
                pass
        return None

    def restore() -> None:
        for s in installed:
            try:
                signal.signal(s, previous[s]
                              if previous[s] is not None else signal.SIG_DFL)
            # can-tpu-lint: disable=SWALLOW(teardown restore is best-effort; process is exiting anyway)
            except (ValueError, TypeError):
                pass

    manager._restore_signals = restore
    return restore
