"""Telemetry event bus: one process-local stream, pluggable sinks.

The reference repo's observability is tqdm bars and optional wandb scalars
(SURVEY §5: "Tracing/profiling: ABSENT"); until this subsystem can_tpu
mirrored that.  A production pod needs a machine-readable record of where
each step's time and memory went — recompiles, input stalls, HBM pressure —
that survives the process and is diffable across runs and hosts.

Schema: one JSON object per line, identical across train / eval / bench so
artifacts are directly comparable::

    {"ts": <unix seconds>, "kind": <str>, "step": <int|null>,
     "host_id": <int>, "payload": {...}}

Kinds emitted by the library: ``compile`` (new (shape, dtype) signature hit
a jitted step, with elapsed first-call time), ``step_window`` (a windowed
batch of per-step wall times), ``stall`` (seconds the consumer spent
blocked on the input pipeline), ``memory`` (device/host memory snapshot),
``heartbeat`` (liveness timestamp from a daemon thread), ``epoch``
(per-epoch scalars — the row wandb used to get directly), ``bench``
(benchmark result records), ``run`` (run-level config, emitted once).
Sinks must tolerate kinds they don't know: the set is open.

Multi-host: every host writes its OWN file (``telemetry.host{k}.jsonl``,
see ``open_host_telemetry``) — no cross-host collectives on the hot path;
merging is an offline join on ``ts``/``host_id``.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Optional

import numpy as np

# the kinds the acceptance contract and tools/telemetry_report.py know;
# informational — emit() accepts any kind string.  serve.* kinds come from
# the online serving subsystem (can_tpu/serve): per-request completions,
# per-flush micro-batches (carrying the queue-depth gauge), and typed
# rejections.  data.* kinds come from the host data pipeline
# (can_tpu/data/prepared.py): per-split prepared-store status (active or
# the fallback reason) and per-epoch decoded-item-cache counters.
# health.* kinds come from the run-health layer (can_tpu/obs/health.py):
# live anomaly alerts (spike / plateau / nan_precursor / nan /
# throughput_regression / stall_budget) and the per-epoch rollup.
# data.planner carries the batch planner's per-epoch decisions and
# schedule economics (padding/schedule overhead, program and lowered-
# launch counts, predicted-vs-realized plan cost — ShardedBatcher.
# planner_stats), exported as can_tpu_planner_* gauges by obs/exporter.py.
# perf.summary and trace.span come from the performance-attribution layer:
# perf.summary is the ProgramCostLedger's aggregate (per-program MFU /
# roofline class / empirical launch cost, obs/costs.py — numeric keys
# become can_tpu_mfu_* etc. gauges) and trace.span is one completed span
# of a request/step trace tree (obs/spans.py; exported to Chrome
# trace-event JSON by tools/trace_export.py).
# tests/test_perf.py pins this tuple against the emit literals in the
# tree — add the kind HERE when adding an emitter, or that test fails.
# fleet.* kinds come from the serving fleet (can_tpu/serve/fleet.py):
# fleet.replica is a replica state transition (quarantine on failure,
# wedge on a watchdog deadline, drain on scale-down, generation bump on
# rollout flip) and fleet.rollout is one completed blue/green checkpoint
# rollout report.  The self-healing layer adds fleet.probe (one
# probation health probe, ok or failed with the escalated backoff),
# fleet.resurrect (a quarantined/wedged replica re-staged at the current
# generation and back in dispatch — can_tpu_fleet_resurrections_total),
# and fleet.scale (one add/remove replica transition, with
# time_to_first_ready_s on the up direction —
# can_tpu_fleet_scale_events_total).
# incident.bundle and slo.burn come from the incident layer:
# incident.bundle records one written incident bundle (obs/incidents.py
# — reason/severity/path/suppressed counts; GaugeSink counts them as
# can_tpu_incidents_total{reason}), and slo.burn is one objective's
# multi-window burn-rate evaluation (obs/slo.py — exported as
# can_tpu_slo_* gauges; `alerting` payloads trigger incident bundles).
# elastic.transition comes from the elastic supervisor
# (parallel/elastic.py): one completed shrink-and-continue transition —
# old/new world (processes, dp), interrupted epoch + step, consumed vs
# remaining items, and the lr/global-batch rescaling applied.
# stream.* kinds come from the per-stream session layer
# (serve/streams.py): stream.session is a session lifecycle mark (open /
# periodic snapshot / TTL evict, carrying the active-session gauge),
# stream.degrade is one degradation-ladder RUNG TRANSITION (full ->
# frame-skip -> reject; individual EWMA-served answers ride
# serve.request with degraded=true + staleness_s), and stream.repin is
# one sticky-pin invalidation after a fleet fault (quarantine / wedge /
# scale-down / resurrection at a new incarnation) with the live replica
# the stream re-pinned to.
# fleet.host and collector.ingest come from the fleet observability
# plane (obs/collector.py): fleet.host is a HOST-level liveness
# transition on the collector's skew-corrected clock (stale when
# heartbeats age past the bound — "no data ≠ healthy" — or back to live
# on recovery; carries the live/stale host counts and triggers an
# incident bundle), and collector.ingest is one accepted ingest batch
# for one host (tail or push transport, event + torn-line counts —
# can_tpu_collector_events_total{host}).
EVENT_KINDS = ("compile", "step_window", "stall", "memory", "heartbeat",
               "epoch", "bench", "run",
               "serve.request", "serve.batch", "serve.reject",
               "serve.warmup",
               "fleet.replica", "fleet.rollout",
               "fleet.probe", "fleet.resurrect", "fleet.scale",
               "fleet.host", "collector.ingest",
               "stream.session", "stream.degrade", "stream.repin",
               "data.prepared", "data.cache", "data.planner",
               "health.alert", "health.summary",
               "perf.summary", "trace.span",
               "incident.bundle", "slo.burn",
               "elastic.transition")


def _jsonable(v):
    """Coerce numpy scalars/arrays into JSON-serialisable python values."""
    if isinstance(v, (np.floating, np.integer)):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()
    if isinstance(v, dict):
        return {k: _jsonable(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_jsonable(x) for x in v]
    return v


class JsonlSink:
    """Append events to a JSONL file, one line per event, flushed per event
    (an abandoned run's last heartbeat must be ON DISK, not in a buffer)."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")

    def emit(self, event: dict) -> None:
        self._f.write(json.dumps(event) + "\n")
        self._f.flush()

    def close(self) -> None:
        self._f.close()


class StdoutSink:
    """Human-greppable one-liners; for quick local runs without a dir."""

    def __init__(self, prefix: str = "[telemetry]"):
        self.prefix = prefix

    def emit(self, event: dict) -> None:
        step = event.get("step")
        print(f"{self.prefix} {event['kind']}"
              f"{'' if step is None else f' step {step}'} "
              f"{json.dumps(event['payload'])}", flush=True)

    def close(self) -> None:
        pass


class MetricLoggerSink:
    """Forward scalar payload entries of selected kinds to a MetricLogger,
    so the existing stdout/wandb logging keeps working unchanged when the
    CLI routes its per-epoch metrics through the bus."""

    def __init__(self, logger, kinds=("epoch",)):
        self.logger = logger
        self.kinds = tuple(kinds)

    def emit(self, event: dict) -> None:
        if event["kind"] not in self.kinds:
            return
        scalars = {k: v for k, v in event["payload"].items()
                   if isinstance(v, (int, float, np.floating, np.integer))
                   and not isinstance(v, bool)}
        if scalars:
            self.logger.log(scalars, step=event.get("step"))

    def close(self) -> None:
        pass  # the CLI owns the logger's lifecycle (logger.finish())


class Telemetry:
    """The bus: builds schema'd events and fans them out to sinks.

    Thread-safe (the heartbeat thread emits concurrently with the train
    loop).  A sink that raises is dropped after one warning — telemetry
    must never kill a training run.  ``step_tick()`` maintains the
    process-global step counter (counts from 0 at construction; a resumed
    run restarts the count — ``step`` in events is a run-local ordinal,
    not the optimizer step) and drives the optional trace window.
    """

    def __init__(self, sinks=(), *, host_id: int = 0, trace=None,
                 clock=time.time):
        self._sinks = list(sinks)
        self.host_id = host_id
        self.trace = trace
        self._clock = clock
        # RLock, not Lock: the SIGTERM/preemption hook (obs/incidents.py)
        # runs ON the main thread at a bytecode boundary — if the signal
        # lands while that thread is inside this very lock (the sink
        # fan-out below), the handler's own bundle emit must be able to
        # re-enter or the process deadlocks in the exact window the
        # incident layer exists to survive.  Each sink.emit writes whole
        # events (one write call per line), so a re-entrant fan-out
        # interleaves complete events, never torn ones.
        self._lock = threading.RLock()
        self._step = 0
        # RecompileTracker keeps per-wrapped-step-name signature sets here
        # so re-wrapping each epoch doesn't re-attribute old signatures
        self.signature_registry: dict = {}
        # performance-attribution collaborators (armed by the CLIs when a
        # consumer exists; None keeps every producer's guard dead cheap):
        # ledger = obs.costs.ProgramCostLedger, spans = obs.spans.SpanTracer
        self.ledger = None
        self.spans = None
        # watchers: called with every event AFTER sink fan-out and
        # OUTSIDE the bus lock, so a watcher may itself emit (the
        # incident manager dumps a bundle + emits incident.bundle; the
        # SLO engine emits slo.burn) without deadlocking.  Armed by the
        # CLIs (obs/incidents.py, obs/slo.py); the default empty list
        # costs one truth test per event.  ``incidents`` is the armed
        # IncidentManager (or None) — the handle the loops use to
        # snapshot an unhandled exception before the stack unwinds.
        self.watchers: list = []
        self.incidents = None

    @property
    def step(self) -> int:
        return self._step

    def step_tick(self) -> int:
        """Advance the run-local step counter; drives the trace window."""
        with self._lock:
            self._step += 1
            step = self._step
        if self.trace is not None:
            self.trace.on_step(step)
        return step

    def emit(self, kind: str, *, step: Optional[int] = None,
             **payload) -> None:
        event = {"ts": self._clock(), "kind": kind,
                 "step": self._step if step is None else int(step),
                 "host_id": self.host_id, "payload": _jsonable(payload)}
        with self._lock:
            for sink in self._sinks:
                try:
                    sink.emit(event)
                    sink._telemetry_warned = False
                except Exception as e:  # noqa: BLE001 — never kill the run
                    # KEEP the sink and retry on the next event: one
                    # transient wandb/filesystem hiccup must not silently
                    # end the run's primary metric record (warn once per
                    # failure streak, not once per event)
                    if not getattr(sink, "_telemetry_warned", False):
                        sink._telemetry_warned = True
                        print(f"[telemetry] sink {type(sink).__name__} "
                              f"failed ({type(e).__name__}: {e}); kept — "
                              f"will retry on the next event", flush=True)
        for watcher in tuple(self.watchers):
            try:
                watcher.on_event(event)
                watcher._telemetry_warned = False
            except Exception as e:  # noqa: BLE001 — same contract as
                # sinks: observation must never kill the run (warn once
                # per failure streak, keep the watcher)
                if not getattr(watcher, "_telemetry_warned", False):
                    watcher._telemetry_warned = True
                    print(f"[telemetry] watcher {type(watcher).__name__} "
                          f"failed ({type(e).__name__}: {e}); kept",
                          flush=True)

    def close(self) -> None:
        if self.trace is not None:
            self.trace.close()
            self.trace = None
        # watchers BEFORE sinks: their close() may emit final events
        # (the SLO engine's tail evaluation) that must still land in the
        # open sinks; the incident manager restores signal handlers here
        for watcher in tuple(self.watchers):
            try:
                watcher.close()
            # can-tpu-lint: disable=SWALLOW(best-effort watcher close at teardown, mirrors the sink-close rule below)
            except Exception:
                pass
        self.watchers = []
        self.incidents = None
        with self._lock:
            for sink in self._sinks:
                try:
                    sink.close()
                # can-tpu-lint: disable=SWALLOW(best-effort sink close at teardown; emit() already warned per failure streak)
                except Exception:
                    pass
            self._sinks = []


def open_host_telemetry(telemetry_dir: str, *, host_id: int = 0,
                        extra_sinks=(), trace=None) -> Telemetry:
    """The standard wiring: ``<dir>/telemetry.host{k}.jsonl`` for THIS host
    plus any extra sinks.  Every host calls this with its own
    ``process_index()`` — per-host files, no cross-host coordination."""
    sinks = [JsonlSink(os.path.join(telemetry_dir,
                                    f"telemetry.host{host_id}.jsonl"))]
    sinks.extend(extra_sinks)
    return Telemetry(sinks, host_id=host_id, trace=trace)
