"""Step-range profiler trigger: trace a WINDOW instead of the whole run.

``profile_trace`` (utils/profiling.py) wraps the entire run — fine for a
smoke run, useless for "steady-state steps 10..12 of a 10-hour job" where
a whole-run trace is gigabytes of mostly-identical timelines.  Here the
``jax.profiler`` trace is armed by the run-local step counter: the CLI's
``--trace-steps A:B`` (python slice semantics: first traced step A,
first untraced step B) starts the trace when step A begins and stops it
when step B begins, so the artifact holds exactly ``B - A`` steps.
"""

from __future__ import annotations

from typing import Optional, Tuple


def parse_trace_steps(spec: str) -> Optional[Tuple[int, int]]:
    """``"10:13"`` -> ``(10, 13)``; empty/None -> None.  Slice semantics:
    steps ``[10, 13)`` are traced.  Raises ValueError on malformed specs
    (argparse ``type=`` surfaces it as a usage error before any work)."""
    if not spec:
        return None
    try:
        lo_s, hi_s = spec.split(":")
        lo, hi = int(lo_s), int(hi_s)
    except ValueError:
        raise ValueError(
            f"--trace-steps wants START:STOP (e.g. 10:13), got {spec!r}")
    if lo < 0 or hi <= lo:
        raise ValueError(
            f"--trace-steps window must satisfy 0 <= START < STOP, "
            f"got {spec!r}")
    return lo, hi


class StepTraceWindow:
    """Start/stop a ``jax.profiler`` trace on run-local step boundaries.

    ``on_step(step)`` is called once per step (step counts from 1, see
    ``Telemetry.step_tick``; the window is interpreted on the 0-based step
    ORDINAL, so ``--trace-steps 0:2`` traces the first two steps).  Safe to
    call after the window has passed — both branches are a pair of integer
    compares.  ``close()`` stops a still-open trace (a window extending
    past the last step must still flush its file)."""

    def __init__(self, log_dir: str, start: int, stop: int,
                 *, profiler=None):
        if not log_dir:
            raise ValueError("StepTraceWindow needs a log_dir "
                             "(pass --profile-dir with --trace-steps)")
        self.log_dir = log_dir
        self.start = int(start)
        self.stop = int(stop)
        self._active = False
        self._done = False
        self._profiler = profiler  # test seam; defaults to jax.profiler

    def _jax_profiler(self):
        if self._profiler is None:
            import jax.profiler

            self._profiler = jax.profiler
        return self._profiler

    def on_step(self, step: int) -> None:
        ordinal = step - 1  # step_tick counts from 1
        if (not self._active and not self._done
                and self.start <= ordinal < self.stop):
            self._jax_profiler().start_trace(self.log_dir)
            self._active = True
        elif self._active and ordinal >= self.stop:
            self._stop_trace()

    def _stop_trace(self) -> None:
        try:
            self._jax_profiler().stop_trace()
        finally:
            self._active = False
            self._done = True  # one window per run: never re-arm

    def close(self) -> None:
        if self._active:
            self._stop_trace()
