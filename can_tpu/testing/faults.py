"""Deterministic fault injection: the test harness elasticity needs.

Elastic shrink-and-continue (parallel/elastic.py) is untestable without
controlled failure: "a host dies mid-epoch" must be reproducible to the
step, or the chaos test (tests/test_multiprocess.py) proves nothing and
flakes forever.  This module delivers a SEEDED, explicit fault schedule
to real processes through an environment trigger, so a subprocess worker
can be killed at exactly step s of epoch e, a checkpoint write can fail
exactly n times, and a rendezvous barrier can be held past its timeout —
with zero cost and zero code reached when the env var is unset.

Delivery: ``CAN_TPU_FAULTS`` holds either inline JSON or a path to a
JSON file (the file trigger lets a driver write the schedule once and
point every worker at it).  Schema::

    {"faults": [
        {"kind": "kill", "rank": 1, "step": 3, "epoch": 0,
         "signal": "SIGTERM"},
        {"kind": "ckpt_io", "op": "save", "fails": 2, "rank": 0},
        {"kind": "rendezvous_timeout", "barrier": "elastic", "rank": 1,
         "delay_s": 30.0},
        {"kind": "replica_crash", "replica": 0, "batch": 3},
        {"kind": "replica_hang", "replica": 1, "batch": 2,
         "delay_s": 30.0},
        {"kind": "stream_burst", "stream": "cam0", "frame": 5,
         "burst": 8},
        {"kind": "frame_gap", "stream": "cam1", "frame": 4,
         "mode": "dup"}
    ]}

* ``kill`` — at the matching (rank, epoch, step) boundary the injector
  sends the named signal to ITS OWN process (default SIGTERM: the
  preemption notice, so the real grace-window choreography — incident
  bundle, leave announcement, coordinated shutdown — runs exactly as it
  would under a preemptor; SIGKILL for the no-grace hard-death case).
* ``ckpt_io`` — the first ``fails`` attempts of the matching checkpoint
  op raise ``InjectedFault`` (an OSError: the transient-FS class the
  retry/backoff in utils/checkpoint.py absorbs; set ``fails`` above the
  retry budget to exercise the typed ``CheckpointIOError`` give-up).
* ``rendezvous_timeout`` — the matching rank holds the matching barrier
  for ``delay_s`` before joining, so every OTHER member's bounded
  ``barrier()`` times out for real and raises the typed
  ``RendezvousTimeoutError`` (parallel/runtime.py).
* ``replica_crash`` — serve-side: when fleet replica ``replica`` is
  about to execute its ``batch``-th micro-batch (1-based, counted per
  replica), the hook raises ``InjectedFault`` INSIDE the worker's
  predict path, so the real quarantine → probation → resurrection
  choreography (serve/fleet.py) runs exactly as on a device fault.
  Fires once.
* ``replica_hang`` — serve-side: the matching (replica, batch) launch
  SLEEPS ``delay_s`` while holding the replica's dispatch lock — a
  wedged device execute from the fleet's point of view — so the hang
  watchdog's priced deadline, batch re-dispatch, and
  wedged-replica probation run for real.  Fires once.
* ``stream_burst`` — stream-driver-side: when the matching (stream,
  frame) is about to be sent, the driver submits ``burst`` EXTRA frames
  back-to-back first — an arrival-rate spike on ONE camera, the load
  shape the degradation ladder (serve/streams.py) exists to absorb
  without drowning the other streams.  Fires once.
* ``frame_gap`` — stream-driver-side: the matching (stream, frame) is
  delivered wrong — ``mode: "dup"`` re-sends the previous frame's
  sequence number, ``mode: "reorder"`` sends this frame's seq minus
  two (an out-of-order arrival) — so the session's monotonic-sequence
  gate (duplicate/out-of-order rejection, never double-serve) runs for
  real.  Fires once.

The stream kinds are directives to the DRIVER (the chaos test's and
bench tier's stream load generators call ``on_stream_frame`` before
each submit and perturb their own traffic), because arrival timing and
frame ordering belong to the client side of the protocol — the serving
stack under test must see them arrive exactly as a misbehaving camera
would send them.

Hooks are consulted only from sites that already gate on
``active_injector()`` (train-loop elastic hook, checkpoint retry loop,
``runtime.barrier``, the fleet worker's ``on_serve_batch``, the stream
drivers' ``on_stream_frame``) — a production run without the env var
never constructs an injector.

``make_kill_schedule`` derives the kill step from a seed (the "seeded
schedule of kill-rank-k-at-step-s"): chaos runs randomise WHERE the
fault lands across seeds while any single seed reproduces exactly.

jax-free by design: importable by workers before jax initialises and by
host-side tools.
"""

from __future__ import annotations

import json
import os
import signal as _signal
import time
from typing import Dict, List, Optional

FAULTS_ENV = "CAN_TPU_FAULTS"


class InjectedFault(OSError):
    """A fault the schedule asked for (OSError: checkpoint-I/O faults
    must look like the transient filesystem errors the retry path
    handles)."""


def make_kill_schedule(seed: int, *, rank: int, max_step: int,
                       epoch: int = 0, min_step: int = 1,
                       sig: str = "SIGTERM") -> dict:
    """A one-kill schedule whose step is drawn from ``seed`` — different
    seeds move the preemption around the epoch, one seed reproduces
    bit-exactly.  Pure arithmetic (no numpy): workers import this before
    heavyweight deps."""
    if max_step < min_step:
        raise ValueError(f"max_step {max_step} < min_step {min_step}")
    # sha256 of the full key: well-mixed and deterministic across
    # platforms/processes (a cheap LCG scramble had degenerate low bits)
    import hashlib

    digest = hashlib.sha256(
        f"can_tpu.faults:{seed}:{rank}:{epoch}".encode()).digest()
    x = int.from_bytes(digest[:8], "big")
    step = min_step + x % (max_step - min_step + 1)
    return {"faults": [{"kind": "kill", "rank": int(rank),
                        "epoch": int(epoch), "step": int(step),
                        "signal": sig}]}


class FaultInjector:
    """Parsed fault schedule + per-site hooks.  Construct via
    :func:`active_injector` (env-gated) or directly in unit tests."""

    def __init__(self, spec: dict):
        faults = spec.get("faults")
        if not isinstance(faults, list):
            raise ValueError(
                "fault schedule must be {'faults': [...]}; got "
                f"{type(spec).__name__} without a fault list")
        self.faults: List[dict] = []
        for f in faults:
            if not isinstance(f, dict) or "kind" not in f:
                raise ValueError(f"malformed fault entry: {f!r}")
            if f["kind"] not in ("kill", "ckpt_io", "rendezvous_timeout",
                                 "replica_crash", "replica_hang",
                                 "stream_burst", "frame_gap"):
                raise ValueError(f"unknown fault kind {f['kind']!r}")
            if (f["kind"] == "frame_gap"
                    and f.get("mode", "dup") not in ("dup", "reorder")):
                raise ValueError(
                    f"frame_gap mode must be dup|reorder, got "
                    f"{f.get('mode')!r}")
            self.faults.append(dict(f))
        self._ckpt_attempts: Dict[str, int] = {}
        self.fired: List[dict] = []  # delivered faults, for assertions

    # -- hooks ------------------------------------------------------------
    def on_step(self, step: int, *, epoch: int = 0,
                rank: int = 0) -> None:
        """Train-loop boundary: deliver any matching ``kill`` by
        signalling OUR OWN process — the real handler chain (incident
        bundle, elastic leave flag) runs, exactly like an external
        preemptor's notice."""
        for f in self.faults:
            if (f["kind"] == "kill" and not f.get("_fired")
                    and int(f.get("rank", 0)) == rank
                    and int(f.get("epoch", 0)) == epoch
                    and int(f.get("step", 0)) == step):
                f["_fired"] = True
                self.fired.append(f)
                signum = getattr(_signal,
                                 str(f.get("signal", "SIGTERM")))
                os.kill(os.getpid(), signum)

    def on_ckpt_io(self, op: str, *, rank: int = 0) -> None:
        """Checkpoint save/restore attempt: raise for the first ``fails``
        matching attempts (utils/checkpoint.py consults this inside its
        retry loop — passing its real process index — so the backoff
        path is exercised for real).  A fault entry WITHOUT ``rank``
        fires on every process; with one, only on that rank."""
        for i, f in enumerate(self.faults):
            if f["kind"] != "ckpt_io" or f.get("op", "save") != op:
                continue
            frank = f.get("rank")
            if frank is not None and int(frank) != rank:
                continue
            key = f"{i}:{op}"
            n = self._ckpt_attempts.get(key, 0) + 1
            self._ckpt_attempts[key] = n
            if n <= int(f.get("fails", 1)):
                self.fired.append(f)
                raise InjectedFault(
                    f"injected checkpoint {op} I/O error "
                    f"(attempt {n}/{f.get('fails', 1)})")

    def on_serve_batch(self, *, replica: int = 0,
                       batch_index: int = 1) -> None:
        """Fleet-worker launch boundary (serve/fleet.py consults this
        inside the predict try, under the replica's dispatch lock):
        ``replica_crash`` raises into the quarantine path;
        ``replica_hang`` sleeps the worker — a wedged execute — into the
        watchdog's.  ``batch_index`` is 1-based per replica."""
        for f in self.faults:
            if (f["kind"] not in ("replica_crash", "replica_hang")
                    or f.get("_fired")
                    or int(f.get("replica", 0)) != replica
                    or int(f.get("batch", 1)) != batch_index):
                continue
            f["_fired"] = True
            self.fired.append(f)
            if f["kind"] == "replica_hang":
                time.sleep(float(f.get("delay_s", 30.0)))
            else:
                raise InjectedFault(
                    f"injected replica {replica} crash at batch "
                    f"{batch_index}")

    def on_stream_frame(self, *, stream: str = "",
                        frame: int = 1) -> Optional[dict]:
        """Stream-driver boundary (consulted BEFORE the driver submits
        the matching 1-based ``frame`` of ``stream``): returns the
        matching directive — ``{"kind": "stream_burst", "burst": n}``
        (submit n extra frames back-to-back first) or ``{"kind":
        "frame_gap", "mode": "dup"|"reorder"}`` (deliver this frame
        duplicated / out of order) — or None.  Fires once per entry."""
        for f in self.faults:
            if (f["kind"] not in ("stream_burst", "frame_gap")
                    or f.get("_fired")
                    or str(f.get("stream", "")) != stream
                    or int(f.get("frame", 1)) != frame):
                continue
            f["_fired"] = True
            self.fired.append(f)
            if f["kind"] == "stream_burst":
                return {"kind": "stream_burst",
                        "burst": int(f.get("burst", 8))}
            return {"kind": "frame_gap",
                    "mode": str(f.get("mode", "dup"))}
        return None

    def on_barrier(self, name: str, *, rank: int = 0) -> None:
        """Barrier entry: the matching rank HOLDS the barrier for
        ``delay_s`` — every other member's bounded wait then times out
        for real (runtime.barrier consults this before joining)."""
        for f in self.faults:
            if (f["kind"] == "rendezvous_timeout" and not f.get("_fired")
                    and int(f.get("rank", 0)) == rank
                    and str(f.get("barrier", "")) in name):
                f["_fired"] = True
                self.fired.append(f)
                time.sleep(float(f.get("delay_s", 30.0)))


_CACHED: Optional[FaultInjector] = None
_CACHED_SPEC: Optional[str] = None


def active_injector() -> Optional[FaultInjector]:
    """The process's injector, or None when ``CAN_TPU_FAULTS`` is unset —
    the one gate every production hook site checks.  The parsed injector
    is cached per spec value (attempt counters must persist across
    hook calls); a malformed schedule raises loudly at the FIRST hook
    rather than silently running the chaos test without its chaos."""
    global _CACHED, _CACHED_SPEC
    spec = os.environ.get(FAULTS_ENV, "")
    if not spec:
        return None
    if _CACHED is not None and spec == _CACHED_SPEC:
        return _CACHED
    text = spec
    if not spec.lstrip().startswith("{"):
        with open(spec) as f:  # a path trigger
            text = f.read()
    _CACHED = FaultInjector(json.loads(text))
    _CACHED_SPEC = spec
    return _CACHED
