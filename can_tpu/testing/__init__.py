from .faults import (
    FAULTS_ENV,
    FaultInjector,
    InjectedFault,
    active_injector,
    make_kill_schedule,
)

__all__ = [
    "FAULTS_ENV",
    "FaultInjector",
    "InjectedFault",
    "active_injector",
    "make_kill_schedule",
]
