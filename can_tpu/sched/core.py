"""One cost-priced scheduling core under train, eval, and serve.

Until round 14 the stack ran FOUR batch-formation engines kept consistent
only by parity tests: the offline ``ShardedBatcher`` (planner-driven since
r8), serve's ``MicroBatcher`` (folklore: pad every flush to ``max_batch``,
flush on a fixed ``max_wait_ms`` timer), eval's prefetch pipeline (a fixed
``depth=2``), and the fleet's shared work queue (pure FIFO).  Only the
first priced anything.  This module is the shared core the other three now
consume, built on the SAME pricing function the offline planner searches
with (``data/planner.py::PlanCostModel``,
``plan_cost = area * padded_slots + launch_cost * n_launches``):

* **Priced sub-batch menu** (``select_menu`` / ``ServeSched``) — instead
  of one ``max_batch``-slot program per (bucket, dtype), serving warms a
  small MENU of batch sizes chosen by the cost model under a program-count
  budget, and every flush is covered by the planner's exact ``decompose``
  DP over that menu: a 2-request flush launches a 2-slot program instead
  of burning ``max_batch - 2`` dead slots of device compute.  The menu is
  static and warmed up front, so the compile count stays
  ``buckets x dtypes x len(menu)`` — bounded, never traffic-dependent.

* **Priced flush deadlines** (``ServeSched.flush_at``) — a group flushes
  the moment waiting longer cannot beat launch-cost amortization: when the
  group already fills the top menu size (waiting buys nothing), when
  coalescing one more request saves no model cost (``coalesce_gain <= 0``),
  or when the bucket's observed arrival rate says the next request is not
  expected inside the remaining window.  At low load that means a lone
  request flushes on the next pump pass instead of idling out the fixed
  timer; ``max_wait_ms`` survives only as the latency CAP, and the
  group's deadline slack bounds the wait from the other side.  With no
  rate estimate yet (cold start) the policy degrades to exactly the old
  timer.

* **Cost/deadline-aware dispatch ordering** (``pick_work``) — the fleet's
  shared queue serves deadline-pressured work earliest-deadline-first and
  everything else cheapest-first, with an age bound that promotes any
  waiting item to the urgent class (the starvation bound the tests pin).

* **Predicted == realized cost, end to end** — the offline planner's
  invariant (planner_stats) extends to serving: every dispatched batch's
  slot count must equal the core's predicted cover (``cover_one``), and
  ``serve.batch`` events carry both predicted and realized cost so the
  ``can_tpu_sched_*`` gauges make a divergence visible live.  The HLO
  audit pins each consumer's program set from THIS module
  (``default_serve_menu`` is the single registry the serve menu programs
  derive from — analysis/hlo_audit.py), so a menu change outside the
  registry turns the audit red.

Everything here is pure-Python and jax-free; determinism (exact tie
rules, seeded estimators) is load-bearing — plans and menus must be
byte-identical across hosts and runs.
"""

from __future__ import annotations

import itertools
from typing import Dict, Optional, Sequence, Tuple

from can_tpu.data.planner import GlobalPlanner, PlanCostModel, decompose

# Default program-count budget per (bucket, dtype) for the serve menu:
# three sizes cover the flush-size distribution well (measured: the
# expected-cost curve is flat past 3) while keeping warmup/AOT bundles
# and the audit surface small.
DEFAULT_MENU_BUDGET = 3
# Fixed cost of one serve launch in SLOT-equivalents (the per-launch
# dispatch overhead divided by one slot's compute at the bucket shape).
# 0.25 means "one extra launch costs a quarter of a slot": small enough
# that exact-size launches win at low fill, large enough that the DP
# never shatters a flush into per-request launches.
DEFAULT_LAUNCH_COST_SLOTS = 0.25
# Arrival-gap EWMA: how many observed interarrivals before the estimate
# is trusted (below this the flush policy is the legacy timer), and the
# smoothing factor (~last 8 arrivals dominate).
MIN_GAP_INTERVALS = 3
GAP_EWMA_ALPHA = 0.25
# How many expected interarrival gaps the policy will wait for one more
# request before declaring the arrival overdue and flushing.
DEFAULT_WAIT_GAP_FACTOR = 2.0


# -- priced sub-batch menu -------------------------------------------------
def cover_cost(n: int, menu: Tuple[int, ...],
               launch_cost_slots: float) -> float:
    """Model cost (in slot units) of serving one flush of ``n`` requests
    with launch sizes from ``menu`` — the offline planner's ``decompose``
    DP at unit area: ``slots + launch_cost_slots * launches``."""
    parts = decompose(n, menu, 1.0, launch_cost_slots)
    return sum(parts) + launch_cost_slots * len(parts)


def _cover_costs(max_n: int, menu: Tuple[int, ...],
                 lc: float) -> list:
    """``[cover_cost(n, menu, lc) for n in 1..max_n]`` from ONE bottom-up
    DP pass (the same recurrence ``decompose`` runs, read out at every
    n instead of once) — ``select_menu`` scores each candidate menu over
    every flush size, and re-running the full DP per n made the search
    O(max_batch^2) per menu (measured: minutes at --max-batch 64)."""
    best = [0.0] * (max_n + 1)
    for r in range(1, max_n + 1):
        best[r] = min((s if r <= s else s + best[r - s]) + lc
                      for s in menu)
    return best[1:]


def costs_match(predicted, realized, *, tol: float = 1e-6) -> bool:
    """THE predicted==realized comparison, owned by the module that owns
    the invariant: the gauge sink, the report, and the bench receipt all
    call this — three hand-rolled epsilon checks could silently disagree
    about whether the invariant held."""
    if predicted is None or realized is None:
        return True  # pre-r14 events carry no cost pair: nothing to judge
    return abs(float(predicted) - float(realized)) <= tol


def select_menu(max_batch: int, *, budget: int = DEFAULT_MENU_BUDGET,
                launch_cost_slots: float = DEFAULT_LAUNCH_COST_SLOTS,
                weights: Optional[Sequence[float]] = None
                ) -> Tuple[int, ...]:
    """The priced sub-batch menu: up to ``budget`` launch sizes (always
    including ``max_batch`` — the full-batch path must exist) minimising
    the expected flush cost ``sum_n w[n] * cover_cost(n, menu)`` over
    flush sizes ``n = 1..max_batch``.

    ``weights[n-1]`` weights flush size ``n`` (default uniform — the
    agnostic prior; a deployment that knows its load shape can pass its
    histogram).  Exact subset search (``max_batch`` is single digits for
    serving); ties prefer FEWER sizes, then the lexicographically
    smallest descending tuple — the same determinism rule as the offline
    planner's decompose.  Returns sizes descending."""
    if max_batch < 1:
        raise ValueError(f"max_batch must be >= 1, got {max_batch}")
    if budget < 1:
        raise ValueError(f"menu budget must be >= 1, got {budget}")
    if weights is None:
        w = [1.0] * max_batch
    else:
        w = [float(x) for x in weights]
        if len(w) != max_batch:
            raise ValueError(f"weights must have max_batch={max_batch} "
                             f"entries, got {len(w)}")
    smaller = list(range(max_batch - 1, 0, -1))  # descending, sans top
    best = None
    for k in range(0, min(budget - 1, len(smaller)) + 1):
        for extra in itertools.combinations(smaller, k):
            menu = (max_batch,) + extra
            costs = _cover_costs(max_batch, menu, launch_cost_slots)
            cost = sum(wn * cn for wn, cn in zip(w, costs))
            key = (cost, len(menu), menu)
            if best is None or key < best:
                best = key
    return best[2]


def default_serve_menu(max_batch: int, *,
                       budget: int = DEFAULT_MENU_BUDGET) -> Tuple[int, ...]:
    """THE serve menu registry: the batch sizes every serve consumer —
    warmup, AOT bake, the HLO audit's contracted program set — derives
    from one call.  A menu changed anywhere else (a hand-rolled warmup
    size, an engine warming off-registry) diverges from the audit's
    expectation and turns it red (tests/test_sched.py pins the
    mutation)."""
    return select_menu(max_batch, budget=budget)


class ServeSched:
    """The serving instance of the core: one menu + flush pricing + the
    predicted-cost function, shared by the MicroBatcher (flush decisions,
    sub-batch covers) and CountService (predicted-vs-realized accounting
    on every ``serve.batch`` event).

    max_wait_s is the latency CAP the priced deadline can never exceed
    (the old timer's only surviving role); ``priced_flush=False`` keeps
    the timer as the flush trigger while the menu still prices sizes
    (the legacy escape hatch the CLI's ``--flush-policy timer`` wires).
    """

    def __init__(self, max_batch: int, *, max_wait_s: float,
                 menu: Optional[Tuple[int, ...]] = None,
                 menu_budget: int = DEFAULT_MENU_BUDGET,
                 launch_cost_slots: float = DEFAULT_LAUNCH_COST_SLOTS,
                 priced_flush: bool = True,
                 wait_gap_factor: float = DEFAULT_WAIT_GAP_FACTOR,
                 min_gap_intervals: int = MIN_GAP_INTERVALS):
        self.max_batch = int(max_batch)
        self.menu = (tuple(sorted(menu, reverse=True)) if menu is not None
                     else default_serve_menu(max_batch, budget=menu_budget))
        if max(self.menu) != self.max_batch:
            raise ValueError(
                f"menu {self.menu} must top out at max_batch="
                f"{self.max_batch}: the full-batch program is the high-"
                f"load path and must exist")
        # the shared pricing function, at unit area (serve flushes are
        # within one bucket; the bucket's pixel area scales predicted and
        # realized cost identically, so slot units price the DECISIONS
        # and the px conversion happens only in the emitted costs)
        self.model = PlanCostModel(menu=self.menu,
                                   launch_cost_px=float(launch_cost_slots))
        self.launch_cost_slots = float(launch_cost_slots)
        self.max_wait_s = float(max_wait_s)
        self.priced_flush = bool(priced_flush)
        self.wait_gap_factor = float(wait_gap_factor)
        self.min_gap_intervals = int(min_gap_intervals)
        # per group key: (ewma gap seconds, intervals seen, last arrival
        # ts).  Touched only from the batcher pump thread.
        self._gaps: Dict[object, Tuple[float, int, float]] = {}

    # -- sizes -----------------------------------------------------------
    def parts_for(self, n: int) -> Tuple[int, ...]:
        """Launch sizes covering a flush of ``n`` requests, descending
        (the planner DP; fill lands in the final part)."""
        return self.model.parts((1, 1), n)

    def cover_one(self, n: int) -> int:
        """Slot count of a single launch holding ``n`` valid requests —
        the smallest menu size covering ``n``.  Every batch the core
        dispatches satisfies ``batch_slots == cover_one(valid)`` (each
        DP part is either exactly full or the tail whose size is its
        remainder's cheapest single-launch cover), which is the
        predicted==realized invariant serve.batch events carry."""
        fits = [s for s in self.menu if s >= n]
        return min(fits) if fits else max(self.menu)

    def predicted_cost_px(self, area_px: float, valid: int) -> float:
        """Model cost of the launch the core predicts for ``valid``
        requests at a bucket of ``area_px`` pixels."""
        return float(area_px) * (self.cover_one(valid)
                                 + self.launch_cost_slots)

    def realized_cost_px(self, area_px: float, slots: int) -> float:
        """Model cost of the launch that actually ran."""
        return float(area_px) * (int(slots) + self.launch_cost_slots)

    def coalesce_gain(self, n: int) -> float:
        """Slot-units saved by one more request joining this flush
        instead of launching alone later: ``C(n) + C(1) - C(n+1)``.
        ``<= 0`` means waiting cannot beat launch-cost amortization —
        flush now."""
        if n >= self.max_batch:
            return 0.0
        c = lambda k: cover_cost(k, self.menu, self.launch_cost_slots)  # noqa: E731
        return c(n) + c(1) - c(n + 1)

    # -- arrival-rate estimate + the priced flush deadline ---------------
    def observe_arrival(self, key, t: float) -> None:
        got = self._gaps.get(key)
        if got is None:
            self._gaps[key] = (0.0, 0, t)
            return
        ewma, n, t_last = got
        gap = max(t - t_last, 0.0)
        ewma = gap if n == 0 else (1 - GAP_EWMA_ALPHA) * ewma \
            + GAP_EWMA_ALPHA * gap
        self._gaps[key] = (ewma, n + 1, t)

    def expected_gap(self, key) -> Optional[float]:
        got = self._gaps.get(key)
        if got is None or got[1] < self.min_gap_intervals:
            return None  # cold: not enough evidence to price the wait
        return got[0]

    def flush_at(self, key, n: int, t0: float, t_last: float,
                 now: float, deadline_ts: Optional[float] = None) -> float:
        """Absolute time this group should flush — the priced deadline.

        t0: oldest request's submit time (the latency cap anchors here);
        t_last: newest arrival; deadline_ts: the group's earliest request
        deadline (flushing after it serves nobody).  Returns ``now`` (or
        earlier) when the group should flush immediately."""
        window_end = t0 + self.max_wait_s
        if deadline_ts is not None:
            window_end = min(window_end, deadline_ts)
        if n >= max(self.menu):
            return now  # full: waiting buys nothing
        if not self.priced_flush:
            return window_end  # legacy timer
        if self.coalesce_gain(n) <= 1e-12:
            return now  # one more request saves no model cost
        gap = self.expected_gap(key)
        if gap is None:
            return window_end  # cold start degrades to the timer
        candidate = t_last + gap * self.wait_gap_factor
        if candidate > window_end:
            # the next arrival is not expected inside the window: waiting
            # longer cannot beat the amortization — flush now
            return now
        return candidate


# -- fleet dispatch ordering ----------------------------------------------
def normalize_sizes(max_batch: int, sizes=None) -> Tuple[int, ...]:
    """ONE menu normalisation for every consumer (engine warmup, fleet
    warmup spec, AOT bake): dedupe, sort descending; None means the
    single ``max_batch`` program (pre-r14).  Three hand-rolled copies of
    this expression would let warmed sizes, the remembered spec, and the
    bundle's staleness axis silently diverge."""
    if sizes is None:
        return (int(max_batch),)
    return tuple(sorted({int(s) for s in sizes}, reverse=True))


def pick_work(items: Sequence, now: float, *,
              starvation_age_s: float = 2.0,
              pressure_s: float = 0.5,
              prefer: Optional[int] = None) -> int:
    """Index of the work item the fleet should run next: cheapest-
    feasible-first under deadline pressure.

    Three tiers, most critical first:

    * DEADLINE-PRESSURED — a live deadline within ``pressure_s``:
      earliest-deadline-first.  These launch now or their requests
      expire; nothing a deadline-less item could gain outranks that (a
      deadline-less batch cannot expire, only wait longer).
    * URGENT — a redispatched batch (its requests already waited
      through one failure) or age ``>= starvation_age_s``: oldest
      enqueue first.
    * RELAXED — everything else, cheapest model cost first (``area *
      slots``): small launches drain fast and keep p50 low while
      nothing is at risk.

    ``prefer`` is the pulling replica's index, for STICKY STREAM
    ROUTING (serve/streams.py): an item whose ``pin`` matches wins over
    an unpinned item, which wins over one pinned elsewhere — primary
    within the relaxed tier (locality is the relaxed tier's whole
    objective), a trailing tiebreak in the pressured/urgent tiers
    (correctness first: a deadline or a starvation bound always
    outranks cache affinity).  Preference, never exclusion — any
    replica may still take any item, so a pin can never starve a
    stream behind a dead or busy replica (pinned by
    tests/test_streams.py).

    The age promotion is the starvation bound: a relaxed item bypassed
    by cheaper work becomes urgent after ``starvation_age_s`` and from
    then on only genuinely expiring work jumps it, so no item waits
    more than ``starvation_age_s`` plus the deadline-pressured drain
    (pinned by tests/test_sched.py).  Items must expose ``t_enqueue``,
    ``seq``, ``cost_px``, ``min_deadline`` (None ok),
    ``redispatches``; ``pin`` (a replica index or None) is optional —
    absent reads as unpinned, so pre-stream items rank exactly as
    before."""
    best_i = 0
    best_rank = None
    for i, it in enumerate(items):
        pin = getattr(it, "pin", None)
        aff = (1 if pin is None or prefer is None
               else (0 if pin == prefer else 2))
        dl = getattr(it, "min_deadline", None)
        if dl is not None and dl - now <= pressure_s:
            rank = (0, dl, aff, it.seq)
        elif (getattr(it, "redispatches", 0) > 0
                or now - it.t_enqueue >= starvation_age_s):
            rank = (1, it.t_enqueue, aff, it.seq)
        else:
            rank = (2, aff, it.cost_px, it.seq)
        if best_rank is None or rank < best_rank:
            best_rank, best_i = rank, i
    return best_i


# -- offline planner + prefetch consumers ---------------------------------
def offline_planner(model: PlanCostModel, *, max_buckets: int,
                    mode: str = "cost", warn=None) -> GlobalPlanner:
    """The offline engine's entry into the core: exactly the r8
    ``GlobalPlanner`` over the shared cost model — plans are BIT-
    identical to constructing it directly (pinned by the legacy
    comparator in tests/test_sched.py), so PLAN_ABLATION_r08 reproduces.
    Routing construction through the core is what lets the audit and the
    gauges treat 'the planner every consumer uses' as one object."""
    return GlobalPlanner(model, max_buckets=max_buckets, mode=mode,
                         warn=warn)


def prefetch_depth(launch_px: float, launch_cost_px: float, *,
                   lo: int = 2, hi: int = 4) -> int:
    """Priced prefetch depth for the train/eval input pipelines: enough
    batches in flight to hide the per-launch dispatch overhead behind
    device compute.  A launch whose fixed cost is a large fraction of
    its compute (tiny batches) needs deeper pipelining; big launches
    need only the classic double buffer.  ``1 + ceil(launch_cost /
    launch_compute)`` clamped to [lo, hi] — at the bench pricing
    (0.05 Mpx launch, ~1 Mpx batches) this is exactly the historical
    depth=2, so default behaviour is unchanged."""
    px = max(float(launch_px), 1.0)
    depth = 1 + int(-(-float(launch_cost_px) // px))
    return max(int(lo), min(int(hi), depth))


def prefetch_depth_for(batcher, *, epoch: int = 0, lo: int = 2,
                       hi: int = 4) -> int:
    """``prefetch_depth`` priced from a ``ShardedBatcher``'s own epoch
    schedule (mean pixels per launch) and its configured launch cost —
    the CLIs call this so the train AND eval input pipelines consume the
    same pricing the planner built the schedule with."""
    sched = batcher.global_schedule(epoch)
    if not sched:
        return int(lo)
    px = sum(k[0] * k[1] * len(g) for k, g in sched) / len(sched)
    return prefetch_depth(px, getattr(batcher, "launch_cost_px", 0.0),
                          lo=lo, hi=hi)
