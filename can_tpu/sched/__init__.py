"""can_tpu.sched — the cost-priced scheduling core all four batch-
formation engines consume (offline ShardedBatcher, serve MicroBatcher,
eval prefetch, fleet work queue).  See sched/core.py."""

from .core import (
    DEFAULT_LAUNCH_COST_SLOTS,
    DEFAULT_MENU_BUDGET,
    ServeSched,
    cover_cost,
    default_serve_menu,
    normalize_sizes,
    offline_planner,
    pick_work,
    prefetch_depth,
    prefetch_depth_for,
    select_menu,
)

__all__ = [
    "DEFAULT_LAUNCH_COST_SLOTS",
    "DEFAULT_MENU_BUDGET",
    "ServeSched",
    "cover_cost",
    "default_serve_menu",
    "normalize_sizes",
    "offline_planner",
    "pick_work",
    "prefetch_depth",
    "prefetch_depth_for",
    "select_menu",
]
