// Native hot loop of the ground-truth density-map generator.
//
// The reference generator spends its time convolving one delta per person
// with a full-image Gaussian (reference:
// data_preparation/k_nearest_gaussian_kernel.py:42-52, O(people x H x W)).
// can_tpu/data/density.py already reduces that to exact windowed stamping;
// this file is the same stamping loop in C++ (dense Gaussian outer products
// over clipped windows), ~10x the numpy version on annotation-dense images
// and independent of Python object overhead.
//
// Exposed C ABI (consumed via ctypes, see can_tpu/data/density.py):
//   stamp_gaussians(density, h, w, rows, cols, sigmas, n, truncate)
//     density: float64[h*w], row-major, accumulated in place
//     rows/cols: float64[n] pixel coordinates (already validated in-bounds)
//     sigmas: float64[n] per-point Gaussian sigma
//
// Build: tools/build_native.py (g++ -O3 -shared -fPIC).

#include <cmath>
#include <cstdint>
#include <vector>

extern "C" {

void stamp_gaussians(double *density, int64_t h, int64_t w,
                     const double *rows, const double *cols,
                     const double *sigmas, int64_t n, double truncate) {
  std::vector<double> kr, kc;
  for (int64_t i = 0; i < n; ++i) {
    const int64_t row = static_cast<int64_t>(rows[i]);
    const int64_t col = static_cast<int64_t>(cols[i]);
    const double sigma = sigmas[i];
    const int64_t radius = static_cast<int64_t>(truncate * sigma + 0.5);
    if (radius < 1) {
      density[row * w + col] += 1.0;
      continue;
    }
    // sampled 1-D Gaussian, normalised to sum 1 over the full support
    // (scipy.ndimage semantics; clipping at image borders loses mass,
    // matching mode='constant')
    const int64_t klen = 2 * radius + 1;
    kr.assign(klen, 0.0);
    double sum = 0.0;
    for (int64_t t = 0; t < klen; ++t) {
      const double x = static_cast<double>(t - radius) / sigma;
      kr[t] = std::exp(-0.5 * x * x);
      sum += kr[t];
    }
    for (int64_t t = 0; t < klen; ++t) kr[t] /= sum;
    kc = kr;  // isotropic

    const int64_t r0 = row - radius < 0 ? 0 : row - radius;
    const int64_t r1 = row + radius + 1 > h ? h : row + radius + 1;
    const int64_t c0 = col - radius < 0 ? 0 : col - radius;
    const int64_t c1 = col + radius + 1 > w ? w : col + radius + 1;
    for (int64_t r = r0; r < r1; ++r) {
      const double krv = kr[r - (row - radius)];
      double *drow = density + r * w;
      const double *kcp = kc.data() + (c0 - (col - radius));
      for (int64_t c = c0; c < c1; ++c) {
        drow[c] += krv * kcp[c - c0];
      }
    }
  }
}

}  // extern "C"
