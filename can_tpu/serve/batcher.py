"""Micro-batcher: group queued requests by bucket shape, flush as one
static-shape batch.

The offline ``ShardedBatcher`` solves variable-resolution-under-XLA with
shape buckets + masked padding; online serving has the same constraint at
request granularity, so this batcher reuses the SAME math — the bucket
mapping is ``data.batching.snap_to_bucket`` and batch assembly is
``data.batching.pad_batch`` — it only swaps the epoch schedule for an
arrival-driven flush policy.

Since round 14 the flush policy and launch sizes come from the shared
scheduling core (``can_tpu/sched``) when a ``ServeSched`` is given:

* a bucket's group flushes the moment it holds the TOP menu size (the
  batch is full — waiting longer buys nothing);
* otherwise it flushes at the core's PRICED deadline
  (``ServeSched.flush_at``): immediately when coalescing one more
  request cannot beat launch-cost amortization or when the bucket's
  observed arrival rate says no request is expected inside the window;
  at the latency cap (``max_wait_ms``) or the group's deadline slack
  otherwise — with no rate estimate yet the priced deadline IS the old
  timer, so cold behaviour is unchanged;
* a flush is covered by the core's menu parts (the planner's exact
  ``decompose`` DP): a 2-request flush launches a 2-slot program
  instead of padding to ``max_batch`` (fill slots remain
  ``sample_mask=0``, the offline dead-slot convention), and every
  emitted size is a menu size — the XLA compile count is
  ``buckets x dtypes x menu sizes``, static and warmed up front.

Without a ``sched`` the pre-r14 behaviour is preserved exactly: pad
every flush to ``max_batch``, flush on the ``max_wait_ms`` timer (the
bit-compatible baseline the tests and the bench's legacy arm drive).

The pump wakes EXACTLY at the earliest pending flush deadline (or on
arrival, via the queue's condition) — never on a fixed poll grain: with
priced deadlines that can be "now", a 50 ms idle poll would have eaten
the entire low-load latency win, and even under the timer policy a poll
interval above a short ``max_wait_ms`` silently inflated the tail.

Requests whose deadline expires before dispatch are rejected, never
launched: a result the client has already given up on still costs a full
batch slot, and under overload those zombie slots are exactly the capacity
the live requests need.

Single consumer thread; dispatch runs ON that thread — the device executes
serially anyway, and one thread means the pending-group state needs no
locking beyond the queue's own.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from can_tpu.data.batching import Batch, pad_batch, snap_to_bucket
from can_tpu.serve.queue import (
    REJECT_DEADLINE,
    REJECT_ERROR,
    BoundedRequestQueue,
    ServeRequest,
)

# (bucket H, bucket W, image dtype): dtype is part of the jit signature, so
# u8 and f32 requests must not share a batch buffer (pad_batch keeps the
# items' dtype)
GroupKey = Tuple[int, int, str]


class _Group:
    """One pending per-key group: requests + the arrival timestamps the
    priced flush deadline needs."""

    __slots__ = ("requests", "t0", "t_last")

    def __init__(self, t0: float):
        self.requests: List[ServeRequest] = []
        self.t0 = t0      # oldest request's submit (latency cap anchor)
        self.t_last = t0  # newest arrival (the wait-for-next anchor)


class MicroBatcher:
    """Pulls from a ``BoundedRequestQueue``, emits padded ``Batch``es.

    dispatch: ``fn(bucket_hw, batch, requests)`` — executes the batch and
    resolves each request (the service wires this to the engine).  A
    dispatch that raises rejects its requests with ``error`` and the
    batcher keeps running: one poison batch must not kill the service.

    sched: optional ``can_tpu.sched.ServeSched`` — the shared scheduling
    core (priced sub-batch menu + priced flush deadlines).  None keeps
    the pre-r14 pad-to-``max_batch`` / fixed-timer behaviour exactly.

    bucket_ladder / pad_multiple / min_bucket_h: forwarded to
    ``snap_to_bucket`` (same semantics as the offline batcher).
    """

    def __init__(self, queue: BoundedRequestQueue, dispatch: Callable,
                 *, max_batch: int = 8, max_wait_ms: float = 5.0,
                 bucket_ladder=None, pad_multiple=None,
                 min_bucket_h: Optional[int] = None, ds: int = 8,
                 telemetry=None, clock=time.monotonic,
                 idle_wait_s: float = 0.05,
                 on_reject: Optional[Callable] = None,
                 sched=None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if sched is not None and sched.max_batch != int(max_batch):
            raise ValueError(
                f"sched menu tops out at {sched.max_batch}, batcher "
                f"max_batch is {max_batch} — one core, one top size")
        self.queue = queue
        self.dispatch = dispatch
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        self.sched = sched
        if isinstance(pad_multiple, int):
            pad_multiple = (pad_multiple, pad_multiple)
        self.bucket_ladder = bucket_ladder
        self.pad_multiple = pad_multiple
        self.min_bucket_h = min_bucket_h
        self.ds = int(ds)
        self.telemetry = telemetry
        # on_reject(reason, count): batcher-side rejections (deadline
        # expiry, poison batch) happen past the admission gate, so the
        # owner's reject counters need this hook to stay truthful
        self.on_reject = on_reject
        self._clock = clock
        self._idle_wait_s = float(idle_wait_s)
        self._pending: Dict[GroupKey, _Group] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- bucket mapping -------------------------------------------------
    def bucket_of(self, hw: Tuple[int, int]) -> Tuple[int, int]:
        return snap_to_bucket(hw, ladder=self.bucket_ladder,
                              pad_multiple=self.pad_multiple,
                              min_bucket_h=self.min_bucket_h)

    # -- flush pricing ---------------------------------------------------
    def _flush_at(self, key: GroupKey, group: _Group, now: float) -> float:
        """Absolute flush deadline for one group — the core's priced
        deadline, or the legacy ``t0 + max_wait`` timer without a core."""
        if self.sched is None:
            return group.t0 + self.max_wait_s
        deadlines = [r.deadline_ts for r in group.requests
                     if r.deadline_ts is not None]
        return self.sched.flush_at(key, len(group.requests), group.t0,
                                   group.t_last, now,
                                   min(deadlines) if deadlines else None)

    def next_wake_s(self, now: Optional[float] = None) -> float:
        """Seconds until the earliest pending flush deadline (the EXACT
        pump wake bound — never a fixed poll grain), or ``idle_wait_s``
        with nothing pending.  >= 0."""
        now = self._clock() if now is None else now
        if not self._pending:
            return self._idle_wait_s
        due = min(self._flush_at(k, g, now)
                  for k, g in self._pending.items())
        return max(0.0, min(self._idle_wait_s, due - now))

    # -- core pump (thread-free, testable with a fake clock) ------------
    def run_once(self, wait_s: Optional[float] = None) -> int:
        """One pump iteration: wait for arrivals (bounded by the earliest
        pending flush deadline), intake, flush what's due.  Returns the
        number of batches dispatched."""
        wait = self.next_wake_s() if wait_s is None else wait_s
        self.queue.wait_nonempty(wait)
        n = self.intake()
        return n + self.poll(self._clock())

    def intake(self) -> int:
        """Drain the queue into per-bucket pending groups; reject already
        expired requests; flush any group that reaches the top launch
        size.  Returns batches dispatched."""
        live, expired = self.queue.drain()
        for r in expired:
            self._reject_expired(r)
        flushed = 0
        for r in live:
            bh, bw = self.bucket_of(r.shape)
            key = (bh, bw, str(r.image.dtype))
            group = self._pending.get(key)
            if group is None:
                group = self._pending[key] = _Group(r.t_submit)
            group.requests.append(r)
            group.t_last = r.t_submit
            if self.sched is not None:
                self.sched.observe_arrival(key, r.t_submit)
            if len(group.requests) >= self.max_batch:
                del self._pending[key]
                flushed += self._flush(key, group.requests)
        return flushed

    def poll(self, now: float) -> int:
        """Reject expired pending requests; flush groups whose priced
        deadline (or legacy timer) has arrived.  Returns batches
        dispatched."""
        flushed = 0
        for key in sorted(self._pending):
            group = self._pending[key]
            kept = []
            for r in group.requests:
                if r.expired(now):
                    self._reject_expired(r)
                else:
                    kept.append(r)
            if not kept:
                del self._pending[key]
                continue
            group.requests = kept
            if now >= self._flush_at(key, group, now):
                del self._pending[key]
                flushed += self._flush(key, kept)
        return flushed

    def flush_all(self) -> int:
        """Dispatch every pending group (shutdown path: an admitted request
        resolves even when the service is closing)."""
        n = 0
        for key in sorted(self._pending):
            group = self._pending.pop(key)
            n += self._flush(key, group.requests)
        return n

    def pending_count(self) -> int:
        return sum(len(g.requests) for g in self._pending.values())

    # -- assembly + dispatch --------------------------------------------
    def _flush(self, key: GroupKey, group: List[ServeRequest]) -> int:
        """Cover the group with menu-size launches (one launch padded to
        ``max_batch`` without a core) and dispatch each.  Returns the
        number of batches dispatched."""
        if self.sched is None:
            # one padded launch per max_batch-full slice (legacy; a group
            # never exceeds max_batch in practice — intake flushes full)
            parts: Tuple[int, ...] = (self.max_batch,) * max(
                1, -(-len(group) // self.max_batch))
        else:
            parts = self.sched.parts_for(len(group))
        n = 0
        pos = 0
        for size in parts:
            take = group[pos:pos + size]
            pos += size
            if not take:
                break
            self._flush_part(key, take, size)
            n += 1
        return n

    def _flush_part(self, key: GroupKey, group: List[ServeRequest],
                    size: int) -> None:
        bh, bw = key[0], key[1]
        try:
            # assembly window stamped on every request (service clock):
            # queue-wait ends where assembly starts, and the service turns
            # the pair into the serve.request breakdown + request spans
            t_asm = self._clock()
            # zero per-item density targets: serve batches reuse the
            # offline Batch layout (image/dmap/pixel_mask/sample_mask) so
            # the engine can run the exact eval-step math; dmap is unused
            # by prediction
            items = [(r.image,
                      np.zeros((r.shape[0] // self.ds,
                                r.shape[1] // self.ds, 1), np.float32))
                     for r in group]
            batch = pad_batch(items, (bh, bw), size,
                              [True] * len(group), self.ds)
            t_ready = self._clock()
            for r in group:
                r.t_assembly = t_asm
                r.t_ready = t_ready
            self.dispatch((bh, bw), batch, group)
        except Exception as e:  # noqa: BLE001 — poison batch, keep serving
            n = 0
            for r in group:
                if not r.done:
                    r.reject(REJECT_ERROR, f"{type(e).__name__}: {e}")
                    n += 1
            if self.on_reject is not None and n:
                self.on_reject(REJECT_ERROR, n)
            if self.telemetry is not None:
                self.telemetry.emit("serve.reject", reason=REJECT_ERROR,
                                    count=n,
                                    detail=f"{type(e).__name__}: {e}")

    def _reject_expired(self, r: ServeRequest) -> None:
        r.reject(REJECT_DEADLINE, "deadline expired before dispatch")
        if self.on_reject is not None:
            self.on_reject(REJECT_DEADLINE, 1)
        if self.telemetry is not None:
            self.telemetry.emit("serve.reject", reason=REJECT_DEADLINE,
                                count=1, request_id=r.id)

    # -- thread lifecycle ------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is not None:
            raise RuntimeError("batcher already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="can-tpu-serve-batcher")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.run_once()
        # drain-on-stop: admitted requests still resolve (close() has
        # already stopped new admissions)
        self.intake()
        self.flush_all()

    def close(self) -> None:
        """Stop the pump thread and flush everything pending (idempotent)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        else:
            self.intake()
            self.flush_all()
