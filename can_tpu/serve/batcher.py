"""Micro-batcher: group queued requests by bucket shape, flush as one
static-shape batch.

The offline ``ShardedBatcher`` solves variable-resolution-under-XLA with
shape buckets + masked padding; online serving has the same constraint at
request granularity, so this batcher reuses the SAME math — the bucket
mapping is ``data.batching.snap_to_bucket`` and batch assembly is
``data.batching.pad_batch`` — it only swaps the epoch schedule for an
arrival-driven flush policy:

* a bucket's group flushes the moment it holds ``max_batch`` requests
  (the batch is full — waiting longer buys nothing);
* otherwise a group flushes once its OLDEST request has waited
  ``max_wait_ms`` (bounded latency cost for batching: an idle service adds
  at most max_wait to any request);
* every flush pads to exactly ``max_batch`` slots (fill slots are
  ``sample_mask=0``, precisely the offline dead-slot convention), so each
  bucket shape is ONE static (B, H, W) signature — the XLA compile count
  is the distinct-bucket count, independent of traffic.

Requests whose deadline expires before dispatch are rejected, never
launched: a result the client has already given up on still costs a full
batch slot, and under overload those zombie slots are exactly the capacity
the live requests need.

Single consumer thread; dispatch runs ON that thread — the device executes
serially anyway, and one thread means the pending-group state needs no
locking beyond the queue's own.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from can_tpu.data.batching import Batch, pad_batch, snap_to_bucket
from can_tpu.serve.queue import (
    REJECT_DEADLINE,
    REJECT_ERROR,
    BoundedRequestQueue,
    ServeRequest,
)

# (bucket H, bucket W, image dtype): dtype is part of the jit signature, so
# u8 and f32 requests must not share a batch buffer (pad_batch keeps the
# items' dtype)
GroupKey = Tuple[int, int, str]


class MicroBatcher:
    """Pulls from a ``BoundedRequestQueue``, emits padded ``Batch``es.

    dispatch: ``fn(bucket_hw, batch, requests)`` — executes the batch and
    resolves each request (the service wires this to the engine).  A
    dispatch that raises rejects its requests with ``error`` and the
    batcher keeps running: one poison batch must not kill the service.

    bucket_ladder / pad_multiple / min_bucket_h: forwarded to
    ``snap_to_bucket`` (same semantics as the offline batcher).
    """

    def __init__(self, queue: BoundedRequestQueue, dispatch: Callable,
                 *, max_batch: int = 8, max_wait_ms: float = 5.0,
                 bucket_ladder=None, pad_multiple=None,
                 min_bucket_h: Optional[int] = None, ds: int = 8,
                 telemetry=None, clock=time.monotonic,
                 idle_wait_s: float = 0.05,
                 on_reject: Optional[Callable] = None):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        self.queue = queue
        self.dispatch = dispatch
        self.max_batch = int(max_batch)
        self.max_wait_s = float(max_wait_ms) / 1e3
        if isinstance(pad_multiple, int):
            pad_multiple = (pad_multiple, pad_multiple)
        self.bucket_ladder = bucket_ladder
        self.pad_multiple = pad_multiple
        self.min_bucket_h = min_bucket_h
        self.ds = int(ds)
        self.telemetry = telemetry
        # on_reject(reason, count): batcher-side rejections (deadline
        # expiry, poison batch) happen past the admission gate, so the
        # owner's reject counters need this hook to stay truthful
        self.on_reject = on_reject
        self._clock = clock
        self._idle_wait_s = float(idle_wait_s)
        # group key -> (requests, oldest enqueue ts)
        self._pending: Dict[GroupKey, Tuple[List[ServeRequest], float]] = {}
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- bucket mapping -------------------------------------------------
    def bucket_of(self, hw: Tuple[int, int]) -> Tuple[int, int]:
        return snap_to_bucket(hw, ladder=self.bucket_ladder,
                              pad_multiple=self.pad_multiple,
                              min_bucket_h=self.min_bucket_h)

    # -- core pump (thread-free, testable with a fake clock) ------------
    def run_once(self, wait_s: Optional[float] = None) -> int:
        """One pump iteration: wait for arrivals (bounded by the earliest
        pending flush deadline), intake, flush what's due.  Returns the
        number of batches dispatched."""
        wait = self._idle_wait_s if wait_s is None else wait_s
        if self._pending:
            due = min(t0 + self.max_wait_s
                      for _, t0 in self._pending.values())
            wait = max(0.0, min(wait, due - self._clock()))
        self.queue.wait_nonempty(wait)
        n = self.intake()
        return n + self.poll(self._clock())

    def intake(self) -> int:
        """Drain the queue into per-bucket pending groups; reject already
        expired requests; flush any group that reaches ``max_batch``.
        Returns batches dispatched."""
        live, expired = self.queue.drain()
        for r in expired:
            self._reject_expired(r)
        flushed = 0
        for r in live:
            bh, bw = self.bucket_of(r.shape)
            key = (bh, bw, str(r.image.dtype))
            group, t0 = self._pending.get(key, ([], r.t_submit))
            group.append(r)
            self._pending[key] = (group, t0)
            if len(group) >= self.max_batch:
                del self._pending[key]
                self._flush(key, group)
                flushed += 1
        return flushed

    def poll(self, now: float) -> int:
        """Reject expired pending requests; flush groups whose oldest
        request has waited ``max_wait_ms``.  Returns batches dispatched."""
        flushed = 0
        for key in sorted(self._pending):
            group, t0 = self._pending[key]
            kept = []
            for r in group:
                if r.expired(now):
                    self._reject_expired(r)
                else:
                    kept.append(r)
            if not kept:
                del self._pending[key]
                continue
            if now - t0 >= self.max_wait_s:
                del self._pending[key]
                self._flush(key, kept)
                flushed += 1
            elif len(kept) != len(group):
                self._pending[key] = (kept, t0)
        return flushed

    def flush_all(self) -> int:
        """Dispatch every pending group (shutdown path: an admitted request
        resolves even when the service is closing)."""
        n = 0
        for key in sorted(self._pending):
            group, _ = self._pending.pop(key)
            self._flush(key, group)
            n += 1
        return n

    def pending_count(self) -> int:
        return sum(len(g) for g, _ in self._pending.values())

    # -- assembly + dispatch --------------------------------------------
    def _flush(self, key: GroupKey, group: List[ServeRequest]) -> None:
        bh, bw = key[0], key[1]
        try:
            # assembly window stamped on every request (service clock):
            # queue-wait ends where assembly starts, and the service turns
            # the pair into the serve.request breakdown + request spans
            t_asm = self._clock()
            # zero per-item density targets: serve batches reuse the
            # offline Batch layout (image/dmap/pixel_mask/sample_mask) so
            # the engine can run the exact eval-step math; dmap is unused
            # by prediction
            items = [(r.image,
                      np.zeros((r.shape[0] // self.ds,
                                r.shape[1] // self.ds, 1), np.float32))
                     for r in group]
            batch = pad_batch(items, (bh, bw), self.max_batch,
                              [True] * len(group), self.ds)
            t_ready = self._clock()
            for r in group:
                r.t_assembly = t_asm
                r.t_ready = t_ready
            self.dispatch((bh, bw), batch, group)
        except Exception as e:  # noqa: BLE001 — poison batch, keep serving
            n = 0
            for r in group:
                if not r.done:
                    r.reject(REJECT_ERROR, f"{type(e).__name__}: {e}")
                    n += 1
            if self.on_reject is not None and n:
                self.on_reject(REJECT_ERROR, n)
            if self.telemetry is not None:
                self.telemetry.emit("serve.reject", reason=REJECT_ERROR,
                                    count=n,
                                    detail=f"{type(e).__name__}: {e}")

    def _reject_expired(self, r: ServeRequest) -> None:
        r.reject(REJECT_DEADLINE, "deadline expired before dispatch")
        if self.on_reject is not None:
            self.on_reject(REJECT_DEADLINE, 1)
        if self.telemetry is not None:
            self.telemetry.emit("serve.reject", reason=REJECT_DEADLINE,
                                count=1, request_id=r.id)

    # -- thread lifecycle ------------------------------------------------
    def start(self) -> "MicroBatcher":
        if self._thread is not None:
            raise RuntimeError("batcher already started")
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="can-tpu-serve-batcher")
        self._thread.start()
        return self

    def _run(self) -> None:
        while not self._stop.is_set():
            self.run_once()
        # drain-on-stop: admitted requests still resolve (close() has
        # already stopped new admissions)
        self.intake()
        self.flush_all()

    def close(self) -> None:
        """Stop the pump thread and flush everything pending (idempotent)."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=30.0)
            self._thread = None
        else:
            self.intake()
            self.flush_all()
