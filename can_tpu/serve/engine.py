"""ServeEngine: params + one jitted predict program per bucket signature.

The prediction math is EXACTLY the offline eval step's (``train/steps.py
make_eval_step``): normalise-on-device for u8 batches, ``cannet_apply``
forward, masked per-image count reduction via ``train.loss.density_counts``
— so a count served online is bit-for-bit the count ``evaluate()`` would
have produced for the same image and params (the offline/online parity the
tests pin).  The engine adds only what serving needs around that math:

* params (and BN ``batch_stats``) are device-resident from construction —
  a host-numpy param tree fed to jit would re-upload ~74 MB per batch —
  and stored in the ``serve_dtype`` format (``serve/quant.py``): f32
  bit-parity, bf16 MXU-rate, or int8 weight-only PTQ with in-program
  dequantization and f32 accumulation;
* ``device=`` pins one engine to one device of the mesh (the fleet's
  replica placement: committed params make jit place the whole program
  on that device) — None keeps the single-device default behaviour;
* ``warmup()`` drives one zero batch through every bucket shape BEFORE
  traffic, so no real request pays the multi-second trace+compile bill,
  and ``utils/compile_cache`` (wired by the CLI) makes warm restarts
  deserialise instead of recompile;
* ``swap_params()`` atomically replaces the device-resident trees with a
  new checkpoint's — same structure means the already-compiled programs
  serve the new weights instantly (params are jit ARGUMENTS, not
  constants), which is what makes the fleet's blue/green flip free;
* every new (shape, dtype) signature is counted and attributed on the
  telemetry bus via ``obs.RecompileTracker`` — a mid-traffic compile is a
  latency cliff an operator must be able to see.
"""

from __future__ import annotations

import time
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from can_tpu.data.batching import Batch, pad_batch
from can_tpu.models import cannet_apply
from can_tpu.obs import RecompileTracker, Telemetry
from can_tpu.serve.quant import (
    compute_dtype_for,
    dequantize_tree,
    quantize_tree,
)
from can_tpu.train.loss import density_counts
from can_tpu.train.steps import _batch_image


def _batch_dict(batch: Batch) -> dict:
    return {"image": batch.image, "dmap": batch.dmap,
            "pixel_mask": batch.pixel_mask,
            "sample_mask": batch.sample_mask}


def tree_signature(tree) -> tuple:
    """Structure + per-leaf (shape, dtype) of a pytree — the compiled
    predict programs' view of the params.  Two trees with equal
    signatures are interchangeable WITHOUT recompilation; a rollout to a
    differently-shaped checkpoint must be refused, not compiled mid-
    traffic."""
    leaves, treedef = jax.tree.flatten(tree)
    return (str(treedef),
            tuple((tuple(x.shape), str(jnp.asarray(x).dtype))
                  for x in leaves))


class ServeEngine:
    """Executes padded serve batches on one device.

    params / batch_stats: as returned by ``cli.test.load_params`` (host or
    device trees; moved on-device once here).
    serve_dtype: "f32" | "bf16" | "int8" — the storage/compute mode
    (serve/quant.py); "f32" is the bit-parity default.
    compute_dtype: overrides the mode's compute dtype (the legacy --bf16
    path: f32 params, bf16 compute).  None derives it from serve_dtype.
    device: pin params (and hence the compiled programs) to this device.
    quantized: params/batch_stats are ALREADY in serve_dtype storage form
    (the fleet quantizes once and replicates, instead of per replica).
    telemetry: optional bus for ``compile`` events; the engine works (and
    still counts compiles) without one.
    """

    def __init__(self, params, batch_stats=None, *, compute_dtype=None,
                 serve_dtype: str = "f32", ds: int = 8, device=None,
                 quantized: bool = False, telemetry=None,
                 name: str = "serve_predict", aot_programs=None):
        self.ds = int(ds)
        self.serve_dtype = serve_dtype
        self.device = device
        self.name = name
        # AOT warm start (serve/aot.py): {(image shape, dtype str):
        # loaded Compiled}.  A matching batch executes the DESERIALIZED
        # binary — no trace, no compile, compile_count untouched; misses
        # fall through to the jit path and are counted like any compile.
        self._aot = dict(aot_programs) if aot_programs else {}
        self.aot_hits = 0
        self.released = False
        if not quantized:
            params = quantize_tree(params, serve_dtype)
        self.params = self._put(params)
        self.batch_stats = (None if batch_stats is None
                            else self._put(batch_stats))
        self._signature = tree_signature((self.params, self.batch_stats))
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        if compute_dtype is None:
            compute_dtype = compute_dtype_for(serve_dtype)

        def predict(params, batch, batch_stats):
            # int8 mode: in-program dequant (fused multiply; HBM holds
            # int8) -> f32 weights -> f32 arithmetic ("f32 accumulation")
            params = dequantize_tree(params, serve_dtype)
            image = _batch_image(batch)  # u8 -> normalised f32, f32 passthru
            if batch_stats is not None:
                pred = cannet_apply(params, image,
                                    compute_dtype=compute_dtype,
                                    batch_stats=batch_stats, train=False)
            else:
                pred = cannet_apply(params, image,
                                    compute_dtype=compute_dtype)
            counts, _ = density_counts(pred, batch)
            mask = (batch["pixel_mask"]
                    * batch["sample_mask"][:, None, None, None])
            return counts, pred.astype(jnp.float32) * mask

        # RecompileTracker attributes each new (shape, dtype) signature —
        # bucket warmup and any mid-traffic compile both land as `compile`
        # events, and len(signatures) is the engine's compile count
        self._predict = RecompileTracker(jax.jit(predict), self.telemetry,
                                         name=name, batch_arg=1)
        self._signatures = self.telemetry.signature_registry[name]
        self._last_compiled = False

    def _put(self, tree):
        if self.device is None:
            return jax.device_put(tree)
        return jax.device_put(tree, self.device)

    @property
    def compile_count(self) -> int:
        """Distinct predict signatures compiled so far."""
        return len(self._signatures)

    def swap_params(self, params, batch_stats=None, *,
                    quantized: bool = False) -> None:
        """Atomically replace the served weights (the blue/green flip).

        The new trees must match the current param signature exactly —
        same structure, shapes, dtypes — so every already-compiled bucket
        program serves the new weights with ZERO recompilation.  A
        mismatch raises instead of silently queueing a mid-traffic
        compile.  The caller serialises against in-flight ``predict_batch``
        calls (the fleet holds the replica's dispatch lock)."""
        if not quantized:
            params = quantize_tree(params, self.serve_dtype)
        params = self._put(params)
        batch_stats = None if batch_stats is None else self._put(batch_stats)
        sig = tree_signature((params, batch_stats))
        if sig != self._signature:
            raise ValueError(
                "swap_params structure mismatch: the new checkpoint's "
                "param tree differs in structure/shape/dtype from the "
                "serving tree — flipping would recompile every bucket "
                "program mid-traffic; deploy it as a fresh fleet instead")
        self.params = params
        self.batch_stats = batch_stats

    def predict_batch(self, batch: Batch, *, want_density: bool = False
                      ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
        """Run one padded batch; returns host (counts (B,), density
        (B, h, w, 1) or None).  Counts are fetched synchronously (the
        caller resolves waiting requests with them, nothing to overlap
        with); the density tensor — orders of magnitude bigger — is only
        shipped device→host when a request actually asked for it.  The
        compiled program is identical either way: only the host fetch is
        conditional, so the jit signature (and the warmup compile budget)
        doesn't fork on ``want_density``.

        With an AOT table (``aot_programs``), a batch whose exact
        (shape, dtype) was baked executes the loaded binary: no trace, no
        compile, ``last_batch_compiled`` False.  Misses fall through to
        the jit path unchanged."""
        if self.released:
            raise RuntimeError(f"engine {self.name}: buffers released "
                               f"(quarantined/retired replica) — build a "
                               f"fresh engine to serve again")
        prog = (self._aot.get((tuple(batch.image.shape),
                               str(batch.image.dtype)))
                if self._aot else None)
        if prog is not None:
            counts, density = prog(self.params, _batch_dict(batch),
                                   self.batch_stats)
            self.aot_hits += 1
            self._last_compiled = False
        else:
            counts, density = self._predict(self.params,
                                            _batch_dict(batch),
                                            self.batch_stats)
            self._last_compiled = self._predict.last_first_call
        # can-tpu-lint: disable=HOSTSYNC(the fetch IS the product: callers resolve waiting requests with it)
        return (np.asarray(counts),
                # can-tpu-lint: disable=HOSTSYNC(fetched only when a request asked for the density tensor)
                np.asarray(density) if want_density else None)

    def is_warm(self, batch: Batch) -> bool:
        """True when dispatching ``batch`` runs an already-built program
        — an AOT table hit or a jit signature this engine has seen.
        False means the dispatch would pay a live trace+lower+compile
        (the fleet's watchdog prices those launches with the compile
        allowance instead of the steady-state deadline)."""
        if (self._aot and (tuple(batch.image.shape),
                           str(batch.image.dtype)) in self._aot):
            return True
        from can_tpu.train.steps import batch_signature

        return batch_signature(_batch_dict(batch)) in self._signatures

    @property
    def last_batch_compiled(self) -> bool:
        """True when the most recent ``predict_batch`` hit a new signature
        (its wall time is compile, not steady-state — keep it out of
        latency reservoirs, exactly like the offline loops do).  AOT hits
        are never compiles."""
        return self._last_compiled

    def release_buffers(self) -> None:
        """Drop every reference to the device-resident param/batch-stats
        trees (and the loaded AOT executables) so the device's bytes are
        freed by refcount.  Deliberately NOT ``x.delete()``: the fleet's
        batched replication can alias per-device shards across replica
        trees, and a force-delete would invalidate a sibling replica's
        params — refcount release frees exactly this replica's bytes once
        nothing else holds them.  Idempotent; a released engine refuses
        ``predict_batch`` with a typed error instead of tracing None
        params into jit."""
        self.params = None
        self.batch_stats = None
        self._aot = {}
        self.released = True
        import gc

        gc.collect()  # quarantine path, rare: make the free deterministic

    # -- AOT export (serve/aot.py bake path) ------------------------------
    def compile_program(self, batch: Batch):
        """Lower+compile the exact predict program this engine would
        dispatch for ``batch`` (the cost-ledger precedent: a second
        compile on an already-slow path, persistent-cache-deduped)."""
        from can_tpu.obs.costs import resolve_jit

        args = (self.params, _batch_dict(batch), self.batch_stats)
        return resolve_jit(self._predict, args).lower(*args).compile()

    def serialize_program(self, batch: Batch) -> Tuple[bytes, dict]:
        """One bucket program as a self-contained payload: the serialized
        executable plus its pickled arg/result treedefs (device-free —
        devices ride the executable itself, keyed by id at load).  Returns
        ``(payload, meta)`` with the program's cost facts in ``meta`` when
        the backend reports them (the bundle's contract receipt)."""
        import pickle

        from jax.experimental import serialize_executable as se

        compiled = self.compile_program(batch)
        ser, in_tree, out_tree = se.serialize(compiled)
        meta = {}
        try:
            ca = compiled.cost_analysis()
            if isinstance(ca, (list, tuple)):
                ca = ca[0] if ca else None
            if ca:
                if ca.get("flops"):
                    # can-tpu-lint: disable=HOSTSYNC(bake path, host floats from cost_analysis — no device value involved)
                    meta["flops"] = float(ca["flops"])
                if ca.get("bytes accessed"):
                    # can-tpu-lint: disable=HOSTSYNC(bake path, host floats from cost_analysis — no device value involved)
                    meta["bytes_accessed"] = float(ca["bytes accessed"])
        # can-tpu-lint: disable=SWALLOW(cost facts are receipts, not requirements; a non-reporting backend still bakes)
        except Exception:
            pass
        return pickle.dumps((ser, in_tree, out_tree)), meta

    def warmup(self, bucket_shapes, max_batch: int, *,
               dtypes=(np.float32,), sizes=None) -> dict:
        """Compile every (bucket shape, batch size, dtype) program before
        traffic.

        bucket_shapes: iterable of (H, W); dtypes: the image dtypes traffic
        will carry (float32, and uint8 if the front end admits raw bytes);
        sizes: the launch-size menu (can_tpu/sched) — every size the
        batcher may dispatch must be warmed here or a live request pays a
        mid-traffic compile.  None keeps the single ``max_batch`` program
        (pre-r14 behaviour).  Returns ``{"shapes": n, "compiles": new,
        "seconds": wall}``.
        """
        from can_tpu.sched import normalize_sizes

        t0 = time.perf_counter()
        before = self.compile_count
        shapes = sorted(set(map(tuple, bucket_shapes)))
        sizes = normalize_sizes(max_batch, sizes)
        for bh, bw in shapes:
            if bh % self.ds or bw % self.ds:
                raise ValueError(f"bucket shape {bh}x{bw} is not a multiple "
                                 f"of the density downsample ({self.ds})")
            for size in sizes:
                for dt in dtypes:
                    img = np.zeros((bh, bw, 3), dt)
                    dm = np.zeros((bh // self.ds, bw // self.ds, 1),
                                  np.float32)
                    batch = pad_batch([(img, dm)], (bh, bw), size,
                                      [False], self.ds)
                    self.predict_batch(batch)  # np.asarray fetch = fence
        dt_s = time.perf_counter() - t0
        report = {"shapes": len(shapes), "sizes": len(sizes),
                  "compiles": self.compile_count - before,
                  "seconds": round(dt_s, 3)}
        self.telemetry.emit("serve.warmup", **report)
        return report
