"""CountService: the serving front door (programmatic API + HTTP).

Wires the pieces into one lifecycle::

    client -> submit() -> BoundedRequestQueue -> MicroBatcher(thread)
                                                   -> ServeEngine.predict_batch
                                                   -> resolve ServeRequests

``submit()/result()`` is the primary API — tests and the bench drive the
full stack through it with zero networking.  The HTTP front end
(``serve_http``) is a thin stdlib adapter over the same calls: one process,
one device owner, many client connections.

The engine may be a single ``ServeEngine`` (dispatch executes inline on
the batcher thread, the original topology) or a ``FleetEngine``
(serve/fleet.py): then dispatch ENQUEUES the assembled batch and returns,
replica worker threads execute on their own devices and call back into
``_complete`` — same resolution/telemetry code either way, so every
guarantee (typed rejection, parity, bounded compiles) holds per replica.

Telemetry (same bus/schema as train/eval, summarised by
``tools/telemetry_report.py``):

* ``serve.request``  — per completed request: latency_s, bucket, ok
* ``serve.batch``    — per flush: bucket, size/valid/fill, execute_s,
                       queue_depth (the depth gauge rides the batch event:
                       sampled exactly when it changes, no extra thread);
                       fleet batches add ``replica``
* ``serve.reject``   — per rejection: reason (queue_full / backpressure /
                       deadline / shutdown / error)
* ``serve.warmup``   — pre-traffic compile pass summary
* ``fleet.replica`` / ``fleet.rollout`` — emitted by serve/fleet.py:
                       replica state transitions and rollout reports
"""

from __future__ import annotations

import io
import json
import threading
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from can_tpu.data.dataset import normalize_host
from can_tpu.serve.batcher import MicroBatcher
from can_tpu.serve.engine import ServeEngine
from can_tpu.serve.queue import (
    REJECT_ERROR,
    REJECT_SHUTDOWN,
    REJECT_STALE_FRAME,
    REJECT_STREAM_OVERLOAD,
    BoundedRequestQueue,
    RejectedError,
    ServeRequest,
    ServeResult,
)
from can_tpu.serve.streams import StreamSessionRegistry
from can_tpu.utils.profiling import StepTimer


def prepare_image(image: np.ndarray, *, ds: int = 8,
                  normalize: bool = True) -> np.ndarray:
    """Snap an arbitrary HWC image to the density grid, exactly as the
    offline ``CrowdDataset.__getitem__`` does: cv2 bilinear resize down to
    the nearest /ds multiple (half-pixel centers — bit-exact with the
    reference), then ImageNet-normalise (u8 input + normalize=False keeps
    bytes for the device-normalised transfer mode)."""
    if image.ndim != 3 or image.shape[2] != 3:
        raise ValueError(f"expected HWC RGB image, got shape {image.shape}")
    h, w = image.shape[:2]
    rows, cols = h // ds, w // ds
    if rows == 0 or cols == 0:
        raise ValueError(f"image {h}x{w} is smaller than one {ds}px "
                         f"density cell")
    if (rows * ds, cols * ds) != (h, w):
        import cv2

        image = cv2.resize(np.ascontiguousarray(image), (cols * ds, rows * ds))
    if normalize:
        image = normalize_host(np.asarray(image))
        if image.dtype != np.float32:
            raise ValueError("normalize=True needs uint8 or already "
                             f"normalised float32 pixels, got {image.dtype}")
    return image


class ServeTicket:
    """Handle returned by ``submit()``; ``result()`` blocks for the
    outcome (raising ``RejectedError`` on any rejection — never hangs:
    the wait is bounded by the request deadline plus a grace window for
    the in-flight batch)."""

    def __init__(self, request: ServeRequest, service: "CountService"):
        self._request = request
        self._service = service
        self.id = request.id

    @property
    def done(self) -> bool:
        return self._request.done

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if timeout is None:
            if self._request.deadline_ts is not None:
                # deadline + a grace window: an expired request is rejected
                # at the next batcher pump, and a dispatched one resolves
                # within the batch execute — either way well under this.
                # "now" comes from the SERVICE clock (deadline_ts does too;
                # mixing in time.monotonic breaks fake-clock tests)
                timeout = (self._request.deadline_ts
                           - self._service._clock()
                           + self._service.grace_s)
            else:
                timeout = self._service.default_result_timeout_s
        return self._request.wait(max(timeout, 0.0))


class CountService:
    """Owns the queue, the batcher thread, and the engine.

    bucket_ladder / pad_multiple: the bucket policy (same semantics as the
    offline batcher; pick the ladder from the deployment's expected shape
    distribution).  ``warmup()`` should be called before traffic.
    """

    def __init__(self, engine: ServeEngine, *, max_batch: int = 8,
                 max_wait_ms: float = 5.0, queue_capacity: int = 64,
                 high_water: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 bucket_ladder=None, pad_multiple=None,
                 min_bucket_h: Optional[int] = None,
                 telemetry=None, clock=time.monotonic,
                 perf_summary_every: int = 32,
                 menu_budget: Optional[int] = None,
                 flush_policy: str = "priced",
                 stream_ttl_s: float = 300.0,
                 degrade_policy: str = "priced",
                 max_body_mb: float = 64.0):
        if flush_policy not in ("priced", "timer"):
            raise ValueError(f"unknown flush_policy {flush_policy!r} "
                             f"(priced | timer)")
        self.engine = engine
        # the scheduling core (can_tpu/sched): priced sub-batch menu +
        # priced flush deadlines.  menu_budget=1 keeps the single
        # max_batch-slot program; menu_budget=1 AND flush_policy="timer"
        # is the bit-compatible pre-r14 service (sched=None entirely).
        from can_tpu.sched import DEFAULT_MENU_BUDGET, ServeSched

        budget = DEFAULT_MENU_BUDGET if menu_budget is None \
            else int(menu_budget)
        if budget == 1 and flush_policy == "timer":
            self.sched = None
        else:
            self.sched = ServeSched(int(max_batch),
                                    max_wait_s=float(max_wait_ms) / 1e3,
                                    menu_budget=budget,
                                    priced_flush=flush_policy == "priced")
        # fleet mode: dispatch enqueues instead of executing inline, and
        # replica workers call _complete/_fail_batch back on this service
        self._fleet = engine if hasattr(engine, "submit_work") else None
        if self._fleet is not None:
            self._fleet.bind(on_complete=self._complete,
                             on_fail=self._fail_batch,
                             on_reject=self._note_reject, clock=clock)
        self._replica_stats: dict = {}
        self.telemetry = telemetry if telemetry is not None else engine.telemetry
        self.max_batch = int(max_batch)
        self.default_deadline_s = (None if default_deadline_ms is None
                                   else float(default_deadline_ms) / 1e3)
        # result() safety margins (see ServeTicket)
        self.grace_s = max(1.0, 4 * float(max_wait_ms) / 1e3)
        self.default_result_timeout_s = 120.0
        self._clock = clock
        self.queue = BoundedRequestQueue(queue_capacity,
                                         high_water=high_water, clock=clock)
        self.batcher = MicroBatcher(self.queue, self._dispatch,
                                    max_batch=max_batch,
                                    max_wait_ms=max_wait_ms,
                                    bucket_ladder=bucket_ladder,
                                    pad_multiple=pad_multiple,
                                    min_bucket_h=min_bucket_h,
                                    ds=engine.ds, telemetry=self.telemetry,
                                    clock=clock,
                                    on_reject=self._note_reject,
                                    sched=self.sched)
        # request latency reservoir: p50/p95/max over recent requests,
        # tagged by bucket shape (skip_first=0 — warmup() already keeps
        # compiles off the request path, so every sample is steady-state).
        # Guarded by _lock: the batcher thread records while HTTP threads
        # read percentiles, and a deque mutated mid-iteration raises.
        self.latency = StepTimer(skip_first=0)
        self._lock = threading.Lock()
        self._stats = {"submitted": 0, "completed": 0, "rejected": 0,
                       "degraded": 0,
                       "batches": 0, "batch_slots": 0, "batch_valid": 0}
        # stream sessions (serve/streams.py): HOST-side per-stream state
        # — count/density EWMAs, sequence hygiene, the degradation
        # ladder, sticky replica pins.  Living here (never on a replica)
        # is what makes sessions survive quarantine, wedge,
        # resurrection, rollout, and scale events by construction.
        # Requests without a stream_id never touch it.
        if max_body_mb <= 0:
            raise ValueError(f"max_body_mb must be positive, got "
                             f"{max_body_mb}")
        self.max_body_bytes = int(float(max_body_mb) * 2 ** 20)
        self.streams = StreamSessionRegistry(
            ttl_s=stream_ttl_s, clock=clock, telemetry=self.telemetry,
            sched=self.sched, policy=degrade_policy)
        self._started = False
        self._closed = False
        # image dtypes warmup() has compiled — the HTTP raw=1 gate: an
        # unwarmed dtype would compile for seconds ON the batcher thread,
        # stalling every bucket's flushes mid-traffic
        self.warmed_dtypes: set = set()
        # perf-attribution cadence: with a cost ledger on the bus
        # (Telemetry.ledger), one perf.summary event per this many
        # batches keeps the can_tpu_mfu_* gauges live without one event
        # per request (0/negative disables the periodic emit; warmup and
        # close still emit one each)
        self.perf_summary_every = int(perf_summary_every)
        self._perf_batches = 0
        import os as _os

        # pid + random tag: pid alone collides across containerised
        # replicas (both typically pid 1), which would merge two
        # unrelated requests' span trees in a joined artifact
        self._trace_prefix = f"req-{_os.getpid():x}{_os.urandom(2).hex()}"

    # -- lifecycle -------------------------------------------------------
    def warmup(self, bucket_shapes: Sequence[Tuple[int, int]],
               dtypes=(np.float32,)) -> dict:
        # the menu rides the warmup: every size the core may dispatch is
        # compiled here, so traffic never mints a program (the zero-new-
        # compiles pin holds per menu size, not just per bucket)
        report = self.engine.warmup(
            bucket_shapes, self.max_batch, dtypes=dtypes,
            sizes=self.sched.menu if self.sched is not None else None)
        self.warmed_dtypes.update(np.dtype(dt) for dt in dtypes)
        ledger = getattr(self.telemetry, "ledger", None)
        if ledger is not None:
            # every warmed bucket's flops/bytes (hence roofline class) is
            # known the moment warmup returns — publish before traffic;
            # MFU joins in once real batches provide timings
            ledger.emit_summary(self.telemetry, phase="serve_warmup")
        return report

    def start(self) -> "CountService":
        if not self._started:
            if self._fleet is not None:
                self._fleet.start()
            self.batcher.start()
            auto = getattr(self, "autoscaler", None)
            if auto is not None:
                # wired by cli/serve.py (or tests): the SLO/queue-driven
                # scale loop lives and dies with the service
                auto.start()
            inc = getattr(self.telemetry, "incidents", None)
            if inc is not None:
                # an incident bundle dumped while this service is alive
                # (replica quarantine, SLO burn, SIGTERM) carries the
                # live serving stats — queue depth, rejects, per-replica
                # health/generation — in its manifest (obs/incidents.py)
                inc.add_info_source("serve_stats", self.stats)
            # can-tpu-lint: disable=LOCKHELD(idempotent lifecycle flag; start/close run on the owner thread)
            self._started = True
        return self

    def close(self) -> None:
        """Stop admissions, drain in-flight work, reject the rest."""
        if self._closed:
            return
        # can-tpu-lint: disable=LOCKHELD(monotonic flag; a submit racing the flip is rejected by queue.close below)
        self._closed = True
        auto = getattr(self, "autoscaler", None)
        if auto is not None:
            # BEFORE the drain: a scale decision mid-teardown would race
            # the fleet's close choreography
            auto.close()
        for r in self.queue.close():
            r.reject(REJECT_SHUTDOWN, "service closing")
            self._count_reject(REJECT_SHUTDOWN)
        self.batcher.close()  # flushes pending groups through the engine
        if self._fleet is not None:
            # after the batcher: its shutdown flush enqueues final work,
            # which the replicas drain before their threads stop
            self._fleet.close()
        ledger = getattr(self.telemetry, "ledger", None)
        if ledger is not None:
            ledger.emit_summary(self.telemetry, phase="serve_close")
        # can-tpu-lint: disable=LOCKHELD(idempotent lifecycle flag; start/close run on the owner thread)
        self._started = False

    def __enter__(self) -> "CountService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the programmatic API --------------------------------------------
    def submit(self, image: np.ndarray, *,
               deadline_ms: Optional[float] = None,
               want_density: bool = False,
               stream_id: Optional[str] = None,
               frame_seq: Optional[int] = None,
               trace_id: Optional[str] = None) -> ServeTicket:
        """Enqueue one prepared image (see ``prepare_image``).  Returns a
        ticket whose ``result()`` either yields a ``ServeResult`` or raises
        ``RejectedError`` — immediate rejection (full queue, shedding,
        shutdown) still returns a ticket, with the rejection stored.

        ``stream_id`` opts the request into a per-stream session
        (serve/streams.py): sequence hygiene on ``frame_seq``, sticky
        replica routing, and the degradation ladder — under overload the
        frame may be answered from the stream's EWMA (``degraded: true``
        + staleness on the result) instead of launched or rejected.
        Without a stream_id the request takes the EXACT stateless path
        (pinned by test)."""
        if frame_seq is not None and stream_id is None:
            # same validation as the HTTP layer: silently dropping the
            # seq would leave a caller believing the sequence gate is
            # on while duplicates sail through
            raise ValueError("frame_seq needs a stream_id (the sequence "
                             "gate is per-stream)")
        deadline_s = (float(deadline_ms) / 1e3 if deadline_ms is not None
                      else self.default_deadline_s)
        req = ServeRequest(np.asarray(image), deadline_s=deadline_s,
                           want_density=want_density, clock=self._clock,
                           stream_id=stream_id, frame_seq=frame_seq)
        # the trace is born at the front door: every span of this
        # request's life (queue wait -> assembly -> device -> respond)
        # keys on this id, and HTTP clients get it back in the response.
        # A caller-provided id (the X-CanTpu-Trace-Id request header, or
        # an upstream service propagating its own) wins over minting —
        # that is what stitches one trace ACROSS hosts: every hop's
        # spans key on the same id, and the fleet collector's snapshot
        # exports them as one skew-corrected timeline
        req.trace_id = trace_id or f"{self._trace_prefix}-{req.id}"
        if req.shape[0] % self.engine.ds or req.shape[1] % self.engine.ds:
            raise ValueError(
                f"image shape {req.shape} is not snapped to the /"
                f"{self.engine.ds} density grid — call prepare_image first")
        bucket = self.batcher.bucket_of(req.shape)
        if bucket[0] < req.shape[0] or bucket[1] < req.shape[1]:
            # above the top ladder bound the snap goes DOWN, and the batch
            # assembly would raise — poisoning every co-batched request.
            # Reject the oversized image at the door instead (client error)
            raise ValueError(
                f"image {req.shape[0]}x{req.shape[1]} exceeds the largest "
                f"bucket {bucket[0]}x{bucket[1]} — resize it or serve with "
                f"a bigger bucket ladder")
        with self._lock:
            self._stats["submitted"] += 1
        if self._closed:
            req.reject(REJECT_SHUTDOWN, "service closed")
            self._count_reject(REJECT_SHUTDOWN)
            return ServeTicket(req, self)
        if stream_id is None:
            reason = self.queue.offer(req)
            if reason is not None:
                self._count_reject(reason)
            return ServeTicket(req, self)
        return self._submit_stream(req, bucket)

    def _submit_stream(self, req: ServeRequest,
                       bucket) -> ServeTicket:
        """The stream admission path: registry decision first (sequence
        gate + degradation ladder), then the queue — and a queue refusal
        degrades to the EWMA when one exists instead of rejecting (the
        "degrade instead of drown" rung the ladder's pricing may not
        have caught yet)."""
        now = self._clock()
        dec = self.streams.admit(req.stream_id, req.frame_seq, now,
                                 bucket)
        if dec.kind == "stale":
            req.reject(REJECT_STALE_FRAME, dec.detail)
            self._count_reject(REJECT_STALE_FRAME)
            return ServeTicket(req, self)
        if dec.kind == "overload":
            req.reject(REJECT_STREAM_OVERLOAD, dec.detail)
            self._count_reject(REJECT_STREAM_OVERLOAD)
            return ServeTicket(req, self)
        if dec.kind == "degrade":
            self._resolve_degraded(req, bucket, dec)
            return ServeTicket(req, self)
        self.streams.note_admitted(req)
        reason = self.queue.offer(req, reject=False)
        if reason is not None:
            fb = self.streams.degrade_fallback(req.stream_id, now)
            if fb is not None:
                self._resolve_degraded(req, bucket, fb,
                                       fallback=reason)
            else:
                # refused with nothing to degrade to: un-commit the
                # frame's sequence so the camera's RETRY of this
                # never-answered frame passes the gate instead of
                # bouncing off it as stale_frame forever
                self.streams.rollback_seq(req.stream_id, req.frame_seq,
                                          dec.prior_seq)
                req.reject(reason,
                           f"outstanding {self.queue.outstanding()}")
                self._count_reject(reason)
        return ServeTicket(req, self)

    def _resolve_degraded(self, req: ServeRequest, bucket, dec,
                          fallback: Optional[str] = None) -> None:
        """Answer a stream frame from its session EWMA — no queue, no
        batch, no launch: a degraded answer must be CHEAP.  Labelled
        ``degraded: true`` with staleness seconds on both the result
        and the ``serve.request`` event; deliberately kept OUT of the
        device-latency reservoir (an instant EWMA answer in the p99
        would make overload look like a latency win)."""
        now = self._clock()
        dens = None
        if req.want_density and dec.density is not None:
            h, w = req.shape
            d = dec.density
            if d.shape[:2] == (h // self.engine.ds, w // self.engine.ds):
                dens = d
        res = ServeResult(count=float(dec.count), density=dens,
                          bucket_hw=tuple(bucket), batch_fill=0.0,
                          latency_s=now - req.t_submit,
                          queue_wait_s=0.0, device_s=0.0,
                          trace_id=req.trace_id, degraded=True,
                          staleness_s=dec.staleness_s,
                          stream_id=req.stream_id)
        req.resolve(res)
        with self._lock:
            self._stats["completed"] += 1
            self._stats["degraded"] += 1
        payload = {"request_id": req.id,
                   "latency_s": round(res.latency_s, 6),
                   "bucket": list(bucket), "ok": True,
                   "trace_id": req.trace_id, "degraded": True,
                   "stream": req.stream_id}
        if dec.staleness_s is not None:
            payload["staleness_s"] = dec.staleness_s
        if fallback is not None:
            # the queue refused this frame (queue_full/backpressure);
            # the session EWMA absorbed it instead of a reject
            payload["fallback"] = fallback
        self.telemetry.emit("serve.request", **payload)

    def predict(self, image: np.ndarray, *,
                deadline_ms: Optional[float] = None,
                want_density: bool = False,
                timeout: Optional[float] = None,
                stream_id: Optional[str] = None,
                frame_seq: Optional[int] = None,
                trace_id: Optional[str] = None) -> ServeResult:
        """submit + result in one call (the closed-loop client pattern)."""
        return self.submit(image, deadline_ms=deadline_ms,
                           want_density=want_density, stream_id=stream_id,
                           frame_seq=frame_seq,
                           trace_id=trace_id).result(timeout)

    def stats(self) -> dict:
        with self._lock:
            s = dict(self._stats)
            lat = self.latency.percentiles()
            rep_counts = {k: dict(v) for k, v in self._replica_stats.items()}
        slots = max(s["batch_slots"], 1)
        out = {
            **s,
            "queue_depth": self.queue.depth(),
            "shedding": self.queue.shedding,
            "mean_batch_fill": round(s["batch_valid"] / slots, 4),
            "latency_p50_s": lat["p50_s"],
            "latency_p95_s": lat["p95_s"],
            "latency_max_s": lat["max_s"],
            "compile_count": self.engine.compile_count,
            # per-stream sessions (serve/streams.py): the operator's
            # view of the degradation ladder and sticky routing
            "streams": self.streams.stats(),
        }
        if self._fleet is not None:
            # per-replica rows: service-side work counters joined with the
            # fleet's health snapshot — obs/exporter.py renders these as
            # can_tpu_serve_*{replica="k"} labelled lines
            fh = self._fleet.healthz()
            health = {r["replica"]: r for r in fh["replicas"]}
            out["replicas"] = {
                str(k): {**rep_counts.get(k, {"batches": 0,
                                              "completed": 0}),
                         "quarantined": int(h["state"] != "active"),
                         "failures": h["failures"],
                         "generation": h["generation"]}
                for k, h in health.items()}
            out["live_replicas"] = fh["live"]
            out["generation"] = fh["generation"]
            # generation skew is an operator-visible fact, not something
            # to diff out of the per-replica rows by hand: a fleet
            # serving two checkpoints at once shows mixed_generations=1
            # on /stats and the scrape
            out["mixed_generations"] = bool(fh.get("mixed_generations"))
        return out

    def latency_percentile(self, q: float):
        """One request-latency percentile under the service lock (the
        reservoir is a deque the batcher thread appends to; an unlocked
        read can see it mutate mid-iteration) — the autoscaler's p99
        signal."""
        with self._lock:
            return self.latency.percentile(q)

    # -- batcher dispatch (runs on the batcher thread) -------------------
    def _dispatch(self, bucket_hw, batch, requests) -> None:
        if self._fleet is not None:
            # hand the assembled batch to whichever replica frees up
            # first; the worker thread calls _complete (or _fail_batch).
            # Stream batches carry their sticky pin (validated against
            # the LIVE replica set right here — a pin to a quarantined/
            # wedged/replaced incarnation is re-pinned before it can
            # queue behind a corpse)
            pin = None
            if (self.streams.active_count()
                    and hasattr(self._fleet, "live_tokens")):
                pin = self.streams.pin_for(requests,
                                           self._fleet.live_tokens())
            self._fleet.submit_work(bucket_hw, batch, requests, pin=pin)
            return
        t_exec0 = self._clock()
        t0 = time.perf_counter()
        counts, density = self.engine.predict_batch(
            batch, want_density=any(r.want_density for r in requests))
        # execute_s stays on perf_counter (honest wall time even under
        # the fake clocks the tests drive); the CLOCK stamps below anchor
        # the spans in the same timeline as t_submit/deadlines
        execute_s = time.perf_counter() - t0
        compiled = self.engine.last_batch_compiled
        self._complete(bucket_hw, batch, requests, counts, density,
                       execute_s, compiled, t_exec0=t_exec0)

    # -- batch completion (batcher thread, or a fleet replica worker) ----
    def _complete(self, bucket_hw, batch, requests, counts, density,
                  execute_s, compiled, replica=None,
                  program: str = "serve_predict", t_exec0=None) -> None:
        t_exec1 = self._clock()
        if t_exec0 is None:
            # fleet path: the worker measured execute_s on perf_counter;
            # anchor the device span by subtracting it on the service
            # clock (exact for the default monotonic clock, and merely a
            # display anchor under test fake clocks)
            t_exec0 = t_exec1 - execute_s
        fill = len(requests) / batch.image.shape[0]
        now = self._clock()
        spans = getattr(self.telemetry, "spans", None)
        # per-slot respond spans tile [t_exec1, ...] back to back: each
        # slot's span starts where the previous slot finished, so a late
        # slot's respond shows ITS OWN density fetch/resolve cost, not
        # the sum of every sibling processed before it in this loop
        t_resp0 = t_exec1
        for slot, req in enumerate(requests):
            h, w = req.shape
            dens = (np.asarray(density[slot, : h // self.engine.ds,
                                       : w // self.engine.ds])
                    if req.want_density else None)
            latency = now - req.t_submit
            # assembly stamps come from the batcher; a request dispatched
            # through a path that skipped them (flush_all on a hand-driven
            # batcher) degrades to a zero-width assembly window
            t_asm = req.t_assembly if req.t_assembly is not None else t_exec0
            t_ready = req.t_ready if req.t_ready is not None else t_exec0
            queue_wait = max(t_asm - req.t_submit, 0.0)
            if req.stream_id is not None:
                # fold the fresh count (and density, when fetched) into
                # the stream's session BEFORE resolving: a degraded
                # answer racing this completion serves the newest EWMA.
                # The serving replica becomes the stream's sticky pin
                # (first completion only; pins move via re-pin, not
                # work stealing).
                self.streams.note_completed(
                    req.stream_id, float(counts[slot]), dens, bucket_hw,
                    now=now, replica=replica,
                    token=None if replica is None else program)
            req.resolve(ServeResult(count=float(counts[slot]), density=dens,
                                    bucket_hw=tuple(bucket_hw),
                                    batch_fill=fill, latency_s=latency,
                                    queue_wait_s=round(queue_wait, 6),
                                    device_s=round(execute_s, 6),
                                    trace_id=req.trace_id,
                                    stream_id=req.stream_id))
            with self._lock:
                self.latency.record(latency, shape=tuple(bucket_hw))
            self.telemetry.emit("serve.request", request_id=req.id,
                               latency_s=round(latency, 6),
                               bucket=list(bucket_hw), ok=True,
                               trace_id=req.trace_id,
                               queue_wait_s=round(queue_wait, 6),
                               assembly_s=round(max(t_ready - t_asm, 0.0), 6),
                               device_s=round(execute_s, 6))
            if spans is not None:
                # the submit->respond tree the Chrome export renders: one
                # request-root with the four phases as children (device
                # start anchored on the service clock, width = the real
                # execute wall time)
                t_done = self._clock()
                root = spans.emit(trace_id=req.trace_id, name="request",
                                  start=req.t_submit, end=t_done,
                                  bucket=list(bucket_hw), ok=True)
                spans.emit(trace_id=req.trace_id, name="queue_wait",
                           start=req.t_submit, end=t_asm, parent_id=root)
                spans.emit(trace_id=req.trace_id, name="batch_assembly",
                           start=t_asm, end=t_ready, parent_id=root)
                spans.emit(trace_id=req.trace_id, name="device",
                           start=t_exec0, end=t_exec0 + execute_s,
                           parent_id=root, compiled=compiled)
                spans.emit(trace_id=req.trace_id, name="respond",
                           start=t_resp0, end=t_done, parent_id=root)
                t_resp0 = t_done
        with self._lock:
            self._stats["completed"] += len(requests)
            self._stats["batches"] += 1
            self._stats["batch_slots"] += batch.image.shape[0]
            self._stats["batch_valid"] += len(requests)
            if replica is not None:
                rs = self._replica_stats.setdefault(
                    replica, {"batches": 0, "completed": 0})
                rs["batches"] += 1
                rs["completed"] += len(requests)
        extra = {} if replica is None else {"replica": replica}
        # scheduler economics on every flush: dead slots, fill %, and the
        # core's predicted vs realized launch cost (pixel units, the
        # offline planner's).  predicted is recomputed INDEPENDENTLY from
        # the valid count (ServeSched.cover_one) — the batcher chose the
        # size through the same core, so any divergence is a scheduling
        # bug the can_tpu_sched_* gauges must surface, not noise.  The
        # legacy no-core service predicts its own contract: every launch
        # pads to max_batch.
        slots = batch.image.shape[0]
        # drain pricing for the stream degradation ladder: every
        # completed batch (stream or not) feeds the bucket's measured
        # seconds-per-slot, so the pricing is warm before the first
        # stream needs a skip decision
        self.streams.observe_batch(bucket_hw, execute_s, slots)
        area = float(bucket_hw[0] * bucket_hw[1])
        if self.sched is not None:
            predicted = self.sched.predicted_cost_px(area, len(requests))
            realized = self.sched.realized_cost_px(area, slots)
        else:
            predicted = area * self.max_batch
            realized = area * slots
        self.telemetry.emit("serve.batch", bucket=list(bucket_hw),
                           size=slots, valid=len(requests),
                           fill=round(fill, 4),
                           fill_pct=round(100.0 * fill, 2),
                           padded_slots=slots - len(requests),
                           predicted_cost_px=round(predicted, 1),
                           realized_cost_px=round(realized, 1),
                           execute_s=round(execute_s, 6),
                           compiled=compiled,
                           queue_depth=self.queue.depth(), **extra)
        ledger = getattr(self.telemetry, "ledger", None)
        if ledger is not None:
            if not compiled:
                # steady-state launch time for this program (first-call
                # compiles are the compile event's bill, same exclusion
                # rule as the step reservoirs); fleet batches bill their
                # replica's own program name
                ledger.observe(program, tuple(batch.image.shape),
                               execute_s, dtype=str(batch.image.dtype))
            # under _lock: fleet replica workers call _complete
            # concurrently, and an unlocked += here can lose counts or
            # double-emit the periodic summary (lint: LOCKHELD)
            with self._lock:
                self._perf_batches += 1
                due = 0 < self.perf_summary_every <= self._perf_batches
                if due:
                    self._perf_batches = 0
            if due:
                ledger.emit_summary(self.telemetry, phase="serve")

    def _note_reject(self, reason: str, count: int = 1) -> None:
        """Count a rejection that already emitted its own telemetry
        (batcher-side deadline/error paths) — stats() must agree with the
        RejectedErrors clients actually saw."""
        with self._lock:
            self._stats["rejected"] += count

    def _count_reject(self, reason: str) -> None:
        self._note_reject(reason)
        self.telemetry.emit("serve.reject", reason=reason, count=1,
                           queue_depth=self.queue.depth())

    def _fail_batch(self, requests, exc) -> None:
        """Fleet failure sink: a batch that failed on two replicas (or
        outlived every replica) rejects its requests with ``error`` —
        mirror of the batcher's poison-batch containment."""
        n = 0
        for r in requests:
            if not r.done:
                r.reject(REJECT_ERROR, f"{type(exc).__name__}: {exc}")
                n += 1
        if n:
            self._note_reject(REJECT_ERROR, n)
            self.telemetry.emit("serve.reject", reason=REJECT_ERROR,
                                count=n,
                                detail=f"{type(exc).__name__}: {exc}")

    # -- fleet health / rollout ------------------------------------------
    def healthz(self) -> dict:
        """Liveness + (for a fleet) per-replica state: the /healthz body.
        A fleet with zero live replicas reports ok=False — the probe that
        tells an orchestrator to restart or reroute."""
        if self._fleet is None:
            return {"ok": True}
        return self._fleet.healthz()

    def rollout(self, params, batch_stats=None, *, run_config=None,
                allow_config_change: bool = False) -> dict:
        """Blue/green checkpoint flip (fleet engines only): see
        ``FleetEngine.rollout``.  Single-engine services must restart —
        there is no second engine to stage on."""
        if self._fleet is None:
            raise RuntimeError("rollout needs a FleetEngine "
                               "(serve with --replicas >= 2 fleet mode)")
        return self._fleet.rollout(params, batch_stats,
                                   run_config=run_config,
                                   allow_config_change=allow_config_change)


# -- HTTP front end -----------------------------------------------------
def make_http_handler(service: CountService):
    """Request handler class bound to ``service``.

    POST /predict    body: .npy bytes (np.save of an HWC uint8/float32
                     image); query: ?deadline_ms=&density=1&raw=1
                     (raw=1 keeps uint8 pixels and normalises ON DEVICE —
                     the u8 transfer mode; needs the u8 programs warmed,
                     cli --u8-warmup); ?stream_id=cam1&frame_seq=17 opts
                     into a per-stream session (serve/streams.py):
                     sticky routing, sequence hygiene, and the
                     degradation ladder — a frame-skipped answer carries
                     "degraded": true + "staleness_s"
                     -> 200 {"count", "latency_ms", "bucket", "batch_fill"
                             [, "density"]}; stream requests add
                             {"degraded"[, "staleness_s"]}
                     -> 408/503 {"error", "reason"} on deadline/shedding;
                        409 on a stale/duplicate frame_seq; 413 when the
                        body exceeds --max-body-mb
    GET  /healthz    -> 200/503 {"ok", ...}; fleet services add per-
                     replica state (quarantine visible here), live count,
                     generation — 503 when zero replicas are live
    GET  /stats      -> 200 stats() JSON
    POST /rollout    (fleet only) body: JSON checkpoint source — the
                     same keys the CLI takes ({"checkpoint_dir", "epoch",
                     "params_npz", "torch_pth", "allow_config_change"}) —
                     loaded via ``service.rollout_loader`` (wired by
                     cli/serve.py), then blue/green-flipped.  Synchronous:
                     replies with the rollout report when the last replica
                     has flipped; live traffic keeps flowing meanwhile.
    """
    from http.server import BaseHTTPRequestHandler
    from urllib.parse import parse_qs, urlparse

    from can_tpu.serve.queue import (
        REJECT_BACKPRESSURE,
        REJECT_DEADLINE,
        REJECT_QUEUE_FULL,
    )

    status_of = {REJECT_DEADLINE: 408, REJECT_QUEUE_FULL: 503,
                 REJECT_BACKPRESSURE: 503, REJECT_SHUTDOWN: 503,
                 # a stale/duplicate stream frame is the client's
                 # ordering problem (409), not server load (503)
                 REJECT_STALE_FRAME: 409, REJECT_STREAM_OVERLOAD: 503}

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _send(self, code: int, payload: dict,
                  headers: Optional[dict] = None) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            for k, v in (headers or {}).items():
                self.send_header(k, v)
            self.end_headers()
            self.wfile.write(body)

        def _body_capped(self) -> Optional[int]:
            """Content-Length, or None after answering 413/400: a
            multi-GB POST must be refused BEFORE ``rfile.read``
            materialises it on the serve host (the DoS shape: one
            request, whole-host OOM).  A malformed or NEGATIVE header
            is a 400 — ``rfile.read(-1)`` would read until EOF, which
            on a keep-alive socket is never: the handler thread hangs,
            and a handful of such requests exhaust the thread pool
            (the same DoS through the guard's own gap).  The cap is
            named so the operator knows which knob moves it."""
            try:
                n = int(self.headers.get("Content-Length", "0"))
                if n < 0:
                    raise ValueError(f"negative Content-Length {n}")
            except ValueError as e:
                self._send(400, {"error": f"bad request: {e}"})
                return None
            if n > service.max_body_bytes:
                self._send(413, {
                    "error": f"request body {n} bytes exceeds the "
                             f"{service.max_body_bytes} byte cap "
                             f"(--max-body-mb="
                             f"{service.max_body_bytes / 2 ** 20:g})"})
                return None
            return n

        def log_message(self, fmt, *args):  # quiet: telemetry is the log
            pass

        def do_GET(self):
            path = urlparse(self.path).path
            if path == "/healthz":
                health = service.healthz()
                self._send(200 if health.get("ok") else 503, health)
            elif path == "/stats":
                self._send(200, service.stats())
            else:
                self._send(404, {"error": f"no such path: {path}"})

        def _do_rollout(self):
            # cap FIRST: an oversized body is refused regardless of
            # rollout wiring (the 413 is the DoS guard, not a feature
            # of the endpoint)
            n = self._body_capped()
            if n is None:
                return
            loader = getattr(service, "rollout_loader", None)
            if loader is None:
                self._send(501, {"error": "rollout is not wired on this "
                                          "server (no rollout_loader; "
                                          "fleet CLI serves wire it)"})
                return
            try:
                spec = json.loads(self.rfile.read(n) or b"{}")
                if not isinstance(spec, dict):
                    raise ValueError("rollout body must be a JSON object")
            except Exception as e:  # noqa: BLE001 — client error
                self._send(400, {"error": f"bad request: {e}"})
                return
            try:
                allow = bool(spec.pop("allow_config_change", False))
                params, batch_stats, run_config = loader(spec)
                report = service.rollout(params, batch_stats,
                                         run_config=run_config,
                                         allow_config_change=allow)
            except (ValueError, RuntimeError, FileNotFoundError) as e:
                # drift guard / structure guard / bad source: refused,
                # the serving fleet is untouched
                self._send(409, {"error": str(e)})
                return
            except Exception as e:  # noqa: BLE001 — corrupt .npz,
                # IsADirectoryError, ... must answer the client, never
                # drop the socket with a raw handler-thread traceback
                self._send(500, {"error": f"{type(e).__name__}: {e}"})
                return
            self._send(200, report)

        def do_POST(self):
            url = urlparse(self.path)
            if url.path == "/rollout":
                self._do_rollout()
                return
            if url.path != "/predict":
                self._send(404, {"error": f"no such path: {url.path}"})
                return
            n = self._body_capped()
            if n is None:
                return
            try:
                arr = np.load(io.BytesIO(self.rfile.read(n)),
                              allow_pickle=False)
                q = parse_qs(url.query)
                deadline_ms = (float(q["deadline_ms"][0])
                               if "deadline_ms" in q else None)
                want_density = q.get("density", ["0"])[0] not in ("0", "")
                raw = q.get("raw", ["0"])[0] not in ("0", "")
                stream_id = q.get("stream_id", [None])[0] or None
                frame_seq = (int(q["frame_seq"][0])
                             if "frame_seq" in q else None)
                # cross-host trace propagation: an upstream hop's id
                # rides in on this header, keys every span this host
                # emits, and is echoed back on the response — one
                # trace_id, one stitched timeline (tools/trace_export.py
                # over a collector snapshot)
                trace_in = self.headers.get("X-CanTpu-Trace-Id") or None
                if frame_seq is not None and stream_id is None:
                    raise ValueError("frame_seq needs a stream_id")
                if raw and arr.dtype != np.uint8:
                    raise ValueError("raw=1 needs uint8 pixels")
                if raw and np.dtype(np.uint8) not in service.warmed_dtypes:
                    # an unwarmed dtype would compile mid-traffic on the
                    # batcher thread, stalling every bucket — refuse at
                    # the door (serve with --u8-warmup to enable)
                    raise ValueError("raw=1 (uint8) programs are not "
                                     "warmed on this server; start it "
                                     "with --u8-warmup")
                image = prepare_image(arr, ds=service.engine.ds,
                                      normalize=not raw)
            except Exception as e:  # noqa: BLE001 — client error, not ours
                self._send(400, {"error": f"bad request: {e}"})
                return
            try:
                res = service.predict(image, deadline_ms=deadline_ms,
                                      want_density=want_density,
                                      stream_id=stream_id,
                                      frame_seq=frame_seq,
                                      trace_id=trace_in)
            except ValueError as e:  # submit-side validation: client error
                self._send(400, {"error": f"bad request: {e}"})
                return
            except RejectedError as e:
                self._send(status_of.get(e.reason, 503),
                           {"error": str(e), "reason": e.reason})
                return
            payload = {"count": res.count,
                       "latency_ms": round(res.latency_s * 1e3, 3),
                       "bucket": list(res.bucket_hw),
                       "batch_fill": res.batch_fill}
            if res.trace_id is not None:
                # the handle into the exported span timeline
                # (tools/trace_export.py --trace-id)
                payload["trace_id"] = res.trace_id
            if res.queue_wait_s is not None:
                payload["queue_wait_ms"] = round(res.queue_wait_s * 1e3, 3)
            if stream_id is not None:
                # stream answers are LABELLED: a client can always tell
                # a fresh inference from a served EWMA.  Non-stream
                # responses keep the exact pre-stream body (pinned)
                payload["degraded"] = bool(res.degraded)
                if res.staleness_s is not None:
                    payload["staleness_s"] = round(res.staleness_s, 6)
            if res.density is not None:
                payload["density"] = res.density[..., 0].tolist()
            self._send(200, payload,
                       headers=({"X-CanTpu-Trace-Id": res.trace_id}
                                if res.trace_id is not None else None))

    return Handler


def serve_http(service: CountService, *, host: str = "127.0.0.1",
               port: int = 8000):
    """Build a ``ThreadingHTTPServer`` for ``service`` (caller runs
    ``serve_forever()``; threads give one blocked client per connection
    while the single batcher thread owns the device)."""
    from http.server import ThreadingHTTPServer

    return ThreadingHTTPServer((host, port), make_http_handler(service))
