"""CountService: the serving front door (programmatic API + HTTP).

Wires the pieces into one lifecycle::

    client -> submit() -> BoundedRequestQueue -> MicroBatcher(thread)
                                                   -> ServeEngine.predict_batch
                                                   -> resolve ServeRequests

``submit()/result()`` is the primary API — tests and the bench drive the
full stack through it with zero networking.  The HTTP front end
(``serve_http``) is a thin stdlib adapter over the same calls: one process,
one device owner, many client connections.

Telemetry (same bus/schema as train/eval, summarised by
``tools/telemetry_report.py``):

* ``serve.request``  — per completed request: latency_s, bucket, ok
* ``serve.batch``    — per flush: bucket, size/valid/fill, execute_s,
                       queue_depth (the depth gauge rides the batch event:
                       sampled exactly when it changes, no extra thread)
* ``serve.reject``   — per rejection: reason (queue_full / backpressure /
                       deadline / shutdown / error)
* ``serve.warmup``   — pre-traffic compile pass summary
"""

from __future__ import annotations

import io
import json
import threading
import time
from typing import Optional, Sequence, Tuple

import numpy as np

from can_tpu.data.dataset import normalize_host
from can_tpu.serve.batcher import MicroBatcher
from can_tpu.serve.engine import ServeEngine
from can_tpu.serve.queue import (
    REJECT_SHUTDOWN,
    BoundedRequestQueue,
    RejectedError,
    ServeRequest,
    ServeResult,
)
from can_tpu.utils.profiling import StepTimer


def prepare_image(image: np.ndarray, *, ds: int = 8,
                  normalize: bool = True) -> np.ndarray:
    """Snap an arbitrary HWC image to the density grid, exactly as the
    offline ``CrowdDataset.__getitem__`` does: cv2 bilinear resize down to
    the nearest /ds multiple (half-pixel centers — bit-exact with the
    reference), then ImageNet-normalise (u8 input + normalize=False keeps
    bytes for the device-normalised transfer mode)."""
    if image.ndim != 3 or image.shape[2] != 3:
        raise ValueError(f"expected HWC RGB image, got shape {image.shape}")
    h, w = image.shape[:2]
    rows, cols = h // ds, w // ds
    if rows == 0 or cols == 0:
        raise ValueError(f"image {h}x{w} is smaller than one {ds}px "
                         f"density cell")
    if (rows * ds, cols * ds) != (h, w):
        import cv2

        image = cv2.resize(np.ascontiguousarray(image), (cols * ds, rows * ds))
    if normalize:
        image = normalize_host(np.asarray(image))
        if image.dtype != np.float32:
            raise ValueError("normalize=True needs uint8 or already "
                             f"normalised float32 pixels, got {image.dtype}")
    return image


class ServeTicket:
    """Handle returned by ``submit()``; ``result()`` blocks for the
    outcome (raising ``RejectedError`` on any rejection — never hangs:
    the wait is bounded by the request deadline plus a grace window for
    the in-flight batch)."""

    def __init__(self, request: ServeRequest, service: "CountService"):
        self._request = request
        self._service = service
        self.id = request.id

    @property
    def done(self) -> bool:
        return self._request.done

    def result(self, timeout: Optional[float] = None) -> ServeResult:
        if timeout is None:
            if self._request.deadline_ts is not None:
                # deadline + a grace window: an expired request is rejected
                # at the next batcher pump, and a dispatched one resolves
                # within the batch execute — either way well under this.
                # "now" comes from the SERVICE clock (deadline_ts does too;
                # mixing in time.monotonic breaks fake-clock tests)
                timeout = (self._request.deadline_ts
                           - self._service._clock()
                           + self._service.grace_s)
            else:
                timeout = self._service.default_result_timeout_s
        return self._request.wait(max(timeout, 0.0))


class CountService:
    """Owns the queue, the batcher thread, and the engine.

    bucket_ladder / pad_multiple: the bucket policy (same semantics as the
    offline batcher; pick the ladder from the deployment's expected shape
    distribution).  ``warmup()`` should be called before traffic.
    """

    def __init__(self, engine: ServeEngine, *, max_batch: int = 8,
                 max_wait_ms: float = 5.0, queue_capacity: int = 64,
                 high_water: Optional[int] = None,
                 default_deadline_ms: Optional[float] = None,
                 bucket_ladder=None, pad_multiple=None,
                 min_bucket_h: Optional[int] = None,
                 telemetry=None, clock=time.monotonic):
        self.engine = engine
        self.telemetry = telemetry if telemetry is not None else engine.telemetry
        self.max_batch = int(max_batch)
        self.default_deadline_s = (None if default_deadline_ms is None
                                   else float(default_deadline_ms) / 1e3)
        # result() safety margins (see ServeTicket)
        self.grace_s = max(1.0, 4 * float(max_wait_ms) / 1e3)
        self.default_result_timeout_s = 120.0
        self._clock = clock
        self.queue = BoundedRequestQueue(queue_capacity,
                                         high_water=high_water, clock=clock)
        self.batcher = MicroBatcher(self.queue, self._dispatch,
                                    max_batch=max_batch,
                                    max_wait_ms=max_wait_ms,
                                    bucket_ladder=bucket_ladder,
                                    pad_multiple=pad_multiple,
                                    min_bucket_h=min_bucket_h,
                                    ds=engine.ds, telemetry=self.telemetry,
                                    clock=clock,
                                    on_reject=self._note_reject)
        # request latency reservoir: p50/p95/max over recent requests,
        # tagged by bucket shape (skip_first=0 — warmup() already keeps
        # compiles off the request path, so every sample is steady-state).
        # Guarded by _lock: the batcher thread records while HTTP threads
        # read percentiles, and a deque mutated mid-iteration raises.
        self.latency = StepTimer(skip_first=0)
        self._lock = threading.Lock()
        self._stats = {"submitted": 0, "completed": 0, "rejected": 0,
                       "batches": 0, "batch_slots": 0, "batch_valid": 0}
        self._started = False
        self._closed = False
        # image dtypes warmup() has compiled — the HTTP raw=1 gate: an
        # unwarmed dtype would compile for seconds ON the batcher thread,
        # stalling every bucket's flushes mid-traffic
        self.warmed_dtypes: set = set()

    # -- lifecycle -------------------------------------------------------
    def warmup(self, bucket_shapes: Sequence[Tuple[int, int]],
               dtypes=(np.float32,)) -> dict:
        report = self.engine.warmup(bucket_shapes, self.max_batch,
                                    dtypes=dtypes)
        self.warmed_dtypes.update(np.dtype(dt) for dt in dtypes)
        return report

    def start(self) -> "CountService":
        if not self._started:
            self.batcher.start()
            self._started = True
        return self

    def close(self) -> None:
        """Stop admissions, drain in-flight work, reject the rest."""
        if self._closed:
            return
        self._closed = True
        for r in self.queue.close():
            r.reject(REJECT_SHUTDOWN, "service closing")
            self._count_reject(REJECT_SHUTDOWN)
        self.batcher.close()  # flushes pending groups through the engine
        self._started = False

    def __enter__(self) -> "CountService":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- the programmatic API --------------------------------------------
    def submit(self, image: np.ndarray, *,
               deadline_ms: Optional[float] = None,
               want_density: bool = False) -> ServeTicket:
        """Enqueue one prepared image (see ``prepare_image``).  Returns a
        ticket whose ``result()`` either yields a ``ServeResult`` or raises
        ``RejectedError`` — immediate rejection (full queue, shedding,
        shutdown) still returns a ticket, with the rejection stored."""
        deadline_s = (float(deadline_ms) / 1e3 if deadline_ms is not None
                      else self.default_deadline_s)
        req = ServeRequest(np.asarray(image), deadline_s=deadline_s,
                           want_density=want_density, clock=self._clock)
        if req.shape[0] % self.engine.ds or req.shape[1] % self.engine.ds:
            raise ValueError(
                f"image shape {req.shape} is not snapped to the /"
                f"{self.engine.ds} density grid — call prepare_image first")
        bucket = self.batcher.bucket_of(req.shape)
        if bucket[0] < req.shape[0] or bucket[1] < req.shape[1]:
            # above the top ladder bound the snap goes DOWN, and the batch
            # assembly would raise — poisoning every co-batched request.
            # Reject the oversized image at the door instead (client error)
            raise ValueError(
                f"image {req.shape[0]}x{req.shape[1]} exceeds the largest "
                f"bucket {bucket[0]}x{bucket[1]} — resize it or serve with "
                f"a bigger bucket ladder")
        with self._lock:
            self._stats["submitted"] += 1
        if self._closed:
            req.reject(REJECT_SHUTDOWN, "service closed")
            self._count_reject(REJECT_SHUTDOWN)
            return ServeTicket(req, self)
        reason = self.queue.offer(req)
        if reason is not None:
            self._count_reject(reason)
        return ServeTicket(req, self)

    def predict(self, image: np.ndarray, *,
                deadline_ms: Optional[float] = None,
                want_density: bool = False,
                timeout: Optional[float] = None) -> ServeResult:
        """submit + result in one call (the closed-loop client pattern)."""
        return self.submit(image, deadline_ms=deadline_ms,
                           want_density=want_density).result(timeout)

    def stats(self) -> dict:
        with self._lock:
            s = dict(self._stats)
            lat = self.latency.percentiles()
        slots = max(s["batch_slots"], 1)
        return {
            **s,
            "queue_depth": self.queue.depth(),
            "shedding": self.queue.shedding,
            "mean_batch_fill": round(s["batch_valid"] / slots, 4),
            "latency_p50_s": lat["p50_s"],
            "latency_p95_s": lat["p95_s"],
            "latency_max_s": lat["max_s"],
            "compile_count": self.engine.compile_count,
        }

    # -- batcher dispatch (runs on the batcher thread) -------------------
    def _dispatch(self, bucket_hw, batch, requests) -> None:
        t0 = time.perf_counter()
        counts, density = self.engine.predict_batch(
            batch, want_density=any(r.want_density for r in requests))
        execute_s = time.perf_counter() - t0
        fill = len(requests) / batch.image.shape[0]
        now = self._clock()
        for slot, req in enumerate(requests):
            h, w = req.shape
            dens = (np.asarray(density[slot, : h // self.engine.ds,
                                       : w // self.engine.ds])
                    if req.want_density else None)
            latency = now - req.t_submit
            req.resolve(ServeResult(count=float(counts[slot]), density=dens,
                                    bucket_hw=tuple(bucket_hw),
                                    batch_fill=fill, latency_s=latency))
            with self._lock:
                self.latency.record(latency, shape=tuple(bucket_hw))
            self.telemetry.emit("serve.request", request_id=req.id,
                               latency_s=round(latency, 6),
                               bucket=list(bucket_hw), ok=True)
        with self._lock:
            self._stats["completed"] += len(requests)
            self._stats["batches"] += 1
            self._stats["batch_slots"] += batch.image.shape[0]
            self._stats["batch_valid"] += len(requests)
        self.telemetry.emit("serve.batch", bucket=list(bucket_hw),
                           size=batch.image.shape[0], valid=len(requests),
                           fill=round(fill, 4),
                           execute_s=round(execute_s, 6),
                           compiled=self.engine.last_batch_compiled,
                           queue_depth=self.queue.depth())

    def _note_reject(self, reason: str, count: int = 1) -> None:
        """Count a rejection that already emitted its own telemetry
        (batcher-side deadline/error paths) — stats() must agree with the
        RejectedErrors clients actually saw."""
        with self._lock:
            self._stats["rejected"] += count

    def _count_reject(self, reason: str) -> None:
        self._note_reject(reason)
        self.telemetry.emit("serve.reject", reason=reason, count=1,
                           queue_depth=self.queue.depth())


# -- HTTP front end -----------------------------------------------------
def make_http_handler(service: CountService):
    """Request handler class bound to ``service``.

    POST /predict    body: .npy bytes (np.save of an HWC uint8/float32
                     image); query: ?deadline_ms=&density=1&raw=1
                     (raw=1 keeps uint8 pixels and normalises ON DEVICE —
                     the u8 transfer mode; needs the u8 programs warmed,
                     cli --u8-warmup)
                     -> 200 {"count", "latency_ms", "bucket", "batch_fill"
                             [, "density"]}
                     -> 408/503 {"error", "reason"} on deadline/shedding
    GET  /healthz    -> 200 {"ok": true}
    GET  /stats      -> 200 stats() JSON
    """
    from http.server import BaseHTTPRequestHandler
    from urllib.parse import parse_qs, urlparse

    from can_tpu.serve.queue import (
        REJECT_BACKPRESSURE,
        REJECT_DEADLINE,
        REJECT_QUEUE_FULL,
    )

    status_of = {REJECT_DEADLINE: 408, REJECT_QUEUE_FULL: 503,
                 REJECT_BACKPRESSURE: 503, REJECT_SHUTDOWN: 503}

    class Handler(BaseHTTPRequestHandler):
        protocol_version = "HTTP/1.1"

        def _send(self, code: int, payload: dict) -> None:
            body = json.dumps(payload).encode()
            self.send_response(code)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # quiet: telemetry is the log
            pass

        def do_GET(self):
            path = urlparse(self.path).path
            if path == "/healthz":
                self._send(200, {"ok": True})
            elif path == "/stats":
                self._send(200, service.stats())
            else:
                self._send(404, {"error": f"no such path: {path}"})

        def do_POST(self):
            url = urlparse(self.path)
            if url.path != "/predict":
                self._send(404, {"error": f"no such path: {url.path}"})
                return
            try:
                n = int(self.headers.get("Content-Length", "0"))
                arr = np.load(io.BytesIO(self.rfile.read(n)),
                              allow_pickle=False)
                q = parse_qs(url.query)
                deadline_ms = (float(q["deadline_ms"][0])
                               if "deadline_ms" in q else None)
                want_density = q.get("density", ["0"])[0] not in ("0", "")
                raw = q.get("raw", ["0"])[0] not in ("0", "")
                if raw and arr.dtype != np.uint8:
                    raise ValueError("raw=1 needs uint8 pixels")
                if raw and np.dtype(np.uint8) not in service.warmed_dtypes:
                    # an unwarmed dtype would compile mid-traffic on the
                    # batcher thread, stalling every bucket — refuse at
                    # the door (serve with --u8-warmup to enable)
                    raise ValueError("raw=1 (uint8) programs are not "
                                     "warmed on this server; start it "
                                     "with --u8-warmup")
                image = prepare_image(arr, ds=service.engine.ds,
                                      normalize=not raw)
            except Exception as e:  # noqa: BLE001 — client error, not ours
                self._send(400, {"error": f"bad request: {e}"})
                return
            try:
                res = service.predict(image, deadline_ms=deadline_ms,
                                      want_density=want_density)
            except ValueError as e:  # submit-side validation: client error
                self._send(400, {"error": f"bad request: {e}"})
                return
            except RejectedError as e:
                self._send(status_of.get(e.reason, 503),
                           {"error": str(e), "reason": e.reason})
                return
            payload = {"count": res.count,
                       "latency_ms": round(res.latency_s * 1e3, 3),
                       "bucket": list(res.bucket_hw),
                       "batch_fill": res.batch_fill}
            if res.density is not None:
                payload["density"] = res.density[..., 0].tolist()
            self._send(200, payload)

    return Handler


def serve_http(service: CountService, *, host: str = "127.0.0.1",
               port: int = 8000):
    """Build a ``ThreadingHTTPServer`` for ``service`` (caller runs
    ``serve_forever()``; threads give one blocked client per connection
    while the single batcher thread owns the device)."""
    from http.server import ThreadingHTTPServer

    return ThreadingHTTPServer((host, port), make_http_handler(service))
