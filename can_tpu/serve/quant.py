"""Quantized predict-program parameter storage for serving.

Serving is forward-only, so the training-grade f32 parameter tree is pure
cost: ~74 MB of HBM reads per predict launch that carry 4x (int8) or 2x
(bf16) more bytes than the arithmetic needs.  This module converts a
trained f32 tree into the storage format each ``--serve-dtype`` mode keeps
device-resident, and provides the in-program dequantization the engine's
jitted predict runs before ``cannet_apply``:

* ``f32``  — identity.  The bit-for-bit offline/online parity mode.
* ``bf16`` — every float leaf stored bf16, compute in bf16 (MXU rate),
  f32 accumulation per the TPU conv contract (ops/conv.py).  Counts move
  ~1e-3 relative vs f32.
* ``int8`` — post-training weight-only quantization: conv kernels and the
  context 1x1 matrices stored as int8 with PER-OUTPUT-CHANNEL f32 scales
  (symmetric, scale = max|w| over the input axes / 127 — per-channel
  because conv channels in this model span ~100x dynamic range, and one
  per-tensor scale would crush the quiet channels to zero).  Biases, BN
  affine/stats, and the final 1-channel output conv stay f32 (the output
  conv is 65 weights whose quantization error lands directly on the count;
  keeping it f32 is free).  Dequantization (``w_i8 * scale``) happens
  INSIDE the jitted predict, so HBM holds int8 and the f32 weights exist
  only as fused temporaries; all arithmetic then runs in f32 — "int8
  storage, f32 accumulation", the numerically conservative PTQ point.

Every mode keeps the same pytree STRUCTURE contract at the engine seam:
``quantize_tree`` returns a tree ``dequantize_tree`` restores to the exact
shapes/dtypes ``cannet_apply`` expects, so one predict body serves all
three modes and the jit signature differs only via the stored leaves.

The parity cost of each mode is measured, not assumed: ``parity_report``
runs the same images through a quantized engine and the f32 reference and
grades the worst count delta against ``PARITY_LADDER`` — the graded rung
is committed with every ``BENCH_SERVE_FLEET_*`` artifact.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

SERVE_DTYPES = ("f32", "bf16", "int8")

# Marker key pair of a quantized leaf: {"q": int8 (..., Cout), "scale":
# f32 (Cout,)}.  A dict is a quantized leaf iff its keys are exactly these.
_QKEYS = frozenset({"q", "scale"})

# The count-delta tolerance ladder parity_report grades against: worst
# relative count delta vs f32 <= bound -> that rung.  Rungs are ordered
# strictest first; "fail" means the mode moved counts more than any rung
# allows and must not ship.  Bounds chosen from the numerics, not wishes:
# bf16 weight rounding is ~2^-8 relative and the count is a large masked
# sum (errors partially cancel), int8 per-channel is ~2^-7 with the same
# cancellation, so each mode should land comfortably inside its rung and
# a regression (e.g. per-tensor scales sneaking in) trips the grade.
PARITY_LADDER = (
    ("exact", 0.0),
    ("tight", 1e-3),
    ("serve", 2e-2),
    ("loose", 1e-1),
)


def is_quantized_leaf(node) -> bool:
    return isinstance(node, dict) and frozenset(node.keys()) == _QKEYS


def quantize_int8(w) -> dict:
    """Symmetric per-output-channel int8: the last axis is Cout (HWIO
    kernels and (Cin, Cout) context matrices both put channels last).
    scale = max|w| over all input axes / 127; all-zero channels get
    scale 1 (q is zero anyway, and 0-scales would NaN the dequant)."""
    w = np.asarray(w, np.float32)
    red = tuple(range(w.ndim - 1))
    amax = np.max(np.abs(w), axis=red)
    scale = np.where(amax > 0, amax / 127.0, 1.0).astype(np.float32)
    q = np.clip(np.rint(w / scale), -127, 127).astype(np.int8)
    return {"q": q, "scale": scale}


def dequantize_int8(leaf, dtype=jnp.float32):
    return (leaf["q"].astype(dtype) * leaf["scale"].astype(dtype))


def _is_output_conv(path) -> bool:
    # the 1x1 output conv's 65 weights stay f32: its error lands directly
    # on the density map with nothing downstream to absorb it
    return len(path) > 0 and path[0] == "output"


def quantize_tree(params, serve_dtype: str):
    """f32 params tree -> the storage tree for ``serve_dtype``.

    f32: identity.  bf16: float leaves astype(bf16).  int8: weight
    tensors (ndim >= 2) quantized per-output-channel except the output
    conv; 1-D leaves (biases, BN affine) stay f32.
    """
    if serve_dtype not in SERVE_DTYPES:
        raise ValueError(f"serve_dtype must be one of {SERVE_DTYPES}, "
                         f"got {serve_dtype!r}")
    if serve_dtype == "f32":
        return params
    if serve_dtype == "bf16":
        return jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if jnp.issubdtype(jnp.asarray(x).dtype, jnp.floating) else x,
            params)

    def walk(node, path):
        if isinstance(node, dict):
            return {k: walk(v, path + (k,)) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return [walk(v, path + (i,)) for i, v in enumerate(node)]
        arr = np.asarray(node)
        if arr.ndim >= 2 and not _is_output_conv(path):
            return quantize_int8(arr)
        return jnp.asarray(arr, jnp.float32)

    return walk(params, ())


def dequantize_tree(qtree, serve_dtype: str):
    """Storage tree -> the f32/bf16 tree ``cannet_apply`` consumes.  Runs
    INSIDE the jitted predict: for int8 the multiply is fused with the
    consumer and HBM only ever holds the int8 bytes."""
    if serve_dtype in ("f32", "bf16"):
        return qtree

    def walk(node):
        if is_quantized_leaf(node):
            return dequantize_int8(node)
        if isinstance(node, dict):
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, (list, tuple)):
            return [walk(v) for v in node]
        return node

    return walk(qtree)


def compute_dtype_for(serve_dtype: str):
    """The ``cannet_apply`` compute dtype per mode: bf16 runs activations
    at MXU rate; f32 and int8 (dequantized to f32) keep f32 end-to-end —
    int8's accumulation is f32 by construction."""
    return jnp.bfloat16 if serve_dtype == "bf16" else None


def host_tree(tree):
    """Device arrays -> host copies, structure/shapes/dtypes preserved
    (int8 leaf dicts and bf16 leaves included, so ``tree_signature`` of
    the host copy equals the device tree's).  The fleet keeps the CURRENT
    generation's quantized tree host-side: resurrection and scale-up can
    stage params onto ANY device from it, without pinning a replicated
    copy in every device's HBM for the life of the process."""
    return jax.device_get(tree)


def param_bytes(tree) -> int:
    """Device-resident parameter bytes of a storage tree (the HBM the
    mode actually holds — the artifact's compression receipt)."""
    return sum(int(np.prod(x.shape)) * jnp.asarray(x).dtype.itemsize
               for x in jax.tree.leaves(tree))


def grade_parity(worst_rel: float) -> str:
    for name, bound in PARITY_LADDER:
        if worst_rel <= bound:
            return name
    return "fail"


def parity_report(engine_q, engine_ref, images: Sequence[np.ndarray], *,
                  max_batch: int = 1, ds: int = 8,
                  ladder=PARITY_LADDER) -> dict:
    """Run ``images`` (prepared HWC arrays) through both engines one item
    per batch; grade the worst relative count delta on ``ladder``.

    Relative to max(|ref count|, 1): crowd counts are naturally large, and
    a near-zero reference count would otherwise explode the ratio for an
    absolutely-tiny delta.
    """
    from can_tpu.data.batching import pad_batch

    deltas = []
    for img in images:
        h, w = img.shape[:2]
        dm = np.zeros((h // ds, w // ds, 1), np.float32)
        batch = pad_batch([(img, dm)], (h, w), max_batch, [True], ds)
        cq, _ = engine_q.predict_batch(batch)
        cr, _ = engine_ref.predict_batch(batch)
        ref = float(cr[0])
        deltas.append(abs(float(cq[0]) - ref) / max(abs(ref), 1.0))
    worst = max(deltas) if deltas else 0.0
    return {
        "images": len(deltas),
        "worst_rel_count_delta": round(worst, 8),
        "mean_rel_count_delta": round(float(np.mean(deltas)), 8)
        if deltas else 0.0,
        "ladder": [{"rung": n, "bound": b} for n, b in ladder],
        "grade": grade_parity(worst),
    }
