"""AOT predict-program bundles: load executables instead of compiling.

A replica's warmup bill is buckets x dtypes live trace+lower+compile
passes — seconds on CPU, MINUTES on a real chip.  That bill is fine once
at fleet startup; it is exactly wrong for the self-healing paths, where a
resurrected or scaled-up replica must reach ready in seconds while the
queue is deepening (ROADMAP item 2).  This module serializes the compiled
predict executables themselves (``jax.experimental.serialize_executable``,
the compiled-binary layer UNDER the persistent compilation cache) into a
bundle directory written beside the checkpoint at warmup time, so a new
replica's warmup becomes deserialise-and-load: zero new compiles, pinned
via the engine's ``compile_count``.

Bundle layout::

    <dir>/
        prog_d<device_id>_<B>x<H>x<W>x<C>_<dtype>.bin   one per program
        aot_manifest.json                               written LAST

Manifest-last is the prepared-store rule (DESIGN §9): a bake torn by a
crash leaves no manifest and reads as ABSENT, never as a half-bundle.

Compiled executables bake their device assignment in, so the bundle keys
programs by ``device_id`` and a bake covers an explicit device list — the
fleet bakes its whole autoscale range, not just the replicas currently
serving (a scale-up lands on a device that was idle at bake time).

Staleness is checked, never assumed (``AotBundle.check``): an executable
is only valid for the exact param-tree signature (structure, shapes,
dtypes — a rollout to a same-signature checkpoint keeps the bundle valid,
because params are jit ARGUMENTS), serve dtype, density grid, batch
geometry, platform/device kind, and jax version it was compiled under.
Any mismatch raises ``AotStaleError`` naming the axis; callers degrade to
live compiles (visible in ``compile_count``) or refuse, but never run a
stale program.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import time
from typing import Dict, List, Optional, Sequence, Tuple

AOT_VERSION = 1
MANIFEST_NAME = "aot_manifest.json"


class AotStaleError(RuntimeError):
    """The bundle does not match the world trying to load it; ``axis``
    names the mismatched invariant (signature, serve_dtype, ...)."""

    def __init__(self, axis: str, detail: str = ""):
        super().__init__(f"AOT bundle stale on {axis}"
                         + (f": {detail}" if detail else ""))
        self.axis = axis


def signature_sha(params, batch_stats=None) -> str:
    """Stable digest of the param tree's compiled-program view (the
    ``tree_signature`` structure+shape+dtype tuple): host and device
    copies of the same tree hash identically, so a bake from committed
    replica params and a load from the checkpoint's host tree agree."""
    from can_tpu.serve.engine import tree_signature

    sig = tree_signature((params, batch_stats))
    return hashlib.sha256(repr(sig).encode()).hexdigest()[:16]


def _program_filename(device_id: int, shape: Tuple[int, ...],
                      dtype: str) -> str:
    dims = "x".join(str(int(d)) for d in shape)
    return f"prog_d{device_id}_{dims}_{dtype}.bin"


def bake_aot_bundle(out_dir: str, *, engines: Sequence, bucket_shapes,
                    max_batch: int, dtypes, ds: int, serve_dtype: str,
                    sig_sha: str, generation: int = 0,
                    telemetry=None, batch_sizes=None) -> dict:
    """Serialize every (bucket, size, dtype) predict executable of every
    engine.

    ``engines``: ``ServeEngine``s, one per target device (their committed
    params pin the compiled device assignment).  ``batch_sizes`` is the
    scheduling core's launch-size menu (None = just ``max_batch``) — the
    menu RIDES the bake axes, so a loaded bundle covers every size the
    batcher may dispatch and a menu change invalidates the bundle
    instead of hiding live compiles.  Each program is lower+compiled
    fresh (``ServeEngine.compile_program`` — the cost-ledger precedent:
    a second compile on the already-slow bake path, deduped by the
    persistent compilation cache where armed) and serialized with its
    arg trees.  Returns the manifest."""
    import jax
    import numpy as np

    from can_tpu.data.batching import pad_batch

    from can_tpu.sched import normalize_sizes

    os.makedirs(out_dir, exist_ok=True)
    shapes = sorted(set(map(tuple, bucket_shapes)))
    sizes = normalize_sizes(max_batch, batch_sizes)
    programs: List[dict] = []
    t0 = time.perf_counter()
    platform = device_kind = None
    for engine in engines:
        dev = engine.device if engine.device is not None else jax.devices()[0]
        platform = dev.platform
        device_kind = dev.device_kind
        for bh, bw in shapes:
            for size in sizes:
                for dt in dtypes:
                    img = np.zeros((bh, bw, 3), dt)
                    dm = np.zeros((bh // ds, bw // ds, 1), np.float32)
                    batch = pad_batch([(img, dm)], (bh, bw), size,
                                      [False], ds)
                    payload, meta = engine.serialize_program(batch)
                    fname = _program_filename(dev.id, batch.image.shape,
                                              str(batch.image.dtype))
                    with open(os.path.join(out_dir, fname), "wb") as f:
                        f.write(payload)
                    programs.append({"device_id": int(dev.id),
                                     "shape": [int(d)
                                               for d in batch.image.shape],
                                     "dtype": str(batch.image.dtype),
                                     "file": fname,
                                     "bytes": len(payload), **meta})
    manifest = {
        "version": AOT_VERSION,
        "jax_version": jax.__version__,
        "platform": platform,
        "device_kind": device_kind,
        "serve_dtype": serve_dtype,
        "ds": int(ds),
        "max_batch": int(max_batch),
        "batch_sizes": [int(s) for s in sizes],
        "bucket_shapes": [list(s) for s in shapes],
        "image_dtypes": sorted(str(np.dtype(dt)) for dt in dtypes),
        "signature_sha": sig_sha,
        "generation": int(generation),
        "created_ts": time.time(),
        "bake_seconds": round(time.perf_counter() - t0, 3),
        "programs": programs,
    }
    # manifest LAST: a torn bake must read as absent, not as a half-bundle
    tmp = os.path.join(out_dir, MANIFEST_NAME + ".tmp")
    with open(tmp, "w") as f:
        json.dump(manifest, f, indent=1)
    os.replace(tmp, os.path.join(out_dir, MANIFEST_NAME))
    if telemetry is not None:
        telemetry.emit("serve.warmup", phase="aot_bake", path=out_dir,
                       programs=len(programs),
                       devices=len(set(p["device_id"] for p in programs)),
                       seconds=manifest["bake_seconds"])
    return manifest


class AotBundle:
    """A loaded (or loadable) bundle: manifest + lazily deserialized
    per-device program tables."""

    def __init__(self, path: str, manifest: dict):
        self.path = path
        self.manifest = manifest
        self._loaded: Dict[int, dict] = {}

    @classmethod
    def open(cls, path: str) -> "AotBundle":
        """Open a bundle directory; absent/torn (no manifest) or
        wrong-version bundles raise ``AotStaleError`` — never a silent
        pass."""
        mpath = os.path.join(path, MANIFEST_NAME)
        if not os.path.isfile(mpath):
            raise AotStaleError("manifest",
                                f"no {MANIFEST_NAME} in {path} (absent or "
                                f"torn bake)")
        try:
            with open(mpath) as f:
                manifest = json.load(f)
        except (json.JSONDecodeError, OSError) as e:
            raise AotStaleError("manifest", f"unreadable: {e}") from e
        if manifest.get("version") != AOT_VERSION:
            raise AotStaleError(
                "version", f"bundle v{manifest.get('version')} != "
                           f"loader v{AOT_VERSION}")
        return cls(path, manifest)

    def check(self, *, sig_sha: str, serve_dtype: str, ds: int,
              max_batch: Optional[int] = None,
              bucket_shapes=None, batch_sizes=None) -> None:
        """Raise ``AotStaleError`` unless the bundle matches the loading
        world on every axis an executable bakes in."""
        import jax

        m = self.manifest
        if m.get("jax_version") != jax.__version__:
            raise AotStaleError("jax_version",
                                f"baked under {m.get('jax_version')}, "
                                f"running {jax.__version__}")
        dev = jax.devices()[0]
        if m.get("platform") != dev.platform:
            raise AotStaleError("platform", f"baked for {m.get('platform')}"
                                            f", running {dev.platform}")
        if m.get("device_kind") != dev.device_kind:
            raise AotStaleError("device_kind",
                                f"baked for {m.get('device_kind')!r}, "
                                f"running {dev.device_kind!r}")
        if m.get("serve_dtype") != serve_dtype:
            raise AotStaleError("serve_dtype",
                                f"baked {m.get('serve_dtype')}, "
                                f"serving {serve_dtype}")
        if int(m.get("ds", -1)) != int(ds):
            raise AotStaleError("ds", f"baked /{m.get('ds')}, "
                                      f"serving /{ds}")
        if m.get("signature_sha") != sig_sha:
            raise AotStaleError(
                "signature",
                "the serving param tree differs in structure/shape/dtype "
                "from the baked one (different checkpoint variant?) — "
                "re-bake with --aot-bake")
        if max_batch is not None and int(m.get("max_batch", -1)) != \
                int(max_batch):
            raise AotStaleError("max_batch",
                                f"baked at {m.get('max_batch')}, "
                                f"serving at {max_batch}")
        if bucket_shapes is not None:
            baked = {tuple(s) for s in m.get("bucket_shapes", ())}
            want = set(map(tuple, bucket_shapes))
            missing = sorted(want - baked)
            if missing:
                raise AotStaleError("bucket_shapes",
                                    f"grid {missing} not in the bundle")
        if batch_sizes is not None:
            # the menu is a bake axis: a size the bundle never baked
            # would compile live on every recovery/scale path — exactly
            # what the bundle exists to prevent (pre-menu bundles baked
            # only max_batch and read as {max_batch})
            baked_sizes = {int(s) for s in
                           m.get("batch_sizes", (m.get("max_batch"),))
                           if s is not None}
            missing_sizes = sorted({int(s) for s in batch_sizes}
                                   - baked_sizes)
            if missing_sizes:
                raise AotStaleError(
                    "batch_sizes",
                    f"menu sizes {missing_sizes} not in the bundle "
                    f"(baked {sorted(baked_sizes)}) — the sub-batch menu "
                    f"changed since the bake; re-bake with --aot-bake")

    def device_ids(self) -> set:
        return {int(p["device_id"]) for p in self.manifest["programs"]}

    def programs_for(self, device) -> dict:
        """``{(image_shape, dtype_str): Compiled}`` for one device —
        empty when the bundle has no coverage for it (the caller falls
        back to live compiles, which stay visible in compile_count)."""
        did = int(device.id)
        cached = self._loaded.get(did)
        if cached is not None:
            return cached
        from jax.experimental import serialize_executable as se

        table: dict = {}
        for p in self.manifest["programs"]:
            if int(p["device_id"]) != did:
                continue
            with open(os.path.join(self.path, p["file"]), "rb") as f:
                ser, in_tree, out_tree = pickle.loads(f.read())
            table[(tuple(p["shape"]), str(p["dtype"]))] = \
                se.deserialize_and_load(ser, in_tree, out_tree)
        self._loaded[did] = table
        return table


def load_aot_bundle(path: str) -> AotBundle:
    return AotBundle.open(path)
