"""FleetEngine: N replica ServeEngines behind one work-stealing dispatcher.

The single ``ServeEngine`` serves one device; the ROADMAP's "millions of
users" target needs every device of the mesh serving, a way to ship a new
checkpoint without dropping traffic, and graceful degradation when a
replica dies.  This module is that fleet layer:

* **Placement** — params are quantized ONCE (``serve/quant.py``), pushed
  to every replica device in one batched transfer via a replicated
  ``NamedSharding`` over a 1-D ``("replica",)`` mesh (the SNIPPETS [2]
  ``get_replicated_sharding`` pattern), then committed per replica with a
  single-device ``device_put`` (free: the bytes are already resident).
  Each replica is a full ``ServeEngine`` pinned to its device — committed
  params make jit place that replica's programs on that device.

* **Work stealing** — one shared FIFO of assembled micro-batches; every
  idle replica thread pulls the next item.  No per-replica queues, no
  assignment policy, therefore no starvation: a replica is only ever idle
  when the queue is empty.  The MicroBatcher keeps its single assembly
  thread; ``CountService`` routes its dispatch here instead of executing
  inline, so assembly and N executions overlap.

* **Failure containment** — a replica whose predict raises is QUARANTINED
  (removed from dispatch, state exported on ``/healthz`` and as a
  ``fleet.replica`` event); its in-flight batch is re-dispatched exactly
  once to a healthy replica.  A batch that fails on a SECOND replica is
  rejected with ``error`` and that replica stays in service (poison
  input, not a dead replica — one bad batch must not take the whole
  fleet down).  When the last replica quarantines, queued work is
  failed instead of hanging.

* **Blue/green rollout** — ``rollout(params, ...)`` ships a new
  checkpoint with zero rejected or dropped requests: config drift guard
  (PR-3's ``check_resume_config`` on the serve-relevant keys), then a
  STAGING engine on the last replica's device warms every (bucket, dtype)
  program with the new weights while live traffic continues, then each
  replica is flipped one at a time under its dispatch lock via
  ``ServeEngine.swap_params`` — params are jit arguments, so a
  same-signature tree swap reuses every compiled program with zero
  recompilation, and at most one replica is briefly paused while the
  others keep pulling work.

* **Self healing** (ISSUE 13) — containment alone shrinks the fleet
  monotonically; this layer grows it back.  A quarantined replica
  RELEASES its device-resident params immediately (a dead replica costs
  zero HBM) and enters probation: after a backoff-with-jitter cooldown
  the maintenance thread re-stages params from the fleet's host-side
  copy of the CURRENT generation (quarantined replicas are skipped by
  rollout, so a naive re-admit would serve stale weights), probes one
  warm-bucket predict off-path, and on success the replica rejoins
  dispatch at the current generation (``fleet.probe`` /
  ``fleet.resurrect`` events).  Repeated probe failures escalate the
  backoff and page once per cooldown via the incident layer.  A HANG is
  caught by the watchdog: every launch carries a deadline priced from
  the cost ledger's measured per-program time x slack (a fixed default
  when no timing exists yet); an overdue replica is marked ``wedged``,
  its in-flight batch re-dispatched under the existing redispatch-once
  rule, and the replica sent to the same probation path — the stuck
  worker thread is abandoned, never waited on.  ``add_replica`` /
  ``remove_replica`` grow and drain the fleet with the same zero-drop
  choreography (``serve/autoscale.py`` drives them from the gauges), and
  an AOT bundle (``serve/aot.py``) makes every one of these paths load
  executables instead of compiling: seconds to ready, zero new compiles.
"""

from __future__ import annotations

import random
import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from can_tpu.obs import Telemetry
from can_tpu.serve.aot import bake_aot_bundle, load_aot_bundle, signature_sha
from can_tpu.serve.engine import ServeEngine, tree_signature
from can_tpu.serve.quant import host_tree, quantize_tree
from can_tpu.testing.faults import active_injector

REPLICA_ACTIVE = "active"
REPLICA_QUARANTINED = "quarantined"
REPLICA_WEDGED = "wedged"        # watchdog-declared hung launch
REPLICA_DRAINING = "draining"    # scale-down: finish in-flight, exit


class FleetClosedError(RuntimeError):
    """Work submitted after the fleet shut down."""


class ReplicaWedgedError(RuntimeError):
    """A launch blew through its priced watchdog deadline."""


def priced_deadline_s(ledger, name_prefix: str, shape, *,
                      slack: float, floor_s: float,
                      default_s: float, dtype=None) -> float:
    """Watchdog deadline for one launch: the cost ledger's measured
    mean execute time for this exact image (shape, dtype) — max over
    this fleet's replica programs, timing-reliable rows only — x
    ``slack``, floored at ``floor_s``.  Falls back to ``default_s``
    when no ledger is armed or no reliable timing exists yet (first
    batches after warmup, or a backend whose cost analysis never
    reported) — a fixed bound beats an unbounded hang, and the priced
    bound takes over as launches accumulate.  ``dtype`` matters: a u8
    batch is a DIFFERENT program than the same-shape f32 one, and
    pricing it off the f32 rows would set a deadline the u8 program
    never agreed to (rows with unknown dtype still match)."""
    if ledger is None:
        return default_s
    try:
        rows = [r for r in ledger.rows()
                if r["name"].startswith(name_prefix)
                and tuple(r["shape"]) == tuple(shape)
                and (dtype is None or r.get("dtype") in (dtype, "?"))
                and r["timing_reliable"] and r["mean_s"]]
    # can-tpu-lint: disable=SWALLOW(pricing must never kill dispatch; the fixed default is the degrade)
    except Exception:
        return default_s
    if not rows:
        return default_s
    return max(max(r["mean_s"] for r in rows) * slack, floor_s)


class _WorkItem:
    __slots__ = ("bucket_hw", "batch", "requests", "redispatches",
                 "t_enqueue", "seq", "cost_px", "min_deadline", "pin")

    def __init__(self, bucket_hw, batch, requests, *,
                 t_enqueue: float = 0.0, seq: int = 0,
                 pin: Optional[int] = None):
        self.bucket_hw = bucket_hw
        self.batch = batch
        self.requests = requests
        self.redispatches = 0
        # priced-dispatch facts (sched.pick_work): enqueue time + seq for
        # the age/tie rules, model cost (area * slots) for cheapest-first,
        # earliest live deadline for the urgency class
        self.t_enqueue = t_enqueue
        self.seq = seq
        self.cost_px = (float(bucket_hw[0] * bucket_hw[1])
                        * batch.image.shape[0])
        deadlines = [r.deadline_ts for r in requests
                     if r.deadline_ts is not None]
        self.min_deadline = min(deadlines) if deadlines else None
        # sticky stream routing (serve/streams.py): the replica index
        # this batch's streams prefer — a dispatch-ordering PREFERENCE
        # only, validated live by the service before enqueue, so a pin
        # to a dead replica never reaches the queue
        self.pin = pin


class ReplicaState:
    """One replica: engine + device + dispatch lock + health.

    ``inflight`` is ``(item, t_start, deadline_s)`` while the worker is
    inside a device execute (guarded by the fleet's ``_cond``): the
    watchdog's whole view of a possibly-hung launch.  ``probe_at`` /
    ``probe_failures`` / ``backoff_s`` drive probation after quarantine.
    Resurrection REPLACES the ReplicaState (same index, fresh engine +
    worker thread) rather than reviving it, so an abandoned worker
    holding the old object can never serve alongside the new one."""

    def __init__(self, index: int, device, engine: ServeEngine):
        self.index = index
        self.device = device
        self.engine = engine
        # held for the duration of each predict AND for a rollout flip —
        # swap_params never races an in-flight batch
        self.lock = threading.Lock()
        self.state = REPLICA_ACTIVE
        self.batches = 0
        self.failures = 0
        self.error: Optional[str] = None
        self.generation = 0
        self.inflight: Optional[Tuple] = None  # guarded by fleet._cond
        self.probe_at: Optional[float] = None
        self.probe_failures = 0
        self.backoff_s: Optional[float] = None
        self.thread: Optional[threading.Thread] = None
        # probation bookkeeping (guarded by fleet._cond): ``probing`` is
        # the start ts of an in-flight probe thread, ``probe_token``
        # invalidates a timed-out/superseded probe so its late result
        # can never swap in
        self.probing: Optional[float] = None
        self.probe_token = 0

    def snapshot(self) -> dict:
        return {"replica": self.index, "device": str(self.device),
                "state": self.state, "batches": self.batches,
                "failures": self.failures, "error": self.error,
                "generation": self.generation,
                "probe_failures": self.probe_failures}


def _replicate(tree, devices):
    """One batched host->devices transfer: every leaf fully replicated
    over a 1-D replica mesh (NamedSharding with an empty PartitionSpec)."""
    # can-tpu-lint: disable=HOSTSYNC(host list of device HANDLES, no device data moves)
    mesh = Mesh(np.asarray(devices), ("replica",))
    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.device_put(tree, sharding)


def _per_device(tree, device):
    """Commit a replicated tree to one device (the bytes are already
    there; this just re-keys the arrays to a single-device sharding)."""
    return jax.tree.map(lambda x: jax.device_put(x, device), tree)


class FleetEngine:
    """N device-pinned replica engines + the shared work queue.

    params / batch_stats: f32 trees (host or device).  serve_dtype picks
    the storage/compute mode for EVERY replica (serve/quant.py).
    replicas: engine count; devices (default ``jax.devices()``) supplies
    the distinct devices, one per replica.
    run_config: the checkpoint's saved run config (utils/checkpoint.py
    ``load_run_config``), kept for the rollout drift guard; None skips
    the config check on rollout (pre-guard checkpoints).
    """

    def __init__(self, params, batch_stats=None, *, replicas: int = 2,
                 serve_dtype: str = "f32", compute_dtype=None, ds: int = 8,
                 devices: Optional[Sequence] = None, telemetry=None,
                 run_config: Optional[dict] = None,
                 name: str = "serve_predict", aot_bundle=None,
                 self_heal: bool = True,
                 maintain_interval_s: float = 0.25,
                 probe_cooldown_s: float = 5.0,
                 probe_backoff_factor: float = 2.0,
                 probe_backoff_max_s: float = 120.0,
                 probe_jitter: float = 0.1,
                 page_after_probes: int = 3,
                 watchdog_slack: float = 10.0,
                 watchdog_floor_s: float = 1.0,
                 watchdog_default_s: float = 30.0,
                 dispatch_order: str = "priced",
                 starvation_age_s: float = 2.0,
                 deadline_pressure_s: float = 0.5):
        if dispatch_order not in ("priced", "fifo"):
            raise ValueError(f"unknown dispatch_order {dispatch_order!r} "
                             f"(priced | fifo)")
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        devices = list(devices if devices is not None else jax.devices())
        if replicas > len(devices):
            raise ValueError(
                f"replicas={replicas} exceeds the {len(devices)} available "
                f"devices — a replica without its own device just time-"
                f"slices another's, add chips or lower --replicas")
        self.ds = int(ds)
        self.serve_dtype = serve_dtype
        self._compute_dtype = compute_dtype
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.run_config = run_config
        self.name = name
        self.generation = 0
        # the scale universe: every device a replica may ever land on —
        # self.devices (below) is just the INITIAL placement
        self._devices_all = devices
        self.devices = devices[:replicas]
        # self-healing knobs (see DESIGN §18)
        self.self_heal = bool(self_heal)
        self.maintain_interval_s = float(maintain_interval_s)
        self.probe_cooldown_s = float(probe_cooldown_s)
        self.probe_backoff_factor = float(probe_backoff_factor)
        self.probe_backoff_max_s = float(probe_backoff_max_s)
        self.probe_jitter = float(probe_jitter)
        self.page_after_probes = int(page_after_probes)
        self.watchdog_slack = float(watchdog_slack)
        self.watchdog_floor_s = float(watchdog_floor_s)
        self.watchdog_default_s = float(watchdog_default_s)
        # probes run on their OWN daemon threads (a probe predict on a
        # still-sick device can hang exactly like the launch that
        # wedged it — it must never hold the maintenance thread or
        # _rollout_lock hostage); this bounds how long a probe may run
        # before it is declared failed and its thread abandoned
        self.probe_timeout_s = 600.0
        # deadline for a launch the engine has NOT built yet (no AOT
        # hit, unseen jit signature): a legitimate live trace+compile
        # is minutes on a real chip, and pricing it like a steady-state
        # launch would wedge a healthy replica on e.g. the first
        # unwarmed raw-u8 request — and cascade-quarantine the fleet
        self.watchdog_compile_s = 900.0
        # jitter is seeded per fleet: chaos tests reproduce bit-exactly
        self._rng = random.Random(0xC0FFEE)
        # shared-queue dispatch ordering (can_tpu/sched.pick_work):
        # "priced" = cheapest-feasible-first under deadline pressure with
        # the starvation age bound; "fifo" = the pre-r14 pure FIFO
        self.dispatch_order = dispatch_order
        self.starvation_age_s = float(starvation_age_s)
        self.deadline_pressure_s = float(deadline_pressure_s)
        self._work_seq = 0

        qparams = quantize_tree(params, serve_dtype)
        # the CURRENT generation's quantized tree, HOST-side: what
        # resurrection and scale-up stage from.  Host RAM (~21-83 MB per
        # mode), not a replicated device tree — a dead replica must cost
        # zero HBM, not "zero plus a pinned param copy".
        self._host_q = (host_tree(qparams),
                        None if batch_stats is None
                        else host_tree(batch_stats))
        self._sig_sha = signature_sha(*self._host_q)
        if isinstance(aot_bundle, str):
            aot_bundle = load_aot_bundle(aot_bundle)
        if aot_bundle is not None:
            aot_bundle.check(sig_sha=self._sig_sha,
                             serve_dtype=serve_dtype, ds=self.ds)
        self._aot = aot_bundle
        rep_params = _replicate(qparams, self.devices)
        rep_stats = (None if batch_stats is None
                     else _replicate(batch_stats, self.devices))
        self.replicas: List[ReplicaState] = []
        for k, dev in enumerate(self.devices):
            engine = ServeEngine(
                _per_device(rep_params, dev),
                None if rep_stats is None else _per_device(rep_stats, dev),
                serve_dtype=serve_dtype, compute_dtype=compute_dtype,
                ds=ds, device=dev, quantized=True, telemetry=self.telemetry,
                name=f"{name}_r{k}",
                aot_programs=(self._aot.programs_for(dev)
                              if self._aot is not None else None))
            self.replicas.append(ReplicaState(k, dev, engine))
        # per-slot incarnation counters: a resurrected replica's engine
        # gets a DISTINCT program name (f"{name}_r{k}i{n}"), so its
        # compile_count starts at zero and any live compile on the
        # recovery path is visible instead of hidden by the old registry
        self._incarnations = {k: 1 for k in range(replicas)}
        self._next_index = replicas

        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._swept = False  # close()'s leftover sweep has run
        self._started = False
        self._threads: List[threading.Thread] = []
        self._rollout_lock = threading.Lock()
        self._warmup_spec: Optional[Tuple] = None
        self._maint_thread: Optional[threading.Thread] = None
        self._maint_stop = threading.Event()
        self._probe_threads: List[threading.Thread] = []
        # serialises scale transitions against EACH OTHER only — device
        # work (a new replica's warmup, a drain join) must never hold
        # _rollout_lock, or a sick spare device would freeze probes,
        # rollout, and the rest of the healing layer with it
        self._scale_lock = threading.Lock()
        # bound by CountService: completion/failure sinks for executed work
        self._on_complete: Optional[Callable] = None
        self._on_fail: Optional[Callable] = None
        self._on_reject: Optional[Callable] = None
        # deadline checks must read the SAME clock that stamped
        # deadline_ts (the service's, injectable for fake-clock tests)
        self._clock = time.monotonic

    # -- service binding --------------------------------------------------
    def bind(self, *, on_complete: Callable, on_fail: Callable,
             on_reject: Optional[Callable] = None, clock=None) -> None:
        """``on_complete(bucket_hw, batch, requests, counts, density,
        execute_s, compiled, replica, program)`` after a successful batch;
        ``on_fail(requests, exc)`` after a twice-failed one;
        ``on_reject(reason, count)`` counts rejections the fleet already
        emitted telemetry for (zombie-batch shedding)."""
        # can-tpu-lint: disable=LOCKHELD(bind() happens-before start(): no worker thread exists yet)
        self._on_complete = on_complete
        # can-tpu-lint: disable=LOCKHELD(bind() happens-before start(): no worker thread exists yet)
        self._on_fail = on_fail
        # can-tpu-lint: disable=LOCKHELD(bind() happens-before start(): no worker thread exists yet)
        self._on_reject = on_reject
        if clock is not None:
            # can-tpu-lint: disable=LOCKHELD(bind() happens-before start(): no worker thread exists yet)
            self._clock = clock

    # -- engine-compatible surface ---------------------------------------
    @property
    def compile_count(self) -> int:
        """Distinct predict signatures across live+quarantined replicas
        (staging engines bill to their own per-generation registry)."""
        return sum(r.engine.compile_count for r in self.replicas)

    def live_replicas(self) -> int:
        return sum(1 for r in self.replicas if r.state == REPLICA_ACTIVE)

    def warmup(self, bucket_shapes, max_batch: int, *,
               dtypes=(np.float32,), sizes=None) -> dict:
        """Warm EVERY replica's full (bucket, size, dtype) program grid —
        the per-replica jit caches are independent, so each pays its own
        compiles here and none during traffic.  ``sizes`` is the
        scheduling core's launch-size menu (None = just ``max_batch``,
        pre-r14).  The spec is remembered: rollout's staging warmup,
        probation, scale-up, and the AOT bake all re-run exactly this
        grid."""
        from can_tpu.sched import normalize_sizes

        sizes = normalize_sizes(max_batch, sizes)
        # can-tpu-lint: disable=LOCKHELD(warmup precedes traffic; rollout reads this under _rollout_lock afterwards)
        self._warmup_spec = (sorted(set(map(tuple, bucket_shapes))),
                             int(max_batch), tuple(dtypes), sizes)
        if self._aot is not None:
            # the bundle must cover THIS grid at THIS batch geometry —
            # a silent partial hit would hide live compiles behind "AOT";
            # the menu is a first-class bake axis (a size the bundle
            # never baked would compile live on every recovery path)
            self._aot.check(sig_sha=self._sig_sha,
                            serve_dtype=self.serve_dtype, ds=self.ds,
                            max_batch=max_batch,
                            bucket_shapes=self._warmup_spec[0],
                            batch_sizes=sizes)
        t0 = time.perf_counter()
        shapes = compiles = 0
        for r in self.replicas:
            with r.lock:
                rep = r.engine.warmup(bucket_shapes, max_batch,
                                      dtypes=dtypes, sizes=sizes)
            shapes = rep["shapes"]
            compiles += rep["compiles"]
        return {"shapes": shapes, "sizes": len(sizes),
                "compiles": compiles,
                "replicas": len(self.replicas),
                "seconds": round(time.perf_counter() - t0, 3)}

    # -- lifecycle --------------------------------------------------------
    def _spawn_worker(self, replica: ReplicaState) -> None:
        t = threading.Thread(target=self._worker, args=(replica,),
                             daemon=True,
                             name=f"can-tpu-fleet-r{replica.index}")
        replica.thread = t
        with self._cond:
            self._threads.append(t)
        t.start()

    def start(self) -> "FleetEngine":
        if self._started:
            return self
        # can-tpu-lint: disable=LOCKHELD(idempotent lifecycle flag; start runs on the owner thread)
        self._started = True
        for r in self.replicas:
            self._spawn_worker(r)
        if self.self_heal and self._maint_thread is None:
            self._maint_stop.clear()
            t = threading.Thread(target=self._maintain_loop, daemon=True,
                                 name="can-tpu-fleet-maint")
            # can-tpu-lint: disable=LOCKHELD(start runs once on the owner thread before any maintenance exists)
            self._maint_thread = t
            t.start()
        return self

    def close(self, *, drain_timeout_s: float = 60.0) -> None:
        """Drain queued work through the replicas, then stop the threads.
        Anything still queued when no live replica remains (or the drain
        times out) is failed, never silently dropped."""
        # maintenance first: a probe mid-close would race the drain
        self._maint_stop.set()
        mt = self._maint_thread
        if mt is not None:
            mt.join(timeout=10.0)
            # can-tpu-lint: disable=LOCKHELD(close is idempotent-guarded below and runs on the owner thread)
            self._maint_thread = None
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        deadline = time.monotonic() + drain_timeout_s
        for t in self._threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))
        # can-tpu-lint: disable=LOCKHELD(only close() touches _threads after start, and close is idempotent-guarded above)
        self._threads = []
        leftovers = []
        with self._cond:
            self._swept = True
            while self._queue:
                leftovers.append(self._queue.popleft())
        for item in leftovers:
            self._fail(item, FleetClosedError("fleet closed with work "
                                              "still queued"))

    # -- dispatch ---------------------------------------------------------
    def live_tokens(self) -> dict:
        """``{replica index: incarnation token}`` of the ACTIVE set —
        what the stream registry validates pins against.  The token is
        the engine's program name: a resurrection REPLACES the engine
        under a fresh name, so a pin into an abandoned incarnation
        fails the token match even though the index came back."""
        with self._cond:
            return {r.index: r.engine.name for r in self.replicas
                    if r.state == REPLICA_ACTIVE}

    def submit_work(self, bucket_hw, batch, requests, *,
                    pin: Optional[int] = None) -> None:
        """Called by the service's dispatch (the batcher thread): enqueue
        one assembled micro-batch for whichever replica frees up first
        (``pin`` biases the priced pick toward that replica — stream
        locality — without ever reserving the item for it)."""
        with self._cond:
            item = _WorkItem(bucket_hw, batch, requests,
                             t_enqueue=self._clock(), seq=self._work_seq,
                             pin=pin)
            self._work_seq += 1
            if not self._closed and self.live_replicas() > 0:
                self._queue.append(item)
                self._cond.notify()
                return
            closed = self._closed
        self._fail(item, FleetClosedError(
            "fleet closed" if closed else "no live replicas"))

    def _pop_next_locked(self, replica: Optional[ReplicaState] = None
                         ) -> _WorkItem:
        """Next work item under ``_cond``: the scheduling core's priced
        order (urgent deadline-pressured work EDF-first, the rest
        cheapest-first, age-promoted against starvation, stream pins as
        an affinity preference for the pulling replica) — or plain FIFO
        when configured.  A redispatched batch sits at the queue FRONT
        and is also urgent-class, so both orders serve it first."""
        if self.dispatch_order == "fifo" or len(self._queue) == 1:
            return self._queue.popleft()
        from can_tpu.sched import pick_work

        i = pick_work(self._queue, self._clock(),
                      starvation_age_s=self.starvation_age_s,
                      pressure_s=self.deadline_pressure_s,
                      prefer=None if replica is None else replica.index)
        item = self._queue[i]
        del self._queue[i]
        return item

    def _take(self, replica: ReplicaState) -> Optional[_WorkItem]:
        with self._cond:
            while True:
                if replica.state != REPLICA_ACTIVE:
                    return None
                if self._queue:
                    return self._pop_next_locked(replica)
                if self._closed:
                    return None
                self._cond.wait(0.1)

    def _worker(self, replica: ReplicaState) -> None:
        while True:
            item = self._take(replica)
            if item is None:
                return
            # zombie-batch shed: a batch whose EVERY request has already
            # expired (deadline passed while it sat behind the work
            # queue) would burn a full device launch producing results
            # nobody is waiting for — reject instead of execute.  A batch
            # with ANY live request still runs whole: slots are padded,
            # and the live results are the point.
            now = self._clock()
            if all(r.done or r.expired(now) for r in item.requests):
                from can_tpu.serve.queue import REJECT_DEADLINE

                n = 0
                for r in item.requests:
                    if not r.done:
                        r.reject(REJECT_DEADLINE,
                                 "expired behind the fleet work queue")
                        n += 1
                if n:
                    self.telemetry.emit("serve.reject",
                                        reason=REJECT_DEADLINE, count=n)
                    if self._on_reject is not None:
                        self._on_reject(REJECT_DEADLINE, n)
                continue
            # register the launch for the watchdog BEFORE entering the
            # execute: (item, start, priced deadline) under _cond is the
            # watchdog's whole view of this replica
            with self._cond:
                replica.inflight = (item, self._clock(),
                                    self._deadline_for(item, replica))
            t0 = time.perf_counter()
            try:
                with replica.lock:
                    inj = active_injector()
                    if inj is not None:
                        # serve chaos hooks (testing/faults.py):
                        # replica_crash raises into the quarantine path,
                        # replica_hang sleeps into the watchdog's arms —
                        # both exactly as a real device fault would
                        inj.on_serve_batch(replica=replica.index,
                                           batch_index=replica.batches + 1)
                    want = any(r.want_density for r in item.requests)
                    counts, density = replica.engine.predict_batch(
                        item.batch, want_density=want)
                    compiled = replica.engine.last_batch_compiled
                    replica.batches += 1
            except Exception as e:  # noqa: BLE001 — replica failure path
                if self._finish_inflight(replica, item):
                    self._quarantine(replica, item, e)
                # else: the watchdog already wedged us and re-dispatched
                # the batch — nothing left to attribute
                continue
            execute_s = time.perf_counter() - t0
            if not self._finish_inflight(replica, item):
                # wedged mid-execute: the watchdog stole the batch (it
                # may already be resolved on a healthy replica) — discard
                # our late results; the next _take sees the wedged state
                # and retires this thread
                continue
            if self._on_complete is not None:
                self._on_complete(item.bucket_hw, item.batch, item.requests,
                                  counts, density, execute_s, compiled,
                                  replica.index, replica.engine.name)

    def _finish_inflight(self, replica: ReplicaState, item: _WorkItem
                         ) -> bool:
        """Clear the replica's in-flight slot iff it still owns ``item``;
        False means the watchdog stole it (exactly one of the worker and
        the watchdog wins — both mutate under ``_cond``)."""
        with self._cond:
            mine = (replica.inflight is not None
                    and replica.inflight[0] is item)
            if mine:
                replica.inflight = None
            return mine

    def _deadline_for(self, item: _WorkItem,
                      replica: ReplicaState) -> float:
        try:
            warm = replica.engine.is_warm(item.batch)
        # can-tpu-lint: disable=SWALLOW(pricing must never kill dispatch; assume warm = the tighter bound)
        except Exception:
            warm = True
        if not warm:
            # a legitimate first-compile launch: give it the compile
            # allowance, not the steady-state deadline
            return max(self.watchdog_compile_s, self.watchdog_default_s)
        ledger = getattr(self.telemetry, "ledger", None)
        return priced_deadline_s(ledger, self.name,
                                 item.batch.image.shape,
                                 dtype=str(item.batch.image.dtype),
                                 slack=self.watchdog_slack,
                                 floor_s=self.watchdog_floor_s,
                                 default_s=self.watchdog_default_s)

    def _quarantine(self, replica: ReplicaState, item: _WorkItem,
                    exc: Exception) -> None:
        replica.failures += 1
        item.redispatches += 1
        if item.redispatches > 1:
            # failed on a SECOND distinct replica (the first was
            # quarantined before the re-dispatch): the batch is the
            # poison, not the fleet — reject it and keep this replica
            # serving.  One bad input must not cascade into
            # quarantining every replica it touches.
            self.telemetry.emit("fleet.replica", **replica.snapshot())
            self._fail(item, exc)
            return
        replica.state = REPLICA_QUARANTINED
        replica.error = f"{type(exc).__name__}: {exc}"
        # the HBM leak fix (ISSUE 13 satellite): a dead replica's params
        # leave the device NOW, not at process exit — probation re-stages
        # from the fleet's host-side current-generation copy
        replica.engine.release_buffers()
        self._schedule_probe(replica, self._clock())
        self.telemetry.emit("fleet.replica", **replica.snapshot())
        self._requeue_or_fail(item, exc)

    def _requeue_or_fail(self, item: _WorkItem, exc: Exception) -> None:
        """The redispatch choreography shared by quarantine and the
        watchdog: requeue to the FRONT while any live worker can drain
        it; fail it (and, after the last replica, everything queued)
        otherwise."""
        stranded = [item]
        with self._cond:
            if self.live_replicas() > 0 and not self._swept:
                # front of the queue: its requests have waited longest.
                # Deliberately ALSO while close() drains: the remaining
                # live workers still pull, and anything they don't reach
                # is failed by close()'s leftover sweep — rejecting here
                # would drop a request a live replica would have served.
                # (_swept guards the post-sweep stragglers of a timed-out
                # drain, the one window where a requeue could strand.)
                self._queue.appendleft(item)
                self._cond.notify()
                return
            if self.live_replicas() == 0:
                # the LAST live replica just died: no worker remains to
                # drain the queue, so everything queued is failed too —
                # never stranded behind a fleet with no executors
                while self._queue:
                    stranded.append(self._queue.popleft())
        for it in stranded:
            self._fail(it, exc)

    def _fail(self, item: _WorkItem, exc: Exception) -> None:
        if self._on_fail is not None:
            self._on_fail(item.requests, exc)
        else:  # unbound fleet (direct tests): reject inline
            from can_tpu.serve.queue import REJECT_ERROR

            for r in item.requests:
                if not r.done:
                    r.reject(REJECT_ERROR, f"{type(exc).__name__}: {exc}")

    # -- self healing: watchdog + probation + resurrection ----------------
    def _maintain_loop(self) -> None:
        from can_tpu.obs import supervised_loop

        supervised_loop(self._maint_stop, self.maintain_interval_s,
                        self.maintenance_tick, "fleet-maintenance")

    def maintenance_tick(self, now: Optional[float] = None) -> None:
        """One supervision pass: wedge overdue launches, probe replicas
        whose cooldown has elapsed.  Runs on the maintenance thread in
        production; tests drive it directly with a fake clock."""
        now = self._clock() if now is None else now
        self._watchdog_sweep(now)
        self._probe_sweep(now)

    def _watchdog_sweep(self, now: float) -> None:
        wedged = []
        with self._cond:
            for r in list(self.replicas):
                # DRAINING replicas are covered too: a launch that hangs
                # during scale-down would otherwise strand its batch
                # behind remove_replica's bounded join — the zero-drop
                # contract holds through every transition
                if (r.state not in (REPLICA_ACTIVE, REPLICA_DRAINING)
                        or r.inflight is None):
                    continue
                item, t0, deadline = r.inflight
                if now - t0 <= deadline:
                    continue
                # overdue: the worker thread is hostage inside a device
                # execute — mark the replica wedged (it leaves dispatch
                # the moment its thread next looks), steal the batch,
                # and send the replica to probation.  The thread is
                # abandoned, never joined: if the execute ever returns,
                # _finish_inflight tells it the batch is no longer its.
                was_draining = r.state == REPLICA_DRAINING
                r.state = REPLICA_WEDGED
                r.failures += 1
                r.error = (f"watchdog: launch exceeded its "
                           f"{deadline:.3f}s priced deadline")
                r.inflight = None
                wedged.append((r, item, was_draining))
        for r, item, was_draining in wedged:
            # drop the engine's own param refs NOW (same zero-HBM rule
            # as quarantine): the stuck execute's runtime references
            # keep its working set pinned until it returns, but the
            # Python-side tree must not ALSO pin a copy forever — and
            # once the execute unwinds, the bytes free immediately
            r.engine.release_buffers()
            self.telemetry.emit("fleet.replica", **r.snapshot())
            exc = ReplicaWedgedError(r.error)
            item.redispatches += 1
            if item.redispatches > 1:
                # second strike (wedged two replicas, or wedged after a
                # quarantine redispatch): the batch is the poison
                self._fail(item, exc)
            else:
                self._requeue_or_fail(item, exc)
            if not was_draining:
                # a draining victim is leaving anyway: remove_replica
                # owns its teardown — probing it would race a
                # resurrection against the removal
                self._schedule_probe(r, now)

    def _schedule_probe(self, replica: ReplicaState, now: float, *,
                        escalate: bool = False) -> None:
        """Backoff-with-jitter probation: a fresh quarantine starts at
        ``probe_cooldown_s``; each failed probe multiplies by
        ``probe_backoff_factor`` up to ``probe_backoff_max_s``.  Jitter
        (seeded) keeps a fleet of replicas from probing in lockstep."""
        if replica.backoff_s is None or not escalate:
            replica.backoff_s = self.probe_cooldown_s
        else:
            replica.backoff_s = min(
                replica.backoff_s * self.probe_backoff_factor,
                self.probe_backoff_max_s)
        jitter = 1.0 + self.probe_jitter * (2.0 * self._rng.random() - 1.0)
        replica.probe_at = now + replica.backoff_s * jitter

    def _probe_sweep(self, now: float) -> None:
        """Launch due probes on their OWN daemon threads and fail probes
        that blew ``probe_timeout_s``.  The maintenance thread never
        blocks on device work: a probe predict on a still-sick device
        can hang exactly like the launch that wedged it, and a hung
        probe must cost one abandoned thread — not the watchdog, the
        other probes, rollout, and the autoscaler."""
        if self._warmup_spec is None or self._closed:
            return
        due, timed_out = [], []
        with self._cond:
            for r in self.replicas:
                if r.state not in (REPLICA_QUARANTINED, REPLICA_WEDGED):
                    continue
                if r.probing is not None:
                    if now - r.probing > self.probe_timeout_s:
                        r.probe_token += 1  # a late result cannot swap in
                        r.probing = None
                        r.probe_failures += 1
                        timed_out.append(r)
                    continue
                if r.probe_at is not None and now >= r.probe_at:
                    r.probing = now
                    r.probe_token += 1
                    due.append((r, r.probe_token))
        for r in timed_out:
            err = f"probe timed out after {self.probe_timeout_s:g}s"
            self._schedule_probe(r, now, escalate=True)
            self.telemetry.emit("fleet.probe", replica=r.index, ok=False,
                                probe_failures=r.probe_failures,
                                error=err,
                                next_backoff_s=round(r.backoff_s, 3))
            self._maybe_page(r, err)
        for r, token in due:
            t = threading.Thread(target=self._probe_worker,
                                 args=(r, token), daemon=True,
                                 name=f"can-tpu-fleet-probe-r{r.index}")
            with self._cond:
                self._probe_threads.append(t)
            t.start()

    def join_probes(self, timeout_s: float = 60.0) -> None:
        """Wait (bounded) for in-flight probe threads — the seam
        deterministic tests drive after a ``maintenance_tick``; a hung
        probe makes this return at the timeout, never blocks forever."""
        deadline = time.monotonic() + timeout_s
        with self._cond:
            threads = list(self._probe_threads)
        for t in threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.0))
        with self._cond:
            self._probe_threads = [t for t in self._probe_threads
                                   if t.is_alive()]

    def _maybe_page(self, replica: ReplicaState, error: str) -> None:
        if replica.probe_failures < self.page_after_probes:
            return
        inc = getattr(self.telemetry, "incidents", None)
        if inc is not None:
            # the incident manager's per-reason cooldown makes this page
            # exactly once per cooldown, however often the probe fails
            inc.trigger("fleet_probe_failed",
                        detail={"replica": replica.index,
                                "probe_failures": replica.probe_failures,
                                "error": error})

    def _build_replica_engine(self, index: int, device) -> ServeEngine:
        """A fresh engine at the CURRENT generation, staged from the
        host-side quantized tree, with the AOT table for its device when
        a bundle is loaded.  Each incarnation gets a distinct program
        name so its compile_count starts at zero — a recovery-path
        compile is visible, never absorbed by the old registry."""
        with self._cond:
            qparams, qstats = self._host_q
            n = self._incarnations.get(index, 0)
            self._incarnations[index] = n + 1
        name = (f"{self.name}_r{index}" if n == 0
                else f"{self.name}_r{index}i{n}")
        return ServeEngine(
            qparams, qstats, serve_dtype=self.serve_dtype,
            compute_dtype=self._compute_dtype, ds=self.ds, device=device,
            quantized=True, telemetry=self.telemetry, name=name,
            aot_programs=(self._aot.programs_for(device)
                          if self._aot is not None else None))

    def _probe_worker(self, replica: ReplicaState, token: int) -> None:
        """One probation attempt, on its own thread: stage current-
        generation params on the replica's device, run one warm-bucket
        predict OFF-PATH, warm the full grid, then swap a fresh
        ReplicaState into dispatch.  Device work happens WITHOUT
        ``_rollout_lock``; the swap-in re-checks the generation under it
        (a rollout that landed mid-probe makes the staged weights stale
        — re-probe promptly rather than serve them)."""
        gen = self.generation
        shapes, max_batch, dtypes, sizes = self._warmup_spec
        t0 = time.perf_counter()
        try:
            engine = self._build_replica_engine(replica.index,
                                                replica.device)
            # the probe proper: ONE warm-bucket predict, off-path — a
            # sick device/params fails here, not on live traffic
            from can_tpu.data.batching import pad_batch

            bh, bw = min(shapes)
            img = np.zeros((bh, bw, 3), dtypes[0])
            dm = np.zeros((bh // self.ds, bw // self.ds, 1), np.float32)
            engine.predict_batch(pad_batch([(img, dm)], (bh, bw),
                                           max_batch, [False], self.ds))
            rep = engine.warmup(shapes, max_batch, dtypes=dtypes,
                                sizes=sizes)
        except Exception as e:  # noqa: BLE001 — probe failure is data
            with self._cond:
                if replica.probe_token != token:
                    return  # timed out / superseded: stale thread
                started = replica.probing
                replica.probing = None
                replica.probe_failures += 1
            # backoff from the probe's START (the clock that scheduled
            # it): deterministic under fake clocks, and a slow-failing
            # probe doesn't stretch its own cooldown
            now = started if started is not None else self._clock()
            self._schedule_probe(replica, now, escalate=True)
            self.telemetry.emit(
                "fleet.probe", replica=replica.index, ok=False,
                probe_failures=replica.probe_failures,
                error=f"{type(e).__name__}: {e}",
                next_backoff_s=round(replica.backoff_s, 3))
            self._maybe_page(replica, f"{type(e).__name__}: {e}")
            return
        with self._rollout_lock:
            if self._closed:
                return
            if self.generation != gen:
                # rolled forward mid-probe: discard the stale staging
                # and re-probe promptly at the new generation
                with self._cond:
                    if replica.probe_token == token:
                        replica.probing = None
                        replica.probe_at = self._clock()
                return
            fresh = ReplicaState(replica.index, replica.device, engine)
            fresh.generation = gen
            fresh.failures = replica.failures  # lifetime count survives
            with self._cond:
                if (replica.probe_token != token
                        or replica not in self.replicas):
                    return  # superseded or retired while we probed
                replica.probing = None
                self.replicas[self.replicas.index(replica)] = fresh
                self._cond.notify_all()
            # the old ReplicaState (and any abandoned wedged thread
            # holding it) is now unreachable from dispatch: its _take
            # sees a non-active state and retires
            if self._started:
                self._spawn_worker(fresh)
            self.telemetry.emit("fleet.probe", replica=replica.index,
                                ok=True,
                                probe_failures=replica.probe_failures)
            self.telemetry.emit(
                "fleet.resurrect", replica=fresh.index, generation=gen,
                live=self.live_replicas(),
                seconds=round(time.perf_counter() - t0, 3),
                warmup_compiles=rep["compiles"],
                aot_hits=engine.aot_hits,
                probe_failures_before=replica.probe_failures)
            self.telemetry.emit("fleet.replica", **fresh.snapshot())

    # -- autoscaling surface ----------------------------------------------
    def spare_devices(self) -> list:
        """Devices of the scale universe not currently owned by any
        replica (quarantined replicas keep their device: probation will
        reuse it)."""
        with self._cond:
            used = {r.device for r in self.replicas}
        return [d for d in self._devices_all if d not in used]

    def add_replica(self, *, reason: str = "manual") -> dict:
        """Grow the fleet by one replica on a spare device, at the
        current generation, warmed before it joins dispatch — zero-drop
        by construction (the shared queue never assigned it work until
        its worker starts pulling).  Returns the scale report (also
        emitted as ``fleet.scale``, with ``time_to_first_ready_s`` the
        bench tier records).

        The staging warmup — device work that can hang on a sick spare
        device — runs under ``_scale_lock`` only: probes, rollout, and
        the watchdog stay live.  ``_rollout_lock`` is taken briefly for
        the registration, re-checking the generation: a rollout that
        landed mid-warmup makes the staged weights stale, and the call
        raises for the autoscaler to retry rather than admit them."""
        if self._warmup_spec is None:
            raise RuntimeError("add_replica before warmup(): the fleet "
                               "has no (bucket, dtype) grid to warm")
        with self._scale_lock:
            if self._closed:
                raise FleetClosedError("add_replica on a closed fleet")
            spare = self.spare_devices()
            if not spare:
                raise RuntimeError(
                    f"no spare device: {len(self._devices_all)} device(s) "
                    f"all owned — the scale universe is the device list "
                    f"the fleet was built with")
            dev = spare[0]
            t0 = time.perf_counter()
            shapes, max_batch, dtypes, sizes = self._warmup_spec
            with self._cond:
                index = self._next_index
                self._next_index = index + 1
            gen = self.generation
            engine = self._build_replica_engine(index, dev)
            rep = engine.warmup(shapes, max_batch, dtypes=dtypes,
                                sizes=sizes)
            with self._rollout_lock:
                if self._closed:
                    raise FleetClosedError("fleet closed during scale-up")
                if self.generation != gen:
                    raise RuntimeError(
                        "fleet rolled out during scale-up staging — the "
                        "staged weights are stale; retry add_replica")
                fresh = ReplicaState(index, dev, engine)
                fresh.generation = gen
                with self._cond:
                    self.replicas.append(fresh)
                    self._cond.notify_all()
                if self._started:
                    self._spawn_worker(fresh)
                report = {"direction": "up", "replica": index,
                          "device": str(dev), "reason": reason,
                          "live": self.live_replicas(),
                          "generation": gen,
                          "time_to_first_ready_s":
                              round(time.perf_counter() - t0, 3),
                          "warmup_compiles": rep["compiles"],
                          "aot_hits": engine.aot_hits}
                self.telemetry.emit("fleet.scale", **report)
                self.telemetry.emit("fleet.replica", **fresh.snapshot())
                return report

    def remove_replica(self, *, reason: str = "manual",
                       drain_timeout_s: float = 60.0) -> dict:
        """Shrink the fleet by one replica, zero-drop: the victim is
        marked ``draining`` (its worker finishes the in-flight batch,
        then retires — queued work belongs to the survivors; a HANG
        during the drain is still the watchdog's to wedge and
        re-dispatch), its device buffers are released, and it leaves
        the replica table entirely (its device returns to the spare
        pool).  The drain join holds ``_scale_lock`` only, never
        ``_rollout_lock``."""
        with self._scale_lock:
            with self._cond:
                live = [r for r in self.replicas
                        if r.state == REPLICA_ACTIVE]
                if len(live) <= 1:
                    raise RuntimeError(
                        "refusing to scale below 1 live replica — close() "
                        "the fleet instead")
                victim = live[-1]
                victim.state = REPLICA_DRAINING
                self._cond.notify_all()
            self.telemetry.emit("fleet.replica", **victim.snapshot())
            t = victim.thread
            if t is not None:
                t.join(timeout=drain_timeout_s)
            victim.engine.release_buffers()
            with self._rollout_lock:
                with self._cond:
                    if victim in self.replicas:
                        self.replicas.remove(victim)
            report = {"direction": "down", "replica": victim.index,
                      "device": str(victim.device), "reason": reason,
                      "live": self.live_replicas(),
                      "generation": self.generation}
            self.telemetry.emit("fleet.scale", **report)
            return report

    # -- AOT warm start ----------------------------------------------------
    def bake_aot(self, out_dir: str, *, devices=None) -> dict:
        """Serialize the warmed (bucket, dtype) predict grid for every
        device of the scale universe (default) into an AOT bundle at
        ``out_dir`` — the artifact resurrection and scale-up load
        executables from.  Live replicas' engines bake their own
        programs; devices without a replica get a transient staging
        engine (its params leave with it)."""
        with self._rollout_lock:
            if self._warmup_spec is None:
                raise RuntimeError("bake_aot before warmup(): no "
                                   "(bucket, dtype) grid to bake")
            shapes, max_batch, dtypes, sizes = self._warmup_spec
            devices = (list(devices) if devices is not None
                       else list(self._devices_all))
            by_dev = {r.device: r.engine for r in self.replicas
                      if r.state == REPLICA_ACTIVE}
            qparams, qstats = self._host_q
            engines = []
            for dev in devices:
                eng = by_dev.get(dev)
                if eng is None:
                    eng = ServeEngine(
                        qparams, qstats, serve_dtype=self.serve_dtype,
                        compute_dtype=self._compute_dtype, ds=self.ds,
                        device=dev, quantized=True,
                        telemetry=self.telemetry,
                        name=f"{self.name}_bake_d{dev.id}")
                engines.append(eng)
            return bake_aot_bundle(
                out_dir, engines=engines, bucket_shapes=shapes,
                max_batch=max_batch, dtypes=dtypes, ds=self.ds,
                serve_dtype=self.serve_dtype, sig_sha=self._sig_sha,
                generation=self.generation, telemetry=self.telemetry,
                batch_sizes=sizes)

    def load_aot(self, bundle) -> None:
        """Attach a bundle (path or ``AotBundle``) for the recovery and
        scale paths; staleness-checked against the serving tree."""
        if isinstance(bundle, str):
            bundle = load_aot_bundle(bundle)
        bundle.check(sig_sha=self._sig_sha, serve_dtype=self.serve_dtype,
                     ds=self.ds)
        with self._rollout_lock:
            self._aot = bundle

    # -- health -----------------------------------------------------------
    def healthz(self) -> dict:
        live = self.live_replicas()
        with self._cond:
            snaps = [r.snapshot() for r in self.replicas]
        # generation skew surfaced, not silent: per-replica generation is
        # in every row, and the serving set's generation spread is a
        # first-class field (a quarantined-then-resurrected fleet that
        # somehow serves two checkpoints must be VISIBLE here)
        serving_gens = sorted({s["generation"] for s in snaps
                               if s["state"] in (REPLICA_ACTIVE,
                                                 REPLICA_DRAINING)})
        return {"ok": live > 0, "replicas": snaps,
                "live": live, "generation": self.generation,
                "generations": serving_gens,
                "mixed_generations": len(serving_gens) > 1,
                "serve_dtype": self.serve_dtype,
                "queue_depth": len(self._queue)}

    # -- blue/green rollout ----------------------------------------------
    def rollout(self, params, batch_stats=None, *,
                run_config: Optional[dict] = None,
                allow_config_change: bool = False) -> dict:
        """Ship a new checkpoint into the serving fleet with zero dropped
        requests.  Synchronous — call it from a background thread (the
        HTTP /rollout handler does); traffic keeps flowing on every
        replica not currently mid-flip.  Returns the rollout report."""
        with self._rollout_lock:
            t0 = time.perf_counter()
            gen = self.generation + 1
            spans = getattr(self.telemetry, "spans", None)
            trace_id = (spans.new_trace_id(f"rollout-g{gen}")
                        if spans is not None else None)

            # 1. free guards first — a refused rollout does no device
            #    work: the staging grid must exist, and a checkpoint
            #    trained as a different model VARIANT must be refused
            if self._warmup_spec is None:
                raise RuntimeError("rollout before warmup(): the fleet "
                                   "has no (bucket, dtype) grid to stage")
            drifted: List[str] = []
            if run_config is not None and self.run_config is not None:
                from can_tpu.utils.checkpoint import check_serve_config

                drifted = check_serve_config(self.run_config, run_config,
                                             allow=allow_config_change)

            # 2. quantize once, replicate once (same path as __init__)
            qparams = quantize_tree(params, self.serve_dtype)
            rep_params = _replicate(qparams, self.devices)
            rep_stats = (None if batch_stats is None
                         else _replicate(batch_stats, self.devices))

            # 3. structural guard BEFORE staging: a tree that would change
            #    the jit signature would recompile mid-traffic on flip.
            #    The reference is the HOST-side current tree, not
            #    replicas[0]'s engine — that replica may be quarantined
            #    with its buffers released (params None), and a released
            #    tree must not make every rollout look structural-drifted
            stage_dev = self.devices[-1]
            new_sig = tree_signature((
                _per_device(rep_params, stage_dev),
                None if rep_stats is None
                else _per_device(rep_stats, stage_dev)))
            old_sig = tree_signature(self._host_q)
            if new_sig != old_sig:
                raise ValueError(
                    "rollout refused: the new checkpoint's param tree "
                    "differs in structure/shape/dtype from the serving "
                    "tree (did the model variant change?) — deploy it as "
                    "a fresh fleet instead of a hot flip")

            # 4. staging warmup in the background of live traffic: every
            #    (bucket, dtype) program runs the NEW weights end-to-end
            #    on the staging device before any live replica flips —
            #    catches NaN checkpoints and numeric blowups off-path
            shapes, max_batch, dtypes, sizes = self._warmup_spec
            t_stage0 = time.perf_counter()
            staging = ServeEngine(
                _per_device(rep_params, stage_dev),
                None if rep_stats is None
                else _per_device(rep_stats, stage_dev),
                serve_dtype=self.serve_dtype,
                compute_dtype=self._compute_dtype, ds=self.ds,
                device=stage_dev, quantized=True, telemetry=self.telemetry,
                name=f"{self.name}_staging_g{gen}")
            stage_report = staging.warmup(shapes, max_batch, dtypes=dtypes,
                                          sizes=sizes)
            t_stage1 = time.perf_counter()
            if spans is not None:
                spans.emit(trace_id=trace_id, name="rollout.staging",
                           start=t_stage0, end=t_stage1,
                           compiles=stage_report["compiles"])

            # 5. flip one replica at a time under its dispatch lock: the
            #    other replicas keep pulling from the shared queue, so no
            #    request is rejected or dropped while any replica flips
            flipped = []
            for r in self.replicas:
                if r.state != REPLICA_ACTIVE:
                    continue  # quarantined replicas stay on the old gen
                t_f0 = time.perf_counter()
                with r.lock:
                    r.engine.swap_params(
                        _per_device(rep_params, r.device),
                        None if rep_stats is None
                        else _per_device(rep_stats, r.device),
                        quantized=True)
                    r.generation = gen
                flipped.append(r.index)
                self.telemetry.emit("fleet.replica", **r.snapshot())
                if spans is not None:
                    spans.emit(trace_id=trace_id,
                               name=f"rollout.flip_r{r.index}",
                               start=t_f0, end=time.perf_counter())

            self.generation = gen
            # the host-side staging copy follows the fleet: a replica
            # resurrected or added AFTER this rollout serves generation
            # ``gen``'s weights, never the boot checkpoint's (the
            # naive-resurrection staleness this layer exists to close)
            self._host_q = (host_tree(qparams),
                            None if batch_stats is None
                            else host_tree(batch_stats))
            if run_config is not None:
                self.run_config = run_config
            report = {"generation": gen, "flipped": flipped,
                      "skipped": [r.index for r in self.replicas
                                  if r.index not in flipped],
                      "staging_compiles": stage_report["compiles"],
                      "staging_seconds": stage_report["seconds"],
                      "config_drift": drifted,
                      "seconds": round(time.perf_counter() - t0, 3)}
            self.telemetry.emit("fleet.rollout", **report)
            if spans is not None:
                spans.emit(trace_id=trace_id, name="rollout",
                           start=t0, end=time.perf_counter(),
                           generation=gen)
            return report
