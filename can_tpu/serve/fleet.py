"""FleetEngine: N replica ServeEngines behind one work-stealing dispatcher.

The single ``ServeEngine`` serves one device; the ROADMAP's "millions of
users" target needs every device of the mesh serving, a way to ship a new
checkpoint without dropping traffic, and graceful degradation when a
replica dies.  This module is that fleet layer:

* **Placement** — params are quantized ONCE (``serve/quant.py``), pushed
  to every replica device in one batched transfer via a replicated
  ``NamedSharding`` over a 1-D ``("replica",)`` mesh (the SNIPPETS [2]
  ``get_replicated_sharding`` pattern), then committed per replica with a
  single-device ``device_put`` (free: the bytes are already resident).
  Each replica is a full ``ServeEngine`` pinned to its device — committed
  params make jit place that replica's programs on that device.

* **Work stealing** — one shared FIFO of assembled micro-batches; every
  idle replica thread pulls the next item.  No per-replica queues, no
  assignment policy, therefore no starvation: a replica is only ever idle
  when the queue is empty.  The MicroBatcher keeps its single assembly
  thread; ``CountService`` routes its dispatch here instead of executing
  inline, so assembly and N executions overlap.

* **Failure containment** — a replica whose predict raises is QUARANTINED
  (removed from dispatch, state exported on ``/healthz`` and as a
  ``fleet.replica`` event); its in-flight batch is re-dispatched exactly
  once to a healthy replica.  A batch that fails on a SECOND replica is
  rejected with ``error`` and that replica stays in service (poison
  input, not a dead replica — one bad batch must not take the whole
  fleet down).  When the last replica quarantines, queued work is
  failed instead of hanging.

* **Blue/green rollout** — ``rollout(params, ...)`` ships a new
  checkpoint with zero rejected or dropped requests: config drift guard
  (PR-3's ``check_resume_config`` on the serve-relevant keys), then a
  STAGING engine on the last replica's device warms every (bucket, dtype)
  program with the new weights while live traffic continues, then each
  replica is flipped one at a time under its dispatch lock via
  ``ServeEngine.swap_params`` — params are jit arguments, so a
  same-signature tree swap reuses every compiled program with zero
  recompilation, and at most one replica is briefly paused while the
  others keep pulling work.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from can_tpu.obs import Telemetry
from can_tpu.serve.engine import ServeEngine, tree_signature
from can_tpu.serve.quant import quantize_tree

REPLICA_ACTIVE = "active"
REPLICA_QUARANTINED = "quarantined"


class FleetClosedError(RuntimeError):
    """Work submitted after the fleet shut down."""


class _WorkItem:
    __slots__ = ("bucket_hw", "batch", "requests", "redispatches")

    def __init__(self, bucket_hw, batch, requests):
        self.bucket_hw = bucket_hw
        self.batch = batch
        self.requests = requests
        self.redispatches = 0


class ReplicaState:
    """One replica: engine + device + dispatch lock + health."""

    def __init__(self, index: int, device, engine: ServeEngine):
        self.index = index
        self.device = device
        self.engine = engine
        # held for the duration of each predict AND for a rollout flip —
        # swap_params never races an in-flight batch
        self.lock = threading.Lock()
        self.state = REPLICA_ACTIVE
        self.batches = 0
        self.failures = 0
        self.error: Optional[str] = None
        self.generation = 0

    def snapshot(self) -> dict:
        return {"replica": self.index, "device": str(self.device),
                "state": self.state, "batches": self.batches,
                "failures": self.failures, "error": self.error,
                "generation": self.generation}


def _replicate(tree, devices):
    """One batched host->devices transfer: every leaf fully replicated
    over a 1-D replica mesh (NamedSharding with an empty PartitionSpec)."""
    # can-tpu-lint: disable=HOSTSYNC(host list of device HANDLES, no device data moves)
    mesh = Mesh(np.asarray(devices), ("replica",))
    sharding = NamedSharding(mesh, PartitionSpec())
    return jax.device_put(tree, sharding)


def _per_device(tree, device):
    """Commit a replicated tree to one device (the bytes are already
    there; this just re-keys the arrays to a single-device sharding)."""
    return jax.tree.map(lambda x: jax.device_put(x, device), tree)


class FleetEngine:
    """N device-pinned replica engines + the shared work queue.

    params / batch_stats: f32 trees (host or device).  serve_dtype picks
    the storage/compute mode for EVERY replica (serve/quant.py).
    replicas: engine count; devices (default ``jax.devices()``) supplies
    the distinct devices, one per replica.
    run_config: the checkpoint's saved run config (utils/checkpoint.py
    ``load_run_config``), kept for the rollout drift guard; None skips
    the config check on rollout (pre-guard checkpoints).
    """

    def __init__(self, params, batch_stats=None, *, replicas: int = 2,
                 serve_dtype: str = "f32", compute_dtype=None, ds: int = 8,
                 devices: Optional[Sequence] = None, telemetry=None,
                 run_config: Optional[dict] = None,
                 name: str = "serve_predict"):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        devices = list(devices if devices is not None else jax.devices())
        if replicas > len(devices):
            raise ValueError(
                f"replicas={replicas} exceeds the {len(devices)} available "
                f"devices — a replica without its own device just time-"
                f"slices another's, add chips or lower --replicas")
        self.ds = int(ds)
        self.serve_dtype = serve_dtype
        self._compute_dtype = compute_dtype
        self.telemetry = telemetry if telemetry is not None else Telemetry()
        self.run_config = run_config
        self.name = name
        self.generation = 0
        self.devices = devices[:replicas]

        qparams = quantize_tree(params, serve_dtype)
        rep_params = _replicate(qparams, self.devices)
        rep_stats = (None if batch_stats is None
                     else _replicate(batch_stats, self.devices))
        self.replicas: List[ReplicaState] = []
        for k, dev in enumerate(self.devices):
            engine = ServeEngine(
                _per_device(rep_params, dev),
                None if rep_stats is None else _per_device(rep_stats, dev),
                serve_dtype=serve_dtype, compute_dtype=compute_dtype,
                ds=ds, device=dev, quantized=True, telemetry=self.telemetry,
                name=f"{name}_r{k}")
            self.replicas.append(ReplicaState(k, dev, engine))

        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._swept = False  # close()'s leftover sweep has run
        self._started = False
        self._threads: List[threading.Thread] = []
        self._rollout_lock = threading.Lock()
        self._warmup_spec: Optional[Tuple] = None
        # bound by CountService: completion/failure sinks for executed work
        self._on_complete: Optional[Callable] = None
        self._on_fail: Optional[Callable] = None
        self._on_reject: Optional[Callable] = None
        # deadline checks must read the SAME clock that stamped
        # deadline_ts (the service's, injectable for fake-clock tests)
        self._clock = time.monotonic

    # -- service binding --------------------------------------------------
    def bind(self, *, on_complete: Callable, on_fail: Callable,
             on_reject: Optional[Callable] = None, clock=None) -> None:
        """``on_complete(bucket_hw, batch, requests, counts, density,
        execute_s, compiled, replica, program)`` after a successful batch;
        ``on_fail(requests, exc)`` after a twice-failed one;
        ``on_reject(reason, count)`` counts rejections the fleet already
        emitted telemetry for (zombie-batch shedding)."""
        # can-tpu-lint: disable=LOCKHELD(bind() happens-before start(): no worker thread exists yet)
        self._on_complete = on_complete
        # can-tpu-lint: disable=LOCKHELD(bind() happens-before start(): no worker thread exists yet)
        self._on_fail = on_fail
        # can-tpu-lint: disable=LOCKHELD(bind() happens-before start(): no worker thread exists yet)
        self._on_reject = on_reject
        if clock is not None:
            # can-tpu-lint: disable=LOCKHELD(bind() happens-before start(): no worker thread exists yet)
            self._clock = clock

    # -- engine-compatible surface ---------------------------------------
    @property
    def compile_count(self) -> int:
        """Distinct predict signatures across live+quarantined replicas
        (staging engines bill to their own per-generation registry)."""
        return sum(r.engine.compile_count for r in self.replicas)

    def live_replicas(self) -> int:
        return sum(1 for r in self.replicas if r.state == REPLICA_ACTIVE)

    def warmup(self, bucket_shapes, max_batch: int, *,
               dtypes=(np.float32,)) -> dict:
        """Warm EVERY replica's full (bucket, dtype) program grid — the
        per-replica jit caches are independent, so each pays its own
        compiles here and none during traffic.  The spec is remembered:
        rollout's staging warmup re-runs exactly this grid."""
        # can-tpu-lint: disable=LOCKHELD(warmup precedes traffic; rollout reads this under _rollout_lock afterwards)
        self._warmup_spec = (sorted(set(map(tuple, bucket_shapes))),
                             int(max_batch), tuple(dtypes))
        t0 = time.perf_counter()
        shapes = compiles = 0
        for r in self.replicas:
            with r.lock:
                rep = r.engine.warmup(bucket_shapes, max_batch,
                                      dtypes=dtypes)
            shapes = rep["shapes"]
            compiles += rep["compiles"]
        return {"shapes": shapes, "compiles": compiles,
                "replicas": len(self.replicas),
                "seconds": round(time.perf_counter() - t0, 3)}

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "FleetEngine":
        if self._started:
            return self
        # can-tpu-lint: disable=LOCKHELD(idempotent lifecycle flag; start runs on the owner thread)
        self._started = True
        for r in self.replicas:
            t = threading.Thread(target=self._worker, args=(r,),
                                 daemon=True,
                                 name=f"can-tpu-fleet-r{r.index}")
            self._threads.append(t)
            t.start()
        return self

    def close(self, *, drain_timeout_s: float = 60.0) -> None:
        """Drain queued work through the replicas, then stop the threads.
        Anything still queued when no live replica remains (or the drain
        times out) is failed, never silently dropped."""
        with self._cond:
            if self._closed:
                return
            self._closed = True
            self._cond.notify_all()
        deadline = time.monotonic() + drain_timeout_s
        for t in self._threads:
            t.join(timeout=max(deadline - time.monotonic(), 0.1))
        # can-tpu-lint: disable=LOCKHELD(only close() touches _threads after start, and close is idempotent-guarded above)
        self._threads = []
        leftovers = []
        with self._cond:
            self._swept = True
            while self._queue:
                leftovers.append(self._queue.popleft())
        for item in leftovers:
            self._fail(item, FleetClosedError("fleet closed with work "
                                              "still queued"))

    # -- dispatch ---------------------------------------------------------
    def submit_work(self, bucket_hw, batch, requests) -> None:
        """Called by the service's dispatch (the batcher thread): enqueue
        one assembled micro-batch for whichever replica frees up first."""
        item = _WorkItem(bucket_hw, batch, requests)
        with self._cond:
            if not self._closed and self.live_replicas() > 0:
                self._queue.append(item)
                self._cond.notify()
                return
            closed = self._closed
        self._fail(item, FleetClosedError(
            "fleet closed" if closed else "no live replicas"))

    def _take(self, replica: ReplicaState) -> Optional[_WorkItem]:
        with self._cond:
            while True:
                if replica.state != REPLICA_ACTIVE:
                    return None
                if self._queue:
                    return self._queue.popleft()
                if self._closed:
                    return None
                self._cond.wait(0.1)

    def _worker(self, replica: ReplicaState) -> None:
        while True:
            item = self._take(replica)
            if item is None:
                return
            # zombie-batch shed: a batch whose EVERY request has already
            # expired (deadline passed while it sat behind the work
            # queue) would burn a full device launch producing results
            # nobody is waiting for — reject instead of execute.  A batch
            # with ANY live request still runs whole: slots are padded,
            # and the live results are the point.
            now = self._clock()
            if all(r.done or r.expired(now) for r in item.requests):
                from can_tpu.serve.queue import REJECT_DEADLINE

                n = 0
                for r in item.requests:
                    if not r.done:
                        r.reject(REJECT_DEADLINE,
                                 "expired behind the fleet work queue")
                        n += 1
                if n:
                    self.telemetry.emit("serve.reject",
                                        reason=REJECT_DEADLINE, count=n)
                    if self._on_reject is not None:
                        self._on_reject(REJECT_DEADLINE, n)
                continue
            t0 = time.perf_counter()
            try:
                with replica.lock:
                    want = any(r.want_density for r in item.requests)
                    counts, density = replica.engine.predict_batch(
                        item.batch, want_density=want)
                    compiled = replica.engine.last_batch_compiled
                    replica.batches += 1
            except Exception as e:  # noqa: BLE001 — replica failure path
                self._quarantine(replica, item, e)
                continue
            execute_s = time.perf_counter() - t0
            if self._on_complete is not None:
                self._on_complete(item.bucket_hw, item.batch, item.requests,
                                  counts, density, execute_s, compiled,
                                  replica.index, replica.engine.name)

    def _quarantine(self, replica: ReplicaState, item: _WorkItem,
                    exc: Exception) -> None:
        replica.failures += 1
        item.redispatches += 1
        if item.redispatches > 1:
            # failed on a SECOND distinct replica (the first was
            # quarantined before the re-dispatch): the batch is the
            # poison, not the fleet — reject it and keep this replica
            # serving.  One bad input must not cascade into
            # quarantining every replica it touches.
            self.telemetry.emit("fleet.replica", **replica.snapshot())
            self._fail(item, exc)
            return
        replica.state = REPLICA_QUARANTINED
        replica.error = f"{type(exc).__name__}: {exc}"
        self.telemetry.emit("fleet.replica", **replica.snapshot())
        stranded = [item]
        with self._cond:
            if self.live_replicas() > 0 and not self._swept:
                # front of the queue: its requests have waited longest.
                # Deliberately ALSO while close() drains: the remaining
                # live workers still pull, and anything they don't reach
                # is failed by close()'s leftover sweep — rejecting here
                # would drop a request a live replica would have served.
                # (_swept guards the post-sweep stragglers of a timed-out
                # drain, the one window where a requeue could strand.)
                self._queue.appendleft(item)
                self._cond.notify()
                return
            if self.live_replicas() == 0:
                # the LAST live replica just died: no worker remains to
                # drain the queue, so everything queued is failed too —
                # never stranded behind a fleet with no executors
                while self._queue:
                    stranded.append(self._queue.popleft())
        for it in stranded:
            self._fail(it, exc)

    def _fail(self, item: _WorkItem, exc: Exception) -> None:
        if self._on_fail is not None:
            self._on_fail(item.requests, exc)
        else:  # unbound fleet (direct tests): reject inline
            from can_tpu.serve.queue import REJECT_ERROR

            for r in item.requests:
                if not r.done:
                    r.reject(REJECT_ERROR, f"{type(exc).__name__}: {exc}")

    # -- health -----------------------------------------------------------
    def healthz(self) -> dict:
        live = self.live_replicas()
        return {"ok": live > 0, "replicas": [r.snapshot()
                                             for r in self.replicas],
                "live": live, "generation": self.generation,
                "serve_dtype": self.serve_dtype,
                "queue_depth": len(self._queue)}

    # -- blue/green rollout ----------------------------------------------
    def rollout(self, params, batch_stats=None, *,
                run_config: Optional[dict] = None,
                allow_config_change: bool = False) -> dict:
        """Ship a new checkpoint into the serving fleet with zero dropped
        requests.  Synchronous — call it from a background thread (the
        HTTP /rollout handler does); traffic keeps flowing on every
        replica not currently mid-flip.  Returns the rollout report."""
        with self._rollout_lock:
            t0 = time.perf_counter()
            gen = self.generation + 1
            spans = getattr(self.telemetry, "spans", None)
            trace_id = (spans.new_trace_id(f"rollout-g{gen}")
                        if spans is not None else None)

            # 1. free guards first — a refused rollout does no device
            #    work: the staging grid must exist, and a checkpoint
            #    trained as a different model VARIANT must be refused
            if self._warmup_spec is None:
                raise RuntimeError("rollout before warmup(): the fleet "
                                   "has no (bucket, dtype) grid to stage")
            drifted: List[str] = []
            if run_config is not None and self.run_config is not None:
                from can_tpu.utils.checkpoint import check_serve_config

                drifted = check_serve_config(self.run_config, run_config,
                                             allow=allow_config_change)

            # 2. quantize once, replicate once (same path as __init__)
            qparams = quantize_tree(params, self.serve_dtype)
            rep_params = _replicate(qparams, self.devices)
            rep_stats = (None if batch_stats is None
                         else _replicate(batch_stats, self.devices))

            # 3. structural guard BEFORE staging: a tree that would change
            #    the jit signature would recompile mid-traffic on flip
            ref = self.replicas[0].engine
            stage_dev = self.devices[-1]
            new_sig = tree_signature((
                _per_device(rep_params, stage_dev),
                None if rep_stats is None
                else _per_device(rep_stats, stage_dev)))
            old_sig = tree_signature((ref.params, ref.batch_stats))
            if new_sig != old_sig:
                raise ValueError(
                    "rollout refused: the new checkpoint's param tree "
                    "differs in structure/shape/dtype from the serving "
                    "tree (did the model variant change?) — deploy it as "
                    "a fresh fleet instead of a hot flip")

            # 4. staging warmup in the background of live traffic: every
            #    (bucket, dtype) program runs the NEW weights end-to-end
            #    on the staging device before any live replica flips —
            #    catches NaN checkpoints and numeric blowups off-path
            shapes, max_batch, dtypes = self._warmup_spec
            t_stage0 = time.perf_counter()
            staging = ServeEngine(
                _per_device(rep_params, stage_dev),
                None if rep_stats is None
                else _per_device(rep_stats, stage_dev),
                serve_dtype=self.serve_dtype,
                compute_dtype=self._compute_dtype, ds=self.ds,
                device=stage_dev, quantized=True, telemetry=self.telemetry,
                name=f"{self.name}_staging_g{gen}")
            stage_report = staging.warmup(shapes, max_batch, dtypes=dtypes)
            t_stage1 = time.perf_counter()
            if spans is not None:
                spans.emit(trace_id=trace_id, name="rollout.staging",
                           start=t_stage0, end=t_stage1,
                           compiles=stage_report["compiles"])

            # 5. flip one replica at a time under its dispatch lock: the
            #    other replicas keep pulling from the shared queue, so no
            #    request is rejected or dropped while any replica flips
            flipped = []
            for r in self.replicas:
                if r.state != REPLICA_ACTIVE:
                    continue  # quarantined replicas stay on the old gen
                t_f0 = time.perf_counter()
                with r.lock:
                    r.engine.swap_params(
                        _per_device(rep_params, r.device),
                        None if rep_stats is None
                        else _per_device(rep_stats, r.device),
                        quantized=True)
                    r.generation = gen
                flipped.append(r.index)
                self.telemetry.emit("fleet.replica", **r.snapshot())
                if spans is not None:
                    spans.emit(trace_id=trace_id,
                               name=f"rollout.flip_r{r.index}",
                               start=t_f0, end=time.perf_counter())

            self.generation = gen
            if run_config is not None:
                self.run_config = run_config
            report = {"generation": gen, "flipped": flipped,
                      "skipped": [r.index for r in self.replicas
                                  if r.index not in flipped],
                      "staging_compiles": stage_report["compiles"],
                      "staging_seconds": stage_report["seconds"],
                      "config_drift": drifted,
                      "seconds": round(time.perf_counter() - t0, 3)}
            self.telemetry.emit("fleet.rollout", **report)
            if spans is not None:
                spans.emit(trace_id=trace_id, name="rollout",
                           start=t0, end=time.perf_counter(),
                           generation=gen)
            return report
