"""can_tpu.serve — online inference: bucketed micro-batching, deadlines,
backpressure.

The training repro already solved variable-resolution-under-XLA once
(``data/batching.py``); this subsystem lifts that solution to request
granularity::

    engine = ServeEngine(params, batch_stats)
    ladder = ((384, 768), (512, 1024))      # per-axis H x W bounds
    svc = CountService(engine, max_batch=8, max_wait_ms=5,
                       queue_capacity=64, high_water=48,
                       bucket_ladder=ladder)
    # compile BEFORE traffic — the ladder's full cross product, because
    # any (H bound, W bound) pairing can occur
    svc.warmup([(h, w) for h in ladder[0] for w in ladder[1]])
    with svc:                               # starts the batcher thread
        res = svc.predict(prepare_image(img), deadline_ms=200)
        print(res.count, res.latency_s)

Guarantees: every submitted request resolves or is rejected with a typed
reason (never hangs); compile count == distinct (bucket, menu size,
dtype) programs — the launch-size menu comes from the shared scheduling
core (``can_tpu/sched``, r14) — all paid in ``warmup``; a served count
is bit-for-bit what ``evaluate()`` computes offline for the same image
and params at the same launch size.
"""

from .aot import AotBundle, AotStaleError, load_aot_bundle
from .autoscale import Autoscaler, AutoscalePolicy
from .batcher import MicroBatcher
from .engine import ServeEngine, tree_signature
from .fleet import (
    REPLICA_ACTIVE,
    REPLICA_DRAINING,
    REPLICA_QUARANTINED,
    REPLICA_WEDGED,
    FleetClosedError,
    FleetEngine,
    ReplicaWedgedError,
    priced_deadline_s,
)
from .quant import (
    PARITY_LADDER,
    SERVE_DTYPES,
    dequantize_tree,
    parity_report,
    quantize_tree,
)
from .queue import (
    REJECT_BACKPRESSURE,
    REJECT_DEADLINE,
    REJECT_ERROR,
    REJECT_QUEUE_FULL,
    REJECT_SHUTDOWN,
    REJECT_STALE_FRAME,
    REJECT_STREAM_OVERLOAD,
    BoundedRequestQueue,
    RejectedError,
    ServeRequest,
    ServeResult,
)
from .service import (
    CountService,
    ServeTicket,
    make_http_handler,
    prepare_image,
    serve_http,
)
from .streams import (
    STREAM_RUNG_FULL,
    STREAM_RUNG_REJECT,
    STREAM_RUNG_SKIP,
    StreamSession,
    StreamSessionRegistry,
    repin_target,
)

__all__ = [
    "AotBundle",
    "AotStaleError",
    "Autoscaler",
    "AutoscalePolicy",
    "BoundedRequestQueue",
    "CountService",
    "FleetClosedError",
    "FleetEngine",
    "MicroBatcher",
    "PARITY_LADDER",
    "REPLICA_ACTIVE",
    "REPLICA_DRAINING",
    "REPLICA_QUARANTINED",
    "REPLICA_WEDGED",
    "ReplicaWedgedError",
    "load_aot_bundle",
    "priced_deadline_s",
    "SERVE_DTYPES",
    "dequantize_tree",
    "parity_report",
    "quantize_tree",
    "tree_signature",
    "REJECT_BACKPRESSURE",
    "REJECT_DEADLINE",
    "REJECT_ERROR",
    "REJECT_QUEUE_FULL",
    "REJECT_SHUTDOWN",
    "REJECT_STALE_FRAME",
    "REJECT_STREAM_OVERLOAD",
    "RejectedError",
    "STREAM_RUNG_FULL",
    "STREAM_RUNG_REJECT",
    "STREAM_RUNG_SKIP",
    "ServeEngine",
    "ServeRequest",
    "ServeResult",
    "ServeTicket",
    "StreamSession",
    "StreamSessionRegistry",
    "make_http_handler",
    "prepare_image",
    "repin_target",
    "serve_http",
]
