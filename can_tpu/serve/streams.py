"""Per-stream serving sessions: sticky host-side state, frame-skip
admission, and survival across every fleet fault.

The paper's deployment shape — fixed cameras sending continuous frames —
is the ROADMAP's "millions of users" scenario, and it breaks two
assumptions the request-level stack was built on: requests from one
camera are TEMPORALLY REDUNDANT (the crowd count moves slowly between
frames; an answer a second stale is still an answer), and they are
STICKY (the same resolution hits the same bucket forever, so the same
replica's program/item caches serve it best).  This module is the
session layer that exploits both, designed around one placement rule:

**Session state lives on the HOST, on the service — never on a
replica.**  A ``StreamSessionRegistry`` hangs off ``CountService`` and
holds, per stream: a count EWMA (and a density-map EWMA when density was
fetched), a count trend, the last-served timestamp, a monotonic frame
sequence with out-of-order/duplicate rejection, the degradation rung,
and a replica pin.  Replicas hold nothing — so quarantine, a watchdog
wedge, resurrection at a new incarnation, a blue/green rollout, and an
autoscale down/up cycle all leave every session intact BY CONSTRUCTION
(the chaos acceptance test in tests/test_streams.py drives all five
faults under sustained streams and pins zero session loss).

Three mechanisms:

* **Sticky stream→replica routing** — a stream is pinned to the replica
  that first served it; the pin rides each work item into the fleet's
  priced ``pick_work`` (``can_tpu/sched``) as a PREFERENCE tier: a
  replica pulls work pinned to itself before unpinned work before work
  pinned elsewhere, within the same urgency class — preference, never
  exclusion, so a pinned item can always be stolen and no pin can
  starve a stream.  Pins are validated at dispatch time against the
  fleet's live ``(index, incarnation)`` tokens: a pin to a quarantined/
  wedged/removed replica — or to an ABANDONED incarnation of a
  resurrected one — is invalidated and deterministically re-pinned to a
  live replica (``stream.repin`` on the bus), so a fault event can
  never leave a stream waiting behind a dead replica.

* **Frame-skip admission (the degradation ladder)** — full inference →
  frame-skip (answer from the EWMA, drop the launch) → reject, driven
  by per-stream load ``L = max(arrival pressure, backlog pressure)``:
  arrival pressure is the PRICED per-frame drain cost over the stream's
  arrival-gap EWMA (the sched core's cost model — serving one more
  frame costs ``cover_one(1) + launch_cost_slots`` slots at the
  bucket's measured seconds-per-slot — so skipping is a planner
  decision, not a timer), and backlog pressure is the stream's own
  outstanding frames over its allowance.  Rung transitions use
  hysteresis bands (enter at 1.0/3.0, exit at 0.5/1.5) AND a cooldown:
  a stream changes rung at most once per ``cooldown_s`` (pinned), so an
  oscillating camera cannot flap the ladder.  Every degraded answer is
  labelled (``degraded: true`` + staleness seconds) in the
  ``ServeResult`` and the HTTP body — a client can always tell a fresh
  count from a served EWMA.

* **TTL eviction** — a camera that disconnects stops paying for its
  session: idle sessions past ``ttl_s`` are swept (under the registry
  lock, on the submit path, amortised) and announced as
  ``stream.session`` events.

Events (EVENT_KINDS): ``stream.session`` (open / periodic snapshot /
evict, with the active-session gauge), ``stream.degrade`` (one per rung
TRANSITION — degraded answers themselves ride ``serve.request`` with
``degraded: true``), ``stream.repin`` (pin invalidation + new target).
GaugeSink turns them into ``can_tpu_stream_*`` gauges; the report and
the ``stream_staleness`` SLO objective read the same bus.

Pure host-side Python, jax-free; thread-safe (HTTP threads submit while
batcher/replica threads complete) behind one RLock.
"""

from __future__ import annotations

import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from can_tpu.sched.core import GAP_EWMA_ALPHA, MIN_GAP_INTERVALS

# degradation rungs, least to most degraded; index IS the rung level
STREAM_RUNG_FULL = "full"
STREAM_RUNG_SKIP = "skip"
STREAM_RUNG_REJECT = "reject"
_RUNGS = (STREAM_RUNG_FULL, STREAM_RUNG_SKIP, STREAM_RUNG_REJECT)

# count-EWMA smoothing: ~the last 5-6 frames dominate (a crowd count
# moves slowly frame to frame; heavier smoothing would lag real trends)
COUNT_EWMA_ALPHA = 0.3
# drain-cost smoothing (seconds-per-slot per bucket): measured from real
# batch completions, so it tracks the fleet's actual capacity through
# quarantines and scale events
DRAIN_EWMA_ALPHA = 0.25


class AdmitDecision:
    """Outcome of ``StreamSessionRegistry.admit`` for one frame."""

    __slots__ = ("kind", "count", "density", "staleness_s", "detail",
                 "rung", "prior_seq")

    # kinds: "serve" (full inference), "degrade" (answer from the EWMA,
    # drop the launch), "stale" (out-of-order/duplicate frame),
    # "overload" (reject rung / no EWMA to degrade to)
    def __init__(self, kind: str, *, count: Optional[float] = None,
                 density=None, staleness_s: Optional[float] = None,
                 detail: str = "", rung: str = STREAM_RUNG_FULL,
                 prior_seq: Optional[int] = None):
        self.kind = kind
        self.count = count
        self.density = density
        self.staleness_s = staleness_s
        self.detail = detail
        self.rung = rung
        # the session's seq BEFORE this frame committed it — what
        # ``rollback_seq`` restores when a "serve" decision's frame is
        # subsequently refused by the queue with nothing to degrade to
        self.prior_seq = prior_seq


class StreamSession:
    """One stream's host-side state.  Mutated only under the registry
    lock; the object itself survives every replica fault because no
    replica ever holds it."""

    __slots__ = ("stream_id", "created_ts", "last_seen_ts",
                 "last_served_ts", "seq", "served", "degraded",
                 "stale_rejects", "overload_rejects", "outstanding",
                 "count_ewma", "trend_per_s", "density_ewma", "bucket_hw",
                 "gap_ewma", "gap_n", "t_last_arrival", "rung",
                 "rung_since", "pin")

    def __init__(self, stream_id: str, now: float):
        self.stream_id = stream_id
        self.created_ts = now
        self.last_seen_ts = now
        self.last_served_ts: Optional[float] = None
        self.seq: Optional[int] = None    # highest ACCEPTED frame seq
        self.served = 0                   # frames fully inferred
        self.degraded = 0                 # frames answered from the EWMA
        self.stale_rejects = 0
        self.overload_rejects = 0
        self.outstanding = 0              # admitted, not yet resolved
        self.count_ewma: Optional[float] = None
        self.trend_per_s = 0.0            # d(count_ewma)/dt, smoothed
        self.density_ewma: Optional[np.ndarray] = None
        self.bucket_hw: Optional[Tuple[int, int]] = None
        # arrival-gap EWMA (the sched core's estimator shape/constants)
        self.gap_ewma = 0.0
        self.gap_n = 0
        self.t_last_arrival: Optional[float] = None
        self.rung = STREAM_RUNG_FULL
        self.rung_since = now
        # sticky routing: (replica index, incarnation token) of the
        # replica that first served this stream; invalidated + re-pinned
        # when that exact incarnation leaves the live set
        self.pin: Optional[Tuple[int, str]] = None

    def snapshot(self) -> dict:
        return {"stream": self.stream_id, "seq": self.seq,
                "served": self.served, "degraded": self.degraded,
                "stale_rejects": self.stale_rejects,
                "overload_rejects": self.overload_rejects,
                "outstanding": self.outstanding,
                "count_ewma": (None if self.count_ewma is None
                               else round(self.count_ewma, 4)),
                "trend_per_s": round(self.trend_per_s, 6),
                "rung": self.rung,
                "pin": None if self.pin is None else list(self.pin)}


def repin_target(stream_id: str, live_indices: List[int]) -> int:
    """Deterministic re-pin choice: spread streams over the live set by
    a stable hash of the stream id (Python's ``hash`` is salted per
    process — two hosts would disagree; crc32 is stable everywhere)."""
    order = sorted(live_indices)
    return order[zlib.crc32(stream_id.encode()) % len(order)]


class StreamSessionRegistry:
    """Every stream session of one ``CountService``, plus the shared
    drain pricing the degradation ladder consults.

    sched: the service's ``ServeSched`` (may be None — the legacy
    timer/pad service): supplies the cost model that prices one more
    frame's launch.  policy: "priced" (the ladder) or "off" (sessions,
    stickiness and sequence hygiene only — a frame is never skipped).
    """

    def __init__(self, *, ttl_s: float = 300.0, clock=time.monotonic,
                 telemetry=None, sched=None, policy: str = "priced",
                 skip_enter: float = 1.0, skip_exit: float = 0.5,
                 reject_enter: float = 3.0, reject_exit: float = 1.5,
                 outstanding_high: int = 4, cooldown_s: float = 1.0,
                 session_event_every: int = 32):
        if policy not in ("priced", "off"):
            raise ValueError(f"unknown degrade policy {policy!r} "
                             f"(priced | off)")
        if not 0.0 <= skip_exit < skip_enter <= reject_exit < reject_enter:
            raise ValueError(
                "hysteresis bands must satisfy skip_exit < skip_enter <= "
                f"reject_exit < reject_enter, got {skip_exit}/{skip_enter}"
                f"/{reject_exit}/{reject_enter}")
        if outstanding_high < 1:
            raise ValueError(f"outstanding_high must be >= 1, got "
                             f"{outstanding_high}")
        self.ttl_s = float(ttl_s)
        self.policy = policy
        self.sched = sched
        self.telemetry = telemetry
        self.skip_enter = float(skip_enter)
        self.skip_exit = float(skip_exit)
        self.reject_enter = float(reject_enter)
        self.reject_exit = float(reject_exit)
        self.outstanding_high = int(outstanding_high)
        self.cooldown_s = float(cooldown_s)
        self.session_event_every = int(session_event_every)
        self._clock = clock
        # RLock: admit() may evict (which emits) while a completion on
        # another thread updates a session; the dump-path rule from the
        # incident layer (re-entry must never deadlock) applies here too
        self._lock = threading.RLock()
        self._sessions: Dict[str, StreamSession] = {}
        # per-bucket drain pricing: EWMA of execute seconds PER SLOT,
        # measured from every completed batch (stream or not) — warm by
        # the time the first stream needs a skip decision
        self._drain: Dict[Tuple[int, int], float] = {}
        self._last_sweep = 0.0
        self._sweep_every = max(min(self.ttl_s / 8.0, 5.0), 0.05)
        self._evicted_total = 0
        self._repins_total = 0
        self._degrade_transitions = 0

    # -- drain pricing (the sched core's cost model, in seconds) --------
    def observe_batch(self, bucket_hw, execute_s: float,
                      slots: int) -> None:
        """Fold one completed batch into the bucket's seconds-per-slot
        EWMA — the measured drain rate the ladder prices against."""
        if slots <= 0 or execute_s <= 0:
            return
        key = (int(bucket_hw[0]), int(bucket_hw[1]))
        s_slot = float(execute_s) / float(slots)
        with self._lock:
            got = self._drain.get(key)
            self._drain[key] = (s_slot if got is None else
                                (1 - DRAIN_EWMA_ALPHA) * got
                                + DRAIN_EWMA_ALPHA * s_slot)

    def expected_cost_s(self, bucket_hw) -> Optional[float]:
        """Priced cost (seconds) of serving ONE more frame at this
        bucket: the sched core's model — a lone frame launches
        ``cover_one(1)`` slots plus the launch overhead — times the
        bucket's measured seconds-per-slot.  None until a batch at this
        bucket has completed (no evidence, no skipping: a cold stream
        is always served)."""
        key = (int(bucket_hw[0]), int(bucket_hw[1]))
        with self._lock:
            s_slot = self._drain.get(key)
        if s_slot is None:
            return None
        if self.sched is not None:
            return s_slot * (self.sched.cover_one(1)
                             + self.sched.launch_cost_slots)
        return s_slot

    # -- admission --------------------------------------------------------
    def admit(self, stream_id: str, frame_seq: Optional[int],
              now: Optional[float] = None,
              bucket_hw: Optional[Tuple[int, int]] = None
              ) -> AdmitDecision:
        """One frame at the front door: sequence hygiene, arrival-rate
        update, the ladder decision.  Called by ``CountService.submit``
        BEFORE the queue — a skipped frame never touches it."""
        now = self._clock() if now is None else now
        events: List[Tuple[str, dict]] = []
        with self._lock:
            self._sweep_locked(now, events)
            sess = self._sessions.get(stream_id)
            if sess is None:
                sess = self._sessions[stream_id] = StreamSession(
                    stream_id, now)
                events.append(("stream.session",
                               {"state": "open",
                                "active": len(self._sessions),
                                **sess.snapshot()}))
            sess.last_seen_ts = now
            if bucket_hw is not None:
                sess.bucket_hw = (int(bucket_hw[0]), int(bucket_hw[1]))
            # monotonic frame sequence GATE: a duplicate or out-of-order
            # frame is rejected BEFORE it can double-serve or regress
            # the session (cameras retransmit; the fleet redispatches —
            # the sequence gate is what makes "exactly once per frame"
            # hold through both).  The seq is only COMMITTED further
            # down, once the frame is actually accepted (served or
            # degraded): a load-based reject (503 = "retry later") must
            # leave the sequence untouched, or the camera's retry of a
            # never-served frame would bounce off this gate as 409
            # forever.
            if frame_seq is not None:
                if sess.seq is not None and int(frame_seq) <= sess.seq:
                    sess.stale_rejects += 1
                    self._emit(events)
                    return AdmitDecision(
                        "stale", rung=sess.rung,
                        detail=f"frame_seq {frame_seq} <= last accepted "
                               f"{sess.seq} (duplicate or out-of-order)")
            # arrival-gap EWMA (the sched core's estimator): every real
            # new frame feeds it — including ones the reject rung is
            # about to refuse, or the pressure estimate would freeze at
            # its overload value and the rung could never exit when the
            # camera slows.  Retransmits (caught above) must not fake a
            # rate spike.
            if sess.t_last_arrival is not None:
                gap = max(now - sess.t_last_arrival, 0.0)
                sess.gap_ewma = (gap if sess.gap_n == 0 else
                                 (1 - GAP_EWMA_ALPHA) * sess.gap_ewma
                                 + GAP_EWMA_ALPHA * gap)
                sess.gap_n += 1
            sess.t_last_arrival = now
            rung = self._decide_locked(sess, now, events)
            if rung == STREAM_RUNG_REJECT:
                sess.overload_rejects += 1
                self._emit(events)
                return AdmitDecision(
                    "overload", rung=rung,
                    detail=f"stream {stream_id} on the reject rung "
                           f"(arrival rate sustained past drain "
                           f"capacity; outstanding {sess.outstanding})")
            prior_seq = sess.seq
            if frame_seq is not None:
                sess.seq = int(frame_seq)  # accepted: commit the gate
            if rung == STREAM_RUNG_SKIP and sess.count_ewma is not None:
                dec = self._degrade_locked(sess, now)
                dec.prior_seq = prior_seq
                self._emit(events)
                return dec
            # full inference (or skip rung on a cold stream with no
            # EWMA yet: the only honest answer is a real one)
            self._emit(events)
            return AdmitDecision("serve", rung=rung,
                                 prior_seq=prior_seq)

    def rollback_seq(self, stream_id: str, frame_seq: Optional[int],
                     prior_seq: Optional[int]) -> None:
        """Un-commit a frame the queue refused with nothing to degrade
        to: the 503'd frame was never answered, so its retry must pass
        the sequence gate.  No-op if a LATER frame already advanced the
        seq (the camera moved on; reviving an old number would re-open
        the gate behind it)."""
        if frame_seq is None:
            return
        with self._lock:
            sess = self._sessions.get(stream_id)
            if sess is not None and sess.seq == int(frame_seq):
                sess.seq = prior_seq

    def degrade_fallback(self, stream_id: str,
                         now: Optional[float] = None
                         ) -> Optional[AdmitDecision]:
        """Degraded answer for a frame the QUEUE just refused
        (queue_full / backpressure): the last rung before a reject —
        a stream with an EWMA gets the EWMA, not the undifferentiated
        reject a stateless client gets.  None when no EWMA exists."""
        if self.policy == "off":
            return None  # the ladder is off: a refusal stays a refusal
        now = self._clock() if now is None else now
        with self._lock:
            sess = self._sessions.get(stream_id)
            if sess is None or sess.count_ewma is None:
                return None
            return self._degrade_locked(sess, now)

    def _degrade_locked(self, sess: StreamSession,
                        now: float) -> AdmitDecision:
        sess.degraded += 1
        staleness = (now - sess.last_served_ts
                     if sess.last_served_ts is not None else None)
        return AdmitDecision(
            "degrade", count=float(sess.count_ewma),
            density=sess.density_ewma,
            staleness_s=(None if staleness is None
                         else round(max(staleness, 0.0), 6)),
            rung=sess.rung)

    # -- the ladder -------------------------------------------------------
    def _load_locked(self, sess: StreamSession) -> Optional[float]:
        """The stream's load score: max of arrival pressure (priced
        per-frame drain cost over the arrival-gap EWMA — > 1 means
        frames arrive faster than the fleet can serve them) and backlog
        pressure (outstanding over the allowance).  None when neither
        signal has evidence yet."""
        pressure = None
        if (self.policy == "priced" and sess.gap_n >= MIN_GAP_INTERVALS
                and sess.gap_ewma > 0.0 and sess.bucket_hw is not None):
            cost_s = self.expected_cost_s(sess.bucket_hw)
            if cost_s is not None:
                pressure = cost_s / sess.gap_ewma
        backlog = sess.outstanding / float(self.outstanding_high)
        if pressure is None:
            return backlog if sess.outstanding > 0 else None
        return max(pressure, backlog)

    def _decide_locked(self, sess: StreamSession, now: float,
                       events: list) -> str:
        if self.policy == "off":
            return STREAM_RUNG_FULL
        load = self._load_locked(sess)
        cur = _RUNGS.index(sess.rung)
        if load is None:
            target = 0
        else:
            up = (self.skip_enter, self.reject_enter)
            down = (self.skip_exit, self.reject_exit)
            target = cur
            while target < 2 and load >= up[target]:
                target += 1
            while target > 0 and load <= down[target - 1]:
                target -= 1
        if target != cur:
            # the flap bound: one rung CHANGE per cooldown, however fast
            # the load oscillates around a band edge (pinned)
            if now - sess.rung_since < self.cooldown_s:
                return sess.rung
            # can-tpu-lint: disable=LOCKHELD(_decide_locked runs only under admit()'s `with self._lock`; the _locked suffix is the contract)
            self._degrade_transitions += 1
            events.append(("stream.degrade",
                           {"stream": sess.stream_id,
                            "rung": _RUNGS[target],
                            "from_rung": _RUNGS[cur],
                            "load": (None if load is None
                                     else round(load, 4)),
                            "outstanding": sess.outstanding,
                            "cooldown_s": self.cooldown_s}))
            sess.rung = _RUNGS[target]
            sess.rung_since = now
        return sess.rung

    # -- completion / accounting -----------------------------------------
    def note_admitted(self, request) -> None:
        """A stream frame entered the queue: count it outstanding, and
        decrement when it resolves (result OR rejection — the request's
        done hook fires exactly once either way)."""
        sid = request.stream_id
        with self._lock:
            sess = self._sessions.get(sid)
            if sess is None:
                return
            sess.outstanding += 1
        request.add_done_hook(lambda _r: self._note_done(sid))

    def _note_done(self, stream_id: str) -> None:
        with self._lock:
            sess = self._sessions.get(stream_id)
            if sess is not None and sess.outstanding > 0:
                sess.outstanding -= 1

    def note_completed(self, stream_id: str, count: float, density,
                       bucket_hw, *, now: Optional[float] = None,
                       replica: Optional[int] = None,
                       token: Optional[str] = None) -> None:
        """A frame came back from the device: fold it into the EWMA /
        trend, refresh the staleness anchor, and pin the stream to the
        serving replica if it has no pin yet (pins MOVE only via
        invalidation — work stealing must not thrash them)."""
        now = self._clock() if now is None else now
        events: List[Tuple[str, dict]] = []
        with self._lock:
            sess = self._sessions.get(stream_id)
            if sess is None:
                return
            prev, t_prev = sess.count_ewma, sess.last_served_ts
            if prev is None:
                sess.count_ewma = float(count)
            else:
                sess.count_ewma = ((1 - COUNT_EWMA_ALPHA) * prev
                                   + COUNT_EWMA_ALPHA * float(count))
                if t_prev is not None and now > t_prev:
                    slope = (sess.count_ewma - prev) / (now - t_prev)
                    sess.trend_per_s = ((1 - COUNT_EWMA_ALPHA)
                                        * sess.trend_per_s
                                        + COUNT_EWMA_ALPHA * slope)
            if density is not None:
                d = np.asarray(density, np.float32)
                if (sess.density_ewma is not None
                        and sess.density_ewma.shape == d.shape):
                    sess.density_ewma = (
                        (1 - COUNT_EWMA_ALPHA) * sess.density_ewma
                        + COUNT_EWMA_ALPHA * d)
                else:
                    sess.density_ewma = d.copy()
            sess.last_served_ts = now
            sess.last_seen_ts = now
            sess.served += 1
            sess.bucket_hw = (int(bucket_hw[0]), int(bucket_hw[1]))
            if sess.pin is None and replica is not None:
                sess.pin = (int(replica), str(token))
            if (self.session_event_every > 0
                    and sess.served % self.session_event_every == 0):
                events.append(("stream.session",
                               {"state": "snapshot",
                                "active": len(self._sessions),
                                "staleness_s": 0.0,
                                **sess.snapshot()}))
        self._emit(events)

    # -- sticky routing ---------------------------------------------------
    def pin_for(self, requests, live_tokens: Dict[int, str],
                now: Optional[float] = None) -> Optional[int]:
        """The replica this assembled batch PREFERS, from its stream
        pins: validate each stream's pin against the live
        ``{index: incarnation token}`` set (re-pinning invalid ones —
        the fault path: quarantine, wedge, scale-down, or a
        resurrection that replaced the incarnation), then majority-vote
        across the batch.  None for a batch with no pinned streams or
        an empty live set."""
        if not live_tokens:
            return None
        now = self._clock() if now is None else now
        events: List[Tuple[str, dict]] = []
        votes: Dict[int, int] = {}
        with self._lock:
            for r in requests:
                sid = getattr(r, "stream_id", None)
                if sid is None:
                    continue
                sess = self._sessions.get(sid)
                if sess is None or sess.pin is None:
                    continue
                idx, tok = sess.pin
                if live_tokens.get(idx) != tok:
                    # the pinned incarnation is gone (dead replica, or
                    # resurrected under a fresh engine): re-pin to a
                    # live one — a pinned stream must never wait behind
                    # a corpse
                    new_idx = repin_target(sid, list(live_tokens))
                    self._repins_total += 1
                    events.append(("stream.repin",
                                   {"stream": sid, "from_replica": idx,
                                    "to_replica": new_idx,
                                    "reason": "replica_lost"}))
                    sess.pin = (new_idx, live_tokens[new_idx])
                    idx = new_idx
                votes[idx] = votes.get(idx, 0) + 1
        self._emit(events)
        if not votes:
            return None
        # majority, smallest index on ties — deterministic per batch
        return min(votes, key=lambda k: (-votes[k], k))

    # -- TTL eviction -----------------------------------------------------
    def _sweep_locked(self, now: float, events: list) -> None:
        if now - self._last_sweep < self._sweep_every:
            return
        # can-tpu-lint: disable=LOCKHELD(_sweep_locked runs only under admit()/evict_idle()'s `with self._lock`; the _locked suffix is the contract)
        self._last_sweep = now
        dead = [sid for sid, s in self._sessions.items()
                if now - s.last_seen_ts >= self.ttl_s]
        for sid in dead:
            sess = self._sessions.pop(sid)
            # can-tpu-lint: disable=LOCKHELD(_sweep_locked runs only under the registry lock, see above)
            self._evicted_total += 1
            events.append(("stream.session",
                           {"state": "evicted",
                            "idle_s": round(now - sess.last_seen_ts, 3),
                            "active": len(self._sessions),
                            **sess.snapshot()}))

    def evict_idle(self, now: Optional[float] = None) -> int:
        """Force a TTL sweep (tests and the stats path); returns the
        number of sessions evicted."""
        now = self._clock() if now is None else now
        events: List[Tuple[str, dict]] = []
        with self._lock:
            before = len(self._sessions)
            self._last_sweep = 0.0
            self._sweep_locked(now, events)
            n = before - len(self._sessions)
        self._emit(events)
        return n

    # -- introspection ----------------------------------------------------
    def get(self, stream_id: str) -> Optional[StreamSession]:
        with self._lock:
            return self._sessions.get(stream_id)

    def active_count(self) -> int:
        with self._lock:
            return len(self._sessions)

    def stats(self) -> dict:
        with self._lock:
            sessions = list(self._sessions.values())
            return {
                "sessions": len(sessions),
                "outstanding": sum(s.outstanding for s in sessions),
                "served_total": sum(s.served for s in sessions),
                "degraded_total": sum(s.degraded for s in sessions),
                "stale_rejects_total": sum(s.stale_rejects
                                           for s in sessions),
                "overload_rejects_total": sum(s.overload_rejects
                                              for s in sessions),
                "rungs": {r: sum(1 for s in sessions if s.rung == r)
                          for r in _RUNGS},
                "repins_total": self._repins_total,
                "evicted_total": self._evicted_total,
                "degrade_transitions": self._degrade_transitions,
            }

    # -- event plumbing ---------------------------------------------------
    def _emit(self, events: List[Tuple[str, dict]]) -> None:
        """Flush queued events OUTSIDE the registry lock where possible
        (callers batch under the lock, then call this; the RLock makes
        the occasional still-locked emit safe, never torn).  One literal
        emit per kind — the EMITKIND lint pins every declared kind to a
        real emitter, and a variable-kind loop would hide all three."""
        if self.telemetry is None:
            events.clear()
            return
        for kind, payload in events:
            if kind == "stream.session":
                self.telemetry.emit("stream.session", **payload)
            elif kind == "stream.degrade":
                self.telemetry.emit("stream.degrade", **payload)
            else:
                self.telemetry.emit("stream.repin", **payload)
        events.clear()
