"""SLO-driven autoscaler: replica count follows the fleet's own gauges.

Production scale is not a fixed N replicas (ROADMAP item 2): it's
replicas that appear in seconds when the queue deepens and leave when the
load does.  This module is the control loop over signals the stack
already exports — outstanding load (the queue's shedding signal), fleet
work-queue depth, the request-latency reservoir's p99 against the
deployment's deadline, and the SLO engine's burn-rate alerts
(``can_tpu_slo_alerting`` on the gauge sink) — acting through
``FleetEngine.add_replica`` / ``remove_replica``, which carry the
rollout-style zero-drop choreography (a new replica warms BEFORE joining
dispatch; a removed one drains its in-flight batch first).

Flap control is structural, not tuned: a scale decision needs the signal
to hold for ``up_consecutive`` / ``down_consecutive`` CONSECUTIVE
evaluations (a one-tick spike buys nothing), the up and down thresholds
are separated (``queue_high`` vs ``queue_low``: between them the fleet
holds), and every action starts a ``cooldown_s`` dead time — a step load
change therefore produces at most one transition, not a limit cycle.
Bounds are hard: never below ``min_replicas`` (and never below 1 live),
never above ``max_replicas`` or the fleet's device universe.

With an AOT bundle loaded on the fleet, a scale-up is executables
deserialised, not compiled — the seconds-to-ready the bench tier records
as ``time_to_first_ready_s``.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Optional


@dataclasses.dataclass
class AutoscalePolicy:
    """The knobs; defaults are deliberately conservative (scale up on
    sustained pressure, down only on sustained idleness)."""

    min_replicas: int = 1
    max_replicas: int = 2
    # outstanding admitted load PER LIVE REPLICA that demands growth /
    # permits shrink (between them: hold)
    queue_high: float = 8.0
    queue_low: float = 1.0
    # latency target: scale up when request p99 exceeds it (None = queue
    # signals only); the CLI wires the request deadline here
    p99_high_s: Optional[float] = None
    up_consecutive: int = 2
    down_consecutive: int = 6
    cooldown_s: float = 10.0
    interval_s: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(f"min_replicas must be >= 1, "
                             f"got {self.min_replicas}")
        if self.max_replicas < self.min_replicas:
            raise ValueError(f"max_replicas ({self.max_replicas}) < "
                             f"min_replicas ({self.min_replicas})")
        if self.queue_low >= self.queue_high:
            raise ValueError(f"queue_low ({self.queue_low}) must be < "
                             f"queue_high ({self.queue_high}) — the gap "
                             f"IS the hysteresis band")


def decide(signals: dict, policy: AutoscalePolicy) -> Optional[str]:
    """Pure per-tick verdict from one signals snapshot: ``"up"``,
    ``"down"``, or None (hold).  Streaks/cooldown/bounds live in the
    Autoscaler — this is just the threshold logic, unit-testable with
    dict literals."""
    live = max(int(signals.get("live", 1)), 1)
    outstanding = float(signals.get("outstanding", 0))
    per_replica = outstanding / live
    p99 = signals.get("p99_s")
    # the latency reservoir is all-time and only decays with NEW
    # traffic: with zero load it replays history forever.  An idle
    # fleet (nothing outstanding, nothing queued) therefore ignores the
    # stale p99 — it must neither block scale-down nor keep voting up.
    idle = outstanding == 0 and int(signals.get("queue_depth", 0)) == 0
    over_latency = (not idle and policy.p99_high_s is not None
                    and p99 is not None and p99 > policy.p99_high_s)
    if (per_replica > policy.queue_high or over_latency
            or signals.get("slo_alerting")):
        return "up"
    under_latency = (idle or policy.p99_high_s is None or p99 is None
                     or p99 < 0.5 * policy.p99_high_s)
    if (per_replica < policy.queue_low and under_latency
            and not signals.get("slo_alerting")
            and int(signals.get("queue_depth", 0)) == 0):
        return "down"
    return None


class Autoscaler:
    """Drives a ``CountService``-fronted ``FleetEngine`` from its gauges.

    ``gauges``: an ``obs.exporter.GaugeSink`` (optional) — the SLO
    engine's ``can_tpu_slo_alerting`` labelled gauges become the burn
    signal.  ``clock`` is injectable for deterministic tests; ``tick()``
    can be driven directly without the thread."""

    def __init__(self, service, policy: AutoscalePolicy, *,
                 gauges=None, clock=time.monotonic):
        fleet = getattr(service, "_fleet", None)
        if fleet is None:
            raise ValueError("Autoscaler needs a fleet-mode CountService "
                             "(serve with --replicas >= 2)")
        self.service = service
        self.fleet = fleet
        self.policy = policy
        self.gauges = gauges
        self._clock = clock
        self._up_streak = 0
        self._down_streak = 0
        self._last_action_ts: Optional[float] = None
        self._actions = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- signals ----------------------------------------------------------
    def _slo_alerting(self) -> bool:
        if self.gauges is None:
            return False
        snap = self.gauges.snapshot()
        return any(g["name"].endswith("_slo_alerting") and g["value"]
                   for g in snap.get("labelled_gauges", ()))

    def observe(self) -> dict:
        """One signals snapshot (the ``decide()`` input)."""
        return {
            "live": self.fleet.live_replicas(),
            "outstanding": self.service.queue.outstanding(),
            "queue_depth": len(self.fleet._queue),
            # via the service: its lock serialises the reservoir read
            # against the recording threads (PR-2's locking rule)
            "p99_s": self.service.latency_percentile(99),
            "slo_alerting": self._slo_alerting(),
        }

    # -- the loop ---------------------------------------------------------
    def tick(self, now: Optional[float] = None) -> Optional[str]:
        """One evaluation; returns the ACTION taken ("up"/"down"/None).
        Streak + cooldown + bounds gate the raw ``decide()`` verdict."""
        now = self._clock() if now is None else now
        sig = self.observe()
        verdict = decide(sig, self.policy)
        self._up_streak = self._up_streak + 1 if verdict == "up" else 0
        self._down_streak = (self._down_streak + 1 if verdict == "down"
                             else 0)
        in_cooldown = (self._last_action_ts is not None
                       and now - self._last_action_ts
                       < self.policy.cooldown_s)
        if in_cooldown:
            return None
        live = sig["live"]
        if (self._up_streak >= self.policy.up_consecutive
                and live < self.policy.max_replicas):
            reason = ("slo_burn" if sig["slo_alerting"] else
                      "p99" if (self.policy.p99_high_s is not None
                                and sig["p99_s"] is not None
                                and sig["p99_s"] > self.policy.p99_high_s)
                      else "queue_depth")
            try:
                self.fleet.add_replica(reason=f"autoscale:{reason}")
            except RuntimeError:
                # no spare device / closed: hold (bounds said yes but the
                # universe said no — max_replicas was set too high)
                return None
            self._after_action(now)
            return "up"
        if (self._down_streak >= self.policy.down_consecutive
                and live > self.policy.min_replicas):
            try:
                self.fleet.remove_replica(reason="autoscale:idle")
            except RuntimeError:
                return None
            self._after_action(now)
            return "down"
        return None

    def _after_action(self, now: float) -> None:
        self._last_action_ts = now
        self._actions += 1
        self._up_streak = 0
        self._down_streak = 0

    def stats(self) -> dict:
        return {"actions": self._actions,
                "up_streak": self._up_streak,
                "down_streak": self._down_streak,
                "min_replicas": self.policy.min_replicas,
                "max_replicas": self.policy.max_replicas,
                "live": self.fleet.live_replicas()}

    # -- lifecycle --------------------------------------------------------
    def start(self) -> "Autoscaler":
        if self._thread is None:
            self._stop.clear()
            t = threading.Thread(target=self._run, daemon=True,
                                 name="can-tpu-autoscaler")
            # can-tpu-lint: disable=LOCKHELD(start runs once on the owner thread before the loop exists)
            self._thread = t
            t.start()
        return self

    def _run(self) -> None:
        from can_tpu.obs import supervised_loop

        supervised_loop(self._stop, self.policy.interval_s, self.tick,
                        "autoscale")

    def close(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10.0)
            # can-tpu-lint: disable=LOCKHELD(close runs on the owner thread after the loop has exited)
            self._thread = None
