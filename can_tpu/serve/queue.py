"""Bounded request queue for online serving: deadlines, structured
rejection, load-shedding backpressure.

Every request admitted to the queue RESOLVES — with a result or with a
typed rejection — never hangs: waiters block on a per-request event with a
timeout derived from the request's deadline, the batcher rejects expired
requests instead of dispatching them, and ``close()`` rejects everything
still queued.  That "no request is ever silently dropped or stuck" rule is
the queue's whole contract; the batching cleverness lives elsewhere.

Backpressure is load shedding with hysteresis over the OUTSTANDING count —
admitted requests not yet resolved (waiting, pending in the batcher, or
executing), maintained via a completion hook on each admitted request.
The waiting-queue length alone can't carry this signal: the batcher drains
the queue eagerly every pump, so depth is transiently ~0 even when the
device is hopelessly behind.  When outstanding crosses ``high_water`` the
queue rejects NEW arrivals (``backpressure``) and keeps rejecting until
outstanding falls to ``low_water`` — without the hysteresis band an
overloaded service oscillates at exactly high_water, admitting every other
request into a backlog it can't clear (each admit then times out later,
which is strictly worse than an instant reject: the client waited its full
deadline for nothing).  ``capacity`` stays the hard bound (``queue_full``)
on the waiting queue itself for the non-shedding configuration
high_water=None.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from typing import List, Optional, Tuple

import numpy as np

# the typed rejection reasons; payload["reason"] of serve.reject events
REJECT_QUEUE_FULL = "queue_full"      # hard capacity bound hit
REJECT_BACKPRESSURE = "backpressure"  # load shedding above high_water
REJECT_DEADLINE = "deadline"          # deadline expired before dispatch
REJECT_SHUTDOWN = "shutdown"          # service closed with the request queued
REJECT_ERROR = "error"                # dispatch raised; message in detail
# stream sessions (serve/streams.py): a duplicate/out-of-order frame of
# a stream's monotonic sequence, and the degradation ladder's last rung
# (arrival rate sustained past drain capacity with nothing left to skip)
REJECT_STALE_FRAME = "stale_frame"
REJECT_STREAM_OVERLOAD = "stream_overload"


class RejectedError(RuntimeError):
    """Raised by ``ServeTicket.result()`` when the request was rejected."""

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(f"request rejected: {reason}"
                         + (f" ({detail})" if detail else ""))
        self.reason = reason
        self.detail = detail


@dataclasses.dataclass
class ServeResult:
    """One completed prediction."""

    count: float                         # predicted head count
    density: Optional[np.ndarray]        # (h, w, 1) masked density, if asked
    bucket_hw: Tuple[int, int]           # static shape the batch ran at
    batch_fill: float                    # valid / total slots of its batch
    latency_s: float                     # submit -> resolve wall time
    # latency breakdown (from the span timestamps; the bench's
    # queue_wait_p95 and the HTTP trace_id ride these)
    queue_wait_s: Optional[float] = None  # submit -> batch assembly start
    device_s: Optional[float] = None      # engine execute wall time
    trace_id: Optional[str] = None        # the request's span-tree id
    # stream sessions (serve/streams.py): a degraded answer was served
    # from the stream's EWMA (the frame-skip rung — no launch ran) and
    # is ``staleness_s`` seconds older than a fresh inference would be.
    # Both default to the non-stream values, so every pre-stream caller
    # reads this dataclass unchanged.
    degraded: bool = False
    staleness_s: Optional[float] = None
    stream_id: Optional[str] = None


class ServeRequest:
    """A queued request plus its resolution rendezvous.

    ``image``: HWC numpy, float32 (host-normalised) or uint8 (device
    normalisation, exactly the offline pipeline's two transfer modes); H, W
    already snapped to the density grid (see ``service.prepare_image``).
    """

    _ids = itertools.count()

    def __init__(self, image: np.ndarray, *, deadline_s: Optional[float],
                 want_density: bool = False, clock=time.monotonic,
                 stream_id: Optional[str] = None,
                 frame_seq: Optional[int] = None):
        self.id = next(self._ids)
        self.image = image
        self.shape = tuple(image.shape[:2])
        self.want_density = bool(want_density)
        # stream sessions (serve/streams.py): which camera this frame
        # belongs to and its monotonic sequence number; None keeps the
        # exact stateless request path
        self.stream_id = stream_id
        self.frame_seq = None if frame_seq is None else int(frame_seq)
        self.t_submit = clock()
        self.deadline_ts = (None if deadline_s is None
                            else self.t_submit + float(deadline_s))
        self._done = threading.Event()
        self._result: Optional[ServeResult] = None
        self._reject: Optional[RejectedError] = None
        # done hooks: each fires exactly once when the request resolves
        # or rejects — the queue tracks outstanding load here, and the
        # stream registry tracks per-stream backlog (two independent
        # observers, so a single slot would drop one)
        self._done_hooks: List = []
        # span plumbing (all in the request's own clock): trace_id is
        # minted by CountService.submit; the batcher stamps the assembly
        # window so the service can price queue-wait vs device time
        self.trace_id: Optional[str] = None
        self.t_assembly: Optional[float] = None  # batch assembly began
        self.t_ready: Optional[float] = None     # padded batch handed off

    def expired(self, now: float) -> bool:
        return self.deadline_ts is not None and now >= self.deadline_ts

    def add_done_hook(self, hook) -> None:
        """Register ``hook(request)`` to fire exactly once at
        resolution/rejection (immediately if already done)."""
        if self._done.is_set():
            hook(self)
            return
        self._done_hooks.append(hook)

    def _fire_done(self) -> None:
        hooks, self._done_hooks = self._done_hooks, []
        for hook in hooks:
            hook(self)

    def resolve(self, result: ServeResult) -> None:
        self._result = result
        self._done.set()
        self._fire_done()

    def reject(self, reason: str, detail: str = "") -> None:
        self._reject = RejectedError(reason, detail)
        self._done.set()
        self._fire_done()

    @property
    def done(self) -> bool:
        return self._done.is_set()

    def wait(self, timeout: Optional[float] = None) -> ServeResult:
        """Block for the outcome; raises ``RejectedError`` on rejection or
        on wait timeout (so a caller polling a dead service gets a typed
        answer, not a hang)."""
        if not self._done.wait(timeout):
            raise RejectedError(REJECT_DEADLINE,
                                f"no result within {timeout}s wait")
        if self._reject is not None:
            raise self._reject
        return self._result


class BoundedRequestQueue:
    """Thread-safe FIFO with capacity, deadline hygiene, and shedding.

    Producers call ``offer`` (admits or instantly rejects the request —
    never blocks: blocking admission would just move the timeout from the
    client's deadline to a hidden lock); the single batcher thread calls
    ``drain``/``wait_nonempty``.
    """

    def __init__(self, capacity: int = 64, *,
                 high_water: Optional[int] = None,
                 low_water: Optional[int] = None,
                 clock=time.monotonic):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self.high_water = None if high_water is None else int(high_water)
        if self.high_water is not None and self.high_water < 1:
            raise ValueError(f"high_water ({high_water}) must be >= 1")
        if low_water is None:
            low_water = (self.high_water // 2 if self.high_water is not None
                         else None)
        self.low_water = low_water
        if (self.high_water is not None
                and not 0 <= self.low_water < self.high_water):
            raise ValueError(f"low_water ({low_water}) must be in "
                             f"[0, high_water={high_water})")
        self._clock = clock
        self._lock = threading.Lock()
        self._nonempty = threading.Condition(self._lock)
        self._items: List[ServeRequest] = []
        self._outstanding = 0  # admitted, not yet resolved/rejected
        self._shedding = False
        self._closed = False

    def depth(self) -> int:
        with self._lock:
            return len(self._items)

    def outstanding(self) -> int:
        """Admitted requests not yet resolved (waiting + pending in the
        batcher + executing) — the load signal shedding keys on."""
        with self._lock:
            return self._outstanding

    @property
    def shedding(self) -> bool:
        return self._shedding

    def _request_done(self, _request) -> None:
        with self._lock:
            self._outstanding -= 1
            if (self._shedding and self.low_water is not None
                    and self._outstanding <= self.low_water):
                self._shedding = False

    def offer(self, request: ServeRequest, *,
              reject: bool = True) -> Optional[str]:
        """Admit ``request`` or reject it; returns the reject reason (also
        recorded on the request) or None when admitted.

        ``reject=False`` returns the reason WITHOUT rejecting the
        request — the stream path's degrade-instead-of-drown hook: a
        refused stream frame falls back to its session EWMA (the caller
        resolves or rejects it, exactly once either way)."""
        with self._lock:
            if self._closed:
                reason = REJECT_SHUTDOWN
            elif len(self._items) >= self.capacity:
                reason = REJECT_QUEUE_FULL
            else:
                if (self.high_water is not None and not self._shedding
                        and self._outstanding >= self.high_water):
                    self._shedding = True
                reason = REJECT_BACKPRESSURE if self._shedding else None
            if reason is None:
                request.add_done_hook(self._request_done)
                self._outstanding += 1
                self._items.append(request)
                self._nonempty.notify()
                return None
        if reject:
            request.reject(reason, f"outstanding {self.outstanding()}")
        return reason

    def wait_nonempty(self, timeout: Optional[float]) -> bool:
        """Block until an item is queued, the queue closes, or ``timeout``
        elapses; True when items are available."""
        with self._lock:
            if not self._items and not self._closed:
                self._nonempty.wait(timeout)
            return bool(self._items)

    def drain(self) -> Tuple[List[ServeRequest], List[ServeRequest]]:
        """Take every queued request, split into (live, expired).  Expired
        requests are NOT rejected here — the caller owns the rejection so
        it can also emit the telemetry event.  Draining does NOT end
        shedding: the requests are still outstanding (the batcher merely
        moved them closer to the device); only resolution drains load."""
        with self._lock:
            items, self._items = self._items, []
        now = self._clock()
        live = [r for r in items if not r.expired(now)]
        expired = [r for r in items if r.expired(now)]
        return live, expired

    def close(self) -> List[ServeRequest]:
        """Stop admissions; returns (without rejecting) whatever was still
        queued so the owner can reject with telemetry."""
        with self._lock:
            self._closed = True
            items, self._items = self._items, []
            self._nonempty.notify_all()
        return items
