"""Program-contract auditor: structured invariants over lowered StableHLO.

The stack ships ten compiled program families (default / bf16 / syncBN
train steps — the syncBN pair on both the full 2x4 mesh and the elastic
dp′=1 shrunk mesh — the eval step, and the f32/bf16/int8 serve predicts)
whose
correctness-critical STRUCTURE — how many collectives, what operand
shapes, which dtypes, whether params live quantized in HBM — used to be
guarded by scattered per-test regexes.  This module lowers each canonical
program once (through the same ``jit_for`` hooks the cost ledger uses,
``obs.costs.resolve_jit``) and checks machine-readable facts against the
committed ``PROGRAM_CONTRACTS.json``:

* **collective counts** per op (``all_reduce`` / ``all_gather`` /
  ``reduce_scatter`` / ``collective_permute`` / ``all_to_all``) — a
  deleted or duplicated psum changes program semantics silently;
* **all_reduce operand shapes** (exact multiset) and the packed-moments
  invariant: one-pass syncBN issues exactly ONE ``(2C+1,)`` packed
  all_reduce per BN layer (ops/bn_moments.py) — the PR-7 win the old
  test could only state as "strictly fewer";
* **dtype discipline** — zero f64 ops in any bf16/f32 program (an f64
  accumulator sneaking in runs ~10x slow on TPU and doubles HBM);
* **no host round-trips** — zero host callbacks / infeed / outfeed;
* **int8 placement** — the int8 predict must take int8 parameter tensors
  (dequant INSIDE the program, HBM holds int8; a hoisted dequant would
  quietly quadruple parameter traffic);
* **flop/byte budgets** — XLA ``cost_analysis()`` within a per-program
  noise band of the contract (bench_compare discipline: cost is
  deterministic, so both directions trip — up is bloat, down is lost
  work).

Facts come from text because text is what XLA was actually given: the
byte-identity pin (tests/test_perf.py) already proves lowering is
deterministic, so exact structural counts are stable, not flaky.

Contract updates are intentional: ``--update`` writes a FRESH contract
to a separate path (``PROGRAM_CONTRACTS_local.json`` by default — the
PR-6/7/8 no-self-overwrite rule), which a human diffs and commits.  A
missing or torn contract is an audit FAILURE, never a pass.

CLI::

    python -m can_tpu.analysis.hlo_audit                  # fast: structure
    python -m can_tpu.analysis.hlo_audit --full           # + cost bands
    python -m can_tpu.analysis.hlo_audit --update OUT     # regenerate

Needs >= 8 devices for the syncBN programs (CPU:
``XLA_FLAGS=--xla_force_host_platform_device_count=8``, as conftest.py
and tools/ci_lint.sh set up).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Sequence

COLLECTIVE_OPS = ("all_reduce", "all_gather", "reduce_scatter",
                  "collective_permute", "all_to_all")
CONTRACT_VERSION = 1
DEFAULT_CONTRACT = "PROGRAM_CONTRACTS.json"
DEFAULT_UPDATE_OUT = "PROGRAM_CONTRACTS_local.json"

# the canonical audit configuration: small but REAL — the full CANNet
# model at the smallest (h, w) the dp=2 x sp=4 mesh legally shards
# (h % (8*sp) == 0 and >= 2 feature rows per shard)
AUDIT_HW = (64, 64)
AUDIT_DP, AUDIT_SP = 2, 4
# the RE-FORMED mesh after an elastic shrink loses half the pod
# (parallel/elastic.py): dp 2 -> 1 at the same sp.  The dp′ programs are
# contracted exactly like the full-mesh ones, so an elastic transition
# cannot silently change the compiled program's collective structure —
# the re-formed world's psums/packing are pinned, not assumed.
AUDIT_DP_SHRUNK = 1
# the serve sub-batch menu programs are pinned from ONE registry
# (can_tpu/sched.default_serve_menu — the same call warmup, the AOT bake,
# and the batcher's covers derive from): for each serve dtype, one
# contracted program per menu size at this max_batch.  A menu changed
# outside the registry shows up as a registry/contract mismatch and
# turns the audit red (the r14 mutation test).
AUDIT_SERVE_MAX_BATCH = 2
# ceiling on the total contracted program count (enforced when the
# committed contract carries "program_budget"): program families — and
# the serve menu especially — must grow by DECISION, not accretion
DEFAULT_PROGRAM_BUDGET = 16


class AuditError(Exception):
    """The AUDIT RUN is invalid (absent/torn contract, no devices) —
    distinct from 'a program violates its contract'."""


# -- facts ----------------------------------------------------------------
@dataclasses.dataclass
class ProgramFacts:
    """What one lowered program structurally IS."""

    name: str
    collectives: Dict[str, int]
    all_reduce_shapes: List[str]   # sorted operand types, e.g. "129xf32"
    f64_ops: int
    host_calls: int
    int8_params: int
    flops: Optional[float] = None
    bytes_accessed: Optional[float] = None

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


# one all_reduce op: quoted form with a reduction region, closed by
# `}) : (input types) -> ...`; regions hold only the tiny combiner, so
# the non-greedy span is safe
_AR_RE = re.compile(
    r'"stablehlo\.all_reduce"\(.*?\}\)\s*:\s*\(([^)]*)\)', re.S)
_TENSOR_RE = re.compile(r"tensor<([^>]*)>")
_MAIN_RE = re.compile(r"func\.func public @main\((.*?)\)\s*->", re.S)
_I8_ARG_RE = re.compile(r"%arg\d+: tensor<(?:\d+x)*i8>")
_HOST_RE = re.compile(
    r"custom_call\s*@\w*(?:callback|infeed|outfeed|host_)\w*"
    r"|stablehlo\.(?:infeed|outfeed)\b")
_PACKED_RE = re.compile(r"^(\d+)xf32$")


def collective_counts(text: str) -> Dict[str, int]:
    """Per-collective op counts in a StableHLO module text.  (Each op
    instance names its kind exactly once — combiner regions contain only
    ``add``/``max`` arithmetic.)"""
    return {op: len(re.findall(rf"stablehlo\.{op}\b", text))
            for op in COLLECTIVE_OPS}


def all_reduce_operand_shapes(text: str) -> List[str]:
    """Sorted operand types of every all_reduce (a packed one-pass BN
    moment round shows up here as its ``(2C+1,)`` f32 vector)."""
    shapes: List[str] = []
    for m in _AR_RE.finditer(text):
        shapes.extend(_TENSOR_RE.findall(m.group(1)))
    return sorted(shapes)


def count_f64_ops(text: str) -> int:
    return len(re.findall(r"f64", text))


def count_host_calls(text: str) -> int:
    return len(_HOST_RE.findall(text))


def count_int8_params(text: str) -> int:
    """int8 tensors among @main's parameters — the 'int8 weights live in
    HBM, dequant runs in-program' placement receipt."""
    m = _MAIN_RE.search(text)
    sig = m.group(1) if m else text
    return len(_I8_ARG_RE.findall(sig))


def facts_from_text(name: str, text: str, *,
                    cost: Optional[tuple] = None) -> ProgramFacts:
    flops = byts = None
    if cost is not None:
        flops, byts = cost
    return ProgramFacts(
        name=name,
        collectives=collective_counts(text),
        all_reduce_shapes=all_reduce_operand_shapes(text),
        f64_ops=count_f64_ops(text),
        host_calls=count_host_calls(text),
        int8_params=count_int8_params(text),
        flops=flops, bytes_accessed=byts)


def packed_bn_reduce_count(all_reduce_shapes: Sequence[str],
                           bn_channels: Sequence[int]) -> int:
    """How many all_reduce operands are packed one-pass BN moment
    vectors: 1-D f32 of size 2C+1 for one of the model's BN widths."""
    packed_sizes = {2 * int(c) + 1 for c in bn_channels}
    n = 0
    for s in all_reduce_shapes:
        m = _PACKED_RE.match(s)
        if m and int(m.group(1)) in packed_sizes:
            n += 1
    return n


# -- invariant checks -----------------------------------------------------
@dataclasses.dataclass(frozen=True)
class Violation:
    program: str
    invariant: str   # e.g. "collectives.all_reduce", "forbid_f64"
    expected: object
    actual: object
    detail: str = ""

    def render(self) -> str:
        extra = f" — {self.detail}" if self.detail else ""
        return (f"{self.program}: {self.invariant}: expected "
                f"{self.expected}, got {self.actual}{extra}")


def check_facts(entry: dict, facts: ProgramFacts) -> List[Violation]:
    """One program's contract entry vs its fresh facts."""
    v: List[Violation] = []
    ec = entry.get("collectives")
    if ec is not None:
        for op in sorted(set(ec) | set(facts.collectives)):
            exp = int(ec.get(op, 0))
            got = int(facts.collectives.get(op, 0))
            if exp != got:
                v.append(Violation(
                    facts.name, f"collectives.{op}", exp, got,
                    "a collective was deleted" if got < exp
                    else "a collective was added"))
    es = entry.get("all_reduce_shapes")
    if es is not None:
        exp, got = sorted(es), sorted(facts.all_reduce_shapes)
        if exp != got:
            from collections import Counter

            ce, cg = Counter(exp), Counter(got)
            missing = sorted((ce - cg).elements())
            added = sorted((cg - ce).elements())
            v.append(Violation(
                facts.name, "all_reduce_shapes",
                f"{len(exp)} operands", f"{len(got)} operands",
                f"missing={missing[:6]} added={added[:6]}"))
    if entry.get("bn_channels") is not None:
        exp = int(entry.get("packed_bn_reduces",
                            len(entry["bn_channels"])))
        got = packed_bn_reduce_count(facts.all_reduce_shapes,
                                     entry["bn_channels"])
        if exp != got:
            v.append(Violation(
                facts.name, "packed_bn_reduces", exp, got,
                "one packed (2C+1,) all_reduce per BN layer"))
    if entry.get("forbid_f64") and facts.f64_ops:
        v.append(Violation(facts.name, "forbid_f64", 0, facts.f64_ops,
                           "f64 ops in a bf16/f32 program (accidental "
                           "upcast?)"))
    if entry.get("forbid_host_calls") and facts.host_calls:
        v.append(Violation(facts.name, "forbid_host_calls", 0,
                           facts.host_calls,
                           "host callback/infeed in a compiled program"))
    if entry.get("require_int8_params") and facts.int8_params == 0:
        v.append(Violation(
            facts.name, "require_int8_params", ">= 1", 0,
            "no int8 parameter tensors: the dequant was hoisted out of "
            "the jit — HBM now holds f32 weights"))
    elif (entry.get("int8_params") is not None
          and facts.int8_params != int(entry["int8_params"])):
        v.append(Violation(facts.name, "int8_params",
                           int(entry["int8_params"]), facts.int8_params))
    noise = float(entry.get("cost_noise_pct", 10.0)) / 100.0
    for key in ("flops", "bytes_accessed"):
        exp = entry.get(key)
        got = getattr(facts, key)
        if exp is None or got is None:
            continue  # fast mode / non-reporting backend: no cost check
        if not (exp * (1 - noise) <= got <= exp * (1 + noise)):
            v.append(Violation(
                facts.name, f"cost.{key}",
                f"{exp:.6g} ±{noise:.0%}", f"{got:.6g}",
                "compiled cost is deterministic: up = bloat, down = "
                "lost work"))
    return v


def render_diff(violations: Sequence[Violation]) -> str:
    if not violations:
        return "program-contract audit: OK"
    lines = [f"program-contract audit: {len(violations)} violation(s)"]
    lines += [f"  {v.render()}" for v in violations]
    lines.append("  (intentional change? regenerate with `python -m "
                 "can_tpu.analysis.hlo_audit --update "
                 f"{DEFAULT_UPDATE_OUT}`, diff, and commit)")
    return "\n".join(lines)


# -- the canonical program registry ---------------------------------------
_LOWERED_CACHE: dict = {}
_COST_CACHE: dict = {}


def _ensure_devices(n: int):
    import jax

    devs = jax.devices()
    if len(devs) < n:
        raise AuditError(
            f"the syncBN audit programs shard over {n} devices; this "
            f"backend has {len(devs)}.  On CPU set XLA_FLAGS="
            f"--xla_force_host_platform_device_count={n} before jax "
            f"initialises (conftest.py / tools/ci_lint.sh do)")
    return devs


def _audit_batch(b: int, dtype=None):
    import numpy as np

    h, w = AUDIT_HW
    dtype = np.float32 if dtype is None else dtype
    return {
        "image": np.zeros((b, h, w, 3), dtype),
        "dmap": np.zeros((b, h // 8, w // 8, 1), np.float32),
        "pixel_mask": np.ones((b, h // 8, w // 8, 1), np.float32),
        "sample_mask": np.ones((b,), np.float32),
    }


def _train_setup(batch_norm: bool):
    import jax

    from can_tpu.models import cannet_init
    from can_tpu.train import (
        create_train_state,
        make_lr_schedule,
        make_optimizer,
    )

    params = cannet_init(jax.random.key(0), batch_norm=batch_norm)
    opt = make_optimizer(make_lr_schedule(1e-3))
    if batch_norm:
        from can_tpu.models.cannet import init_batch_stats

        state = create_train_state(params, opt, init_batch_stats(params))
    else:
        state = create_train_state(params, opt)
    return params, opt, state


def _lower_train_default(compute_dtype=None):
    import jax

    from can_tpu.models import cannet_apply
    from can_tpu.train import make_train_step

    _, opt, state = _train_setup(batch_norm=False)
    step = jax.jit(make_train_step(cannet_apply, opt,
                                   compute_dtype=compute_dtype))
    return step.lower(state, _audit_batch(1))


def _lower_sp_syncbn(impl: str, dp: int = AUDIT_DP):
    """The dp x sp syncBN train step.  ``dp=AUDIT_DP_SHRUNK`` lowers the
    program an elastic shrink RE-FORMS (same sp, half the pod, lr peak
    follows the linear rule) — audited under its own contract entry so
    the transition's collective structure is an invariant, not an
    accident."""
    from can_tpu.ops.bn_moments import make_bn_ops
    from can_tpu.parallel.mesh import make_mesh
    from can_tpu.parallel.spatial import make_sp_train_step
    from can_tpu.train import make_lr_schedule, make_optimizer

    devs = _ensure_devices(dp * AUDIT_SP)
    mesh = make_mesh(devs[:dp * AUDIT_SP], dp=dp, sp=AUDIT_SP)
    opt = make_optimizer(make_lr_schedule(1e-3, world_size=dp))
    _, _, state = _train_setup(batch_norm=True)
    step = make_sp_train_step(opt, mesh, AUDIT_HW, donate=False,
                              bn_ops=make_bn_ops(impl))
    return step.lower(state, _audit_batch(dp))


def _lower_eval():
    import jax

    from can_tpu.models import cannet_apply
    from can_tpu.train import make_eval_step

    params, _, _ = _train_setup(batch_norm=False)
    step = jax.jit(make_eval_step(cannet_apply))
    batch = _audit_batch(1)
    return step.lower(params, batch)


def serve_predict_lowerable(serve_dtype: str,
                            batch_size: int = AUDIT_SERVE_MAX_BATCH):
    """(jitted predict, lowering args) for a fresh ServeEngine in this
    mode at one menu batch size — via the same ``jit_for`` hook the cost
    ledger uses, so the audited program IS the one a replica executes.
    Exposed (not just used by the registry) so the mutation tests can
    lower variants — e.g. feeding PRE-dequantized params to simulate a
    hoisted dequant."""
    import jax
    import numpy as np

    from can_tpu.data.batching import pad_batch
    from can_tpu.models import cannet_init
    from can_tpu.obs.costs import resolve_jit
    from can_tpu.serve.engine import ServeEngine, _batch_dict

    params = cannet_init(jax.random.key(0))
    eng = ServeEngine(params, serve_dtype=serve_dtype)
    h, w = AUDIT_HW
    img = np.zeros((h, w, 3), np.float32)
    dm = np.zeros((h // 8, w // 8, 1), np.float32)
    batch = _batch_dict(pad_batch([(img, dm)], (h, w), int(batch_size),
                                  [False], 8))
    args = (eng.params, batch, eng.batch_stats)
    return resolve_jit(eng._predict, args), args


def _lower_serve(serve_dtype: str,
                 batch_size: int = AUDIT_SERVE_MAX_BATCH):
    fn, args = serve_predict_lowerable(serve_dtype, batch_size)
    return fn.lower(*args)


def serve_menu_sizes():
    """The audited serve batch sizes — THE registry call
    (can_tpu/sched.default_serve_menu at the audit's max_batch).  The
    contracted serve program set derives from this at audit time, so a
    menu change anywhere (including after import) diverges from the
    committed contract and fails the audit."""
    from can_tpu.sched import default_serve_menu

    return default_serve_menu(AUDIT_SERVE_MAX_BATCH)


SERVE_DTYPES_AUDITED = ("f32", "bf16", "int8")


def serve_program_name(serve_dtype: str, size: int) -> str:
    """Top menu size keeps the historical name (``serve_predict_f32``);
    the sub-batch menu sizes are suffixed (``serve_predict_f32_b1``)."""
    base = f"serve_predict_{serve_dtype}"
    return base if size == AUDIT_SERVE_MAX_BATCH else f"{base}_b{size}"


def expected_serve_programs() -> Dict[str, object]:
    """name -> builder for every (dtype, menu size) serve program, from
    the LIVE registry menu."""
    return {serve_program_name(dt, s):
            (lambda dt=dt, s=s: _lower_serve(dt, s))
            for dt in SERVE_DTYPES_AUDITED
            for s in serve_menu_sizes()}


PROGRAM_BUILDERS = {
    "train_step_default": lambda: _lower_train_default(None),
    "train_step_bf16": lambda: _lower_train_default("bfloat16"),
    "train_step_syncbn_onepass": lambda: _lower_sp_syncbn("onepass"),
    "train_step_syncbn_twopass": lambda: _lower_sp_syncbn("twopass"),
    # the elastic dp′ mesh (shrink 2x4 -> 1x4): the programs training
    # resumes on after losing half the pod
    "train_step_syncbn_onepass_dp1": lambda: _lower_sp_syncbn(
        "onepass", dp=AUDIT_DP_SHRUNK),
    "train_step_syncbn_twopass_dp1": lambda: _lower_sp_syncbn(
        "twopass", dp=AUDIT_DP_SHRUNK),
    "eval_step_f32": _lower_eval,
    # the serve menu programs, from the one registry
    **expected_serve_programs(),
}


def bn_channels() -> List[int]:
    """Every BN layer's channel width, from the model config — the
    packed-psum sizes are 2C+1 of these."""
    from can_tpu.models.cannet import BACKEND_CFG, FRONTEND_CFG

    return ([int(v) for v in FRONTEND_CFG if v != "M"]
            + [int(v) for v in BACKEND_CFG])


def lower_program(name: str):
    """Lower (and memoise) one canonical program."""
    if name not in PROGRAM_BUILDERS:
        raise AuditError(f"unknown program {name!r} (known: "
                         f"{', '.join(sorted(PROGRAM_BUILDERS))})")
    if name not in _LOWERED_CACHE:
        _LOWERED_CACHE[name] = PROGRAM_BUILDERS[name]()
    return _LOWERED_CACHE[name]


def _cost_of_lowered(lowered) -> Optional[tuple]:
    """(flops, bytes accessed) via compile().cost_analysis(); None when
    the backend doesn't report.  Same key handling as obs/costs.py."""
    try:
        ca = lowered.compile().cost_analysis()
    except Exception as e:  # non-reporting backend: cost checks skip
        print(f"[hlo_audit] cost_analysis unavailable "
              f"({type(e).__name__}: {e}); structure-only", flush=True)
        return None
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    if not ca:
        return None
    flops = ca.get("flops")
    byts = ca.get("bytes accessed")
    flops = float(flops) if flops is not None and flops > 0 else None
    byts = float(byts) if byts is not None and byts > 0 else None
    if flops is None and byts is None:
        return None
    return flops, byts


def program_facts(name: str, *, with_cost: bool = False) -> ProgramFacts:
    lowered = lower_program(name)
    cost = None
    if with_cost:
        if name not in _COST_CACHE:
            _COST_CACHE[name] = _cost_of_lowered(lowered)
        cost = _COST_CACHE[name]
    return facts_from_text(name, lowered.as_text(), cost=cost)


# -- contract I/O + audit -------------------------------------------------
def load_contract(path: str) -> dict:
    """A missing, torn, or wrong-version contract is an AUDIT FAILURE:
    'could not read the invariants' must never read as 'no invariants,
    pass'."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError as e:
        raise AuditError(
            f"contract {path} does not exist — the committed "
            f"PROGRAM_CONTRACTS.json is part of the tree; regenerate "
            f"with --update if it was deleted intentionally") from e
    except json.JSONDecodeError as e:
        raise AuditError(f"contract {path} is not valid JSON (torn "
                         f"write?): {e}") from e
    if (not isinstance(doc, dict)
            or doc.get("version") != CONTRACT_VERSION
            or not isinstance(doc.get("programs"), dict)
            or not doc["programs"]):
        raise AuditError(
            f"contract {path}: expected {{'version': {CONTRACT_VERSION}, "
            f"'programs': {{name: entry, ...}}}} with >= 1 program")
    return doc


def audit_programs(contract: dict,
                   names: Optional[Sequence[str]] = None,
                   *, with_cost: bool = False
                   ) -> List[Violation]:
    """Lower every contracted program fresh and check it.  A contract
    entry whose program no longer exists in the registry is itself a
    violation (contracts can't rot), and — on a full audit — so is a
    registry program with NO contract entry (a new program family must
    not ship unguarded)."""
    violations: List[Violation] = []
    if names is None:
        for name in sorted(set(PROGRAM_BUILDERS) - set(contract["programs"])):
            violations.append(Violation(
                name, "program_contracted", "a contract entry", "absent",
                "the registry builds a program the contract does not "
                "guard — add it via --update"))
        # the serve menu is pinned from ONE registry call
        # (sched.default_serve_menu): the LIVE menu's program set must
        # equal both the import-time registry and the contract — a menu
        # changed outside the registry path (or after import) turns the
        # audit red here, with the divergent sizes named
        live = sorted(expected_serve_programs())
        contracted = sorted(n for n in contract["programs"]
                            if n.startswith("serve_predict"))
        registered = sorted(n for n in PROGRAM_BUILDERS
                            if n.startswith("serve_predict"))
        if live != contracted or live != registered:
            violations.append(Violation(
                "<serve menu>", "serve_menu_registry",
                contracted, live,
                "the serve sub-batch menu diverged from the committed "
                "contract — menu changes go through "
                "sched.default_serve_menu + --update, never around them"))
        budget = contract.get("program_budget")
        if budget is not None and len(PROGRAM_BUILDERS) > int(budget):
            violations.append(Violation(
                "<registry>", "program_budget", f"<= {int(budget)}",
                len(PROGRAM_BUILDERS),
                "the registry grew past the committed program-count "
                "budget — raise it intentionally via --update + commit"))
    for name in (sorted(contract["programs"]) if names is None
                 else names):
        entry = contract["programs"].get(name)
        if entry is None:
            raise AuditError(f"program {name!r} is not in the contract")
        if name not in PROGRAM_BUILDERS:
            violations.append(Violation(
                name, "program_exists", "a registry builder", "absent",
                "contract names a program the registry no longer builds"))
            continue
        violations.extend(
            check_facts(entry, program_facts(name, with_cost=with_cost)))
    return violations


def build_contract(names: Optional[Sequence[str]] = None, *,
                   with_cost: bool = True) -> dict:
    """A fresh contract document from the live registry (the --update
    path; a human diffs and commits the result)."""
    import jax

    programs: dict = {}
    chans = bn_channels()
    for name in (sorted(PROGRAM_BUILDERS) if names is None else names):
        facts = program_facts(name, with_cost=with_cost)
        entry: dict = {
            "collectives": facts.collectives,
            "all_reduce_shapes": facts.all_reduce_shapes,
            "forbid_f64": True,
            "forbid_host_calls": True,
            "flops": facts.flops,
            "bytes_accessed": facts.bytes_accessed,
            "cost_noise_pct": 10,
        }
        if "syncbn" in name:
            entry["bn_channels"] = chans
            entry["packed_bn_reduces"] = packed_bn_reduce_count(
                facts.all_reduce_shapes, chans)
        if "int8" in name:
            entry["require_int8_params"] = True
            entry["int8_params"] = facts.int8_params
        programs[name] = entry
    return {
        "version": CONTRACT_VERSION,
        "program_budget": DEFAULT_PROGRAM_BUDGET,
        "generated": {
            "jax": jax.__version__,
            "backend": jax.devices()[0].platform,
            "image_hw": list(AUDIT_HW),
            "mesh": {"dp": AUDIT_DP, "sp": AUDIT_SP},
            "serve_menu": list(serve_menu_sizes()),
            "with_cost": bool(with_cost),
        },
        "programs": programs,
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    import argparse

    ap = argparse.ArgumentParser(
        description="Audit the canonical compiled programs against "
                    "PROGRAM_CONTRACTS.json")
    ap.add_argument("--contract", default=DEFAULT_CONTRACT)
    ap.add_argument("--full", action="store_true",
                    help="also compile each program and check the "
                         "flop/byte bands (slower)")
    ap.add_argument("--programs", default=None,
                    help="comma-separated subset")
    ap.add_argument("--update", nargs="?", const=DEFAULT_UPDATE_OUT,
                    default=None, metavar="OUT",
                    help=f"write a FRESH contract to OUT (default "
                         f"{DEFAULT_UPDATE_OUT}) instead of auditing")
    ap.add_argument("--force", action="store_true",
                    help="allow --update to overwrite the --contract "
                         "path itself")
    args = ap.parse_args(argv)
    names = (args.programs.split(",") if args.programs else None)

    if args.update is not None:
        if (os.path.abspath(args.update) == os.path.abspath(args.contract)
                and not args.force):
            print(f"refusing to overwrite the committed contract "
                  f"{args.contract} in place (the gate would then "
                  f"compare the fresh run against itself and pass "
                  f"vacuously) — write to {DEFAULT_UPDATE_OUT}, diff, "
                  f"and commit; or pass --force")
            return 2
        doc = build_contract(names, with_cost=True)
        with open(args.update, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {len(doc['programs'])} program contracts to "
              f"{args.update}")
        return 0

    try:
        contract = load_contract(args.contract)
        violations = audit_programs(contract, names,
                                    with_cost=args.full)
    except AuditError as e:
        print(f"hlo_audit error: {e}")
        return 2
    print(render_diff(violations))
    n = len(contract["programs"] if names is None else names)
    if not violations:
        print(f"{n} program(s) match {args.contract}"
              f" ({'structure+cost' if args.full else 'structure'})")
    return 1 if violations else 0


if __name__ == "__main__":
    raise SystemExit(main())
