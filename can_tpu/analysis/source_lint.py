"""JAX/concurrency-aware AST linter for the can_tpu source tree.

Generic linters know nothing about the failure modes that actually bite
this stack: a stray ``.item()`` in the step loop serialises the pipeline
per batch, an ``except Exception: pass`` turns a dead telemetry sink into
a silent data loss, an ``.emit("kind")`` literal that skips ``EVENT_KINDS``
drops a whole event family from the report/gauge layer, and an attribute
write outside the owning lock is a race the tests only catch when the
scheduler feels like it.  Each PR-7/8 review round re-found one of these
by hand; this module makes them a machine check.

Rules (each finding carries its rule id):

* ``HOSTSYNC``  — host-sync calls in HOT-PATH modules: ``.item()``,
  ``.block_until_ready()``, ``np.asarray(...)``, ``float(<expr>)``.
  Every one forces a device→host fetch (or hints one); on the step/serve
  path that is a pipeline stall.  Deliberate fences carry a pragma.
* ``TIMETIME``  — ``time.time()`` in hot-path modules: device timing
  without a fence measures dispatch, not execution (and wall clocks
  step); hot paths use ``perf_counter`` around a fenced fetch.
* ``SWALLOW``   — ``except Exception`` / bare ``except`` whose handler
  neither re-raises, nor uses the bound exception, nor logs (print /
  ``log``/``warn``/``error``/``exception``/``debug``/``info`` /
  ``.emit``): the error evaporates.  Tree-wide.
* ``EMITKIND``  — ``.emit("<literal>")`` kinds vs ``obs/bus.py
  EVENT_KINDS``, BOTH directions (an undeclared kind silently misses
  report/gauge coverage; a declared-never-emitted kind is dead weight).
* ``LOCKHELD``  — in ``serve/`` classes that declare a lock attribute
  (``threading.Lock/RLock/Condition`` assigned in ``__init__``, or an
  attribute literally named ``lock``/``_lock``), every ``self.<attr>``
  write outside ``__init__`` must happen under ``with self.<some
  declared lock>``.  Single-writer lifecycle flags carry a pragma
  stating the invariant that makes them safe.
* ``F64LIT``    — ``float64`` literals (``np/jnp.float64`` or the string
  ``"float64"``) in DEVICE modules: f64 runs at 1/10+ rate on TPU and
  usually means an accidental upcast.  (Host-side density generation in
  ``data/`` legitimately uses f64 and is out of scope.)

Suppression: ``# can-tpu-lint: disable=RULE(reason)`` on the finding's
line or the line above.  The reason is REQUIRED — a pragma without one,
or naming an unknown rule, is a usage error, not a suppression.  A
committed baseline (``tools/lint_baseline.json``) may carry findings the
tree accepts without touching the source; a baselined finding that no
longer fires is an ERROR (baselines can't rot into dead weight).

This module deliberately imports neither jax nor anything that does —
linting the tree must cost milliseconds and run anywhere.
"""

from __future__ import annotations

import ast
import dataclasses
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

RULES: Dict[str, str] = {
    "HOSTSYNC": "host-sync call (.item/.block_until_ready/np.asarray/"
                "float) in a hot-path module",
    "TIMETIME": "time.time() in a hot-path module (unfenced device "
                "timing; use perf_counter around a fenced fetch)",
    "SWALLOW": "except Exception swallowed: no raise, no use of the "
               "exception, no logging",
    "EMITKIND": ".emit(kind) literal not declared in EVENT_KINDS (or a "
                "declared kind with no emitter)",
    "LOCKHELD": "attribute write outside `with self.<lock>` in a "
                "lock-declaring serve class",
    "F64LIT": "float64 literal in a device-code module",
}

# Module scopes, as repo-relative posix prefixes (a trailing "/" scopes a
# directory).  Hot path = code on the per-step / per-request critical
# path, where one stray sync costs throughput.
HOT_PATH_MODULES: Tuple[str, ...] = (
    "can_tpu/train/loop.py",
    "can_tpu/train/steps.py",
    "can_tpu/data/prefetch.py",
    "can_tpu/serve/engine.py",
    "can_tpu/serve/batcher.py",
    "can_tpu/serve/fleet.py",
    "can_tpu/parallel/spatial.py",
    "can_tpu/parallel/data_parallel.py",
    "can_tpu/models/cannet.py",
    "can_tpu/ops/",
)
# Device modules: code that traces into compiled programs (plus the quant
# storage layer whose dtypes land in HBM).
DEVICE_MODULES: Tuple[str, ...] = (
    "can_tpu/ops/",
    "can_tpu/models/",
    "can_tpu/train/",
    "can_tpu/parallel/",
    "can_tpu/serve/engine.py",
    "can_tpu/serve/quant.py",
)
LOCK_MODULES: Tuple[str, ...] = ("can_tpu/serve/",)

EVENT_KINDS_FILE = "can_tpu/obs/bus.py"

_LOG_ATTRS = frozenset({"emit", "warning", "warn", "error", "exception",
                        "log", "info", "debug", "print_exc"})
_LOCK_FACTORY_ATTRS = frozenset({"Lock", "RLock", "Condition"})
_LOCK_NAME_RE = re.compile(r"^_?lock$")

# one pragma per comment; the reason runs to the comment's final ")" so
# it may itself contain calls/parens
PRAGMA_RE = re.compile(
    r"#\s*can-tpu-lint:\s*disable=([A-Za-z0-9_]+)\s*(?:\((.*)\))?\s*$")


class LintUsageError(Exception):
    """Bad pragma / unreadable baseline / unparsable source: the LINT RUN
    is invalid — distinct from 'the tree has findings'."""


@dataclasses.dataclass(frozen=True)
class Finding:
    path: str       # repo-relative posix path
    line: int       # 1-indexed
    rule: str
    message: str
    snippet: str    # stripped source line — the baseline fingerprint key

    def fingerprint(self) -> Tuple[str, str, str]:
        # line numbers rot on unrelated edits; (path, rule, code text)
        # survives them and still pins the finding to a real site
        return (self.path, self.rule, self.snippet)

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.rule}: {self.message}"


def _in_scope(rel: str, prefixes: Sequence[str]) -> bool:
    return any(rel == p or (p.endswith("/") and rel.startswith(p))
               for p in prefixes)


def parse_pragmas(src: str, rel: str) -> Dict[int, set]:
    """Line -> set of disabled rule ids, parsed from COMMENT tokens only
    (a pragma quoted inside a string — this module's own docstring, a
    test fixture literal — is not a pragma).  Unknown rules and missing
    reasons raise ``LintUsageError`` — a typo'd pragma must not silently
    suppress nothing (or worse, look like it suppressed something)."""
    import io
    import tokenize

    out: Dict[int, set] = {}
    try:
        tokens = list(tokenize.generate_tokens(io.StringIO(src).readline))
    except tokenize.TokenError as e:
        raise LintUsageError(f"{rel}: untokenizable source: {e}") from e
    for tok in tokens:
        if tok.type != tokenize.COMMENT or "can-tpu-lint" not in tok.string:
            continue
        lineno = tok.start[0]
        m = PRAGMA_RE.search(tok.string)
        if m is None:
            raise LintUsageError(
                f"{rel}:{lineno}: malformed can-tpu-lint pragma (expected "
                f"`# can-tpu-lint: disable=RULE(reason)`): "
                f"{tok.string.strip()}")
        rule, reason = m.group(1), m.group(2)
        if rule not in RULES:
            raise LintUsageError(
                f"{rel}:{lineno}: pragma disables unknown rule "
                f"{rule!r} (known: {', '.join(sorted(RULES))})")
        if not reason or not reason.strip():
            raise LintUsageError(
                f"{rel}:{lineno}: pragma for {rule} has no reason — "
                f"write `disable={rule}(why this is safe)`")
        out.setdefault(lineno, set()).add(rule)
    return out


def _snippet(lines: List[str], lineno: int) -> str:
    return lines[lineno - 1].strip() if 0 < lineno <= len(lines) else ""


# -- per-node rule helpers ------------------------------------------------
def _is_np_asarray(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "asarray"
            and isinstance(f.value, ast.Name)
            and f.value.id in ("np", "numpy"))


def _is_time_time(call: ast.Call) -> bool:
    f = call.func
    return (isinstance(f, ast.Attribute) and f.attr == "time"
            and isinstance(f.value, ast.Name) and f.value.id == "time")


def _is_f64_attr(node: ast.Attribute) -> bool:
    if node.attr != "float64":
        return False
    v = node.value
    if isinstance(v, ast.Name) and v.id in ("np", "numpy", "jnp"):
        return True
    # jax.numpy.float64
    return (isinstance(v, ast.Attribute) and v.attr == "numpy"
            and isinstance(v.value, ast.Name) and v.value.id == "jax")


def _broad_except(handler: ast.ExceptHandler) -> bool:
    t = handler.type
    if t is None:
        return True
    names = []
    if isinstance(t, ast.Name):
        names = [t.id]
    elif isinstance(t, ast.Tuple):
        names = [e.id for e in t.elts if isinstance(e, ast.Name)]
    return any(n in ("Exception", "BaseException") for n in names)


def _handler_swallows(handler: ast.ExceptHandler) -> bool:
    """True when the handler neither raises, nor touches the bound
    exception, nor calls anything that looks like logging."""
    for node in ast.walk(handler):
        if isinstance(node, ast.Raise):
            return False
        if (handler.name and isinstance(node, ast.Name)
                and node.id == handler.name):
            return False
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Name) and f.id == "print":
                return False
            if isinstance(f, ast.Attribute) and f.attr in _LOG_ATTRS:
                return False
    return True


def _self_attr_root(target: ast.expr) -> Optional[str]:
    """The attribute name X for a write whose target roots at ``self.X``
    (through any Subscript/Attribute chain), else None."""
    node = target
    while isinstance(node, (ast.Subscript, ast.Attribute)):
        parent = node
        node = node.value
        if (isinstance(node, ast.Name) and node.id == "self"
                and isinstance(parent, ast.Attribute)):
            return parent.attr
    return None


def _lock_attrs_of(cls: ast.ClassDef) -> set:
    """Lock-like attributes this class declares in ``__init__``:
    ``self.X = threading.Lock()/RLock()/Condition(...)`` or an attribute
    literally named ``lock``/``_lock``."""
    locks: set = set()
    for fn in cls.body:
        if not (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                and fn.name == "__init__"):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for tgt in node.targets:
                if not (isinstance(tgt, ast.Attribute)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id == "self"):
                    continue
                v = node.value
                if (isinstance(v, ast.Call)
                        and isinstance(v.func, ast.Attribute)
                        and v.func.attr in _LOCK_FACTORY_ATTRS):
                    locks.add(tgt.attr)
                elif _LOCK_NAME_RE.match(tgt.attr):
                    locks.add(tgt.attr)
    return locks


def _with_holds_lock(node: ast.With, locks: set) -> bool:
    for item in node.items:
        ctx = item.context_expr
        if (isinstance(ctx, ast.Attribute) and ctx.attr in locks
                and isinstance(ctx.value, ast.Name)
                and ctx.value.id == "self"):
            return True
    return False


class _LockVisitor(ast.NodeVisitor):
    """Flags self-attribute writes outside ``with self.<lock>`` within
    one lock-declaring class's non-__init__ methods."""

    def __init__(self, rel: str, lines: List[str], locks: set,
                 findings: List[Finding]):
        self.rel = rel
        self.lines = lines
        self.locks = locks
        self.findings = findings
        self.depth = 0  # with-lock nesting

    def visit_With(self, node: ast.With) -> None:
        held = _with_holds_lock(node, self.locks)
        self.depth += 1 if held else 0
        self.generic_visit(node)
        self.depth -= 1 if held else 0

    def _check_write(self, node, targets) -> None:
        if self.depth > 0:
            return
        for tgt in targets:
            attr = _self_attr_root(tgt)
            if attr is not None:
                self.findings.append(Finding(
                    self.rel, node.lineno, "LOCKHELD",
                    f"write to self.{attr} outside `with self.<lock>` in "
                    f"a class declaring {sorted(self.locks)}",
                    _snippet(self.lines, node.lineno)))

    def visit_Assign(self, node: ast.Assign) -> None:
        self._check_write(node, node.targets)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        self._check_write(node, [node.target])
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            self._check_write(node, [node.target])
        self.generic_visit(node)


def _lint_locks(tree: ast.AST, rel: str, lines: List[str],
                findings: List[Finding]) -> None:
    for cls in ast.walk(tree):
        if not isinstance(cls, ast.ClassDef):
            continue
        locks = _lock_attrs_of(cls)
        if not locks:
            continue
        for fn in cls.body:
            if (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and fn.name != "__init__"):
                _LockVisitor(rel, lines, locks, findings).visit(fn)


def lint_source(rel: str, src: str
                ) -> Tuple[List[Finding], List[Tuple[int, str, str]]]:
    """Lint one file's source.  Returns (raw findings — pragmas NOT yet
    applied, emit-kind literals as (line, kind, snippet))."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        raise LintUsageError(f"{rel}:{e.lineno}: unparsable source: "
                             f"{e.msg}") from e
    lines = src.splitlines()
    findings: List[Finding] = []
    emits: List[Tuple[int, str, str]] = []
    hot = _in_scope(rel, HOT_PATH_MODULES)
    dev = _in_scope(rel, DEVICE_MODULES)

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            f = node.func
            if (isinstance(f, ast.Attribute) and f.attr == "emit"
                    and node.args
                    and isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)):
                emits.append((node.lineno, node.args[0].value,
                              _snippet(lines, node.lineno)))
            if hot:
                if (isinstance(f, ast.Attribute)
                        and f.attr in ("item", "block_until_ready")
                        and not node.args):
                    findings.append(Finding(
                        rel, node.lineno, "HOSTSYNC",
                        f".{f.attr}() forces a device->host sync on the "
                        f"hot path", _snippet(lines, node.lineno)))
                elif _is_np_asarray(node):
                    findings.append(Finding(
                        rel, node.lineno, "HOSTSYNC",
                        "np.asarray on the hot path fetches device data "
                        "to host", _snippet(lines, node.lineno)))
                elif (isinstance(f, ast.Name) and f.id == "float"
                      and len(node.args) == 1
                      and isinstance(node.args[0],
                                     (ast.Subscript, ast.Call))):
                    # float(metrics["loss"]) / float(x.mean()) — the
                    # array-access shapes that block on a device value;
                    # bare float(name) config coercions are host scalars
                    findings.append(Finding(
                        rel, node.lineno, "HOSTSYNC",
                        "float(...) on the hot path blocks on the value "
                        "it converts", _snippet(lines, node.lineno)))
                if _is_time_time(node):
                    findings.append(Finding(
                        rel, node.lineno, "TIMETIME",
                        "time.time() around device work measures "
                        "dispatch, not execution (and wall clocks step)",
                        _snippet(lines, node.lineno)))
        elif isinstance(node, ast.ExceptHandler):
            if _broad_except(node) and _handler_swallows(node):
                findings.append(Finding(
                    rel, node.lineno, "SWALLOW",
                    "broad except neither raises, uses the exception, "
                    "nor logs — the error evaporates",
                    _snippet(lines, node.lineno)))
        elif dev and isinstance(node, ast.Attribute) and _is_f64_attr(node):
            findings.append(Finding(
                rel, node.lineno, "F64LIT",
                "float64 literal in device code (f64 is ~10x slow on "
                "TPU and usually an accidental upcast)",
                _snippet(lines, node.lineno)))
        elif (dev and isinstance(node, ast.Constant)
              and node.value == "float64"):
            findings.append(Finding(
                rel, node.lineno, "F64LIT",
                '"float64" dtype string in device code',
                _snippet(lines, node.lineno)))

    if _in_scope(rel, LOCK_MODULES):
        _lint_locks(tree, rel, lines, findings)
    return findings, emits


# -- EVENT_KINDS ----------------------------------------------------------
def declared_event_kinds(root: str) -> Tuple[List[str], int]:
    """(kinds, lineno of the declaration) parsed from obs/bus.py's AST —
    no import, so the linter stays jax-free."""
    path = os.path.join(root, EVENT_KINDS_FILE)
    with open(path) as f:
        tree = ast.parse(f.read())
    for node in ast.walk(tree):
        if (isinstance(node, ast.Assign)
                and any(isinstance(t, ast.Name) and t.id == "EVENT_KINDS"
                        for t in node.targets)
                and isinstance(node.value, (ast.Tuple, ast.List))):
            kinds = [e.value for e in node.value.elts
                     if isinstance(e, ast.Constant)
                     and isinstance(e.value, str)]
            return kinds, node.lineno
    raise LintUsageError(f"{EVENT_KINDS_FILE}: EVENT_KINDS tuple not found")


def default_paths(root: str) -> List[str]:
    """The lint scope: the library, the bench entry points, the tools —
    same universe the EVENT_KINDS drift test always scanned."""
    import glob

    paths = sorted(
        glob.glob(os.path.join(root, "can_tpu", "**", "*.py"),
                  recursive=True)
        + glob.glob(os.path.join(root, "bench*.py"))
        + glob.glob(os.path.join(root, "tools", "*.py")))
    return paths


def emit_kind_drift(root: str, paths: Optional[Sequence[str]] = None
                    ) -> Tuple[Dict[str, list], List[str]]:
    """The two drift directions, as data (tests assert on this directly):
    (undeclared: kind -> [(path, line)], declared-but-never-emitted)."""
    kinds, _ = declared_event_kinds(root)
    declared = set(kinds)
    emitted: Dict[str, list] = {}
    for path in (default_paths(root) if paths is None else paths):
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path) as f:
            _, emits = lint_source(rel, f.read())
        for line, kind, _snip in emits:
            emitted.setdefault(kind, []).append((rel, line))
    undeclared = {k: v for k, v in emitted.items() if k not in declared}
    unemitted = sorted(declared - set(emitted))
    return undeclared, unemitted


# -- tree-level run -------------------------------------------------------
def lint_paths(root: str, paths: Optional[Sequence[str]] = None,
               *, rules: Optional[Sequence[str]] = None
               ) -> Tuple[List[Finding], int]:
    """Lint the tree.  Returns (findings with pragmas applied, number of
    pragma-suppressed findings).  ``rules`` restricts to a subset."""
    full_scan = paths is None
    paths = default_paths(root) if paths is None else list(paths)
    selected = set(RULES) if rules is None else set(rules)
    unknown = selected - set(RULES)
    if unknown:
        raise LintUsageError(f"unknown rule(s): {sorted(unknown)}")
    all_findings: List[Finding] = []
    pragmas_by_rel: Dict[str, Dict[int, set]] = {}
    emits_by_rel: Dict[str, List[Tuple[int, str, str]]] = {}
    for path in paths:
        rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path) as f:
            src = f.read()
        pragmas_by_rel[rel] = parse_pragmas(src, rel)
        findings, emits = lint_source(rel, src)
        emits_by_rel[rel] = emits
        all_findings.extend(findings)

    if "EMITKIND" in selected:
        kinds, decl_line = declared_event_kinds(root)
        declared = set(kinds)
        seen: set = set()
        for rel, emits in emits_by_rel.items():
            for line, kind, snip in emits:
                seen.add(kind)
                if kind not in declared:
                    all_findings.append(Finding(
                        rel, line, "EMITKIND",
                        f'emitted kind "{kind}" is not declared in '
                        f"EVENT_KINDS ({EVENT_KINDS_FILE})", snip))
        # the reverse direction ("declared but never emitted") is only
        # meaningful over the FULL tree: a subset-path run hasn't seen
        # the other files' emitters and would report false drift
        if full_scan:
            for kind in sorted(declared - seen):
                all_findings.append(Finding(
                    EVENT_KINDS_FILE, decl_line, "EMITKIND",
                    f'declared kind "{kind}" has no emitter in the tree',
                    f'EVENT_KINDS entry "{kind}"'))

    kept: List[Finding] = []
    suppressed = 0
    for f in all_findings:
        if f.rule not in selected:
            continue
        pragmas = pragmas_by_rel.get(f.path, {})
        if (f.rule in pragmas.get(f.line, ())
                or f.rule in pragmas.get(f.line - 1, ())):
            suppressed += 1
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    return kept, suppressed


# -- baseline -------------------------------------------------------------
def load_baseline(path: str) -> Dict[Tuple[str, str, str], int]:
    """Committed-baseline fingerprints -> accepted count.  An unreadable
    or torn baseline is a usage error — it must never read as 'empty
    baseline, everything is new' OR 'nothing to check, pass'."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except FileNotFoundError as e:
        raise LintUsageError(f"baseline {path} does not exist") from e
    except json.JSONDecodeError as e:
        raise LintUsageError(f"baseline {path} is not valid JSON "
                             f"(torn write?): {e}") from e
    if not isinstance(doc, dict) or doc.get("version") != 1:
        raise LintUsageError(f"baseline {path}: expected "
                             '{"version": 1, "findings": [...]}')
    out: Dict[Tuple[str, str, str], int] = {}
    for rec in doc.get("findings", []):
        if rec.get("rule") not in RULES:
            raise LintUsageError(
                f"baseline {path}: unknown rule {rec.get('rule')!r}")
        fp = (rec["path"], rec["rule"], rec["snippet"])
        out[fp] = out.get(fp, 0) + int(rec.get("count", 1))
    return out


def check_baseline(findings: Sequence[Finding],
                   baseline: Dict[Tuple[str, str, str], int]
                   ) -> Tuple[List[Finding], List[Tuple[str, str, str]]]:
    """(new findings beyond the baseline, stale baseline entries).  Both
    must be empty for a clean run: new = the tree regressed, stale = the
    finding was fixed but the baseline still carries it (rot)."""
    counts: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        counts[f.fingerprint()] = counts.get(f.fingerprint(), 0) + 1
    new: List[Finding] = []
    seen_over: Dict[Tuple[str, str, str], int] = {}
    for f in findings:
        fp = f.fingerprint()
        seen_over[fp] = seen_over.get(fp, 0) + 1
        if seen_over[fp] > baseline.get(fp, 0):
            new.append(f)
    stale = [fp for fp, n in sorted(baseline.items())
             if counts.get(fp, 0) < n]
    return new, stale
