"""can_tpu.analysis — static analysis over the compiled programs and the
source tree.

Two passes, two failure classes:

* ``hlo_audit`` — lowers each canonical compiled program (the eight
  program families the stack ships: default/bf16/syncBN train steps, the
  eval step, and the quantized serve predicts) and checks STRUCTURED
  invariants over the StableHLO text and XLA ``cost_analysis()`` against
  the committed ``PROGRAM_CONTRACTS.json``: collective counts and operand
  shapes, dtype discipline (no f64), no host callbacks, int8 params held
  in HBM, flop/byte budgets.  The invariants the repo used to guard with
  per-test regexes (the ``all_reduce`` count in tests/test_batchnorm.py)
  now live here once.

* ``source_lint`` — a JAX/concurrency-aware AST linter for the hazards
  type checkers don't see: host-sync calls in hot-path modules, unfenced
  ``time.time()`` device timing, swallowed ``except Exception``,
  ``.emit(kind)`` literals drifting from ``EVENT_KINDS``, unlocked
  attribute writes in lock-declaring serve classes, and f64 literals in
  device code.  ``# can-tpu-lint: disable=RULE(reason)`` pragmas and a
  committed baseline keep the tree clean without hiding the exceptions.

Entry points: ``tools/can_tpu_lint.py`` (lint CLI),
``python -m can_tpu.analysis.hlo_audit`` (audit CLI), ``tools/ci_lint.sh``
(both, as a CI gate beside ``ci_bench_gate.sh``), and
``tests/test_analysis.py`` (tier-1).
"""

from can_tpu.analysis.source_lint import (  # noqa: F401
    Finding,
    LintUsageError,
    check_baseline,
    emit_kind_drift,
    lint_paths,
)

__all__ = [
    "Finding",
    "LintUsageError",
    "check_baseline",
    "emit_kind_drift",
    "lint_paths",
]
