"""Data-parallel scaling sweep: img/s and efficiency vs chip count.

The north star (BASELINE.json) includes 1->64-chip scaling efficiency; the
reference's only scaling evidence is "it runs" at world sizes 1/4/6
(reference README.md:24-26).  This harness measures it properly: for each
divisor-of-available chip count N it builds an N-device `data` mesh, runs
the SAME per-chip batch through the jitted dp train step (gradients psum
over ICI), and reports images/sec plus efficiency vs the 1-chip rate
(linear scaling == 1.0).

On this dev environment only one real chip is visible, so the sweep
degenerates to one point there; on a pod slice run it as-is (one process
per host, same command).  `BENCH_SCALING_PLATFORM=cpu8` demonstrates the
harness on an 8-device virtual CPU mesh (the numbers then measure CPU
core contention, not ICI — structural validation only, and it says so).

One JSON line per point:
  {"metric": "scaling_dp{N}", "value": img/s, "per_chip": ..., "efficiency": ...}
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def measure(ndev_use: int, *, b: int, h: int, w: int, steps: int,
            warmup: int = 3):
    import jax
    import jax.numpy as jnp

    from can_tpu.data.batching import Batch
    from can_tpu.models import cannet_apply, cannet_init
    from can_tpu.parallel import make_dp_train_step, make_global_batch, make_mesh
    from can_tpu.train import create_train_state, make_lr_schedule, make_optimizer

    if ndev_use == jax.device_count():
        devices = jax.devices()  # full mesh: valid on pods too
    else:
        # sub-full sweep points: jax.devices() on a multi-host pod includes
        # non-addressable devices, and a mesh that drops some hosts'
        # devices can't be fed by those hosts — so sub-full counts are
        # single-host only, built from local (addressable) devices
        local = jax.local_devices()
        if jax.process_count() > 1:
            raise SystemExit(
                f"ndev={ndev_use}: sub-full sweep points require a "
                f"single-host run (multi-host meshes must include every "
                f"process's devices); run the sweep per host or at the "
                f"full device count")
        if ndev_use > len(local):
            raise SystemExit(f"ndev={ndev_use} > {len(local)} local devices")
        devices = local[:ndev_use]
    mesh = make_mesh(devices)
    rng = np.random.default_rng(0)
    local_b = b * ndev_use
    batch = Batch(
        image=rng.normal(size=(local_b, h, w, 3)).astype(np.float32),
        dmap=rng.uniform(size=(local_b, h // 8, w // 8, 1)).astype(np.float32),
        pixel_mask=np.ones((local_b, h // 8, w // 8, 1), np.float32),
        sample_mask=np.ones((local_b,), np.float32),
    )
    gbatch = make_global_batch(batch, mesh)
    opt = make_optimizer(make_lr_schedule(1e-7, world_size=ndev_use))
    state = create_train_state(cannet_init(jax.random.key(0)), opt)
    step = make_dp_train_step(cannet_apply, opt, mesh,
                              compute_dtype=jnp.bfloat16)
    for _ in range(warmup):
        state, metrics = step(state, gbatch)
    float(jax.device_get(metrics["loss"]))
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, gbatch)
    loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    assert np.isfinite(loss)
    return local_b * steps / dt


MEASURED_V5E_IMG_PER_S = 94.5   # 1-chip 576x768 b16 bf16 (BENCH_SUITE_r05)
# v5e ICI: 4 links x 400 Gbps = 1600 Gbps aggregate per chip; a
# bidirectional ring all-reduce drives 2 links -> ~100 GB/s effective.
# Stated as an assumption in the artifact, not hidden in the code.
V5E_ICI_EFFECTIVE_GBS = 100.0
# fraction of the all-reduce XLA fails to overlap with the backward pass
# (GSPMD overlaps most of it; 0.5 is deliberately pessimistic)
ALLREDUCE_EXPOSED_FRAC = 0.5


def scaling_model(*, dps=(1, 2, 4, 8, 16, 32, 64), per_chip_batch=16,
                  shape=(576, 768), n_images=300, chips_per_host=4,
                  base_img_per_s=MEASURED_V5E_IMG_PER_S):
    """Model-predicted dp=1..64 efficiency (VERDICT r5 item 8): the
    hardware-blocked '1->64 chips' axis gets a number built from the
    MEASURED single-chip rate plus the two scale costs this framework
    can compute exactly without chips:

    * collective overhead — ring all-reduce of the real parameter count
      over v5e ICI (2(dp-1)/dp * grad_bytes / bw), derated by the
      exposed (non-overlapped) fraction;
    * plan overhead — the batch planner run for the TRUE dp
      configuration (global batch = per_chip_batch * dp, quantum = lcm
      of dp and host count, v5e HBM cap): a fixed-size varres dataset at
      growing global batch pays growing padding/fill, and that is a
      schedule property this host computes bit-exactly (data/planner.py).

    Each dp row is a prediction, labelled as such; the harness's
    measured sweep replaces it the day a pod slice exists.  Returns the
    artifact dict (also written by --model / SCALING_MODEL env)."""
    import math as _math

    from bench_suite import SynthVarResDataset
    from can_tpu.cli.common import (
        hbm_bytes_for_device_kind,
        max_launch_pixels,
    )
    import jax

    from can_tpu.data import ShardedBatcher
    from can_tpu.models import cannet_init

    params = cannet_init(jax.random.key(0))
    grad_bytes = sum(x.size * 4 for x in jax.tree_util.tree_leaves(params))
    px = shape[0] * shape[1]
    t_comp = per_chip_batch / base_img_per_s  # seconds/step/chip, measured
    ds = SynthVarResDataset(n_images)
    rows = []
    base_overhead = None
    for dp in dps:
        hosts = max(1, dp // chips_per_host)
        quantum = _math.lcm(dp, hosts)
        cap = max_launch_pixels(
            bf16=True, shards=dp,
            hbm_bytes=hbm_bytes_for_device_kind("TPU v5e"))
        b = ShardedBatcher(ds, per_chip_batch * dp, shuffle=True, seed=0,
                           pad_multiple="auto", max_buckets=24,
                           remnant_sizes=True, batch_quantum=quantum,
                           launch_cost_px=0.05e6, max_launch_px=cap)
        overhead = b.schedule_overhead(0)
        if base_overhead is None:
            base_overhead = overhead
        eff_plan = (1 + base_overhead) / (1 + overhead)
        t_ar = (2 * (dp - 1) / dp) * grad_bytes / (V5E_ICI_EFFECTIVE_GBS * 1e9)
        eff_coll = t_comp / (t_comp + ALLREDUCE_EXPOSED_FRAC * t_ar)
        eff = eff_plan * eff_coll
        rows.append({
            "dp": dp,
            "predicted_efficiency": round(eff, 4),
            "predicted_img_per_s": round(base_img_per_s * dp * eff, 1),
            "plan_efficiency": round(eff_plan, 4),
            "collective_efficiency": round(eff_coll, 4),
            "schedule_overhead": round(overhead, 4),
            "programs": b.program_count(0),
            "batches_per_epoch": b.batches_per_epoch(0),
            "global_batch": per_chip_batch * dp,
            "batch_quantum": quantum,
        })
    return {
        "kind": "scaling_model",
        "note": "PREDICTED dp scaling (no pod slice in this environment; "
                "VERDICT r5 item 8): measured 1-chip rate x modelled "
                "plan + collective efficiencies.  Plan overhead is exact "
                "(the planner runs the real dp config on the bench "
                "varres distribution, n_images fixed at "
                f"{n_images} — a fixed dataset at growing global batch "
                "is the pessimistic case); the collective term assumes "
                f"a ring all-reduce of {grad_bytes / 1e6:.1f} MB f32 "
                f"grads over {V5E_ICI_EFFECTIVE_GBS:.0f} GB/s effective "
                f"ICI with {ALLREDUCE_EXPOSED_FRAC:.0%} exposed.",
        "base_img_per_s": base_img_per_s,
        "per_chip_batch": per_chip_batch,
        "shape": list(shape),
        "n_images": n_images,
        "grad_bytes": grad_bytes,
        "results": rows,
    }


def main() -> None:
    import sys

    model_out = os.environ.get("BENCH_SCALING_MODEL_OUT")
    if "--model" in sys.argv[1:] or model_out:
        # host-side prediction path: no devices needed beyond CPU init
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        doc = scaling_model()
        out = model_out or "SCALING_MODEL_r08.json"
        with open(out, "w") as f:
            json.dump(doc, f, indent=1)
            f.write("\n")
        print(f"# wrote {out}")
        for r in doc["results"]:
            print(json.dumps({"metric": f"scaling_model_dp{r['dp']}",
                              "value": r["predicted_efficiency"],
                              "unit": "efficiency_pred",
                              **{k: v for k, v in r.items() if k != "dp"}}))
        return
    if os.environ.get("BENCH_SCALING_PLATFORM") == "cpu8":
        from __graft_entry__ import _ensure_cpu_flags

        _ensure_cpu_flags(8)
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax  # noqa: F811

    from can_tpu.utils import await_devices, emit_null_result, enable_compilation_cache

    # fail fast on a dead tunnel, leaving a machine-readable null line
    await_devices(on_timeout=emit_null_result("bench_scaling"))
    enable_compilation_cache()

    ndev = jax.device_count()
    cpu = jax.devices()[0].platform == "cpu"
    quick = bool(os.environ.get("BENCH_SCALING_QUICK")) or cpu
    b, h, w, steps = (1, 128, 160, 4) if quick else (16, 576, 768, 20)
    print(f"# bench_scaling devices={ndev} platform="
          f"{jax.devices()[0].platform} shape={h}x{w} b{b}/chip"
          + (" (CPU: structural validation only — efficiency here measures"
               " host core contention, not ICI)" if cpu else ""), flush=True)

    counts = [n for n in (1, 2, 4, 8, 16, 32, 64) if n <= ndev]
    base = None
    for n in counts:
        img_s = measure(n, b=b, h=h, w=w, steps=steps)
        per_chip = img_s / n
        if base is None:
            base = per_chip
        print(json.dumps({
            "metric": f"scaling_dp{n}_{h}x{w}_b{b}_bf16",
            "value": round(img_s, 3),
            "unit": "images/sec",
            "per_chip": round(per_chip, 3),
            "efficiency": round(per_chip / base, 4),
        }), flush=True)


if __name__ == "__main__":
    main()
