"""Data-parallel scaling sweep: img/s and efficiency vs chip count.

The north star (BASELINE.json) includes 1->64-chip scaling efficiency; the
reference's only scaling evidence is "it runs" at world sizes 1/4/6
(reference README.md:24-26).  This harness measures it properly: for each
divisor-of-available chip count N it builds an N-device `data` mesh, runs
the SAME per-chip batch through the jitted dp train step (gradients psum
over ICI), and reports images/sec plus efficiency vs the 1-chip rate
(linear scaling == 1.0).

On this dev environment only one real chip is visible, so the sweep
degenerates to one point there; on a pod slice run it as-is (one process
per host, same command).  `BENCH_SCALING_PLATFORM=cpu8` demonstrates the
harness on an 8-device virtual CPU mesh (the numbers then measure CPU
core contention, not ICI — structural validation only, and it says so).

One JSON line per point:
  {"metric": "scaling_dp{N}", "value": img/s, "per_chip": ..., "efficiency": ...}
"""

from __future__ import annotations

import json
import os
import time

import numpy as np


def measure(ndev_use: int, *, b: int, h: int, w: int, steps: int,
            warmup: int = 3):
    import jax
    import jax.numpy as jnp

    from can_tpu.data.batching import Batch
    from can_tpu.models import cannet_apply, cannet_init
    from can_tpu.parallel import make_dp_train_step, make_global_batch, make_mesh
    from can_tpu.train import create_train_state, make_lr_schedule, make_optimizer

    if ndev_use == jax.device_count():
        devices = jax.devices()  # full mesh: valid on pods too
    else:
        # sub-full sweep points: jax.devices() on a multi-host pod includes
        # non-addressable devices, and a mesh that drops some hosts'
        # devices can't be fed by those hosts — so sub-full counts are
        # single-host only, built from local (addressable) devices
        local = jax.local_devices()
        if jax.process_count() > 1:
            raise SystemExit(
                f"ndev={ndev_use}: sub-full sweep points require a "
                f"single-host run (multi-host meshes must include every "
                f"process's devices); run the sweep per host or at the "
                f"full device count")
        if ndev_use > len(local):
            raise SystemExit(f"ndev={ndev_use} > {len(local)} local devices")
        devices = local[:ndev_use]
    mesh = make_mesh(devices)
    rng = np.random.default_rng(0)
    local_b = b * ndev_use
    batch = Batch(
        image=rng.normal(size=(local_b, h, w, 3)).astype(np.float32),
        dmap=rng.uniform(size=(local_b, h // 8, w // 8, 1)).astype(np.float32),
        pixel_mask=np.ones((local_b, h // 8, w // 8, 1), np.float32),
        sample_mask=np.ones((local_b,), np.float32),
    )
    gbatch = make_global_batch(batch, mesh)
    opt = make_optimizer(make_lr_schedule(1e-7, world_size=ndev_use))
    state = create_train_state(cannet_init(jax.random.key(0)), opt)
    step = make_dp_train_step(cannet_apply, opt, mesh,
                              compute_dtype=jnp.bfloat16)
    for _ in range(warmup):
        state, metrics = step(state, gbatch)
    float(jax.device_get(metrics["loss"]))
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, gbatch)
    loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    assert np.isfinite(loss)
    return local_b * steps / dt


def main() -> None:
    if os.environ.get("BENCH_SCALING_PLATFORM") == "cpu8":
        from __graft_entry__ import _ensure_cpu_flags

        _ensure_cpu_flags(8)
        import jax

        jax.config.update("jax_platforms", "cpu")
    import jax  # noqa: F811

    from can_tpu.utils import await_devices, emit_null_result, enable_compilation_cache

    # fail fast on a dead tunnel, leaving a machine-readable null line
    await_devices(on_timeout=emit_null_result("bench_scaling"))
    enable_compilation_cache()

    ndev = jax.device_count()
    cpu = jax.devices()[0].platform == "cpu"
    quick = bool(os.environ.get("BENCH_SCALING_QUICK")) or cpu
    b, h, w, steps = (1, 128, 160, 4) if quick else (16, 576, 768, 20)
    print(f"# bench_scaling devices={ndev} platform="
          f"{jax.devices()[0].platform} shape={h}x{w} b{b}/chip"
          + (" (CPU: structural validation only — efficiency here measures"
               " host core contention, not ICI)" if cpu else ""), flush=True)

    counts = [n for n in (1, 2, 4, 8, 16, 32, 64) if n <= ndev]
    base = None
    for n in counts:
        img_s = measure(n, b=b, h=h, w=w, steps=steps)
        per_chip = img_s / n
        if base is None:
            base = per_chip
        print(json.dumps({
            "metric": f"scaling_dp{n}_{h}x{w}_b{b}_bf16",
            "value": round(img_s, 3),
            "unit": "images/sec",
            "per_chip": round(per_chip, 3),
            "efficiency": round(per_chip / base, 4),
        }), flush=True)


if __name__ == "__main__":
    main()
