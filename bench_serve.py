"""Benchmark: online serving latency/throughput through can_tpu/serve.

Drives the FULL serving stack (queue -> micro-batcher thread -> jitted
engine) with mixed-resolution synthetic requests, two ways:

* **closed loop** — K concurrent clients, each waiting for its result
  before sending the next request: measures the stack's sustainable
  throughput and the latency it gives cooperative clients.
* **open loop** — Poisson arrivals at a target rate that does NOT slow
  down when the service does (the real-traffic model): measures tail
  latency under pressure and exercises the deadline + backpressure
  rejection paths (a closed loop can never overload the queue, so it
  never tests them).

Emits ONE JSON report to ``BENCH_SERVE_<tag>.json`` and prints it; fields:
per-phase p50/p95/p99 latency (ms), throughput (req/s), reject rate, plus
mean batch fill, compile count vs bucket count, and the telemetry-derived
event totals.  Config via env (defaults are CPU-smoke scale — one v5e chip
serves far bigger shapes; override for real runs):

    BENCH_SERVE_REQUESTS=96   requests per phase
    BENCH_SERVE_CLIENTS=8     closed-loop concurrent clients
    BENCH_SERVE_RATE=0        open-loop arrivals/s (0 = 2x measured
                              closed-loop throughput, guaranteeing pressure)
    BENCH_SERVE_MAX_BATCH=8   micro-batch size
    BENCH_SERVE_MAX_WAIT_MS=5 flush deadline
    BENCH_SERVE_DEADLINE_MS=2000  open-loop request deadline
    BENCH_SERVE_SIZES=60x60,90x90,64x90,90x64   request resolutions
    BENCH_SERVE_OUT=local     report tag
    BENCH_SERVE_REPLICAS=0    0/1 = single ServeEngine; >= 2 = FleetEngine
                              with that many device-pinned replicas
                              (artifact becomes BENCH_SERVE_FLEET_<tag>)
    BENCH_SERVE_DTYPE=f32     predict-program mode (f32 | bf16 | int8);
                              quantized modes also run the f32 parity
                              ladder and record the graded rung
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np


def _sizes_from_env() -> list:
    spec = os.environ.get("BENCH_SERVE_SIZES", "60x60,90x90,64x90,90x64")
    return [(int(h), int(w)) for h, w in
            (part.split("x") for part in spec.split(","))]


def _percentiles_ms(latencies_s: list) -> dict:
    if not latencies_s:
        return {"p50_ms": None, "p95_ms": None, "p99_ms": None,
                "max_ms": None}
    arr = np.asarray(latencies_s, np.float64) * 1e3
    return {"p50_ms": round(float(np.percentile(arr, 50)), 3),
            "p95_ms": round(float(np.percentile(arr, 95)), 3),
            "p99_ms": round(float(np.percentile(arr, 99)), 3),
            "max_ms": round(float(arr.max()), 3)}


def _queue_wait_p95_ms(queue_waits_s: list):
    """p95 of the submit->assembly waits the span timestamps price — the
    number that says whether tail latency is batching or the device."""
    if not queue_waits_s:
        return None
    arr = np.asarray(queue_waits_s, np.float64) * 1e3
    return round(float(np.percentile(arr, 95)), 3)


def run_closed_loop(service, images, n_requests: int, n_clients: int) -> dict:
    """K clients, each submit->wait->repeat; returns latency/throughput."""
    from can_tpu.serve import RejectedError

    latencies, queue_waits, rejects = [], [], [0]
    lock = threading.Lock()
    counter = [0]

    def client():
        while True:
            with lock:
                i = counter[0]
                if i >= n_requests:
                    return
                counter[0] += 1
            try:
                res = service.predict(images[i % len(images)],
                                      timeout=120.0)
                with lock:
                    latencies.append(res.latency_s)
                    if res.queue_wait_s is not None:
                        queue_waits.append(res.queue_wait_s)
            except RejectedError:
                with lock:
                    rejects[0] += 1

    t0 = time.perf_counter()
    threads = [threading.Thread(target=client) for _ in range(n_clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.perf_counter() - t0
    done = len(latencies)
    return {"requests": n_requests, "completed": done,
            "rejected": rejects[0],
            "reject_rate": round(rejects[0] / max(n_requests, 1), 4),
            "throughput_rps": round(done / wall, 2),
            "wall_s": round(wall, 3),
            "queue_wait_p95_ms": _queue_wait_p95_ms(queue_waits),
            **_percentiles_ms(latencies)}


def run_open_loop(service, images, n_requests: int, rate_rps: float,
                  deadline_ms: float, seed: int = 0,
                  on_arrival=None) -> dict:
    """Poisson arrivals at ``rate_rps``; every request carries a deadline.
    Tickets are collected afterwards — arrival timing never blocks on
    results, so the service feels true open-loop pressure.
    ``on_arrival(i)`` fires before request ``i`` is submitted — the
    autoscale tier uses it to trigger a mid-run scale-up and measure p99
    THROUGH the transition."""
    from can_tpu.serve import RejectedError

    rng = np.random.default_rng(seed)
    tickets = []
    t0 = time.perf_counter()
    next_t = 0.0
    for i in range(n_requests):
        next_t += float(rng.exponential(1.0 / rate_rps))
        sleep = t0 + next_t - time.perf_counter()
        if sleep > 0:
            time.sleep(sleep)
        if on_arrival is not None:
            on_arrival(i)
        tickets.append(service.submit(images[i % len(images)],
                                      deadline_ms=deadline_ms))
    latencies, queue_waits, rejects = [], [], 0
    for t in tickets:
        try:
            res = t.result()
            latencies.append(res.latency_s)
            if res.queue_wait_s is not None:
                queue_waits.append(res.queue_wait_s)
        except RejectedError:
            rejects += 1
    wall = time.perf_counter() - t0
    return {"requests": n_requests, "completed": len(latencies),
            "rejected": rejects,
            "reject_rate": round(rejects / max(n_requests, 1), 4),
            "offered_rps": round(rate_rps, 2),
            "throughput_rps": round(len(latencies) / wall, 2),
            "wall_s": round(wall, 3),
            "queue_wait_p95_ms": _queue_wait_p95_ms(queue_waits),
            **_percentiles_ms(latencies)}


def measure_time_to_first_ready(params, *, device, bucket_shapes,
                                max_batch: int, serve_dtype: str = "f32",
                                aot_bundle=None, telemetry=None,
                                name: str = "ttfr") -> dict:
    """Build + fully warm ONE replica engine on ``device`` — the
    recovery-path latency the self-healing fleet pays for a resurrection
    or scale-up.  Cold = live trace+compile per bucket; with an AOT
    bundle = deserialized executables (zero new compiles, pinned via the
    returned ``compiles``).  ``name`` must be unique per call: the
    signature registry is per program name, and a reused name would hide
    the cold path's compiles."""
    from can_tpu.obs import Telemetry
    from can_tpu.serve import ServeEngine

    tel = telemetry if telemetry is not None else Telemetry()
    t0 = time.perf_counter()
    aot_tab = (aot_bundle.programs_for(device)
               if aot_bundle is not None else None)
    engine = ServeEngine(params, device=device, serve_dtype=serve_dtype,
                         telemetry=tel, name=name, aot_programs=aot_tab)
    rep = engine.warmup(bucket_shapes, max_batch)
    return {"time_to_first_ready_s": round(time.perf_counter() - t0, 3),
            "compiles": rep["compiles"], "aot_hits": engine.aot_hits}


def main() -> None:
    if os.environ.get("BENCH_SERVE_PLATFORM") == "cpu8":
        # 8 virtual CPU devices (the fleet needs one device per replica;
        # same smoke-mesh trick as bench_suite BENCH_SUITE_PLATFORM=cpu8)
        from __graft_entry__ import _ensure_cpu_flags

        _ensure_cpu_flags(8)
        import jax as _jax

        _jax.config.update("jax_platforms", "cpu")
    n_requests = int(os.environ.get("BENCH_SERVE_REQUESTS", "96"))
    n_clients = int(os.environ.get("BENCH_SERVE_CLIENTS", "8"))
    rate = float(os.environ.get("BENCH_SERVE_RATE", "0"))
    max_batch = int(os.environ.get("BENCH_SERVE_MAX_BATCH", "8"))
    max_wait_ms = float(os.environ.get("BENCH_SERVE_MAX_WAIT_MS", "5"))
    deadline_ms = float(os.environ.get("BENCH_SERVE_DEADLINE_MS", "2000"))
    tag = os.environ.get("BENCH_SERVE_OUT", "local")
    replicas = int(os.environ.get("BENCH_SERVE_REPLICAS", "0"))
    serve_dtype = os.environ.get("BENCH_SERVE_DTYPE", "f32")
    sizes = _sizes_from_env()

    import jax

    from can_tpu.models import cannet_init
    from can_tpu.obs import Telemetry
    from can_tpu.serve import (
        CountService,
        FleetEngine,
        ServeEngine,
        parity_report,
        prepare_image,
    )
    from can_tpu.serve.quant import param_bytes
    from can_tpu.utils import enable_compilation_cache

    enable_compilation_cache(None)  # no-op on CPU, warm restarts on TPU
    # serving cost is weight-independent: random init serves the same
    # FLOPs a trained checkpoint would (swap in cli/serve.py for accuracy)
    params = cannet_init(jax.random.key(0))
    telemetry = Telemetry()  # in-memory bus: engine compile attribution

    ladder = (tuple(sorted({-(-h // 8) * 8 for h, _ in sizes})),
              tuple(sorted({-(-w // 8) * 8 for _, w in sizes})))
    buckets = [(h, w) for h in ladder[0] for w in ladder[1]]
    fleet = replicas >= 2
    if fleet:
        engine = FleetEngine(params, replicas=replicas,
                             serve_dtype=serve_dtype, telemetry=telemetry)
    else:
        engine = ServeEngine(params, serve_dtype=serve_dtype,
                             telemetry=telemetry)
    service = CountService(engine, max_batch=max_batch,
                           max_wait_ms=max_wait_ms,
                           queue_capacity=max(64, 4 * max_batch),
                           high_water=max(48, 3 * max_batch),
                           bucket_ladder=ladder, telemetry=telemetry)
    t0 = time.perf_counter()
    warm = service.warmup(buckets)

    rng = np.random.default_rng(7)
    images = [prepare_image(
        (rng.uniform(0, 1, (h, w, 3)) * 255).astype(np.uint8))
        for h, w in sizes]

    # quantized modes carry a parity receipt: the same images through a
    # fresh engine of this mode vs the f32 reference, graded on the
    # committed count-delta tolerance ladder (serve/quant.py)
    parity = None
    if serve_dtype != "f32":
        ref = ServeEngine(params, telemetry=telemetry, name="parity_f32")
        quant = ServeEngine(params, serve_dtype=serve_dtype,
                            telemetry=telemetry,
                            name=f"parity_{serve_dtype}")
        parity = parity_report(quant, ref, images)

    with service:
        closed = run_closed_loop(service, images, n_requests, n_clients)
        if rate <= 0:
            rate = 2.0 * max(closed["throughput_rps"], 1.0)
        open_ = run_open_loop(service, images, n_requests, rate,
                              deadline_ms)
    stats = service.stats()

    # compile budget: one program per (bucket, menu size, dtype) PER
    # replica engine (the r14 sub-batch menu rides the warmup)
    menu = service.sched.menu if service.sched is not None else (max_batch,)
    compile_budget = len(buckets) * max(replicas, 1) * len(menu)
    report = {
        "metric": f"cannet_serve_b{max_batch}_w{int(max_wait_ms)}ms"
                  + (f"_r{replicas}" if fleet else "")
                  + (f"_{serve_dtype}" if serve_dtype != "f32" else ""),
        "unit": "ms latency / req_s",
        "config": {"requests": n_requests, "clients": n_clients,
                   "max_batch": max_batch, "menu": list(menu),
                   "max_wait_ms": max_wait_ms,
                   "deadline_ms": deadline_ms,
                   "replicas": replicas if fleet else 1,
                   "serve_dtype": serve_dtype,
                   "sizes": [f"{h}x{w}" for h, w in sizes],
                   "buckets": [f"{h}x{w}" for h, w in buckets],
                   "platform": jax.devices()[0].platform},
        "warmup": warm,
        "compile_count": engine.compile_count,
        "bucket_count": len(buckets),
        "compiles_bounded": engine.compile_count <= compile_budget,
        # the tree the replicas actually hold resident — measuring it
        # (instead of re-quantizing) cannot diverge from what is served
        "param_bytes": param_bytes(
            engine.replicas[0].engine.params if fleet else engine.params),
        "closed_loop": closed,
        "open_loop": open_,
        "mean_batch_fill": stats["mean_batch_fill"],
        "batches": stats["batches"],
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    if parity is not None:
        report["parity_vs_f32"] = parity
    if fleet:
        report["replica_stats"] = stats["replicas"]
        report["live_replicas"] = stats["live_replicas"]
    out = (f"BENCH_SERVE_FLEET_{tag}.json" if fleet
           else f"BENCH_SERVE_{tag}.json")
    with open(out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    print(f"[bench_serve] wrote {out}")


if __name__ == "__main__":
    main()
