"""Benchmark sweep over the BASELINE.json config list (one JSON line each).

``bench.py`` stays single-config (the driver parses exactly one line); this
suite measures what that number can't — the throughput that actually
predicts training time on real data:

1. fixed-shape train, bf16 and f32 (576x768 b16 — ShanghaiTech-A scale);
2. the REAL pipeline on a variable-resolution dataset: ShardedBatcher with
   the auto bucket ladder + host->device prefetch + the windowed-metrics
   epoch loop, reporting first-epoch (compile-heavy) vs steady-state img/s
   and the compile (distinct-shape) count — BASELINE.json config 3;
3. high-resolution eval (1536x2048, batch 1) — the UCF-QNRF analogue,
   BASELINE.json config 5;
4. the HOST pipeline on real files: JPEG decode + density .npy load +
   resize + flip + pad, no device involved — the img/s the host can feed
   the chip, at worker counts 0/4/8 (the reference's DataLoader
   num_workers knob, train.py:90, measured instead of assumed).

A persistent XLA compilation cache is enabled by default (disable with
BENCH_SUITE_NO_CACHE=1): a second fresh-process run reports
``compile_epoch_s`` near zero, and the pipeline config also measures the
in-process warm-restart epoch (executables dropped, disk cache kept) as
``warm_compile_epoch_s``.

Run: ``python bench_suite.py`` (real TPU; single process only), or
``BENCH_SUITE_PLATFORM=cpu8`` for a smoke run on an 8-device CPU mesh.
Smaller/faster: ``BENCH_SUITE_QUICK=1``.
"""

from __future__ import annotations

import json
import os
import time

import numpy as np

from bench import BASELINE_IMG_PER_S_H100 as BASELINE_EST

# BENCH_TELEMETRY_DIR wiring (see bench.py): every record is ALSO emitted
# as a ``bench`` event, and the pipeline configs run their epochs with the
# telemetry bus attached — so suite artifacts carry the same compile /
# step_window / stall / memory stream a training run does.  None when the
# env var is unset: zero cost.
_TELEMETRY = None


def _emit(metric: str, value: float, unit: str, *, per_chip: float = None,
          **extra) -> None:
    rec = {"metric": metric, "value": round(value, 3), "unit": unit}
    if per_chip is not None:
        rec["vs_baseline"] = round(per_chip / BASELINE_EST, 3)
        rec["baseline_estimate"] = BASELINE_EST
    rec.update(extra)
    if _TELEMETRY is not None:
        _TELEMETRY.emit("bench", **rec)
    print(json.dumps(rec), flush=True)


class SynthVarResDataset:
    """ShanghaiTech-A-like resolution mix, served from one pre-generated
    buffer (items are views into it — per-item host cost is just the
    pad_batch copy, so the bench isolates the batching/padding/prefetch/
    transfer/compute pipeline rather than random-number generation).

    40% of items sit at the dominant 768x1024; the rest spread uniformly —
    the clustered-but-wild histogram real crowd datasets have."""

    def __init__(self, n: int, seed: int = 0, lo: int = 384, hi: int = 1024,
                 dominant=(768, 1024), u8: bool = False):
        rng = np.random.default_rng(seed)
        self.sizes = []
        for _ in range(n):
            if rng.uniform() < 0.4:
                h, w = dominant
            else:
                h = int(rng.integers(lo, hi + 1))
                w = int(rng.integers(lo, hi + 1))
            self.sizes.append(((h // 8) * 8, (w // 8) * 8))
        mh = max(h for h, _ in self.sizes) + 64
        mw = max(w for _, w in self.sizes) + 64
        img = rng.random((mh, mw, 3), dtype=np.float32)
        self._img_buf = (img * 255).astype(np.uint8) if u8 else img
        self._dmap_buf = rng.random((mh // 8, mw // 8, 1), dtype=np.float32)
        self._offs = [(int(rng.integers(0, 64)), int(rng.integers(0, 64)))
                      for _ in range(n)]

    def __len__(self):
        return len(self.sizes)

    def snapped_shape(self, i):
        return self.sizes[i]

    def __getitem__(self, i, rng=None):
        h, w = self.sizes[i]
        ro, co = self._offs[i]
        img = self._img_buf[ro:ro + h, co:co + w]
        dmap = self._dmap_buf[ro // 8:ro // 8 + h // 8,
                              co // 8:co // 8 + w // 8]
        return img, dmap


def bench_fixed(jnp, compute_dtype, *, b, h, w, steps, warmup=3):
    import jax

    from can_tpu.data.batching import Batch
    from can_tpu.models import cannet_apply, cannet_init
    from can_tpu.parallel import make_dp_train_step, make_global_batch, make_mesh
    from can_tpu.train import create_train_state, make_lr_schedule, make_optimizer

    ndev = jax.device_count()
    mesh = make_mesh()
    rng = np.random.default_rng(0)
    local_b = b * ndev
    batch = Batch(
        image=rng.normal(size=(local_b, h, w, 3)).astype(np.float32),
        dmap=rng.uniform(size=(local_b, h // 8, w // 8, 1)).astype(np.float32),
        pixel_mask=np.ones((local_b, h // 8, w // 8, 1), np.float32),
        sample_mask=np.ones((local_b,), np.float32),
    )
    gbatch = make_global_batch(batch, mesh)
    opt = make_optimizer(make_lr_schedule(1e-7, world_size=ndev))
    state = create_train_state(cannet_init(jax.random.key(0)), opt)
    step = make_dp_train_step(cannet_apply, opt, mesh, compute_dtype=compute_dtype)
    for _ in range(warmup):
        state, metrics = step(state, gbatch)
    float(jax.device_get(metrics["loss"]))
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, gbatch)
    loss = float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    assert np.isfinite(loss)
    img_per_s = local_b * steps / dt
    tag = "f32" if compute_dtype is None else "bf16"
    _emit(f"train_fixed_{h}x{w}_b{b}_{tag}", img_per_s, "images/sec",
          per_chip=img_per_s / ndev)


def bench_pipeline(jnp, compute_dtype, *, n_images, batch, epochs,
                   lo=384, hi=1024, dominant=(768, 1024), u8=False,
                   remat="off"):
    """The number that predicts real training time: variable-resolution
    images through the full pipeline (bucketing, padding, per-shape
    compiles) into the sharded train step.

    Two throughputs are reported:

    * ``value`` — steady-state img/s over the epoch's PRE-STAGED device
      batches (bucket-shape switching and donation included; host->device
      transfer excluded, and steps are dispatched back-to-back with ONE
      terminal fetch — the train loop's windowed metric fetch every
      check_every=8 steps is NOT in this number, so on dispatch-bound
      tunnels the loop achieves somewhat less; the end_to_end entry
      carries that cost).  On real TPU hosts PCIe (tens of
      GB/s) overlapped by prefetch keeps the end-to-end rate at this
      number, so this is the capability figure.
    * ``end_to_end_img_per_s`` — the same epoch through ``train_one_epoch``
      with prefetch, transfers included.  Over the axon dev tunnel H2D
      sustains only ~30 MB/s and worsens when overlapped with compute, so
      there this measures the tunnel, not the framework
      (``transfer_mb_per_batch`` quantifies the pressure).
    """
    import jax

    from can_tpu.data import ShardedBatcher
    from can_tpu.models import cannet_apply, cannet_init
    from can_tpu.parallel import make_dp_train_step, make_global_batch, make_mesh
    from can_tpu.train import (
        create_train_state,
        make_lr_schedule,
        make_optimizer,
        train_one_epoch,
    )

    ndev = jax.device_count()
    mesh = make_mesh()
    ds = SynthVarResDataset(n_images, lo=lo, hi=hi, dominant=dominant, u8=u8)
    max_buckets = int(os.environ.get("BENCH_SUITE_MAX_BUCKETS", "24"))
    # remnant sub-batches on by default (the CLI default); quantum = ndev so
    # every sub-batch still splits across the dp mesh axis
    remnant = not os.environ.get("BENCH_SUITE_NO_REMNANT")
    from can_tpu.cli.common import DEVICE_LAUNCH_COST_MPX, max_launch_pixels

    # the QUOTED number below is steady-state compute (launches enqueued
    # back-to-back), so the schedule is planned at DEVICE-regime launch
    # pricing — the r5 suite planned at the tunnel's 2.0 Mpx and then
    # paid 30.7% pixel overhead (b16) in the very regime that gets
    # launches nearly free (VERDICT r5 item 7).  Override the env var to
    # study dispatch-bound pricing.
    launch_mpx = float(os.environ.get("BENCH_SUITE_LAUNCH_COST_MPX",
                                      str(DEVICE_LAUNCH_COST_MPX)))
    plan_mode = os.environ.get("BENCH_SUITE_PLAN_MODE", "cost")
    cap = (max_launch_pixels(bf16=compute_dtype is not None, shards=ndev)
           if remnant else None)
    batcher = ShardedBatcher(ds, batch * ndev, shuffle=True, seed=0,
                             pad_multiple="auto", max_buckets=max_buckets,
                             remnant_sizes=remnant, batch_quantum=ndev,
                             launch_cost_px=launch_mpx * 1e6,
                             max_launch_px=cap, plan_mode=plan_mode)
    opt = make_optimizer(make_lr_schedule(1e-7, world_size=ndev))
    state = create_train_state(cannet_init(jax.random.key(0)), opt)
    put = lambda b: make_global_batch(b, mesh)

    def make_step():
        # per-bucket remat (VERDICT r3 item 3): THE CLI's dispatch, shared
        # via make_bucketed_train_step — jax.checkpoint only on bucket
        # shapes the policy flags, so b16 varres runs where it used to OOM
        from can_tpu.cli.common import make_bucketed_train_step, make_remat_policy

        policy = make_remat_policy(remat, global_batch=batch * ndev,
                                   bf16=compute_dtype is not None,
                                   shards=ndev)
        return make_bucketed_train_step(cannet_apply, opt, mesh,
                                        compute_dtype=compute_dtype,
                                        policy=policy)

    step = make_step()

    # epoch 0 end-to-end: pays every bucket-shape compile (near zero on a
    # second fresh process once the persistent cache is populated).  With
    # BENCH_TELEMETRY_DIR the epochs run with the bus attached: per-shape
    # compile events, step windows, and stall accounting land in the same
    # JSONL schema a training run writes.
    t0 = time.perf_counter()
    state, s0 = train_one_epoch(step, state, batcher.epoch(0), put_fn=put,
                                epoch=0, show_progress=False,
                                telemetry=_TELEMETRY)
    compile_epoch_s = time.perf_counter() - t0

    # steady-state end-to-end (transfers + prefetch overlap included)
    state, s1 = train_one_epoch(step, state, batcher.epoch(1), put_fn=put,
                                epoch=1, show_progress=False,
                                telemetry=_TELEMETRY)

    # warm restart: drop the in-memory executables (what a fresh process
    # starts without) but keep the on-disk cache — the epoch now measures
    # deserialisation instead of compilation.  Only meaningful when the
    # persistent cache is active (auto mode skips the CPU smoke backend).
    warm_compile_epoch_s = None
    if jax.config.jax_compilation_cache_dir:
        jax.clear_caches()
        step = make_step()
        t0 = time.perf_counter()
        state, _ = train_one_epoch(step, state, batcher.epoch(1), put_fn=put,
                                   epoch=1, show_progress=False)
        warm_compile_epoch_s = round(time.perf_counter() - t0, 1)

    # steady-state compute: stage one epoch's batches on device, then step
    staged = [put(b) for b in batcher.epoch(2)]
    jax.block_until_ready(staged[-1]["image"])
    n_imgs = sum(float(np.sum(jax.device_get(g["sample_mask"]))) for g in staged)
    mb = sum(g["image"].nbytes for g in staged) / 1e6 / len(staged)
    for g in staged:  # warm pass (shapes already compiled in epoch 0)
        state, metrics = step(state, g)
    float(jax.device_get(metrics["loss"]))
    t0 = time.perf_counter()
    for _ in range(max(1, epochs - 1)):
        for g in staged:
            state, metrics = step(state, g)
    float(jax.device_get(metrics["loss"]))
    dt = time.perf_counter() - t0
    compute_img_per_s = n_imgs * max(1, epochs - 1) / dt

    tag = ("f32" if compute_dtype is None else "bf16") + ("_u8" if u8 else "")
    if remat != "off":
        tag += f"_remat_{remat}"
    # the QUOTED varres number (VERDICT r4 missing-4) is the end-to-end
    # one: pipeline + transfer + compute through train_one_epoch with
    # prefetch overlap — emitted as its own record so it can't be
    # mistaken for the staged-compute ceiling below
    _emit(f"train_pipeline_varres_b{batch}_{tag}_end_to_end",
          s1.img_per_s, "images/sec", per_chip=s1.img_per_s / ndev,
          steady_state_compute_img_per_s=round(compute_img_per_s, 3))
    planner = batcher.planner_stats(1) if remnant else {}
    if _TELEMETRY is not None and planner:
        _TELEMETRY.emit("data.planner", realized_programs=s1.programs,
                        **planner)
    _emit(f"train_pipeline_varres_b{batch}_{tag}", compute_img_per_s,
          "images/sec", per_chip=compute_img_per_s / ndev,
          end_to_end_img_per_s=round(s1.img_per_s, 3),
          compile_epoch_s=round(compile_epoch_s, 1),
          warm_compile_epoch_s=warm_compile_epoch_s,
          transfer_mb_per_batch=round(mb, 1),
          distinct_shapes=s1.distinct_shapes,
          programs=batcher.program_count(1),
          padding_overhead=round(batcher.padding_overhead(), 4),
          schedule_overhead=round(batcher.schedule_overhead(1), 4),
          max_buckets=max_buckets,
          remnant_batches=remnant,
          launch_cost_mpx=launch_mpx,
          plan_mode=plan_mode,
          lowered_launches=planner.get("lowered_launches"),
          buckets=batcher.describe_buckets())


def bench_host_pipeline(*, n_images, batch, h=576, w=768, workers=(0, 4, 8),
                        jpeg_quality=90, repeats=5, cache_mb=1024):
    """Host-side materialisation rate on REAL files — no device anywhere.

    Writes n JPEG images + full-res float32 ``.npy`` density maps (the
    on-disk format the reference trains from), then times full
    ``ShardedBatcher.epoch`` passes — JPEG decode, grayscale/alpha
    handling, flip, /8-snap cv2 resize, normalise, pad — at each worker
    count, across the pipeline's storage tiers: legacy decode, the
    prepared 1/8-density store (data/prepared.py — the offline bake that
    kills the per-epoch 1.7 MB density load+resize), and the prepared
    store plus the in-RAM decoded-item cache (the dataset-fits-in-RAM
    ceiling).  The chip consumes ~95 img/s at 576x768 (BENCH_r02); this
    measures whether the host can feed it.

    VARIANCE-AWARE (VERDICT r5 weak #2): each configuration times
    ``repeats`` distinct epochs and reports the MEDIAN as ``value`` plus
    the min/max/spread — single-epoch timings on a small n_images wobble
    enough (~±5% observed) to manufacture non-monotonic worker-count
    "anomalies" out of noise, which is exactly what the spread field now
    makes checkable.
    """
    import shutil
    import tempfile

    import cv2
    from PIL import Image

    from can_tpu.data import CrowdDataset, ItemCache, ShardedBatcher
    from can_tpu.data.prepared import write_store

    tmp = tempfile.mkdtemp(prefix="can_tpu_hostbench_")
    img_dir = os.path.join(tmp, "images")
    gt_dir = os.path.join(tmp, "ground_truth")
    os.makedirs(img_dir)
    os.makedirs(gt_dir)
    rng = np.random.default_rng(0)
    try:
        for i in range(n_images):
            # smooth-ish content so JPEG size/decode cost is realistic
            # (pure noise decodes slower than photographs)
            base = rng.integers(0, 256, (h // 8, w // 8, 3), np.uint8)
            arr = cv2.resize(base, (w, h), interpolation=cv2.INTER_LINEAR)
            Image.fromarray(arr).save(
                os.path.join(img_dir, f"img_{i:04d}.jpg"),
                quality=jpeg_quality)
            np.save(os.path.join(gt_dir, f"img_{i:04d}.npy"),
                    rng.random((h, w), np.float32))
        write_store(img_dir, gt_dir)
        # (u8, prepared, cached): u8 = the --u8-input transfer mode
        # (flip/resize on bytes, no host normalise); prepared = the baked
        # 1/8 store; cached = + bounded decoded-item LRU
        configs = [(False, False, False), (True, False, False),
                   (False, True, False), (True, True, False),
                   (True, True, True)]
        combos = []
        for u8, prep, cached in configs:
            cache = ItemCache(int(cache_mb * 1e6)) if cached else None
            ds = CrowdDataset(img_dir, gt_dir, gt_downsample=8,
                              phase="train", u8_output=u8,
                              prepared="auto" if prep else "off",
                              item_cache=cache)
            assert (ds.prepared is not None) == prep, ds.prepared_note
            tag = (("_u8" if u8 else "") + ("_prepared" if prep else "")
                   + ("_cache" if cached else ""))
            for wk in workers:
                batcher = ShardedBatcher(ds, batch, shuffle=True, seed=0,
                                         pad_multiple="auto", num_workers=wk)
                combos.append({"tag": tag, "wk": wk, "batcher": batcher,
                               "cache": cache, "rates": [],
                               "cache_delta": {"hits": 0, "misses": 0,
                                               "evictions": 0}})
        try:
            # warm fs cache / thread pools (a second epoch for the cached
            # combos so both flip orientations are mostly resident), then
            # time epochs ROUND-ROBIN across combos: host-load drift over
            # the suite's runtime lands on every combo instead of biasing
            # whichever config ran last (measured ~15% drift on the 2-cpu
            # bench host — enough to invert a sequential comparison)
            for c in combos:
                for we in range(2 if c["cache"] is not None else 1):
                    list(c["batcher"].epoch(we))
            for rep in range(repeats):
                for c in combos:
                    cache = c["cache"]
                    before = cache.stats() if cache is not None else None
                    t0 = time.perf_counter()
                    n_done = sum(b.num_valid
                                 for b in c["batcher"].epoch(2 + rep))
                    c["rates"].append(n_done / (time.perf_counter() - t0))
                    if cache is not None:
                        # attribute counter deltas to THIS combo's timed
                        # epochs — the cache object is shared across the
                        # config's worker counts, so cumulative totals
                        # describe no single measurement
                        after = cache.stats()
                        for k in c["cache_delta"]:
                            c["cache_delta"][k] += after[k] - before[k]
        finally:
            for c in combos:
                c["batcher"].close()  # 15 abandoned pools leaked threads
        for c in combos:
            rates = sorted(c["rates"])
            med = float(np.median(rates))
            extra = {}
            if c["cache"] is not None:
                d = dict(c["cache_delta"])
                got = d["hits"] + d["misses"]
                d["hit_rate"] = round(d["hits"] / got, 4) if got else None
                d["bytes"] = c["cache"].stats()["bytes"]
                extra["cache"] = d
            _emit(f"host_pipeline_{h}x{w}_b{batch}_w{c['wk']}{c['tag']}",
                  med, "images/sec", workers=c["wk"],
                  cpus=os.cpu_count(), n_images=n_images,
                  repeats=repeats,
                  img_per_s_min=round(rates[0], 3),
                  img_per_s_max=round(rates[-1], 3),
                  spread_pct=round(100 * (rates[-1] - rates[0])
                                   / max(med, 1e-9), 1),
                  **extra)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_eval_pipeline(jnp, compute_dtype, *, n_images, batch, lo, hi,
                        dominant, u8=False):
    """End-to-end ``evaluate()``: host materialisation + H2D transfer +
    device compute + windowed metric fetches, with the background-thread
    prefetch OFF vs ON (VERDICT r4 weak-1: eval used to pay every
    transfer in series with the device; this measures what
    prefetch_to_device buys on this host — expect a large move on
    dispatch-latency-bound tunnels, small where H2D is already cheap).
    Metrics must be bit-identical across depths (asserted)."""
    import jax

    from can_tpu.data import ShardedBatcher
    from can_tpu.models import cannet_apply, cannet_init
    from can_tpu.parallel import make_dp_eval_step, make_global_batch, make_mesh
    from can_tpu.train import evaluate

    ndev = jax.device_count()
    mesh = make_mesh()
    ds = SynthVarResDataset(n_images, lo=lo, hi=hi, dominant=dominant, u8=u8)
    batcher = ShardedBatcher(ds, batch * ndev, shuffle=False, seed=0,
                             pad_multiple="auto", max_buckets=8,
                             remnant_sizes=True, batch_quantum=ndev)
    params = cannet_init(jax.random.key(0))
    ev = make_dp_eval_step(cannet_apply, mesh, compute_dtype=compute_dtype)
    put = lambda b: make_global_batch(b, mesh)

    # one throwaway pass pays the per-bucket-shape compiles
    evaluate(ev, params, batcher.epoch(0), put_fn=put,
             dataset_size=batcher.dataset_size)
    got = {}
    for depth in (0, 2):
        t0 = time.perf_counter()
        got[depth] = evaluate(ev, params, batcher.epoch(0), put_fn=put,
                              dataset_size=batcher.dataset_size,
                              prefetch=depth)
        got[depth]["img_per_s"] = n_images / (time.perf_counter() - t0)
    assert got[0]["mae"] == got[2]["mae"], "prefetch changed eval math"
    tag = ("f32" if compute_dtype is None else "bf16") + ("_u8" if u8 else "")
    dom = f"{dominant[0]}x{dominant[1]}"
    for depth in (0, 2):
        v = got[depth]["img_per_s"]
        _emit(f"eval_pipeline_varres_{dom}_b{batch}_{tag}_prefetch{depth}",
              v, "images/sec", per_chip_img_per_s=round(v / ndev, 3),
              buckets=batcher.describe_buckets())
    batcher.close()


def bench_plan_space(*, n_images=64, batches=(8, 16), repeats=5,
                     max_buckets=24,
                     launch_costs_mpx=None) -> list:
    """Plan-space ablation tier: SIMULATED (host-only, no device) sweep
    over the batch planner's candidate space on the suite's varres
    distribution, under the v5e HBM cap the r5 chip run hit.

    For every (batch, plan mode, launch pricing) candidate the tier
    builds the full epoch plan and reports predicted cost (the planner's
    own model) NEXT TO realized cost re-derived from the emitted
    schedule — the two must agree exactly (a divergence is a planner
    bug; ``predicted_eq_realized`` makes it greppable), plus the
    padding/schedule overheads, program/launch/lowered counts, and the
    plan build wall time, median-of-``repeats`` with min/max/spread and
    rounds interleaved round-robin across candidates (PR-3
    variance-aware style — build time is the only measured quantity
    here, and host drift lands on every candidate instead of the last).

    The b16 x legacy x 2.0-Mpx row reproduces BENCH_SUITE_r05's 30.67%
    schedule overhead bit-exactly on any host; the b16 x cost x
    device-pricing row is the round-8 headline (VERDICT r5 item 7).
    """
    from can_tpu.cli.common import (
        DEVICE_LAUNCH_COST_MPX,
        hbm_bytes_for_device_kind,
        max_launch_pixels,
    )
    from can_tpu.data import ShardedBatcher

    if launch_costs_mpx is None:
        launch_costs_mpx = (2.0, 0.5, DEVICE_LAUNCH_COST_MPX)
    # the r5 chip configuration: v5e spec HBM (memory_stats absent on the
    # axon client, so the spec fallback was what capped the run), bf16,
    # single chip
    cap = max_launch_pixels(bf16=True, shards=1,
                            hbm_bytes=hbm_bytes_for_device_kind("TPU v5e"))
    ds = SynthVarResDataset(n_images)
    combos = [{"batch": b, "mode": mode, "mpx": mpx, "times": []}
              for b in batches
              for mode in ("legacy", "cost")
              for mpx in launch_costs_mpx]

    def build(c):
        t0 = time.perf_counter()
        sb = ShardedBatcher(ds, c["batch"], shuffle=True, seed=0,
                            pad_multiple="auto", max_buckets=max_buckets,
                            remnant_sizes=True, batch_quantum=1,
                            launch_cost_px=c["mpx"] * 1e6,
                            max_launch_px=cap, plan_mode=c["mode"])
        sb.planner_stats(1)  # force the plan + schedule walk
        return sb, time.perf_counter() - t0

    records = []
    for rep in range(repeats):
        for c in combos:
            sb, dt = build(c)
            c["times"].append(dt)
            if rep == repeats - 1:
                c["batcher"] = sb
    for c in combos:
        sb = c["batcher"]
        st = sb.planner_stats(1)
        times = sorted(c["times"])
        med = float(np.median(times))
        name = (f"plan_space_varres_b{c['batch']}_{c['mode']}"
                f"_L{str(c['mpx']).replace('.', 'p')}")
        extra = dict(
            plan_mode=c["mode"], launch_cost_mpx=c["mpx"],
            batch=c["batch"], max_buckets=max_buckets,
            max_launch_mpx=round(cap / 1e6, 3),
            padding_overhead=st["padding_overhead"],
            programs=st["program_count"],
            launches=st["batches_per_epoch"],
            lowered_launches=st.get("lowered_launches"),
            menu_sizes=st.get("menu_sizes"),
            predicted_cost_mpx=round(st.get("plan_cost_px",
                                            st["realized_cost_px"]) / 1e6, 3),
            realized_cost_mpx=round(st["realized_cost_px"] / 1e6, 3),
            predicted_eq_realized=bool(
                abs(st.get("plan_cost_px", st["realized_cost_px"])
                    - st["realized_cost_px"]) < 1.0),
            plan_s=round(med, 4),
            plan_s_min=round(times[0], 4), plan_s_max=round(times[-1], 4),
            spread_pct=round(100 * (times[-1] - times[0])
                             / max(med, 1e-9), 1),
            buckets=sb.describe_buckets(),
        )
        _emit(name, st["schedule_overhead"], "overhead_frac", **extra)
        records.append({"metric": name, "value": st["schedule_overhead"],
                        "unit": "overhead_frac", **extra})
    return records


def bench_perf_ledger(jnp, compute_dtype, *, n_images=32, batch=2,
                      lo=64, hi=160, dominant=(128, 160),
                      out_path=None) -> list:
    """Perf-attribution tier: run the varres pipeline with the
    ProgramCostLedger armed and emit the ledger as bench records + one
    committed artifact (``PERF_LEDGER_cpu_r09.json``).

    The per-program flops/bytes come from XLA ``cost_analysis()`` and are
    DETERMINISTIC for a given jax version and config — which is what makes
    this tier gateable: ``tools/ci_bench_gate.sh`` compare-only mode
    (CI_BENCH_ONLY=perf) trips when a model or XLA change silently moves a
    compiled program's cost.  MFU / mean_s ride along as extra fields
    (informational — timing noise on the CPU box, and the CPU peak is
    labelled NOMINAL), value = gflops is what gates.  Small shapes by
    design, in quick AND full mode: the ledger's bookkeeping is
    shape-agnostic, and chip-scale numbers belong to telemetry_report on
    real runs, not this CPU gate.
    """
    import jax

    from can_tpu import obs
    from can_tpu.cli.common import DEVICE_LAUNCH_COST_MPX
    from can_tpu.data import ShardedBatcher
    from can_tpu.models import cannet_apply, cannet_init
    from can_tpu.parallel import make_dp_train_step, make_global_batch, make_mesh
    from can_tpu.train import (
        create_train_state,
        make_lr_schedule,
        make_optimizer,
        train_one_epoch,
    )

    ndev = jax.device_count()
    mesh = make_mesh()
    ds = SynthVarResDataset(n_images, lo=lo, hi=hi, dominant=dominant)
    batcher = ShardedBatcher(ds, batch * ndev, shuffle=True, seed=0,
                             pad_multiple="auto", max_buckets=8,
                             remnant_sizes=True, batch_quantum=ndev,
                             launch_cost_px=DEVICE_LAUNCH_COST_MPX * 1e6)
    opt = make_optimizer(make_lr_schedule(1e-7, world_size=ndev))
    state = create_train_state(cannet_init(jax.random.key(0)), opt)
    step = make_dp_train_step(cannet_apply, opt, mesh,
                              compute_dtype=compute_dtype)
    put = lambda b: make_global_batch(b, mesh)

    tel = _TELEMETRY if _TELEMETRY is not None else obs.Telemetry()
    prev_ledger = tel.ledger
    # The suite shares one Telemetry across tiers, and earlier tiers (same
    # synth distribution, fresh jit steps) may already hold this tier's
    # exact train_step signatures in signature_registry — which would
    # suppress ledger.register for those programs (dropping them from the
    # gate artifact) AND fold their genuine first-call compile time into
    # the steady-state means.  Scope a clean registry for the tier.
    prev_reg = tel.signature_registry.pop("train_step", None)
    tel.ledger = ledger = obs.ProgramCostLedger(
        compute="bf16" if compute_dtype is not None else "f32",
        plan_launch_cost_px=DEVICE_LAUNCH_COST_MPX * 1e6)
    try:
        # epoch 0 pays the compiles (registering every program's cost);
        # epoch 1 provides the steady-state timings MFU joins against
        state, _ = train_one_epoch(step, state, batcher.epoch(0),
                                   put_fn=put, epoch=0,
                                   show_progress=False, telemetry=tel)
        state, _ = train_one_epoch(step, state, batcher.epoch(1),
                                   put_fn=put, epoch=1,
                                   show_progress=False, telemetry=tel)
    finally:
        tel.ledger = prev_ledger
        if prev_reg is not None:
            tel.signature_registry["train_step"] = prev_reg
        else:
            tel.signature_registry.pop("train_step", None)
        batcher.close()

    tag = "f32" if compute_dtype is None else "bf16"
    records = []
    for r in ledger.rows():
        if r["name"] != "train_step" or not r["flops"]:
            continue
        b_, h_, w_ = r["shape"][0], r["shape"][1], r["shape"][2]
        rec = {"metric": f"perf_ledger_train_{h_}x{w_}_b{b_}_{tag}",
               "value": round(r["flops"] / 1e9, 3), "unit": "gflops",
               "bytes_gb": (round(r["bytes_accessed"] / 1e9, 4)
                            if r["bytes_accessed"] else None),
               "intensity_flop_per_byte": r["intensity"],
               "roofline": r["roofline"],
               "mfu": r["mfu"], "bw_util": r["bw_util"],
               "mean_step_s": r["mean_s"], "launches": r["launches"]}
        records.append(rec)
        if _TELEMETRY is not None:
            _TELEMETRY.emit("bench", **rec)
        print(json.dumps(rec), flush=True)
    summary = ledger.summary()
    out = out_path or os.environ.get("BENCH_PERF_LEDGER_OUT")
    if not out:
        # the committed gate baseline is only the default for an EXPLICIT
        # perf-only run (the documented regeneration command); the perf
        # tier riding along in a full suite run writes the bench_serve
        # -style _local name instead of silently dirtying the checkout
        out = ("PERF_LEDGER_cpu_r09.json"
               if os.environ.get("BENCH_SUITE_ONLY") == "perf"
               else "PERF_LEDGER_local.json")
    doc = {"metric": "perf_ledger",
           "config": {"n_images": n_images, "batch": batch, "lo": lo,
                      "hi": hi, "dominant": list(dominant), "tag": tag,
                      "devices": ndev,
                      "platform": jax.devices()[0].platform},
           "summary": summary,
           "detail": ledger.rows(),
           "results": records}
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# perf ledger: {len(records)} programs, "
          f"mfu_weighted={summary.get('mfu_weighted')} "
          f"(peak {summary.get('peak_source')}) -> {out}", flush=True)
    return records


def bench_bn(jnp, compute_dtype, *, b=2, h=64, w=64, steps=3,
             out_path=None) -> list:
    """BatchNorm-moments tier: the syncBN train step per moments path —
    plain (no-BN ceiling) vs masked-twopass vs onepass vs pallas
    (interpret mode off-TPU) — attributed through the ProgramCostLedger.

    Two gateable records per variant, both from deterministic XLA
    ``cost_analysis()`` (same contract as the perf tier):

    * unit ``gflops`` — two-sided (a BN path must not silently gain or
      lose work);
    * unit ``gbytes`` — gated UPWARD only (bytes growing = the moments
      path lost a fusion; shrinking is the improvement this tier exists
      to hold).  The r10 acceptance pin rides this artifact: the onepass
      rows must show strictly fewer bytes than the twopass rows
      (tests/test_batchnorm.py::TestBNBenchArtifact).

    img/s and MFU ride as informational extras (CPU timing noise — the
    committed artifact's numbers gate nothing).  A running-stats parity
    delta vs twopass is recorded per variant: the bench double-checks the
    test suite's numerics pin on the exact shapes it prices.
    """
    import functools

    import jax

    from can_tpu.data.batching import Batch
    from can_tpu.models import cannet_apply, cannet_init, init_batch_stats
    from can_tpu.models.cannet import LocalOps
    from can_tpu.obs.costs import ProgramCostLedger
    from can_tpu.ops.bn_moments import make_bn_ops
    from can_tpu.parallel import make_dp_train_step, make_global_batch, make_mesh
    from can_tpu.train import (
        batch_signature,
        create_train_state,
        make_lr_schedule,
        make_optimizer,
    )

    ndev = jax.device_count()
    mesh = make_mesh()
    on_tpu = jax.devices()[0].platform == "tpu"
    rng = np.random.default_rng(0)
    local_b = b * ndev
    # real padding in the batch so the MASKED moments are what's priced:
    # the last /8-row of every map is bucket padding and the final slot is
    # a dead fill slot — all-ones masks would let XLA fold the multiply
    pm = np.ones((local_b, h // 8, w // 8, 1), np.float32)
    pm[:, -1] = 0.0
    sm = np.ones((local_b,), np.float32)
    sm[-1] = 0.0
    batch = Batch(
        image=rng.normal(size=(local_b, h, w, 3)).astype(np.float32),
        dmap=rng.uniform(size=(local_b, h // 8, w // 8, 1)).astype(np.float32),
        pixel_mask=pm,
        sample_mask=sm,
    )
    gbatch = make_global_batch(batch, mesh)
    opt = make_optimizer(make_lr_schedule(1e-7, world_size=ndev))
    plain_params = cannet_init(jax.random.key(0))
    bn_params = cannet_init(jax.random.key(0), batch_norm=True)

    variants = [("plain", "none"), ("syncbn_twopass", "twopass"),
                ("syncbn_onepass", "onepass"), ("syncbn_pallas", "pallas")]
    tag = "f32" if compute_dtype is None else "bf16"
    compute = "bf16" if compute_dtype is not None else "f32"
    records = []
    detail = []
    stats_by_variant = {}
    for name, impl in variants:
        if impl == "pallas" and ndev > 1:
            # same refusal as the train CLI: pallas_call has no GSPMD
            # partitioning rule, and this tier prices the jit-sharded dp
            # step — a forced gather would corrupt the A/B bytes.  The
            # committed baseline is devices=1 (like the perf tier).
            print(f"# bn tier: skipping {name} on the {ndev}-device GSPMD "
                  "dp step (no pallas partitioning rule)", flush=True)
            continue
        ledger = ProgramCostLedger(compute=compute)
        if impl == "none":
            apply_fn, params, stats = cannet_apply, plain_params, None
        else:
            bn_ops = make_bn_ops(impl, interpret=not on_tpu)
            apply_fn = (cannet_apply if bn_ops is None else
                        functools.partial(cannet_apply,
                                          ops=LocalOps(bn_ops=bn_ops)))
            params, stats = bn_params, init_batch_stats(bn_params)
        state = create_train_state(params, opt, stats)
        step = make_dp_train_step(apply_fn, opt, mesh, donate=False,
                                  compute_dtype=compute_dtype)
        # deterministic cost BEFORE the timed loop (registration also
        # pays the compile, so the loop below times steady state)
        ledger.register(name, batch_signature(gbatch), fn=step,
                        args=(state, gbatch))
        state, metrics = step(state, gbatch)  # warm + the parity state
        float(jax.device_get(metrics["loss"]))
        if state.batch_stats is not None:
            stats_by_variant[name] = jax.device_get(state.batch_stats)
        t0 = time.perf_counter()
        for _ in range(steps):
            state, metrics = step(state, gbatch)
        float(jax.device_get(metrics["loss"]))
        dt = time.perf_counter() - t0
        ledger.observe(name, gbatch["image"].shape, dt, n=steps)
        (row,) = ledger.rows()
        parity = None
        if name in stats_by_variant and "syncbn_twopass" in stats_by_variant \
                and name != "syncbn_twopass":
            ref = stats_by_variant["syncbn_twopass"]
            got = stats_by_variant[name]
            # scale-relative per leaf (max delta over the leaf's own max
            # magnitude): elementwise relative error on near-zero running
            # -stat entries would read bf16 rounding as divergence
            parity = max(
                float(np.max(np.abs(np.asarray(a) - np.asarray(b)))
                      / max(float(np.max(np.abs(np.asarray(b)))), 1e-6))
                for a, b in zip(jax.tree.leaves(got), jax.tree.leaves(ref)))
        extra = dict(
            bytes_gb=(round(row["bytes_accessed"] / 1e9, 4)
                      if row["bytes_accessed"] else None),
            img_per_s=round(local_b * steps / dt, 2),
            mean_step_s=row["mean_s"], mfu=row["mfu"],
            roofline=row["roofline"], interpret=(impl == "pallas"
                                                 and not on_tpu),
            parity_vs_twopass_max_rel=(round(parity, 6)
                                       if parity is not None else None),
        )
        stem = f"bn_train_{h}x{w}_b{b}_{tag}_{name}"
        recs = []
        if row["flops"]:
            # same rule as bytes below: a backend that stops reporting
            # flops must fail the gate loudly (missing metric -> removed/
            # min-overlap), never pass vacuously on an incomparable null
            recs.append({"metric": stem, "unit": "gflops",
                         "value": round(row["flops"] / 1e9, 3), **extra})
        if row["bytes_accessed"]:
            recs.append({"metric": f"bn_bytes_{h}x{w}_b{b}_{tag}_{name}",
                         "value": round(row["bytes_accessed"] / 1e9, 4),
                         "unit": "gbytes", "variant": name})
        for r in recs:
            records.append(r)
            if _TELEMETRY is not None:
                _TELEMETRY.emit("bench", **r)
            print(json.dumps(r), flush=True)
        detail.extend(ledger.rows())

    out = out_path or os.environ.get("BENCH_BN_OUT")
    if not out:
        # committed gate baseline only for the EXPLICIT bn-only run, same
        # rule as the perf tier's artifact
        out = ("BENCH_BN_cpu_r10.json"
               if os.environ.get("BENCH_SUITE_ONLY") == "bn"
               else "BENCH_BN_local.json")
    doc = {"metric": "bench_bn",
           "config": {"b": b, "h": h, "w": w, "steps": steps, "tag": tag,
                      "devices": ndev,
                      "platform": jax.devices()[0].platform},
           "detail": detail,
           "results": records}
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# bn tier: {len(records)} records -> {out}", flush=True)
    return records


def bench_serve_fleet(*, replicas=2, modes=("f32", "bf16", "int8"),
                      n_requests=32, repeats=3, max_batch=4,
                      rate_rps=None, out_path=None) -> list:
    """Serving-fleet tier: the FULL fleet stack (queue -> batcher ->
    work-stealing replicas) per ``--serve-dtype`` mode, open-loop at a
    FIXED offered rate so p99 is comparable run-to-run (an adaptive rate
    would change the offered load between baseline and fresh run, making
    the latency gate meaningless).

    Per mode: ``serve_fleet_p99_<mode>`` (unit ``ms``: bench_compare
    gates latency UPWARD-only) and ``serve_fleet_rps_<mode>`` (unit
    ``req/s``: gates downward), both median-of-``repeats`` with the
    measured min/max ``spread_pct`` recorded — the gate's noise floor,
    same discipline as the host tier.  Quantized modes also record their
    f32 parity-ladder grade (context, never gated: it is deterministic
    and pinned by tests/test_fleet.py instead)."""
    import statistics

    import jax

    from bench_serve import run_open_loop
    from can_tpu.models import cannet_init
    from can_tpu.obs import Telemetry
    from can_tpu.serve import (
        CountService,
        FleetEngine,
        ServeEngine,
        parity_report,
        prepare_image,
    )
    from can_tpu.serve.quant import param_bytes

    if rate_rps is None:
        # BELOW the CPU gate box's ~5 req/s fleet capacity on purpose: an
        # offered rate past saturation turns p99 into an end-of-arrivals
        # backlog measure that grows with request count — stable gating
        # needs the queue to drain between bursts (~75% utilization).
        # Real-chip sweeps override BENCH_FLEET_RATE upward.
        rate_rps = float(os.environ.get("BENCH_FLEET_RATE", "4"))
    if len(jax.devices()) < replicas:
        # the tier pins one device per replica; a plain 1-device suite
        # run must skip it, not abort the whole suite (the CI gate runs
        # it via BENCH_SUITE_PLATFORM=cpu8)
        print(f"# fleet tier skipped: {len(jax.devices())} device(s) < "
              f"replicas={replicas} (use BENCH_SUITE_PLATFORM=cpu8 or a "
              f"multi-chip host)", flush=True)
        return []
    params = cannet_init(jax.random.key(0))
    sizes = [(64, 64), (96, 64)]
    ladder = (tuple(sorted({h for h, _ in sizes})),
              tuple(sorted({w for _, w in sizes})))
    buckets = [(h, w) for h in ladder[0] for w in ladder[1]]
    rng = np.random.default_rng(7)
    images = [prepare_image(
        (rng.uniform(0, 1, (h, w, 3)) * 255).astype(np.uint8))
        for h, w in sizes]
    records = []
    ref_engine = None
    for mode in modes:
        tel = Telemetry()
        fleet = FleetEngine(params, replicas=replicas, serve_dtype=mode,
                            telemetry=tel, name=f"fleet_{mode}")
        svc = CountService(fleet, max_batch=max_batch, max_wait_ms=2.0,
                           queue_capacity=256, bucket_ladder=ladder,
                           telemetry=tel)
        warm = svc.warmup(buckets)
        parity = None
        if mode != "f32":
            if ref_engine is None:
                ref_engine = ServeEngine(params, telemetry=tel,
                                         name="fleet_parity_f32")
            quant = ServeEngine(params, serve_dtype=mode, telemetry=tel,
                                name=f"fleet_parity_{mode}")
            parity = parity_report(quant, ref_engine, images)
        p99s, rpss, rejects = [], [], 0
        with svc:
            for rep in range(repeats):
                o = run_open_loop(svc, images, n_requests, rate_rps,
                                  deadline_ms=30_000, seed=rep)
                p99s.append(o["p99_ms"])
                rpss.append(o["throughput_rps"])
                rejects += o["rejected"]
        st = svc.stats()
        spread = lambda xs: round(  # noqa: E731
            100.0 * (max(xs) - min(xs)) / max(statistics.median(xs), 1e-9),
            1)
        # compile budget is menu-aware since r14: one program per
        # (bucket, menu size, dtype) per replica (can_tpu/sched)
        menu_len = len(svc.sched.menu) if svc.sched is not None else 1
        base = {"replicas": replicas, "serve_dtype": mode,
                "offered_rps": rate_rps, "requests": n_requests,
                "repeats": repeats, "rejects": rejects,
                "warmup_compiles": warm["compiles"],
                "compiles_bounded":
                    fleet.compile_count
                    <= len(buckets) * replicas * menu_len,
                "param_bytes": param_bytes(
                    fleet.replicas[0].engine.params),
                "replica_batches": {k: v["batches"]
                                    for k, v in st["replicas"].items()}}
        if parity is not None:
            base["parity_grade"] = parity["grade"]
            base["parity_worst_rel"] = parity["worst_rel_count_delta"]
        rec_p99 = {"metric": f"serve_fleet_p99_{mode}",
                   "value": round(statistics.median(p99s), 3),
                   "unit": "ms", "spread_pct": spread(p99s), **base}
        rec_rps = {"metric": f"serve_fleet_rps_{mode}",
                   "value": round(statistics.median(rpss), 2),
                   "unit": "req/s", "spread_pct": spread(rpss), **base}
        for rec in (rec_p99, rec_rps):
            records.append(rec)
            if _TELEMETRY is not None:
                _TELEMETRY.emit("bench", **rec)
            print(json.dumps(rec), flush=True)
    out = out_path or os.environ.get("BENCH_FLEET_OUT")
    if not out:
        # committed gate baseline only for an explicit fleet-only run
        # (same overwrite rule as the perf/bn tiers)
        out = ("BENCH_FLEET_cpu_r11.json"
               if os.environ.get("BENCH_SUITE_ONLY") == "fleet"
               else "BENCH_FLEET_local.json")
    doc = {"metric": "serve_fleet",
           "config": {"replicas": replicas, "modes": list(modes),
                      "requests": n_requests, "repeats": repeats,
                      "rate_rps": rate_rps, "max_batch": max_batch,
                      "buckets": [f"{h}x{w}" for h, w in buckets],
                      "platform": jax.devices()[0].platform},
           "results": records}
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# fleet tier: {len(records)} records over {len(modes)} modes "
          f"-> {out}", flush=True)
    return records


def bench_sched(*, n_requests=32, repeats=3, max_batch=4,
                max_wait_ms=50.0, out_path=None) -> list:
    """Scheduling-core tier (r14): serve fill % / p99 / time-to-flush at
    LOW and MIXED load through the priced menu+flush core
    (can_tpu/sched), with the pre-r14 timer+pad-to-max arm measured in
    the SAME run as context — the committed artifact is the receipt
    that fill strictly improved at both loads with p99 no worse.

    Single engine on one device (runs on the plain CI box: no cpu8);
    mixed load reuses the fleet tier's offered-rate discipline (fixed
    rate below saturation so p99 is comparable run-to-run).  Gated
    records: ``serve_sched_fill_{low,mixed}`` (unit ``fill_pct``,
    bench_compare gates DOWNWARD only — fill dropping is the
    regression), ``serve_sched_p99_{low,mixed}`` (ms, upward),
    ``serve_sched_ttf_p95_low`` (ms, upward: submit->assembly wait at
    low load, the time-to-flush distribution vs the old timer), and
    ``serve_sched_rps_mixed`` (req/s, downward).  Each record carries
    the legacy arm's number as ``legacy_*`` context plus the
    predicted==realized receipt (``cost_mismatches`` must be 0)."""
    import statistics

    import jax

    from bench_serve import run_open_loop
    from can_tpu.models import cannet_init
    from can_tpu.obs import Telemetry
    from can_tpu.serve import CountService, ServeEngine, prepare_image

    low_rate = float(os.environ.get("BENCH_SCHED_LOW_RATE", "2"))
    mixed_rate = float(os.environ.get("BENCH_SCHED_MIXED_RATE", "4"))
    params = cannet_init(jax.random.key(0))
    sizes = [(64, 64), (96, 64)]
    ladder = (tuple(sorted({h for h, _ in sizes})),
              tuple(sorted({w for _, w in sizes})))
    buckets = [(h, w) for h in ladder[0] for w in ladder[1]]
    rng = np.random.default_rng(7)
    images = [prepare_image(
        (rng.uniform(0, 1, (h, w, 3)) * 255).astype(np.uint8))
        for h, w in sizes]

    def run_arm(tag, **svc_kw):
        mism = [0]
        tel = Telemetry([_SchedMismatchSink(mism)])
        engine = ServeEngine(params, telemetry=tel, name=f"sched_{tag}")
        svc = CountService(engine, max_batch=max_batch,
                           max_wait_ms=max_wait_ms, queue_capacity=256,
                           bucket_ladder=ladder, telemetry=tel, **svc_kw)
        warm = svc.warmup(buckets)
        out = {"warmup_compiles": warm["compiles"]}
        with svc:
            for phase, rate in (("low", low_rate), ("mixed", mixed_rate)):
                p99s, rpss, fills, ttfs = [], [], [], []
                for rep in range(repeats):
                    before = svc.stats()
                    o = run_open_loop(svc, images, n_requests, rate,
                                      deadline_ms=30_000, seed=rep)
                    after = svc.stats()
                    slots = after["batch_slots"] - before["batch_slots"]
                    valid = after["batch_valid"] - before["batch_valid"]
                    p99s.append(o["p99_ms"])
                    rpss.append(o["throughput_rps"])
                    fills.append(100.0 * valid / max(slots, 1))
                    if o["queue_wait_p95_ms"] is not None:
                        ttfs.append(o["queue_wait_p95_ms"])
                out[phase] = {"p99_ms": p99s, "rps": rpss, "fill": fills,
                              "ttf_p95_ms": ttfs}
        out["cost_mismatches"] = mism[0]
        out["compile_count"] = engine.compile_count
        return out

    # the priced arm (the r14 default) and the pre-r14 timer+pad arm,
    # same run, same offered traffic — the improvement receipt
    sched_arm = run_arm("priced")
    legacy_arm = run_arm("legacy", menu_budget=1, flush_policy="timer")

    med = statistics.median
    spread = lambda xs: round(  # noqa: E731
        100.0 * (max(xs) - min(xs)) / max(abs(med(xs)), 1e-9), 1)
    base = {"requests": n_requests, "repeats": repeats,
            "max_batch": max_batch, "max_wait_ms": max_wait_ms,
            "low_rate_rps": low_rate, "mixed_rate_rps": mixed_rate,
            "conditions": "fleet_r11-style fixed offered rate, 30s "
                          "deadline, buckets 64x64/96x64",
            "cost_mismatches": sched_arm["cost_mismatches"],
            "warmup_compiles": sched_arm["warmup_compiles"]}
    records = []

    def rec(metric, vals, unit, **extra):
        records.append({"metric": metric, "value": round(med(vals), 3),
                        "unit": unit, "spread_pct": spread(vals),
                        **base, **extra})

    rec("serve_sched_fill_low", sched_arm["low"]["fill"], "fill_pct",
        legacy_fill=round(med(legacy_arm["low"]["fill"]), 2))
    rec("serve_sched_fill_mixed", sched_arm["mixed"]["fill"], "fill_pct",
        legacy_fill=round(med(legacy_arm["mixed"]["fill"]), 2))
    rec("serve_sched_p99_low", sched_arm["low"]["p99_ms"], "ms",
        legacy_p99_ms=round(med(legacy_arm["low"]["p99_ms"]), 3))
    rec("serve_sched_p99_mixed", sched_arm["mixed"]["p99_ms"], "ms",
        legacy_p99_ms=round(med(legacy_arm["mixed"]["p99_ms"]), 3))
    rec("serve_sched_ttf_p95_low", sched_arm["low"]["ttf_p95_ms"], "ms",
        legacy_ttf_p95_ms=round(med(legacy_arm["low"]["ttf_p95_ms"]), 3))
    rec("serve_sched_rps_mixed", sched_arm["mixed"]["rps"], "req/s",
        legacy_rps=round(med(legacy_arm["mixed"]["rps"]), 2))
    for r in records:
        if _TELEMETRY is not None:
            _TELEMETRY.emit("bench", **r)
        print(json.dumps(r), flush=True)

    out = out_path or os.environ.get("BENCH_SCHED_OUT")
    if not out:
        # committed gate baseline only for an explicit sched-only run
        # (the perf/bn/fleet/autoscale no-self-overwrite rule, 5th use)
        out = ("BENCH_SCHED_cpu_r14.json"
               if os.environ.get("BENCH_SUITE_ONLY") == "sched"
               else "BENCH_SCHED_local.json")
    doc = {"metric": "serve_sched",
           "config": {**base,
                      "platform": jax.devices()[0].platform},
           "legacy_arm": {k: legacy_arm[k] for k in ("low", "mixed",
                                                     "warmup_compiles",
                                                     "cost_mismatches")},
           "results": records}
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# sched tier: {len(records)} records -> {out}", flush=True)
    return records


class _SchedMismatchSink:
    """Counts serve.batch events whose predicted cost != realized cost —
    the core's invariant, carried as a receipt in the sched artifact."""

    def __init__(self, counter):
        self._c = counter

    def emit(self, event):
        from can_tpu.sched.core import costs_match

        if event.get("kind") != "serve.batch":
            return
        p = event.get("payload", {})
        if not costs_match(p.get("predicted_cost_px"),
                           p.get("realized_cost_px")):
            self._c[0] += 1

    def close(self):
        pass


def _run_stream_load(service, images, *, n_streams, frames, rate_rps,
                     deadline_ms, seed, use_streams=True, seqs=None):
    """Open-loop stream driver: ``n_streams`` synthetic cameras sending
    ``frames`` frames each at an aggregate Poisson ``rate_rps``, with
    monotonic per-stream frame_seq (``use_streams=False`` is the legacy
    no-session arm: the SAME traffic as stateless requests).  Consults
    the fault injector's stream grammar (``stream_burst`` rate spikes,
    ``frame_gap`` dup/out-of-order delivery) per frame, like the chaos
    test's driver.  Returns fresh/degraded latencies, stalenesses, and
    rejects by reason."""
    from can_tpu.serve import RejectedError
    from can_tpu.testing.faults import active_injector

    rng = np.random.default_rng(seed)
    seqs = seqs if seqs is not None else {k: 0 for k in range(n_streams)}
    tickets = []

    def submit(k, seq_override=None):
        sid = f"cam{k}"
        if not use_streams:
            tickets.append(service.submit(images[k % len(images)],
                                          deadline_ms=deadline_ms))
            return
        if seq_override is None:
            seqs[k] += 1
            fs = seqs[k]
        else:
            fs = seq_override
        tickets.append(service.submit(images[k % len(images)],
                                      deadline_ms=deadline_ms,
                                      stream_id=sid, frame_seq=fs))

    t0 = time.perf_counter()
    next_t = 0.0
    for f in range(frames):
        for k in range(n_streams):
            next_t += float(rng.exponential(1.0 / rate_rps))
            sleep = t0 + next_t - time.perf_counter()
            if sleep > 0:
                time.sleep(sleep)
            inj = active_injector()
            if inj is not None:
                d = inj.on_stream_frame(stream=f"cam{k}", frame=f + 1)
                if d is not None and d["kind"] == "stream_burst":
                    for _ in range(d["burst"]):
                        submit(k)
                elif d is not None:  # frame_gap
                    submit(k, seq_override=(seqs[k] if d["mode"] == "dup"
                                            else max(seqs[k] - 2, 0)))
            submit(k)
    fresh, degraded, staleness = [], [], []
    rejects = {}
    for t in tickets:
        try:
            res = t.result(timeout=120.0)
            if getattr(res, "degraded", False):
                degraded.append(res.latency_s)
                if res.staleness_s is not None:
                    staleness.append(res.staleness_s)
            else:
                fresh.append(res.latency_s)
        except RejectedError as e:
            rejects[e.reason] = rejects.get(e.reason, 0) + 1
    wall = time.perf_counter() - t0
    return {"submitted": len(tickets), "fresh": fresh,
            "degraded": degraded, "staleness": staleness,
            "rejects": rejects, "wall_s": wall,
            "served_rps": (len(fresh) + len(degraded)) / max(wall, 1e-9)}


def bench_stream(*, n_streams=4, frames=8, repeats=3, max_batch=4,
                 out_path=None) -> list:
    """Streaming-session tier (r15): sustained per-stream p99 and
    streams-per-device at a fixed deadline, and the degradation ladder
    under 2x overload — with the legacy (no-session) arm driven by the
    SAME traffic in the SAME run.  The committed artifact is the
    receipt that the ladder ENGAGES under overload (degraded fraction
    > 0 where the legacy arm can only reject) and that degraded answers
    are CHEAP (their p99 is the EWMA-lookup cost, not a launch).

    Phases per arm: capacity probe (a back-to-back burst measures the
    box's served rate — "2x overload" means 2x THAT, not 2x an
    arbitrary offered rate), sustained at ``BENCH_STREAM_RATE`` (default
    4 req/s aggregate, below capacity), then overload at 2x measured
    capacity.  Gated records: ``serve_stream_p99_sustained`` (ms,
    upward), ``serve_stream_rps_sustained`` (req/s, downward),
    ``serve_stream_streams_per_device`` (unit ``streams``, downward-
    gated — how many fixed-rate cameras one device sustains inside the
    deadline), ``serve_stream_degraded_p99_2x`` (ms, upward: degraded
    answers must stay cheap) and ``serve_stream_fresh_p99_2x`` (ms,
    upward).  ``serve_stream_degraded_frac_2x`` (unit ``frac``) rides
    ungated as the ladder-engagement receipt, with the legacy arm's
    reject fraction as context."""
    import statistics

    import jax

    from can_tpu.models import cannet_init
    from can_tpu.obs import Telemetry
    from can_tpu.serve import CountService, ServeEngine, prepare_image

    rate = float(os.environ.get("BENCH_STREAM_RATE", "4"))
    deadline_ms = float(os.environ.get("BENCH_STREAM_DEADLINE_MS", "2000"))
    params = cannet_init(jax.random.key(0))
    sizes = [(64, 64)]
    ladder = ((64,), (64,))
    rng = np.random.default_rng(7)
    images = [prepare_image(
        (rng.uniform(0, 1, (h, w, 3)) * 255).astype(np.uint8))
        for h, w in sizes]

    def run_arm(tag, use_streams):
        tel = Telemetry()
        engine = ServeEngine(params, telemetry=tel, name=f"stream_{tag}")
        svc = CountService(engine, max_batch=max_batch, max_wait_ms=5.0,
                           queue_capacity=64, bucket_ladder=ladder,
                           telemetry=tel,
                           degrade_policy="priced" if use_streams
                           else "off")
        svc.warmup(sizes)
        out = {"sustained": [], "overload": []}
        with svc:
            # capacity probe: a burst of stateless requests back to
            # back — the served rate the overload phase doubles
            burst = [svc.submit(images[0], deadline_ms=30_000)
                     for _ in range(4 * max_batch)]
            t0 = time.perf_counter()
            for t in burst:
                t.result(timeout=120.0)
            cap_rps = len(burst) / max(time.perf_counter() - t0, 1e-9)
            out["capacity_rps"] = round(cap_rps, 2)
            seqs = {k: 0 for k in range(n_streams)}
            for rep in range(repeats):
                out["sustained"].append(_run_stream_load(
                    svc, images, n_streams=n_streams, frames=frames,
                    rate_rps=rate, deadline_ms=deadline_ms, seed=rep,
                    use_streams=use_streams, seqs=seqs))
                # overload runs LONGER than sustained (4x the frames):
                # the ladder triggers on accumulated backlog, and a
                # fraction-of-a-second burst would end before the
                # per-stream outstanding ever crossed its allowance
                out["overload"].append(_run_stream_load(
                    svc, images, n_streams=n_streams, frames=4 * frames,
                    rate_rps=2.0 * cap_rps, deadline_ms=deadline_ms,
                    seed=100 + rep, use_streams=use_streams, seqs=seqs))
            out["stream_stats"] = svc.stats()["streams"]
        return out

    stream_arm = run_arm("sessions", True)
    legacy_arm = run_arm("legacy", False)

    med = statistics.median
    p99 = lambda xs: (  # noqa: E731
        float(np.percentile(np.asarray(xs, np.float64) * 1e3, 99))
        if xs else None)
    spread = lambda xs: round(  # noqa: E731
        100.0 * (max(xs) - min(xs)) / max(abs(med(xs)), 1e-9), 1)

    sus_p99 = [p99(r["fresh"]) for r in stream_arm["sustained"]]
    sus_rps = [r["served_rps"] for r in stream_arm["sustained"]]
    # streams-per-device at the fixed deadline: how many cameras at
    # this per-stream frame rate one device absorbs while serving
    # inside the deadline — served rate over the per-stream offered rate
    per_stream_rate = rate / n_streams
    spd = [r["served_rps"] / per_stream_rate
           for r in stream_arm["sustained"]]
    ov_fresh_p99 = [p99(r["fresh"]) for r in stream_arm["overload"]]
    ov_deg_p99 = [p99(r["degraded"]) for r in stream_arm["overload"]
                  if r["degraded"]]
    deg_frac = [len(r["degraded"]) / max(r["submitted"], 1)
                for r in stream_arm["overload"]]
    leg_sus_p99 = [p99(r["fresh"]) for r in legacy_arm["sustained"]]
    leg_rej_frac = [sum(r["rejects"].values()) / max(r["submitted"], 1)
                    for r in legacy_arm["overload"]]

    base = {"n_streams": n_streams, "frames": frames, "repeats": repeats,
            "max_batch": max_batch, "rate_rps": rate,
            "deadline_ms": deadline_ms,
            "capacity_rps": stream_arm["capacity_rps"],
            "conditions": "single device, 64x64 bucket, capacity-probed "
                          "2x overload, sessions vs legacy same run"}
    records = []

    def rec(metric, vals, unit, **extra):
        vals = [v for v in vals if v is not None]
        if not vals:
            return
        records.append({"metric": metric, "value": round(med(vals), 3),
                        "unit": unit, "spread_pct": spread(vals),
                        **base, **extra})

    rec("serve_stream_p99_sustained", sus_p99, "ms",
        legacy_p99_ms=(round(med([x for x in leg_sus_p99
                                  if x is not None]), 3)
                       if any(x is not None for x in leg_sus_p99)
                       else None))
    rec("serve_stream_rps_sustained", sus_rps, "req/s")
    rec("serve_stream_streams_per_device", spd, "streams")
    leg_ov_p99 = [p99(r["fresh"]) for r in legacy_arm["overload"]]
    rec("serve_stream_fresh_p99_2x", ov_fresh_p99, "ms",
        legacy_p99_2x_ms=(round(med([x for x in leg_ov_p99
                                     if x is not None]), 3)
                          if any(x is not None for x in leg_ov_p99)
                          else None))
    rec("serve_stream_degraded_p99_2x", ov_deg_p99, "ms")
    rec("serve_stream_degraded_frac_2x", deg_frac, "frac",
        legacy_reject_frac=round(med(leg_rej_frac), 4),
        stream_stats=stream_arm["stream_stats"])
    for r in records:
        if _TELEMETRY is not None:
            _TELEMETRY.emit("bench", **r)
        print(json.dumps(r), flush=True)

    out = out_path or os.environ.get("BENCH_STREAM_OUT")
    if not out:
        # committed gate baseline only for an explicit stream-only run
        # (the perf/bn/fleet/autoscale/sched no-self-overwrite rule,
        # 6th use)
        out = ("BENCH_STREAM_cpu_r15.json"
               if os.environ.get("BENCH_SUITE_ONLY") == "stream"
               else "BENCH_STREAM_local.json")
    doc = {"metric": "serve_stream",
           "config": {**base, "platform": jax.devices()[0].platform},
           "legacy_arm": {
               "capacity_rps": legacy_arm["capacity_rps"],
               "overload_reject_frac": round(med(leg_rej_frac), 4),
               "sustained_p99_ms": [x for x in leg_sus_p99],
               "overload_p99_ms": [x for x in leg_ov_p99],
           },
           "results": records}
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# stream tier: {len(records)} records -> {out}", flush=True)
    return records


def bench_autoscale(*, replicas=2, n_requests=32, repeats=3, max_batch=4,
                    rate_rps=None, out_path=None) -> list:
    """Self-healing/autoscale tier (ISSUE 13): time-to-first-ready for a
    recovery-path replica, cold (live compiles) vs AOT-loaded
    (deserialized executables), and open-loop p99 THROUGH a mid-run
    scale-up event.

    Records: ``serve_autoscale_ttfr_cold`` / ``serve_autoscale_ttfr_aot``
    (unit ``s``: bench_compare gates duration UPWARD via its smaller-is-
    better rule) and ``serve_autoscale_p99_scaleup`` (unit ``ms``, fixed
    offered rate — the fleet tier's comparable-run discipline), each
    median-of-``repeats`` with the measured spread recorded as the
    gate's noise floor.  The AOT row also carries ``compiles`` (0 — the
    zero-new-compiles receipt tests/test_autoscale.py pins)."""
    import statistics
    import tempfile

    import jax

    from bench_serve import measure_time_to_first_ready, run_open_loop
    from can_tpu.models import cannet_init
    from can_tpu.obs import Telemetry
    from can_tpu.serve import (
        CountService,
        FleetEngine,
        load_aot_bundle,
        prepare_image,
    )

    if rate_rps is None:
        # below the 2-replica CPU box's saturation (the fleet tier's
        # rule): p99 must measure latency, not end-of-run backlog
        rate_rps = float(os.environ.get("BENCH_AUTOSCALE_RATE", "4"))
    need = replicas + 1  # the scale-up's spare device
    if len(jax.devices()) < need:
        print(f"# autoscale tier skipped: {len(jax.devices())} device(s) "
              f"< replicas+1={need} (use BENCH_SUITE_PLATFORM=cpu8 or a "
              f"multi-chip host)", flush=True)
        return []
    params = cannet_init(jax.random.key(0))
    sizes = [(64, 64), (96, 64)]
    ladder = (tuple(sorted({h for h, _ in sizes})),
              tuple(sorted({w for _, w in sizes})))
    buckets = [(h, w) for h in ladder[0] for w in ladder[1]]
    rng = np.random.default_rng(7)
    images = [prepare_image(
        (rng.uniform(0, 1, (h, w, 3)) * 255).astype(np.uint8))
        for h, w in sizes]
    tel = Telemetry()
    fleet = FleetEngine(params, replicas=replicas, telemetry=tel,
                        name="autoscale_fleet",
                        devices=jax.devices()[:need])
    # pinned to the pre-r14 single-size/timer config: this tier measures
    # AOT vs cold recovery mechanics, and its committed r13 baseline was
    # recorded at one program per (bucket, dtype) — the scheduler's own
    # tier (bench_sched) measures the menu
    svc = CountService(fleet, max_batch=max_batch, max_wait_ms=2.0,
                       queue_capacity=256, bucket_ladder=ladder,
                       telemetry=tel, menu_budget=1, flush_policy="timer")
    warm = svc.warmup(buckets)
    with tempfile.TemporaryDirectory() as aot_dir:
        manifest = fleet.bake_aot(aot_dir)
        bundle = load_aot_bundle(aot_dir)
        # time-to-first-ready on the SPARE device (exactly what a
        # resurrection or scale-up pays), cold vs AOT, interleaved so
        # host drift hits both arms equally (the host-tier discipline)
        spare = jax.devices()[replicas]
        cold_s, aot_s = [], []
        aot_compiles = cold_compiles = 0
        for rep in range(repeats):
            c = measure_time_to_first_ready(
                params, device=spare, bucket_shapes=buckets,
                max_batch=max_batch, telemetry=tel,
                name=f"ttfr_cold_{rep}")
            a = measure_time_to_first_ready(
                params, device=spare, bucket_shapes=buckets,
                max_batch=max_batch, aot_bundle=bundle, telemetry=tel,
                name=f"ttfr_aot_{rep}")
            cold_s.append(c["time_to_first_ready_s"])
            aot_s.append(a["time_to_first_ready_s"])
            cold_compiles = max(cold_compiles, c["compiles"])
            aot_compiles = max(aot_compiles, a["compiles"])

        # p99 through a scale-up: fixed-rate open loop; at 1/3 of the
        # arrivals the fleet grows onto the spare device from the bundle
        fleet.load_aot(aot_dir)
        p99s, rejects, scale_reports = [], 0, []
        with svc:
            for rep in range(repeats):
                trigger_at = n_requests // 3
                fired = []

                def on_arrival(i, _fired=fired):
                    if i == trigger_at and not _fired:
                        _fired.append(True)
                        scale_reports.append(
                            fleet.add_replica(reason="bench_scaleup"))

                o = run_open_loop(svc, images, n_requests, rate_rps,
                                  deadline_ms=30_000, seed=rep,
                                  on_arrival=on_arrival)
                p99s.append(o["p99_ms"])
                rejects += o["rejected"]
                if fired:
                    fleet.remove_replica(reason="bench_reset")
        spread = lambda xs: round(  # noqa: E731
            100.0 * (max(xs) - min(xs)) / max(statistics.median(xs), 1e-9),
            1)
        base = {"replicas": replicas, "offered_rps": rate_rps,
                "requests": n_requests, "repeats": repeats,
                "warmup_compiles": warm["compiles"],
                "aot_programs": len(manifest["programs"]),
                "aot_devices": len({p["device_id"]
                                    for p in manifest["programs"]})}
        records = [
            {"metric": "serve_autoscale_ttfr_cold",
             "value": round(statistics.median(cold_s), 3), "unit": "s",
             "spread_pct": spread(cold_s), "compiles": cold_compiles,
             **base},
            {"metric": "serve_autoscale_ttfr_aot",
             "value": round(statistics.median(aot_s), 3), "unit": "s",
             "spread_pct": spread(aot_s), "compiles": aot_compiles,
             **base},
            {"metric": "serve_autoscale_p99_scaleup",
             "value": round(statistics.median(p99s), 3), "unit": "ms",
             "spread_pct": spread(p99s), "rejects": rejects,
             "scale_ttfr_s": [r["time_to_first_ready_s"]
                              for r in scale_reports],
             "scale_compiles": [r["warmup_compiles"]
                                for r in scale_reports], **base},
        ]
    for rec in records:
        if _TELEMETRY is not None:
            _TELEMETRY.emit("bench", **rec)
        print(json.dumps(rec), flush=True)
    out = out_path or os.environ.get("BENCH_AUTOSCALE_OUT")
    if not out:
        # committed gate baseline only for an explicit autoscale-only
        # run (the perf/bn/fleet no-self-overwrite rule)
        out = ("BENCH_AUTOSCALE_cpu_r13.json"
               if os.environ.get("BENCH_SUITE_ONLY") == "autoscale"
               else "BENCH_AUTOSCALE_local.json")
    doc = {"metric": "serve_autoscale",
           "config": {"replicas": replicas, "requests": n_requests,
                      "repeats": repeats, "rate_rps": rate_rps,
                      "max_batch": max_batch,
                      "buckets": [f"{h}x{w}" for h, w in buckets],
                      "platform": jax.devices()[0].platform},
           "results": records}
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# autoscale tier: {len(records)} records -> {out}",
          flush=True)
    return records


def _rss_mb() -> float:
    """Current process resident set, MB (/proc VmRSS; ru_maxrss peak as
    the fallback on boxes without /proc)."""
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return float(line.split()[1]) / 1024.0
    except OSError:
        pass
    import resource

    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def bench_obsplane(*, hosts=4, events_per_host=2000, batch=200,
                   repeats=3, out_path=None) -> list:
    """Fleet-observability-plane tier (r16): the collector's ingest
    throughput, steady-state memory, and scrape cost at ``hosts``
    simulated pushers (obs/collector.py).

    Pure host-side — no device work; the numbers bound how much fleet
    telemetry one collector absorbs before it, not the run, is the
    bottleneck.  The workload is the real push path end to end: batched
    JSONL bodies through ``ingest_push`` (parse + skew sampling + gauges
    + ring + watermark merge) with the global SLO engine grading the
    merged stream, one host running 120 s fast to keep the correction
    in the measured path.  Gated records: ``obsplane_ingest_events_per_s``
    (events/s, downward = regression), ``obsplane_rss_mb`` (mb, upward =
    the bounded-ring discipline leaked; rings and pending queues are the
    ONLY per-host state allowed to grow), ``obsplane_scrape_ms`` (ms —
    the /metrics text render over the full fleet)."""
    import statistics

    from can_tpu.obs.collector import FleetCollector
    from can_tpu.obs.slo import load_slo_spec

    spec = load_slo_spec(os.path.join(os.path.dirname(
        os.path.abspath(__file__)), "slo_spec.json"))
    base_ts = 1_000_000.0
    # host 1 runs 120 s fast: every rep exercises offset freezing and
    # the corrected-release path, not just the zero-skew fast path
    skews = {h: (120.0 if h == 1 else 0.0) for h in range(hosts)}

    def host_batches(h):
        evs = []
        for i in range(events_per_host):
            ts = base_ts + skews[h] + i * 0.05
            if i % 50 == 0:
                evs.append({"ts": ts, "host_id": h, "kind": "heartbeat",
                            "payload": {"seq": i // 50,
                                        "start_ts": base_ts + skews[h]}})
            else:
                evs.append({"ts": ts, "host_id": h,
                            "kind": "serve.request",
                            "payload": {"latency_s":
                                        0.02 if i % 10 else 3.0}})
        return ["\n".join(json.dumps(e) for e in evs[j:j + batch]) + "\n"
                for j in range(0, len(evs), batch)]

    bodies = {h: [b.encode() for b in host_batches(h)] for h in
              range(hosts)}
    total_events = hosts * events_per_host
    med = statistics.median
    spread = lambda xs: round(  # noqa: E731
        100.0 * (max(xs) - min(xs)) / max(abs(med(xs)), 1e-9), 1)
    rates, scrapes, rss = [], [], []
    evals = None
    for rep in range(repeats):
        col = FleetCollector(spec, poll_interval_s=3600.0)
        n_batches = max(len(bodies[h]) for h in bodies)
        t0 = time.perf_counter()
        for j in range(n_batches):  # interleaved, like real pushers
            for h in range(hosts):
                if j < len(bodies[h]):
                    col.ingest_push(bodies[h][j])
            col.poll(now=base_ts + (j + 1) * batch * 0.05)
        col.drain(now=base_ts + events_per_host * 0.05)
        rates.append(total_events / (time.perf_counter() - t0))
        t_s = [0.0] * 10
        for k in range(len(t_s)):
            s0 = time.perf_counter()
            text = col.render_metrics()
            t_s[k] = (time.perf_counter() - s0) * 1e3
        assert "can_tpu_slo_burn_global" in text
        scrapes.append(med(t_s))
        rss.append(_rss_mb())
        if evals is None:
            evals = len(col.evals())
        col.close(drain=False)
    base = {"hosts": hosts, "events_per_host": events_per_host,
            "batch": batch, "repeats": repeats, "evaluations": evals,
            "conditions": "push path end-to-end (JSONL parse -> merge "
                          "-> global SLO engine), host 1 skewed +120s"}
    records = [
        {"metric": "obsplane_ingest_events_per_s",
         "value": round(med(rates), 1), "unit": "events/s",
         "spread_pct": spread(rates), **base},
        {"metric": "obsplane_rss_mb", "value": round(med(rss), 1),
         "unit": "mb", "spread_pct": spread(rss), **base},
        {"metric": "obsplane_scrape_ms", "value": round(med(scrapes), 3),
         "unit": "ms", "spread_pct": spread(scrapes), **base},
    ]
    for r in records:
        if _TELEMETRY is not None:
            _TELEMETRY.emit("bench", **r)
        print(json.dumps(r), flush=True)
    out = out_path or os.environ.get("BENCH_OBSPLANE_OUT")
    if not out:
        # committed gate baseline only for an explicit obsplane-only run
        # (the perf/bn/fleet/autoscale/sched/stream no-self-overwrite
        # rule, 7th use)
        out = ("BENCH_OBSPLANE_cpu_r16.json"
               if os.environ.get("BENCH_SUITE_ONLY") == "obsplane"
               else "BENCH_OBSPLANE_local.json")
    doc = {"metric": "obsplane", "config": base, "results": records}
    with open(out, "w") as f:
        json.dump(doc, f, indent=1)
    print(f"# obsplane tier: {len(records)} records -> {out}", flush=True)
    return records


def bench_highres_eval(jnp, compute_dtype, *, h, w, steps, warmup=2):
    import jax

    from can_tpu.data.batching import Batch
    from can_tpu.models import cannet_apply, cannet_init
    from can_tpu.parallel import make_dp_eval_step, make_global_batch, make_mesh
    ndev = jax.device_count()
    mesh = make_mesh()
    rng = np.random.default_rng(0)
    local_b = ndev  # one image per chip: the reference's batch-1 eval habit
    batch = Batch(
        image=rng.normal(size=(local_b, h, w, 3)).astype(np.float32),
        dmap=rng.uniform(size=(local_b, h // 8, w // 8, 1)).astype(np.float32),
        pixel_mask=np.ones((local_b, h // 8, w // 8, 1), np.float32),
        sample_mask=np.ones((local_b,), np.float32),
    )
    gbatch = make_global_batch(batch, mesh)
    params = cannet_init(jax.random.key(0))
    ev = make_dp_eval_step(cannet_apply, mesh, compute_dtype=compute_dtype)
    for _ in range(warmup):
        m = ev(params, gbatch, None)
    jax.device_get(m)
    t0 = time.perf_counter()
    for _ in range(steps):
        m = ev(params, gbatch, None)
    jax.device_get(m)
    dt = time.perf_counter() - t0
    img_per_s = local_b * steps / dt
    tag = "f32" if compute_dtype is None else "bf16"
    _emit(f"eval_highres_{h}x{w}_b1_{tag}", img_per_s, "images/sec",
          per_chip_img_per_s=round(img_per_s / ndev, 3))


def main() -> None:
    if os.environ.get("BENCH_SUITE_PLATFORM") == "cpu8":
        from __graft_entry__ import _ensure_cpu_flags

        _ensure_cpu_flags(8)
        import jax

        jax.config.update("jax_platforms", "cpu")
    from can_tpu.utils import await_devices, emit_null_result

    # fail fast on a dead tunnel, leaving a machine-readable null line
    await_devices(on_timeout=emit_null_result("bench_suite"))
    import jax  # noqa: F811
    import jax.numpy as jnp

    if not os.environ.get("BENCH_SUITE_NO_CACHE"):
        from can_tpu.utils import enable_compilation_cache

        cache = enable_compilation_cache()
        print(f"# compilation cache: {cache}", flush=True)

    quick = bool(os.environ.get("BENCH_SUITE_QUICK"))
    only = os.environ.get("BENCH_SUITE_ONLY", "")  # substring filter
    print(f"# bench_suite devices={jax.device_count()} "
          f"platform={jax.devices()[0].platform} quick={quick}", flush=True)

    global _TELEMETRY
    if os.environ.get("BENCH_TELEMETRY_DIR"):
        from can_tpu import obs

        _TELEMETRY = obs.open_host_telemetry(
            os.environ["BENCH_TELEMETRY_DIR"])
        _TELEMETRY.emit("run", config={"suite": True, "quick": quick,
                                       "only": only,
                                       "devices": jax.device_count()})

    def want(name: str) -> bool:
        return only in name

    if quick:
        if want("fixed"):
            bench_fixed(jnp, jnp.bfloat16, b=1, h=128, w=160, steps=4)
            bench_fixed(jnp, None, b=1, h=128, w=160, steps=4)
        if want("pipeline") or want("u8"):
            if want("pipeline"):
                bench_pipeline(jnp, jnp.bfloat16, n_images=16, batch=1,
                               epochs=2, lo=64, hi=160, dominant=(128, 160))
            bench_pipeline(jnp, jnp.bfloat16, n_images=16, batch=1, epochs=2,
                           lo=64, hi=160, dominant=(128, 160), u8=True)
        if want("eval"):
            bench_highres_eval(jnp, jnp.bfloat16, h=256, w=256, steps=4)
            bench_eval_pipeline(jnp, jnp.bfloat16, n_images=8, batch=2,
                                lo=64, hi=160, dominant=(128, 160))
            bench_eval_pipeline(jnp, jnp.bfloat16, n_images=8, batch=2,
                                lo=64, hi=160, dominant=(128, 160), u8=True)
        if want("host"):
            bench_host_pipeline(n_images=16, batch=4, h=128, w=160,
                                workers=(0, 4), repeats=3)
        if want("plan"):
            bench_plan_space(repeats=2)
        if want("perf"):
            bench_perf_ledger(jnp, jnp.bfloat16)
        if want("bn"):
            bench_bn(jnp, jnp.bfloat16)
        if want("fleet"):
            bench_serve_fleet(n_requests=16, repeats=2)
        if want("autoscale"):
            bench_autoscale(n_requests=16, repeats=2)
        if want("sched"):
            bench_sched(n_requests=16, repeats=2)
        if want("stream"):
            bench_stream(n_streams=2, frames=6, repeats=2)
        if want("obsplane"):
            bench_obsplane(hosts=2, events_per_host=800, repeats=2)
    else:
        if want("fixed"):
            bench_fixed(jnp, jnp.bfloat16, b=16, h=576, w=768, steps=20)
            bench_fixed(jnp, None, b=16, h=576, w=768, steps=20)
        if want("pipeline"):
            bench_pipeline(jnp, jnp.bfloat16, n_images=64, batch=8, epochs=3)
        if want("pipeline") or want("u8"):
            bench_pipeline(jnp, jnp.bfloat16, n_images=64, batch=8, epochs=3,
                           u8=True)
        if want("b16varres"):
            # VERDICT r3 item 3: b16 varres used to OOM on the largest
            # bucket; per-bucket auto remat must let it run end-to-end
            bench_pipeline(jnp, jnp.bfloat16, n_images=64, batch=16,
                           epochs=3, remat="auto")
        if want("eval"):
            bench_highres_eval(jnp, jnp.bfloat16, h=1536, w=2048, steps=8)
            # the 576x768-dominant b16 eval config the r4 verdict expects
            # to move materially with prefetch on the tunnel
            bench_eval_pipeline(jnp, jnp.bfloat16, n_images=48, batch=16,
                                lo=384, hi=768, dominant=(576, 768))
            # the u8 transfer mode of the same config (VERDICT r5 weak #3:
            # eval_pipeline had no _u8 entry, so the 4x-transfer-cut mode
            # was only ever measured on the train path)
            bench_eval_pipeline(jnp, jnp.bfloat16, n_images=48, batch=16,
                                lo=384, hi=768, dominant=(576, 768),
                                u8=True)
        if want("host"):
            bench_host_pipeline(n_images=48, batch=8, workers=(0, 4, 8))
        if want("plan"):
            # simulated: runs (and means the same) on any backend
            bench_plan_space()
        if want("perf"):
            # same small-shape config as quick mode ON PURPOSE: the gate
            # baseline (PERF_LEDGER_cpu_r09.json) must be reproducible on
            # the CPU CI box either way
            bench_perf_ledger(jnp, jnp.bfloat16)
        if want("bn"):
            # same rule as the perf tier: one small config in both modes,
            # reproducible on the CPU gate box (BENCH_BN_cpu_r10.json)
            bench_bn(jnp, jnp.bfloat16)
        if want("fleet"):
            # small shapes + fixed offered rate, reproducible on the CPU
            # gate box (BENCH_FLEET_cpu_r11.json); chip-scale serving
            # numbers come from bench_serve.py open-loop sweeps
            bench_serve_fleet()
        if want("autoscale"):
            # same reproducible-on-the-gate-box rule
            # (BENCH_AUTOSCALE_cpu_r13.json)
            bench_autoscale()
        if want("sched"):
            # scheduling-core tier: single engine, no cpu8 needed
            # (BENCH_SCHED_cpu_r14.json)
            bench_sched()
        if want("stream"):
            # streaming-session tier: single engine, capacity-probed 2x
            # overload, sessions + legacy arms (BENCH_STREAM_cpu_r15.json)
            bench_stream()
        if want("obsplane"):
            # fleet-observability tier: pure host-side, 4 simulated
            # pushers through the real ingest path
            # (BENCH_OBSPLANE_cpu_r16.json)
            bench_obsplane()

    if _TELEMETRY is not None:
        from can_tpu.obs import emit_memory

        emit_memory(_TELEMETRY, where="suite_end")
        _TELEMETRY.close()
        _TELEMETRY = None


if __name__ == "__main__":
    main()
