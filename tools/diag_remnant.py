"""Diagnose the varres remnant-batch throughput regression (round 4).

Round 3's varres schedule (9 full-gbs batches, 21.7% waste) ran at
56.3 img/s; the remnant schedule (25 batches incl. small sub-batches,
10.9% waste) measured 35.8 — killing dead slots LOST 20 img/s.  Candidate
causes, separated here on staged device batches:

A. per-batch step times by (shape, batch): small-batch chip inefficiency;
B. program-interleave cost: the same batches run grouped-by-program vs in
   schedule order — a gap means executable switching (param relayout /
   instruction reload) dominates;
C. the no-remnant baseline, same process, for the r3 comparison point.

Run (single process, real TPU): python tools/diag_remnant.py
"""

from __future__ import annotations

import collections
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def stage(batcher, put, epoch=2):
    staged = []
    for b in batcher.epoch(epoch):
        staged.append(put(b))
    return staged


def run_epoch(step, state, staged, reps=2):
    import jax

    for g in staged:  # warm
        state, m = step(state, g)
    float(jax.device_get(m["loss"]))
    t0 = time.perf_counter()
    for _ in range(reps):
        for g in staged:
            state, m = step(state, g)
    float(jax.device_get(m["loss"]))
    dt = time.perf_counter() - t0
    imgs = sum(float(np.sum(jax.device_get(g["sample_mask"]))) for g in staged)
    return state, imgs * reps / dt


def per_batch_times(step, state, staged, reps=3):
    import jax

    rows = collections.defaultdict(list)
    for g in staged:  # warm every program
        state, m = step(state, g)
    float(jax.device_get(m["loss"]))
    for g in staged:
        t0 = time.perf_counter()
        for _ in range(reps):
            state, m = step(state, g)
        float(jax.device_get(m["loss"]))
        dt = (time.perf_counter() - t0) / reps
        shape = tuple(int(s) for s in g["image"].shape[:3])
        rows[shape].append(dt)
    return state, rows


def main():
    from bench_suite import SynthVarResDataset

    from can_tpu.data import ShardedBatcher
    from can_tpu.models import cannet_apply, cannet_init
    from can_tpu.parallel import make_dp_train_step, make_global_batch, make_mesh
    from can_tpu.train import create_train_state, make_lr_schedule, make_optimizer
    from can_tpu.utils import await_devices, enable_compilation_cache

    await_devices()  # fail fast on a dead tunnel instead of hanging
    enable_compilation_cache()
    import jax
    import jax.numpy as jnp

    ndev = jax.device_count()
    mesh = make_mesh()
    put = lambda b: make_global_batch(b, mesh)
    ds = SynthVarResDataset(64)
    opt = make_optimizer(make_lr_schedule(1e-7, world_size=ndev))
    state = create_train_state(cannet_init(jax.random.key(0)), opt)
    step = make_dp_train_step(cannet_apply, opt, mesh,
                              compute_dtype=jnp.bfloat16)

    for remnant in (True, False):
        batcher = ShardedBatcher(ds, 8 * ndev, shuffle=True, seed=0,
                                 pad_multiple="auto", max_buckets=24,
                                 remnant_sizes=remnant, batch_quantum=ndev)
        staged = stage(batcher, put)
        jax.block_until_ready(staged[-1]["image"])
        tag = "remnant" if remnant else "legacy "
        # schedule order (what the epoch actually runs)
        state, sched_ips = run_epoch(step, state, staged)
        # grouped by program: same batches, all same-shape consecutive
        grouped = sorted(staged, key=lambda g: tuple(g["image"].shape))
        state, grouped_ips = run_epoch(step, state, grouped)
        print(f"[{tag}] batches={len(staged)} schedule-order={sched_ips:.1f} "
              f"grouped-by-program={grouped_ips:.1f} img/s", flush=True)
        if remnant:
            state, rows = per_batch_times(step, state, staged)
            print("  per-(B,H,W) mean step ms / imgs-per-s-equivalent:")
            for shape in sorted(rows):
                ts = rows[shape]
                b = shape[0]
                ms = 1e3 * float(np.mean(ts))
                print(f"    {shape}: {ms:7.1f} ms  n={len(ts)} "
                      f"({b / np.mean(ts):6.1f} img/s)", flush=True)


if __name__ == "__main__":
    main()
